"""Pallas TPU kernels for windowed aggregation.

No reference counterpart — the reference's hot loop is per-sample JVM
iteration (``query/.../PeriodicSamplesMapper.scala``); this is its
explicitly-scheduled TPU form.

The jit/XLA path (``kernels.py``) is the default engine; these Pallas
formulations exist for the cases XLA's fusion can't reach — keeping the
entire window evaluation in VMEM with explicit grids. Shapes follow the VPU
tiling: the sample axis rides the 128-lane dimension; one grid cell
processes one series row.

``windowed_sum_pallas`` evaluates ``sum_over_time`` for every step of every
series with a fori loop over steps and a masked lane reduction per step —
O(S) lane work per step, all in VMEM (compare the prefix-sum formulation in
``kernels.range_eval``, which is O(1) gathers per step but materializes
[P, S+1] prefix arrays in HBM).

Kernels are validated in interpret mode on CPU; device selection between the
XLA and Pallas paths is a benchmarking decision on real hardware.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _windowed_sum_kernel(steps_ref, window_ref, ts_ref, vals_ref, out_ref):
    ts = ts_ref[0, :]
    vals = vals_ref[0, :]
    K = out_ref.shape[1]
    window = window_ref[0]

    def body(k, _):
        t = steps_ref[k]
        in_win = (ts > t - window) & (ts <= t)
        out_ref[0, k] = jnp.sum(jnp.where(in_win, vals, 0.0))
        return 0

    lax.fori_loop(0, K, body, 0)


@partial(jax.jit, static_argnames=("interpret",))
def windowed_sum_pallas(ts, vals, steps, window, interpret: bool = False):
    """sum over (t-w, t] per series per step: ts int32 [P,S] (TS_PAD padded),
    vals f32 [P,S], steps int32 [K], window int32 → f32 [P,K].

    Invalid (padded) lanes carry TS_PAD > any step, so the window mask
    excludes them; vals padding must be 0."""
    P, S = ts.shape
    K = steps.shape[0]
    return pl.pallas_call(
        _windowed_sum_kernel,
        out_shape=jax.ShapeDtypeStruct((P, K), vals.dtype),
        grid=(P,),
        in_specs=[
            pl.BlockSpec((K,), lambda p: (0,)),
            pl.BlockSpec((1,), lambda p: (0,)),
            pl.BlockSpec((1, S), lambda p: (p, 0)),
            pl.BlockSpec((1, S), lambda p: (p, 0)),
        ],
        out_specs=pl.BlockSpec((1, K), lambda p: (p, 0)),
        interpret=interpret,
    )(steps, window.reshape(1), ts, vals)


# ---------------------------------------------------------------------------
# fused decode -> window(rate) pipeline (VERDICT r3 #4)
#
# One Pallas program per series row: bit-packed device pages are unpacked,
# counter-corrected and window-evaluated entirely in VMEM — the decoded
# [P, S] tensors never round-trip through HBM (the XLA-fused composition
# materializes them between the decode and window stages). HBM traffic
# drops to packed-page reads + a [P, K] write.
#
# Scans (carry-forward fill, prefix sums) use log-doubling with STATIC
# shifts (lax.pad + slice) so the kernel avoids relying on lax.cum* Mosaic
# lowering. Validated in interpret mode against the XLA reference
# (kernels.range_eval_masked); real-TPU timing runs via bench.py.

from filodb_tpu.memory.device_pages import BLOCK, WORDS_PER_BLOCK_MAX


def _shift_right(x, n):
    """x[i-n] with zero fill (static n) for 1D vectors."""
    if n == 0:
        return x
    return jnp.pad(x, (n, 0))[:-n]


def _scan_sum(x):
    """Inclusive prefix sum via log-doubling (static shifts)."""
    n = x.shape[0]
    sh = 1
    while sh < n:
        x = x + _shift_right(x, sh)
        sh *= 2
    return x


def _carry_forward(vals, known):
    """Last known value at-or-before each position (log-doubling)."""
    n = vals.shape[0]
    sh = 1
    while sh < n:
        pv = _shift_right(vals, sh)
        pk = _shift_right(known.astype(vals.dtype), sh) > 0
        vals = jnp.where(known, vals, pv)
        known = known | pk
        sh *= 2
    return vals, known


def _decode_series(rb, sl, tw, twd, vf, vs, vw, vwd, bc):
    """[NB,...] page rows -> (ts i32 [S], vals f32 [S], valid bool [S])."""
    nb = rb.shape[0]
    col = lax.broadcasted_iota(jnp.uint32, (nb, BLOCK), 1)
    # timestamps: zigzag residuals at per-block width
    w_col = tw.astype(jnp.uint32)[:, None]
    bit0 = col * w_col
    word_idx = (bit0 >> 5).astype(jnp.int32)
    bit_off = bit0 & 31
    lo = jnp.take_along_axis(twd, word_idx, axis=1)
    hi = jnp.take_along_axis(
        twd, jnp.minimum(word_idx + 1, WORDS_PER_BLOCK_MAX - 1), axis=1)
    mask = jnp.where(w_col >= 32, jnp.uint32(0xFFFFFFFF),
                     (jnp.uint32(1) << w_col) - jnp.uint32(1))
    zz = ((lo >> bit_off)
          | jnp.where(bit_off > 0, hi << (32 - bit_off), 0).astype(
              jnp.uint32)) & mask
    zz = jnp.where(w_col == 0, jnp.uint32(0), zz)
    resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
    lane = lax.broadcasted_iota(jnp.int32, (nb, BLOCK), 1)
    ts = rb[:, None] + sl[:, None] * lane + resid
    # values: XOR-vs-block-first at per-block width/shift
    vw_col = vw.astype(jnp.uint32)[:, None]
    bit0v = col * vw_col
    widx = (bit0v >> 5).astype(jnp.int32)
    boff = bit0v & 31
    vlo = jnp.take_along_axis(vwd, widx, axis=1)
    vhi = jnp.take_along_axis(
        vwd, jnp.minimum(widx + 1, WORDS_PER_BLOCK_MAX - 1), axis=1)
    vmask = jnp.where(vw_col >= 32, jnp.uint32(0xFFFFFFFF),
                      (jnp.uint32(1) << vw_col) - jnp.uint32(1))
    x = ((vlo >> boff)
         | jnp.where(boff > 0, vhi << (32 - boff), 0).astype(
             jnp.uint32)) & vmask
    x = jnp.where(vw_col == 0, jnp.uint32(0), x)
    tz = vs.astype(jnp.uint32)[:, None]
    xored = jnp.where(tz >= 32, jnp.uint32(0), x << tz)
    bits = xored ^ vf[:, None]
    vals = lax.bitcast_convert_type(bits, jnp.float32)
    valid = lane < bc[:, None]
    ts = jnp.where(valid, ts, jnp.int32(-(2**31) + 2))
    return ts.reshape(-1), vals.reshape(-1), valid.reshape(-1)


def _fused_rate_kernel(steps_ref, window_ref, rb_ref, sl_ref, tw_ref,
                       twd_ref, vf_ref, vs_ref, vw_ref, vwd_ref, bc_ref,
                       out_ref, *, counter: bool, kind: str):
    window = window_ref[0]
    ts, vals, valid = _decode_series(
        rb_ref[0], sl_ref[0], tw_ref[0], twd_ref[0],
        lax.bitcast_convert_type(vf_ref[0], jnp.uint32), vs_ref[0],
        vw_ref[0], vwd_ref[0], bc_ref[0])
    S = ts.shape[0]
    v = jnp.where(valid, vals, 0.0)
    idx = lax.broadcasted_iota(jnp.int32, (S,), 0)
    if counter:
        filled, known = _carry_forward(jnp.where(valid, v, 0.0), valid)
        prevv = _shift_right(filled, 1)
        prevk = _shift_right(known.astype(jnp.int32), 1) > 0
        drop = valid & prevk & (v < prevv)
        corr = _scan_sum(jnp.where(drop, prevv, 0.0))
        cv = v + corr
    else:
        cv = v
    K = out_ref.shape[1]

    def body(k, _):
        t = steps_ref[k]
        in_win = (ts > t - window) & (ts <= t) & valid
        n = jnp.sum(in_win.astype(jnp.float32))
        first_i = jnp.min(jnp.where(in_win, idx, S))
        last_i = jnp.max(jnp.where(in_win, idx, -1))
        sel_first = idx == first_i
        sel_last = idx == last_i
        v_first = jnp.sum(jnp.where(sel_first, cv, 0.0))
        v_last = jnp.sum(jnp.where(sel_last, cv, 0.0))
        raw_first = jnp.sum(jnp.where(sel_first, v, 0.0))
        t_first = jnp.sum(jnp.where(sel_first, ts, 0).astype(
            jnp.float32)) / 1000.0
        t_last = jnp.sum(jnp.where(sel_last, ts, 0).astype(
            jnp.float32)) / 1000.0
        result = v_last - v_first
        # Prometheus extrapolatedRate (kernels._range_impl parity)
        range_start = (t - window).astype(jnp.float32) / 1000.0
        range_end = t.astype(jnp.float32) / 1000.0
        sampled = t_last - t_first
        avg_dur = sampled / jnp.maximum(n - 1.0, 1.0)
        dur_start = t_first - range_start
        dur_end = range_end - t_last
        if kind in ("rate", "increase"):
            dur_to_zero = jnp.where(
                result > 0,
                sampled * raw_first / jnp.maximum(result, 1e-30),
                jnp.inf)
            dur_start = jnp.minimum(dur_start, dur_to_zero)
        threshold = avg_dur * 1.1
        extend = sampled
        extend = extend + jnp.where(dur_start < threshold, dur_start,
                                    avg_dur / 2.0)
        extend = extend + jnp.where(dur_end < threshold, dur_end,
                                    avg_dur / 2.0)
        factor = extend / jnp.maximum(sampled, 1e-10)
        result = result * factor
        if kind == "rate":
            result = result / (window.astype(jnp.float32) / 1000.0)
        out_ref[0, k] = jnp.where(n >= 2, result, jnp.nan)
        return 0

    lax.fori_loop(0, K, body, 0)


@partial(jax.jit, static_argnames=("kind", "counter", "interpret"))
def fused_decode_rate_pallas(packed, steps, window, kind: str = "rate",
                             counter: bool = True,
                             interpret: bool = False):
    """Fused pipeline: packed [P, NB, ...] device pages -> per-series
    windowed rate/increase/delta [P, K], decode + correction + window all
    in VMEM (one grid cell per series)."""
    (rel_bases, ts_slopes, ts_widths, ts_words, v_firsts, v_shifts,
     v_widths, v_words, blk_counts) = packed
    P, NB = rel_bases.shape
    K = steps.shape[0]
    v_firsts_i32 = lax.bitcast_convert_type(v_firsts, jnp.int32)
    kernel = partial(_fused_rate_kernel, counter=counter, kind=kind)
    row = lambda p: (p, 0)  # noqa: E731
    row3 = lambda p: (p, 0, 0)  # noqa: E731
    rep = lambda p: (0,)  # noqa: E731
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((P, K), jnp.float32),
        grid=(P,),
        in_specs=[
            pl.BlockSpec((K,), rep),
            pl.BlockSpec((1,), rep),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB, WORDS_PER_BLOCK_MAX), row3),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB), row),
            pl.BlockSpec((1, NB, WORDS_PER_BLOCK_MAX), row3),
            pl.BlockSpec((1, NB), row),
        ],
        out_specs=pl.BlockSpec((1, K), lambda p: (p, 0)),
        interpret=interpret,
    )(steps, window.reshape(1), rel_bases, ts_slopes, ts_widths, ts_words,
      v_firsts_i32, v_shifts, v_widths, v_words, blk_counts)
