"""Pallas TPU kernels for windowed aggregation.

No reference counterpart — the reference's hot loop is per-sample JVM
iteration (``query/.../PeriodicSamplesMapper.scala``); this is its
explicitly-scheduled TPU form.

The jit/XLA path (``kernels.py``) is the default engine; these Pallas
formulations exist for the cases XLA's fusion can't reach — keeping the
entire window evaluation in VMEM with explicit grids. Shapes follow the VPU
tiling: the sample axis rides the 128-lane dimension; one grid cell
processes one series row.

``windowed_sum_pallas`` evaluates ``sum_over_time`` for every step of every
series with a fori loop over steps and a masked lane reduction per step —
O(S) lane work per step, all in VMEM (compare the prefix-sum formulation in
``kernels.range_eval``, which is O(1) gathers per step but materializes
[P, S+1] prefix arrays in HBM).

Kernels are validated in interpret mode on CPU; device selection between the
XLA and Pallas paths is a benchmarking decision on real hardware.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl


def _windowed_sum_kernel(steps_ref, window_ref, ts_ref, vals_ref, out_ref):
    ts = ts_ref[0, :]
    vals = vals_ref[0, :]
    K = out_ref.shape[1]
    window = window_ref[0]

    def body(k, _):
        t = steps_ref[k]
        in_win = (ts > t - window) & (ts <= t)
        out_ref[0, k] = jnp.sum(jnp.where(in_win, vals, 0.0))
        return 0

    lax.fori_loop(0, K, body, 0)


@partial(jax.jit, static_argnames=("interpret",))
def windowed_sum_pallas(ts, vals, steps, window, interpret: bool = False):
    """sum over (t-w, t] per series per step: ts int32 [P,S] (TS_PAD padded),
    vals f32 [P,S], steps int32 [K], window int32 → f32 [P,K].

    Invalid (padded) lanes carry TS_PAD > any step, so the window mask
    excludes them; vals padding must be 0."""
    P, S = ts.shape
    K = steps.shape[0]
    return pl.pallas_call(
        _windowed_sum_kernel,
        out_shape=jax.ShapeDtypeStruct((P, K), vals.dtype),
        grid=(P,),
        in_specs=[
            pl.BlockSpec((K,), lambda p: (0,)),
            pl.BlockSpec((1,), lambda p: (0,)),
            pl.BlockSpec((1, S), lambda p: (p, 0)),
            pl.BlockSpec((1, S), lambda p: (p, 0)),
        ],
        out_specs=pl.BlockSpec((1, K), lambda p: (p, 0)),
        interpret=interpret,
    )(steps, window.reshape(1), ts, vals)
