"""Cross-series (label-grouped) aggregation kernels.

Counterpart of the reference's RowAggregators
(``query/src/main/scala/filodb/query/exec/aggregator/RowAggregator.scala`` and
its sum/min/max/count/avg/stddev/topk/quantile/count_values impls) — lowered
to ``jax.ops.segment_*`` over a host-computed group-id vector, as scoped by the
north star (AggregateMapReduce → ``segment_sum``).

Inputs are [P, K] step matrices with NaN = absent; NaN entries are excluded
from every aggregate, matching Prometheus semantics where a series without a
sample at a step simply doesn't participate.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from filodb_tpu.query.engine.kernels import fdtype


@partial(jax.jit, static_argnames=("op", "num_groups"))
def aggregate(op: str, values, group_ids, num_groups: int, param=0.0):
    """Aggregate [P, K] -> [G, K] by group id.

    op: sum|min|max|count|avg|group|stddev|stdvar|count_values is host-side.
    """
    dt = fdtype()
    values = values.astype(dt)
    present = ~jnp.isnan(values)
    zeroed = jnp.where(present, values, 0.0)
    cnt = jax.ops.segment_sum(present.astype(dt), group_ids, num_groups)
    nan = jnp.array(jnp.nan, dt)

    if op == "count":
        return jnp.where(cnt > 0, cnt, nan)
    if op == "group":
        return jnp.where(cnt > 0, 1.0, nan).astype(dt)
    if op in ("sum", "avg", "stddev", "stdvar"):
        s = jax.ops.segment_sum(zeroed, group_ids, num_groups)
        if op == "sum":
            return jnp.where(cnt > 0, s, nan)
        mean = s / jnp.maximum(cnt, 1.0)
        if op == "avg":
            return jnp.where(cnt > 0, mean, nan)
        s2 = jax.ops.segment_sum(zeroed * zeroed, group_ids, num_groups)
        var = jnp.maximum(s2 / jnp.maximum(cnt, 1.0) - mean * mean, 0.0)
        if op == "stdvar":
            return jnp.where(cnt > 0, var, nan)
        return jnp.where(cnt > 0, jnp.sqrt(var), nan)
    if op == "min":
        m = jax.ops.segment_min(jnp.where(present, values, jnp.inf),
                                group_ids, num_groups)
        return jnp.where(cnt > 0, m, nan)
    if op == "max":
        m = jax.ops.segment_max(jnp.where(present, values, -jnp.inf),
                                group_ids, num_groups)
        return jnp.where(cnt > 0, m, nan)
    raise ValueError(f"unknown aggregation {op}")


@partial(jax.jit, static_argnames=("k", "num_groups", "bottom"))
def topk_mask(values, group_ids, num_groups: int, k: int, bottom: bool = False):
    """Boolean [P, K] mask selecting each group's top/bottom-k series per step.

    Counterpart of the reference's TopBottomK RowAggregator (priority queues);
    here a vmapped ``lax.top_k`` per group over the series axis.
    """
    dt = fdtype()
    v = values.astype(dt)
    sign = -1.0 if bottom else 1.0
    masked_all = jnp.where(jnp.isnan(v), -jnp.inf, sign * v)  # [P, K]

    def per_group(g):
        vg = jnp.where(group_ids[:, None] == g, masked_all, -jnp.inf)  # [P, K]
        kk = min(k, vg.shape[0])
        # select by INDEX, not threshold: Prometheus returns exactly k
        # series even on ties (tie-break arbitrary; here lowest index)
        vals, idx = jax.lax.top_k(vg.T, kk)  # [K, kk]
        finite = jnp.isfinite(vals)  # drop -inf fillers (NaN/out-of-group)
        onehot = jax.nn.one_hot(idx, vg.shape[0], dtype=bool)  # [K, kk, P]
        return jnp.any(onehot & finite[..., None], axis=1).T  # [P, K]

    sels = jax.vmap(per_group)(jnp.arange(num_groups))  # [G, P, K]
    return jnp.any(sels, axis=0)


@partial(jax.jit, static_argnames=("num_groups",))
def quantile_across(q, values, group_ids, num_groups: int):
    """phi-quantile across the series of each group, per step."""
    dt = fdtype()
    v = values.astype(dt)
    P = v.shape[0]

    def per_group(g):
        in_g = (group_ids == g)[:, None] & ~jnp.isnan(v)
        masked = jnp.where(in_g, v, jnp.inf)
        srt = jnp.sort(masked, axis=0)  # [P, K]
        n = jnp.sum(in_g, axis=0).astype(dt)  # [K]
        pos = q * jnp.maximum(n - 1.0, 0.0)
        i0 = jnp.floor(pos).astype(jnp.int32)
        frac = (pos - i0)[None, :]
        a = jnp.take_along_axis(srt, i0[None, :], axis=0)
        b = jnp.take_along_axis(srt, jnp.minimum(i0 + 1, P - 1)[None, :], axis=0)
        out = (a + (b - a) * frac)[0]
        return jnp.where(n > 0, out, jnp.nan)

    return jax.vmap(per_group)(jnp.arange(num_groups))  # [G, K]


@jax.jit
def histogram_quantile(q, bucket_rates, les):
    """Prometheus histogram_quantile over first-class histogram step values.

    bucket_rates: [..., B] cumulative-bucket values per step (e.g. the output
    of rate() applied per bucket); les: [B] upper bounds, last = +Inf.
    Linear interpolation within the located bucket, reference
    ``HistogramQuantileMapper.scala`` / promql ``bucketQuantile``.
    """
    dt = fdtype()
    h = bucket_rates.astype(dt)
    les = les.astype(dt)
    B = h.shape[-1]
    total = h[..., B - 1]
    rank = q * total
    # first bucket with cumulative count >= rank
    ge = h >= rank[..., None]
    idx = jnp.argmax(ge, axis=-1)
    cum_hi = jnp.take_along_axis(h, idx[..., None], -1)[..., 0]
    cum_lo = jnp.where(idx > 0,
                       jnp.take_along_axis(h, jnp.maximum(idx - 1, 0)[..., None],
                                           -1)[..., 0], 0.0)
    le_hi = les[idx]
    le_lo = jnp.where(idx > 0, les[jnp.maximum(idx - 1, 0)], 0.0)
    frac = (rank - cum_lo) / jnp.maximum(cum_hi - cum_lo, 1e-30)
    val = le_lo + (le_hi - le_lo) * frac
    # highest bucket: return le of the second-highest bound
    val = jnp.where(idx >= B - 1, les[jnp.maximum(B - 2, 0)], val)
    val = jnp.where(total > 0, val, jnp.nan)
    val = jnp.where(jnp.isnan(total), jnp.nan, val)
    return jnp.where((q < 0) | (q > 1),
                     jnp.where(q < 0, -jnp.inf, jnp.inf), val)


# ---------------------------------------------------------------------------
# chunk-sidecar log2 sketches (memory/chunk.py): mergeable fixed-width value
# histograms served for quantile_over_time under declared approximation
# (FILODB_SIDECAR_APPROX=1, engine/sidecar_lane.py)

def merge_sketches(sketches) -> np.ndarray:
    """Sum per-chunk sketches into one bucket-count vector (the mergeability
    property: counts add, no rank information is lost beyond bucket width)."""
    out = None
    for sk in sketches:
        if sk is None:
            continue
        s = np.asarray(sk, np.int64)
        out = s.copy() if out is None else out + s
    return out


def _sketch_bucket_value(b: int) -> float:
    """Representative value of sketch bucket ``b`` (geometric midpoint of the
    power-of-two span; bucket layout in memory/chunk.py::_sketch_values)."""
    if b == 32:
        return 0.0
    if b > 32:
        mag = b - 33  # clipped exponent-1+16 → span [2^(mag-16), 2^(mag-15))
        return float(2.0 ** (mag - 16) * 1.5)
    mag = 31 - b
    return float(-(2.0 ** (mag - 16) * 1.5))


def sketch_quantile(q: float, sketch: np.ndarray) -> float:
    """Quantile estimate from a merged sketch: walk cumulative bucket counts
    to the rank (nearest-rank, matching the kernels' lower-index convention
    within bucket resolution) and return the bucket's representative value.
    Error is bounded by the bucket width (a factor-of-two span)."""
    if q < 0:
        return -np.inf
    if q > 1:
        return np.inf
    counts = np.asarray(sketch, np.float64)
    total = counts.sum()
    if total <= 0:
        return np.nan
    rank = q * (total - 1)
    cum = np.cumsum(counts)
    b = int(np.searchsorted(cum, rank, side="right"))
    b = min(b, len(counts) - 1)
    return _sketch_bucket_value(b)
