"""Instant (elementwise) functions and scalar/vector binary operators.

Counterpart of reference ``rangefn/InstantFunction.scala:1-383`` (~30 functions,
``PlanEnums.InstantFunctionId``) and ``BinaryOperator`` evaluation inside
``ScalarOperationMapper``/``BinaryJoinExec``. Everything is elementwise on the
[P, K] step matrices, so these are plain jnp ops fused by XLA into the
surrounding kernels.
"""

from __future__ import annotations

import jax.numpy as jnp


def _days_in_month(y, m):
    # y, m float arrays; gregorian rules
    thirty_one = jnp.isin(m, jnp.array([1, 3, 5, 7, 8, 10, 12]))
    thirty = jnp.isin(m, jnp.array([4, 6, 9, 11]))
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    feb = jnp.where(leap, 29.0, 28.0)
    return jnp.where(thirty_one, 31.0, jnp.where(thirty, 30.0, feb))


def _civil_from_epoch_days(z):
    """Epoch days -> (year, month, day) via Howard Hinnant's algorithm,
    vectorized."""
    z = z + 719468
    era = jnp.floor_divide(z, 146097)
    doe = z - era * 146097
    yoe = jnp.floor_divide(doe - jnp.floor_divide(doe, 1460)
                           + jnp.floor_divide(doe, 36524)
                           - jnp.floor_divide(doe, 146096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def apply_instant_fn(fn: str, values, epoch_ts_s=None, params=()):
    """values: [P, K]; epoch_ts_s: [K] step times (seconds) for time fns."""
    v = values
    if fn == "abs":
        return jnp.abs(v)
    if fn == "ceil":
        return jnp.ceil(v)
    if fn == "floor":
        return jnp.floor(v)
    if fn == "exp":
        return jnp.exp(v)
    if fn == "ln":
        return jnp.log(v)
    if fn == "log2":
        return jnp.log2(v)
    if fn == "log10":
        return jnp.log10(v)
    if fn == "sqrt":
        return jnp.sqrt(v)
    if fn == "round":
        nearest = params[0] if params else 1.0
        return jnp.round(v / nearest) * nearest
    if fn == "clamp_min":
        return jnp.maximum(v, params[0])
    if fn == "clamp_max":
        return jnp.minimum(v, params[0])
    if fn == "clamp":
        return jnp.clip(v, params[0], params[1])
    if fn == "sgn":
        return jnp.sign(v)
    if fn in ("deg", "degrees"):
        return jnp.degrees(v)
    if fn in ("rad", "radians"):
        return jnp.radians(v)
    for trig in ("sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh",
                 "tanh", "asinh", "acosh", "atanh"):
        if fn == trig:
            return getattr(jnp, trig)(v)
    # time component functions operate on the sample timestamps (or the value
    # when applied to vector(time()) results)
    if fn in ("hour", "minute", "month", "year", "day_of_month", "day_of_week",
              "day_of_year", "days_in_month"):
        t = v  # per promql: argument is a vector of epoch seconds
        days = jnp.floor_divide(t, 86400.0)
        secs_of_day = t - days * 86400.0
        if fn == "hour":
            return jnp.floor_divide(secs_of_day, 3600.0)
        if fn == "minute":
            return jnp.floor_divide(secs_of_day % 3600.0, 60.0)
        if fn == "day_of_week":
            return (days + 4) % 7  # epoch day 0 = Thursday
        y, m, d = _civil_from_epoch_days(days.astype(jnp.int64)
                                         if days.dtype != jnp.int32
                                         else days.astype(jnp.int32))
        if fn == "year":
            return y.astype(v.dtype)
        if fn == "month":
            return m.astype(v.dtype)
        if fn == "day_of_month":
            return d.astype(v.dtype)
        if fn == "days_in_month":
            return _days_in_month(y, m).astype(v.dtype)
        if fn == "day_of_year":
            jan1 = _days_from_civil(y, 1, 1)
            return (days - jan1 + 1).astype(v.dtype)
    raise ValueError(f"unknown instant function {fn}")


def _days_from_civil(y, m, d):
    y = jnp.where(m <= 2, y - 1, y)
    era = jnp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = jnp.floor_divide(153 * mp + 2, 5) + d - 1
    doe = yoe * 365 + jnp.floor_divide(yoe, 4) - jnp.floor_divide(yoe, 100) + doy
    return era * 146097 + doe - 719468


_COMPARISONS = {"==": jnp.equal, "!=": jnp.not_equal, ">": jnp.greater,
                "<": jnp.less, ">=": jnp.greater_equal, "<=": jnp.less_equal}


def apply_binary_op(op: str, lhs, rhs, bool_mode: bool = False):
    """Arithmetic/comparison binary operator on aligned [..] arrays.

    Comparison without ``bool``: keep lhs value where true, NaN where false
    (vector filtering). With ``bool``: 1.0/0.0.
    """
    if op == "+":
        return lhs + rhs
    if op == "-":
        return lhs - rhs
    if op == "*":
        return lhs * rhs
    if op == "/":
        return lhs / rhs
    if op == "%":
        return jnp.fmod(lhs, rhs)
    if op == "^":
        return jnp.power(lhs, rhs)
    if op == "atan2":
        return jnp.arctan2(lhs, rhs)
    if op in _COMPARISONS:
        c = _COMPARISONS[op](lhs, rhs)
        both = ~jnp.isnan(lhs) & ~jnp.isnan(rhs)
        if bool_mode:
            return jnp.where(both, jnp.where(c, 1.0, 0.0), jnp.nan)
        return jnp.where(c & both, lhs, jnp.nan)
    raise ValueError(f"unknown binary operator {op}")
