"""Device-decoded series batches: compressed pages in, tensors never leave
the TPU.

No reference counterpart — this is the TPU-native replacement for the
reference's decode-at-read of NibblePack chunks from block memory
(``memory/src/main/scala/filodb.memory/format/vectors/``), per BASELINE.json's
north star ("ships off-heap BinaryVector chunk pages to a TPU sidecar...
decoded on device").

The host ships bit-packed device pages (``memory/device_pages.py``) instead
of decoded samples; decode (shifts/masks + slope reconstruction) runs
on-device and feeds the mask-aware kernels directly. This is the north-star
data path: PCIe/ICI carries compressed pages, HBM holds the decoded tensors
only transiently inside the fused program.

Layout: per series, chunks contribute whole 128-sample blocks; the last
block of each chunk is partially filled, so the assembled [P, NB*128] layout
has interior gaps — handled by ``range_eval_masked`` (gap positions carry
the previous real timestamp via an in-kernel running max, preserving
sortedness for the binary search).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from filodb_tpu.memory.device_pages import (
    BLOCK,
    WORDS_PER_BLOCK_MAX,
    DevicePage,
    encode_f32_page,
    encode_ts_page,
)

TS_GAP_MIN = -(2**31) + 2


def _pow2(n: int, floor: int = 1) -> int:
    v = floor
    while v < n:
        v *= 2
    return v


@dataclass
class DeviceSeriesBatch:
    """Masked batch whose ts/vals/valid live on device."""

    base_ts: int
    ts_dev: object       # int32 [P, S]
    vals_dev: object     # f32 [P, S] (or [P, S, B] for histograms)
    valid_dev: object    # bool [P, S]
    counts: np.ndarray   # int32 [P] total valid (host stats)
    part_ids: list[int]
    les: np.ndarray | None = None  # [B] bucket bounds (histogram batches)
    masked = True

    @property
    def is_histogram(self) -> bool:
        return self.les is not None

    @property
    def num_series(self) -> int:
        return len(self.part_ids)

    def device_arrays(self):
        return self.ts_dev, self.vals_dev, self.valid_dev


def chunk_device_pages(chunk, schema, value_col: int):
    """Device pages for (ts, value column) of a chunk, memoized on the chunk
    (encoded from decoded arrays on first use; ingest-time encoding attaches
    them up front via ``attach_pages``). Histogram columns yield
    ``("hist", les, ts_page, [per-bucket int pages])``."""
    from filodb_tpu.memory.codecs import HistogramColumn

    cache = chunk.__dict__.get("_dev_pages")
    if cache is None:
        object.__setattr__(chunk, "_dev_pages", {})
        cache = chunk.__dict__["_dev_pages"]
    pages = cache.get(value_col)
    if pages is None:
        ts = chunk.decode_column(0)
        vals = chunk.decode_column(value_col)
        if isinstance(vals, HistogramColumn):
            pages = cache[value_col] = _hist_pages(ts, vals.les, vals.rows)
        else:
            pages = cache[value_col] = (
                encode_ts_page(ts),
                encode_f32_page(np.asarray(vals, np.float64)))
    return pages


def _hist_pages(ts, les, rows):
    # cumulative bucket counts suit the sloped-line int page predictor
    bucket_pages = [encode_ts_page(rows[:, b].astype(np.int64))
                    for b in range(rows.shape[1])]
    return ("hist", np.asarray(les, np.float64), encode_ts_page(ts),
            bucket_pages)


def attach_pages(chunk, ts: np.ndarray, cols: dict[int, object]) -> None:
    """Ingest-time page encoding (no decode round trip). Values are float
    arrays, or ``(les, rows)`` tuples for histogram columns."""
    out = {}
    for col, v in cols.items():
        if isinstance(v, tuple):
            les, rows = v
            out[col] = _hist_pages(ts, les, np.asarray(rows, np.int64))
        else:
            out[col] = (encode_ts_page(ts), encode_f32_page(v))
    object.__setattr__(chunk, "_dev_pages", out)


@partial(jax.jit, static_argnames=())
def _assemble(rel_bases, ts_slopes, ts_widths, ts_words,
              v_firsts, v_shifts, v_widths, v_words, blk_counts,
              range_len):
    """[P, NB, ...] page arrays → masked (ts, vals, valid) [P, NB*BLOCK]."""
    from filodb_tpu.memory.device_pages import (
        _unpack_block_jax,
    )

    P, NB = rel_bases.shape

    def decode_series(rb, sl, tw, twd, vf, vs, vw, vwd, bc):
        def one_block(rb_b, sl_b, tw_b, twd_b, vf_b, vs_b, vw_b, vwd_b, bc_b):
            zz = _unpack_block_jax(twd_b, tw_b)
            resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
            lane = jnp.arange(BLOCK, dtype=jnp.int32)
            ts = rb_b + sl_b * lane + resid
            x = _unpack_block_jax(vwd_b, vw_b)
            xored = jnp.where(vs_b >= 32, jnp.uint32(0),
                              x << vs_b.astype(jnp.uint32))
            vals = lax.bitcast_convert_type(xored ^ vf_b, jnp.float32)
            valid = lane < bc_b
            ts = jnp.where(valid, ts, TS_GAP_MIN)
            return ts, vals, valid

        return jax.vmap(one_block)(rb, sl, tw, twd, vf, vs, vw, vwd, bc)

    ts_b, vals_b, valid_b = jax.vmap(decode_series)(
        rel_bases, ts_slopes, ts_widths, ts_words, v_firsts, v_shifts,
        v_widths, v_words, blk_counts)
    S = NB * BLOCK
    ts = ts_b.reshape(P, S)
    vals = vals_b.reshape(P, S)
    valid = valid_b.reshape(P, S)
    # gaps inherit the previous real timestamp (keeps ts sorted for the
    # window binary search); leading gaps stay at TS_GAP_MIN
    ts = lax.cummax(ts, axis=1)
    # restrict to the query range: [0, range_len] relative
    valid = valid & (ts >= 0) & (ts <= range_len)
    return ts, vals, valid


def _query_chunks(p, start, end, extra_chunks):
    """In-memory chunks + ODP-paged chunks, deduped and time-ordered."""
    chunks = p.chunks_in_range(start, end, include_buffer=False)
    extra = (extra_chunks or {}).get(p.part_id)
    if extra:
        have = {c.id for c in chunks}
        for c in extra:
            if c.id not in have and c.end_time >= start \
                    and c.start_time <= end:
                chunks.append(c)
        chunks.sort(key=lambda c: c.id)
    return chunks


def build_device_batch(partitions, start: int, end: int,
                       value_col: int | None = None,
                       extra_chunks: dict | None = None) -> DeviceSeriesBatch:
    """Assemble a device-decoded batch from partitions' chunk pages
    (including ODP-paged cold chunks)."""
    from filodb_tpu.core.schemas import ColumnType

    col0 = value_col if value_col is not None \
        else partitions[0].schema.data.value_column
    if partitions[0].schema.data.columns[col0].ctype == ColumnType.HISTOGRAM:
        return _build_hist_device_batch(partitions, start, end, col0,
                                        extra_chunks)
    per_series: list[list[tuple[DevicePage, DevicePage, int]]] = []
    for p in partitions:
        col = value_col if value_col is not None \
            else p.schema.data.value_column
        entries = []
        for c in _query_chunks(p, start, end, extra_chunks):
            tsp, vp = chunk_device_pages(c, p.schema, col)
            entries.append((tsp, vp, c.num_rows))
        b = p._buf
        if b.n:
            bts = b.ts[: b.n]
            if bts[-1] >= start and bts[0] <= end:
                tsp = encode_ts_page(bts)
                vp = encode_f32_page(np.asarray(b.cols[col - 1][: b.n],
                                                np.float64))
                entries.append((tsp, vp, int(b.n)))
        per_series.append(entries)

    packed, counts = pack_series_pages(per_series, start)
    ts_dev, vals_dev, valid_dev = _assemble(
        *(jnp.asarray(a) for a in packed),
        jnp.asarray(np.int32(end - start)))
    return DeviceSeriesBatch(start, ts_dev, vals_dev, valid_dev, counts,
                             [p.part_id for p in partitions])


def pack_series_pages(per_series, start: int):
    """Pack per-series (ts_page, val_page, nrows) entries into the dense
    [P, NB, ...] arrays ``_assemble`` decodes on device. Shapes bucket to
    powers of two so the jitted assemble/eval kernels reuse compilation
    across queries (mirrors engine/batch.py). Returns (packed_arrays,
    counts) with packed_arrays ordered as _assemble's parameters."""
    P = _pow2(len(per_series), 4)
    nb_per = [sum(t.num_blocks for t, _, _ in e) for e in per_series]
    NB = _pow2(max(max(nb_per, default=1), 1))
    rel_bases = np.zeros((P, NB), np.int32)
    ts_slopes = np.zeros((P, NB), np.int32)
    ts_widths = np.zeros((P, NB), np.int32)
    ts_words = np.zeros((P, NB, WORDS_PER_BLOCK_MAX), np.uint32)
    v_firsts = np.zeros((P, NB), np.uint32)
    v_shifts = np.zeros((P, NB), np.int32)
    v_widths = np.zeros((P, NB), np.int32)
    v_words = np.zeros((P, NB, WORDS_PER_BLOCK_MAX), np.uint32)
    blk_counts = np.zeros((P, NB), np.int32)
    counts = np.zeros(P, np.int32)
    for i, entries in enumerate(per_series):
        bi = 0
        for tsp, vp, nrows in entries:
            nb = tsp.num_blocks
            rel_bases[i, bi : bi + nb] = (tsp.bases - start).astype(np.int32)
            ts_slopes[i, bi : bi + nb] = tsp.slopes
            ts_widths[i, bi : bi + nb] = tsp.widths
            ts_words[i, bi : bi + nb] = tsp.words
            v_firsts[i, bi : bi + nb] = vp.bases
            v_shifts[i, bi : bi + nb] = vp.slopes
            v_widths[i, bi : bi + nb] = vp.widths
            v_words[i, bi : bi + nb] = vp.words
            full, rem = divmod(nrows, BLOCK)
            bc = [BLOCK] * full + ([rem] if rem else [])
            blk_counts[i, bi : bi + nb] = bc + [0] * (nb - len(bc))
            counts[i] += nrows
            bi += nb
    packed = (rel_bases, ts_slopes, ts_widths, ts_words, v_firsts, v_shifts,
              v_widths, v_words, blk_counts)
    return packed, counts


# ---------------------------------------------------------------------------
# histogram batches: per-bucket int pages → [P, S, B] on device

@jax.jit
def _assemble_hist(rel_bases, ts_slopes, ts_widths, ts_words,
                   b_bases, b_slopes, b_widths, b_words,
                   blk_counts, range_len):
    """ts page arrays [P, NB, ...] + bucket page arrays [P, NB, B, ...] →
    (ts [P, S], hist [P, S, B], valid [P, S])."""
    from filodb_tpu.memory.device_pages import _unpack_block_jax
    from filodb_tpu.query.engine.kernels import fdtype

    P, NB = rel_bases.shape
    B = b_bases.shape[2]
    dt = fdtype()

    def dec_int_block(base, slope, w, words):
        zz = _unpack_block_jax(words, w)
        resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
        lane = jnp.arange(BLOCK, dtype=jnp.int32)
        return base.astype(dt) + (slope * lane + resid).astype(dt)

    def per_series(rb, sl, tw, twd, bb, bs, bw, bwd, bc):
        def per_block(rb_b, sl_b, tw_b, twd_b, bb_b, bs_b, bw_b, bwd_b,
                      bc_b):
            lane = jnp.arange(BLOCK, dtype=jnp.int32)
            zz = _unpack_block_jax(twd_b, tw_b)
            resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
            ts = rb_b + sl_b * lane + resid
            valid = lane < bc_b
            ts = jnp.where(valid, ts, TS_GAP_MIN)
            # buckets: vmap the int decode over B
            rows = jax.vmap(dec_int_block)(bb_b, bs_b, bw_b, bwd_b)  # [B,128]
            return ts, rows.T, valid  # rows.T: [128, B]

        return jax.vmap(per_block)(rb, sl, tw, twd, bb, bs, bw, bwd, bc)

    ts_b, hist_b, valid_b = jax.vmap(per_series)(
        rel_bases, ts_slopes, ts_widths, ts_words, b_bases, b_slopes,
        b_widths, b_words, blk_counts)
    S = NB * BLOCK
    ts = lax.cummax(ts_b.reshape(P, S), axis=1)
    hist = hist_b.reshape(P, S, B)
    valid = valid_b.reshape(P, S)
    valid = valid & (ts >= 0) & (ts <= range_len)
    return ts, hist, valid


def _build_hist_device_batch(partitions, start: int, end: int,
                             col: int,
                             extra_chunks: dict | None = None
                             ) -> DeviceSeriesBatch:
    per_series = []
    les_out = None
    for p in partitions:
        entries = []
        for c in _query_chunks(p, start, end, extra_chunks):
            tag = chunk_device_pages(c, p.schema, col)
            _, les, tsp, bpages = tag
            if les_out is None or len(les) > len(les_out):
                les_out = les
            entries.append((tsp, bpages, c.num_rows))
        b = p._buf
        if b.n and b.cols[col - 1] is not None:
            bts = b.ts[: b.n]
            if bts[-1] >= start and bts[0] <= end:
                rows = b.cols[col - 1][: b.n]
                les = (p.bucket_les if p.bucket_les is not None
                       else np.zeros(rows.shape[1]))
                if les_out is None or len(les) > len(les_out):
                    les_out = np.asarray(les, np.float64)
                tsp = encode_ts_page(bts)
                bpages = [encode_ts_page(rows[:, j].astype(np.int64))
                          for j in range(rows.shape[1])]
                entries.append((tsp, bpages, int(b.n)))
        per_series.append(entries)

    P = _pow2(len(per_series), 4)
    B = len(les_out) if les_out is not None else 1
    nb_per = [sum(t.num_blocks for t, _, _ in e) for e in per_series]
    NB = _pow2(max(max(nb_per, default=1), 1))
    rel_bases = np.zeros((P, NB), np.int32)
    ts_slopes = np.zeros((P, NB), np.int32)
    ts_widths = np.zeros((P, NB), np.int32)
    ts_words = np.zeros((P, NB, WORDS_PER_BLOCK_MAX), np.uint32)
    b_bases = np.zeros((P, NB, B), np.int64)
    b_slopes = np.zeros((P, NB, B), np.int32)
    b_widths = np.zeros((P, NB, B), np.int32)
    b_words = np.zeros((P, NB, B, WORDS_PER_BLOCK_MAX), np.uint32)
    blk_counts = np.zeros((P, NB), np.int32)
    counts = np.zeros(P, np.int32)
    for i, entries in enumerate(per_series):
        bi = 0
        for tsp, bpages, nrows in entries:
            nb = tsp.num_blocks
            rel_bases[i, bi : bi + nb] = (tsp.bases - start).astype(np.int32)
            ts_slopes[i, bi : bi + nb] = tsp.slopes
            ts_widths[i, bi : bi + nb] = tsp.widths
            ts_words[i, bi : bi + nb] = tsp.words
            for j, bp in enumerate(bpages[:B]):
                b_bases[i, bi : bi + nb, j] = bp.bases
                b_slopes[i, bi : bi + nb, j] = bp.slopes
                b_widths[i, bi : bi + nb, j] = bp.widths
                b_words[i, bi : bi + nb, j] = bp.words
            full, rem = divmod(nrows, BLOCK)
            bc = [BLOCK] * full + ([rem] if rem else [])
            blk_counts[i, bi : bi + nb] = bc + [0] * (nb - len(bc))
            counts[i] += nrows
            bi += nb
    ts_dev, hist_dev, valid_dev = _assemble_hist(
        jnp.asarray(rel_bases), jnp.asarray(ts_slopes),
        jnp.asarray(ts_widths), jnp.asarray(ts_words),
        jnp.asarray(b_bases), jnp.asarray(b_slopes),
        jnp.asarray(b_widths), jnp.asarray(b_words),
        jnp.asarray(blk_counts), jnp.asarray(np.int32(end - start)))
    return DeviceSeriesBatch(start, ts_dev, hist_dev, valid_dev, counts,
                             [p.part_id for p in partitions],
                             les=les_out if les_out is not None
                             else np.array([np.inf]))
