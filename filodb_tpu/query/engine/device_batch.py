"""Device-decoded series batches: compressed pages in, tensors never leave
the TPU.

The host ships bit-packed device pages (``memory/device_pages.py``) instead
of decoded samples; decode (shifts/masks + slope reconstruction) runs
on-device and feeds the mask-aware kernels directly. This is the north-star
data path: PCIe/ICI carries compressed pages, HBM holds the decoded tensors
only transiently inside the fused program.

Layout: per series, chunks contribute whole 128-sample blocks; the last
block of each chunk is partially filled, so the assembled [P, NB*128] layout
has interior gaps — handled by ``range_eval_masked`` (gap positions carry
the previous real timestamp via an in-kernel running max, preserving
sortedness for the binary search).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from filodb_tpu.memory.device_pages import (
    BLOCK,
    WORDS_PER_BLOCK_MAX,
    DevicePage,
    encode_f32_page,
    encode_ts_page,
)

TS_GAP_MIN = -(2**31) + 2


@dataclass
class DeviceSeriesBatch:
    """Masked batch whose ts/vals/valid live on device."""

    base_ts: int
    ts_dev: object       # int32 [P, S]
    vals_dev: object     # f32 [P, S]
    valid_dev: object    # bool [P, S]
    counts: np.ndarray   # int32 [P] total valid (host stats)
    part_ids: list[int]
    les = None
    masked = True
    is_histogram = False

    @property
    def num_series(self) -> int:
        return len(self.part_ids)

    def device_arrays(self):
        return self.ts_dev, self.vals_dev, self.valid_dev


def chunk_device_pages(chunk, schema, value_col: int):
    """Device pages for (ts, value column) of a chunk, memoized on the chunk
    (encoded from decoded arrays on first use; ingest-time encoding attaches
    them up front via ``attach_pages``)."""
    cache = chunk.__dict__.get("_dev_pages")
    if cache is None:
        object.__setattr__(chunk, "_dev_pages", {})
        cache = chunk.__dict__["_dev_pages"]
    pages = cache.get(value_col)
    if pages is None:
        ts = chunk.decode_column(0)
        vals = np.asarray(chunk.decode_column(value_col), np.float64)
        pages = cache[value_col] = (encode_ts_page(ts),
                                    encode_f32_page(vals))
    return pages


def attach_pages(chunk, ts: np.ndarray, cols: dict[int, np.ndarray]) -> None:
    """Ingest-time page encoding (no decode round trip)."""
    object.__setattr__(chunk, "_dev_pages", {
        col: (encode_ts_page(ts), encode_f32_page(v))
        for col, v in cols.items()})


@partial(jax.jit, static_argnames=())
def _assemble(rel_bases, ts_slopes, ts_widths, ts_words,
              v_firsts, v_shifts, v_widths, v_words, blk_counts,
              range_len):
    """[P, NB, ...] page arrays → masked (ts, vals, valid) [P, NB*BLOCK]."""
    from filodb_tpu.memory.device_pages import (
        _unpack_block_jax,
    )

    P, NB = rel_bases.shape

    def decode_series(rb, sl, tw, twd, vf, vs, vw, vwd, bc):
        def one_block(rb_b, sl_b, tw_b, twd_b, vf_b, vs_b, vw_b, vwd_b, bc_b):
            zz = _unpack_block_jax(twd_b, tw_b)
            resid = (zz >> 1).astype(jnp.int32) ^ -(zz & 1).astype(jnp.int32)
            lane = jnp.arange(BLOCK, dtype=jnp.int32)
            ts = rb_b + sl_b * lane + resid
            x = _unpack_block_jax(vwd_b, vw_b)
            xored = jnp.where(vs_b >= 32, jnp.uint32(0),
                              x << vs_b.astype(jnp.uint32))
            vals = lax.bitcast_convert_type(xored ^ vf_b, jnp.float32)
            valid = lane < bc_b
            ts = jnp.where(valid, ts, TS_GAP_MIN)
            return ts, vals, valid

        return jax.vmap(one_block)(rb, sl, tw, twd, vf, vs, vw, vwd, bc)

    ts_b, vals_b, valid_b = jax.vmap(decode_series)(
        rel_bases, ts_slopes, ts_widths, ts_words, v_firsts, v_shifts,
        v_widths, v_words, blk_counts)
    S = NB * BLOCK
    ts = ts_b.reshape(P, S)
    vals = vals_b.reshape(P, S)
    valid = valid_b.reshape(P, S)
    # gaps inherit the previous real timestamp (keeps ts sorted for the
    # window binary search); leading gaps stay at TS_GAP_MIN
    ts = lax.cummax(ts, axis=1)
    # restrict to the query range: [0, range_len] relative
    valid = valid & (ts >= 0) & (ts <= range_len)
    return ts, vals, valid


def build_device_batch(partitions, start: int, end: int,
                       value_col: int | None = None) -> DeviceSeriesBatch:
    """Assemble a device-decoded batch from partitions' chunk pages."""
    per_series: list[list[tuple[DevicePage, DevicePage, int]]] = []
    for p in partitions:
        col = value_col if value_col is not None \
            else p.schema.data.value_column
        entries = []
        for c in p.chunks_in_range(start, end, include_buffer=False):
            tsp, vp = chunk_device_pages(c, p.schema, col)
            entries.append((tsp, vp, c.num_rows))
        b = p._buf
        if b.n:
            bts = b.ts[: b.n]
            if bts[-1] >= start and bts[0] <= end:
                tsp = encode_ts_page(bts)
                vp = encode_f32_page(np.asarray(b.cols[col - 1][: b.n],
                                                np.float64))
                entries.append((tsp, vp, int(b.n)))
        per_series.append(entries)

    P = len(per_series)
    nb_per = [sum(t.num_blocks for t, _, _ in e) for e in per_series]
    NB = max(max(nb_per, default=1), 1)
    rel_bases = np.zeros((P, NB), np.int32)
    ts_slopes = np.zeros((P, NB), np.int32)
    ts_widths = np.zeros((P, NB), np.int32)
    ts_words = np.zeros((P, NB, WORDS_PER_BLOCK_MAX), np.uint32)
    v_firsts = np.zeros((P, NB), np.uint32)
    v_shifts = np.zeros((P, NB), np.int32)
    v_widths = np.zeros((P, NB), np.int32)
    v_words = np.zeros((P, NB, WORDS_PER_BLOCK_MAX), np.uint32)
    blk_counts = np.zeros((P, NB), np.int32)
    counts = np.zeros(P, np.int32)
    for i, entries in enumerate(per_series):
        bi = 0
        for tsp, vp, nrows in entries:
            nb = tsp.num_blocks
            rel_bases[i, bi : bi + nb] = (tsp.bases - start).astype(np.int32)
            ts_slopes[i, bi : bi + nb] = tsp.slopes
            ts_widths[i, bi : bi + nb] = tsp.widths
            ts_words[i, bi : bi + nb] = tsp.words
            v_firsts[i, bi : bi + nb] = vp.bases
            v_shifts[i, bi : bi + nb] = vp.slopes
            v_widths[i, bi : bi + nb] = vp.widths
            v_words[i, bi : bi + nb] = vp.words
            full, rem = divmod(nrows, BLOCK)
            bc = [BLOCK] * full + ([rem] if rem else [])
            blk_counts[i, bi : bi + nb] = bc + [0] * (nb - len(bc))
            counts[i] += nrows
            bi += nb
    ts_dev, vals_dev, valid_dev = _assemble(
        jnp.asarray(rel_bases), jnp.asarray(ts_slopes),
        jnp.asarray(ts_widths), jnp.asarray(ts_words),
        jnp.asarray(v_firsts), jnp.asarray(v_shifts),
        jnp.asarray(v_widths), jnp.asarray(v_words),
        jnp.asarray(blk_counts), jnp.asarray(np.int32(end - start)))
    return DeviceSeriesBatch(start, ts_dev, vals_dev, valid_dev, counts,
                             [p.part_id for p in partitions])
