"""Pyramid-served cold-tier evaluation: O(log) range folds over stored
aggregate levels, zero chunk-payload paging for covered interiors.

The sidecar lane (``sidecar_lane.py``) folds WARM partitions from
chunk-level summaries.  This module is its cold-tier twin: for
:class:`~filodb_tpu.query.federation.ColdPartition` leaves backed by an
object store that publishes pyramid objects (``core/store/pyramid.py``),
each partition's history becomes an ordered list of summary NODES

    bucket node    one row covering a whole compacted bucket
    segment node   one row per segment, children = per-chunk rows
    chunk node     one row from a segment pyramid entry (no payload)
    decode node    payload fallback: the chunk is demand-paged and its
                   summary (re)computed — the read-race / legacy path

and every window folds top-down: ``_interior_fold`` over the node rows
covers the window interior from whichever level spans it, while the (at
most two) boundary nodes DESCEND one level — bucket → segments → chunks
→ a single demand-paged edge decode.  A year-long ``query_range`` thus
folds O(log) stored aggregates and downloads zero chunk payload bytes
when the grid aligns with chunk seams (asserted against
``filodb_objectstore_payload_bytes_down_total``).

Exact/bypass algebra is inherited unchanged: anything inexact — missing
pyramid (mid-backfill race), partial summary coverage, out-of-order
spans — demotes ONE level, bottoming out at the decode lane via
``_Bypass``; results are bitwise identical between mode ``1`` (stored
rows) and mode ``decode`` (every row recomputed from decoded payloads,
same tree shape) because both run the same strict-left-fold merge
(``pyramid.merge_rows_seq``) over cid-sorted chunk rows.

``quantile_over_time`` is served from segment/bucket log2 sketches under
``FILODB_SIDECAR_APPROX=1`` only (declared approximation).
"""

from __future__ import annotations

import time

import numpy as np

from filodb_tpu.core.schemas import ColumnType
from filodb_tpu.core.store import pyramid as pyr
from filodb_tpu.core.store.localstore import _pk_blob
from filodb_tpu.memory.chunk import (
    S_COUNT,
    S_FIRST_TS,
    S_LAST_TS,
    STATS_WIDTH,
    ensure_summary,
    summarize_values,
)
from filodb_tpu.query.engine import sidecar_lane as sl
from filodb_tpu.utils.tracing import span

_SCALAR_CTYPES = (ColumnType.DOUBLE, ColumnType.LONG, ColumnType.INT)


class _Node:
    """One summary node in a partition's cold-history tree."""

    __slots__ = ("level", "row", "start", "end", "children", "ref",
                 "chunk", "sketch", "n_chunks", "seq")

    def __init__(self, level, row, children=None, ref=None, chunk=None,
                 sketch=None, n_chunks=1):
        self.level = level          # bucket | segment | chunk | decode
        self.row = row              # [STATS_WIDTH] float64, count > 0
        self.start = int(row[S_FIRST_TS])
        self.end = int(row[S_LAST_TS])
        self.children = children    # next level down (None for leaves)
        self.ref = ref              # _ChunkRef for leaf payload paging
        self.chunk = chunk          # already-paged Chunk, if any
        self.sketch = sketch        # int64 log2 histogram or None
        self.n_chunks = n_chunks    # chunk-equivalents this node covers
        self.seq = None             # owning segment seq (segment nodes)


def _zero_rows(W: int) -> np.ndarray:
    out = np.zeros((W, STATS_WIDTH), np.float64)
    out[:, sl.S_MIN:sl.S_LAST_VAL + 1] = np.nan
    return out


class _NodeBundle:
    """Duck-typed ``_ChunkBundle`` surface for ``_interior_fold``."""

    __slots__ = ("starts", "ends", "stats")

    def __init__(self, nodes):
        self.starts = np.array([n.start for n in nodes], np.int64)
        self.ends = np.array([n.end for n in nodes], np.int64)
        self.stats = np.vstack([n.row for n in nodes])


# ---------------------------------------------------------------------------
# payload paging (the ONLY place this lane downloads chunk bytes)

def _page_chunk(shard, p, ref, acc):
    """Demand-page exactly one chunk by its ref (non-overlapping raw
    spans make a point lookup at start_time unambiguous)."""
    for lo, hi in ((ref.start_time, ref.start_time),
                   (ref.start_time, ref.end_time)):
        for ch in shard.odp_cache.get_or_load(shard, p, lo, hi):
            if ch.id == ref.chunk_id:
                acc.setdefault("_decoded_ids", set()).add(ref.chunk_id)
                return ch
    raise sl._Bypass


def _node_chunk(n: _Node, shard, p, acc):
    if n.chunk is None:
        n.chunk = _page_chunk(shard, p, n.ref, acc)
    return n.chunk


def _chunk_row(ch, col: int, decode_mode: bool):
    """(stats row, uint16 sketch) of one paged chunk — stored summary in
    mode 1, recomputed from the decoded vectors in decode mode."""
    if decode_mode:
        cs = summarize_values(np.asarray(ch.decode_column(0), np.int64),
                              np.asarray(ch.decode_column(col), np.float64))
        return cs.stats, cs.sketch
    summary = ensure_summary(ch)
    cs = summary[col] if summary is not None and col < len(summary) else None
    if cs is None:
        raise sl._Bypass
    return cs.stats, cs.sketch


# ---------------------------------------------------------------------------
# node-tree construction

def _decode_nodes(refs, col, shard, p, decode_mode, acc) -> list[_Node]:
    """Payload-fallback leaves: page each chunk and summarize it."""
    out = []
    for ref in refs:
        ch = _page_chunk(shard, p, ref, acc)
        row, sk = _chunk_row(ch, col, decode_mode)
        if row[S_COUNT] > 0:
            out.append(_Node("decode", row, ref=ref, chunk=ch,
                             sketch=None if sk is None
                             else sk.astype(np.int64)))
    return out


def _entry_chunk_nodes(entry, idxs, rr, col, shard, p, decode_mode,
                       acc) -> list[_Node]:
    """Chunk-level nodes straight from a segment pyramid entry's rows —
    zero payload bytes in mode 1; decode mode recomputes each row."""
    out = []
    for i, ref in zip(idxs, rr):
        if decode_mode:
            ch = _page_chunk(shard, p, ref, acc)
            row, _sk = _chunk_row(ch, col, True)
        else:
            ch = None
            row = entry["rows"][i]
        if row[S_COUNT] > 0:
            out.append(_Node("chunk", row, ref=ref, chunk=ch))
    return out


def _seg_node(entry, rr, col, shard, p, decode_mode, acc) -> list[_Node]:
    """One segment node whose children are the entry's chunk rows.  In
    decode mode both levels are recomputed through the same
    ``merge_rows_seq`` fold the writer ran — bitwise parity."""
    children = _entry_chunk_nodes(entry, range(len(rr)), rr, col, shard,
                                  p, decode_mode, acc)
    if decode_mode:
        row = pyr.merge_rows_seq([c.row for c in children])
    else:
        row = entry["row"]
    if row is None or row[S_COUNT] <= 0:
        return []
    sk = entry.get("sketch")
    return [_Node("segment", row, children=children, sketch=sk,
                  n_chunks=len(children))]


def _run_nodes(blob, col, seq, rr, single_run, cache, seg_set, shard, p,
               decode_mode, acc) -> list[_Node]:
    """Nodes for one cid-contiguous run of refs in segment ``seq``,
    demoting level by level when the pyramid can't cover the run."""
    if seq in seg_set:
        sp = cache.segment(seq)
        if sp is not None:
            entry = sp["entries"].get((blob, col))
            if entry is not None:
                ecids = entry["cids"]
                rcids = np.array([r.chunk_id for r in rr], np.int64)
                if single_run and len(ecids) == len(rcids) \
                        and np.array_equal(ecids, rcids):
                    return _seg_node(entry, rr, col, shard, p,
                                     decode_mode, acc)
                # interleaved/partial run: the merged segment row is
                # unusable but the per-chunk rows still are
                idx = {int(c): i for i, c in enumerate(ecids)}
                out = []
                for ref in rr:
                    i = idx.get(ref.chunk_id)
                    if i is None:
                        out.extend(_decode_nodes([ref], col, shard, p,
                                                 decode_mode, acc))
                    else:
                        out.extend(_entry_chunk_nodes(
                            entry, [i], [ref], col, shard, p,
                            decode_mode, acc))
                return out
    pyr.PYR_FALLBACK.inc()
    return _decode_nodes(rr, col, shard, p, decode_mode, acc)


def _wrap_bucket(nodes, blob, col, bucket_info, cache,
                 decode_mode) -> list[_Node]:
    """Collapse the contiguous segment-node run covered by the bucket
    pyramid into one bucket node (children = those segment nodes)."""
    bp = cache.bucket(int(bucket_info["bucket"]), int(bucket_info["seq"]))
    if bp is None:
        return nodes
    entry = bp["entries"].get((blob, col))
    if entry is None:
        return nodes
    covers = list(bp["covers"])
    # the covered segment nodes must be contiguous and complete
    run: list[int] = []
    for i, n in enumerate(nodes):
        if n.level == "segment" and n.seq in covers:
            run.append(i)
    if not run or run != list(range(run[0], run[-1] + 1)):
        return nodes
    segs = [nodes[i] for i in run]
    if sorted(s.seq for s in segs) != sorted(covers):
        return nodes
    child_cids = np.concatenate(
        [[c.ref.chunk_id for c in s.children] for s in segs]) \
        if segs else np.zeros(0, np.int64)
    if len(child_cids) != len(entry["cids"]) \
            or not np.array_equal(np.sort(np.asarray(child_cids, np.int64)),
                                  np.sort(entry["cids"])):
        return nodes
    if decode_mode:
        row = pyr.merge_rows_seq([s.row for s in segs])
    else:
        row = entry["row"]
    if row is None or row[S_COUNT] <= 0:
        return nodes
    bnode = _Node("bucket", row, children=segs, sketch=entry.get("sketch"),
                  n_chunks=sum(s.n_chunks for s in segs))
    return nodes[:run[0]] + [bnode] + nodes[run[-1] + 1:]


def _partition_nodes(p, col, shard, decode_mode, acc) -> list[_Node]:
    cache = shard.pyramids
    blob = _pk_blob(p.part_key)
    refs, seg_set, bucket_info = cache.refs(p.part_key)
    if not refs:
        return []
    runs: list[tuple[int, list]] = []
    for r in refs:
        if runs and runs[-1][0] == r.seq:
            runs[-1][1].append(r)
        else:
            runs.append((r.seq, [r]))
    run_count: dict[int, int] = {}
    for seq, _ in runs:
        run_count[seq] = run_count.get(seq, 0) + 1
    nodes: list[_Node] = []
    for seq, rr in runs:
        new = _run_nodes(blob, col, seq, rr, run_count[seq] == 1, cache,
                         seg_set, shard, p, decode_mode, acc)
        for n in new:
            if n.level == "segment":
                n.seq = seq
        nodes.extend(new)
    if bucket_info is not None:
        nodes = _wrap_bucket(nodes, blob, col, bucket_info, cache,
                             decode_mode)
    # exactness precondition, same as _part_bundle: valid-sample spans
    # strictly ordered and non-overlapping across the node list
    if len(nodes) > 1:
        starts = np.array([n.start for n in nodes], np.int64)
        ends = np.array([n.end for n in nodes], np.int64)
        if np.any(np.diff(starts) <= 0) or np.any(starts[1:] <= ends[:-1]):
            pyr.PYR_FALLBACK.inc()
            return _fallback_nodes(p, col, shard, refs, decode_mode, acc)
    return nodes


def _fallback_nodes(p, col, shard, refs, decode_mode, acc) -> list[_Node]:
    """Whole-partition payload fallback (disordered pyramid spans): every
    chunk becomes a decode node; a second disorder here bypasses."""
    nodes = _decode_nodes(refs, col, shard, p, decode_mode, acc)
    if len(nodes) > 1:
        starts = np.array([n.start for n in nodes], np.int64)
        ends = np.array([n.end for n in nodes], np.int64)
        if np.any(np.diff(starts) <= 0) or np.any(starts[1:] <= ends[:-1]):
            raise sl._Bypass
    return nodes


# ---------------------------------------------------------------------------
# top-down window fold

def _edge_node_stats(nodes, col, edge_idx, t0s, t1s, shard, p,
                     decode_mode, acc) -> np.ndarray:
    W = len(edge_idx)
    out = _zero_rows(W)
    for c in np.unique(edge_idx[edge_idx >= 0]):
        k = np.flatnonzero(edge_idx == c)
        n = nodes[c]
        if n.children is not None:
            # descend one level: the seam windows recurse into the
            # node's children, bottoming out at single edge decodes
            out[k] = _fold_nodes(n.children, col, t0s[k], t1s[k], shard,
                                 p, decode_mode, acc)
        else:
            ch = _node_chunk(n, shard, p, acc)
            fa = sl._chunk_fa(ch, col)
            out[k] = sl._fold_windows(fa, t0s[k], t1s[k])
    return out


def _fold_nodes(nodes, col, t0s, t1s, shard, p, decode_mode,
                acc) -> np.ndarray:
    """Merged stats rows [W, 12] for windows (t0, t1] over a node list —
    the node-level analog of ``eval_partition_windows`` minus the write
    buffer (cold history has none)."""
    W = len(t0s)
    if not nodes:
        return _zero_rows(W)
    bundle = _NodeBundle(nodes)
    interior, i0, i1 = sl._interior_fold(bundle, t0s, t1s)
    # per-level accounting for interior-covered nodes (union over
    # windows via a diff array — windows overlap heavily on dense grids)
    diff = np.zeros(len(nodes) + 1, np.int64)
    np.add.at(diff, i0, 1)
    np.add.at(diff, i1, -1)
    for idx in np.flatnonzero(np.cumsum(diff[:-1]) > 0):
        n = nodes[idx]
        if n.level != "decode":
            acc["nodes_" + n.level] = acc.get("nodes_" + n.level, 0) + 1
            acc["sidecar_chunks"] = acc.get("sidecar_chunks", 0) \
                + n.n_chunks
    o0 = np.searchsorted(bundle.ends, t0s, side="right")
    left = np.where(o0 < i0, o0, -1)
    re_idx = np.searchsorted(bundle.starts, t1s, side="right") - 1
    N = len(nodes)
    right = np.where((re_idx >= i1) & (re_idx >= 0) & (re_idx < N)
                     & (re_idx != left), re_idx, -1)
    lstats = _edge_node_stats(nodes, col, left, t0s, t1s, shard, p,
                              decode_mode, acc)
    rstats = _edge_node_stats(nodes, col, right, t0s, t1s, shard, p,
                              decode_mode, acc)
    return sl._merge_vec(sl._merge_vec(lstats, interior), rstats)


# ---------------------------------------------------------------------------
# approximate quantile over node sketches

def _leaf_nodes(n: _Node):
    if n.children is None:
        yield n
    else:
        for c in n.children:
            yield from _leaf_nodes(c)


def _node_sketch(n: _Node, col, shard, p, acc) -> np.ndarray:
    """int64 log2 sketch of ALL the node's samples, paging the payload
    only for chunk-level nodes that carry none."""
    if n.sketch is not None:
        return n.sketch
    ch = _node_chunk(n, shard, p, acc)
    _row, sk = _chunk_row(ch, col, False)
    if sk is None:
        raise sl._Bypass
    n.sketch = sk.astype(np.int64)
    return n.sketch


def _eval_cold_quantile(sparts, col, q, t0s, t1s, shard, decode_mode,
                        acc, ctx=None) -> np.ndarray:
    from filodb_tpu.memory.chunk import SKETCH_BUCKETS, _sketch_values
    from filodb_tpu.query.engine.aggregations import sketch_quantile
    P, W = len(sparts), len(t0s)
    gate = sl._sealed_gate()
    static_serve = not (gate > 0 and P * W > gate)
    serve = static_serve
    if ctx is not None:
        # learned pyramid-vs-decode for the cold sketch-merge path; the
        # amortization gate stays the static arm, <=0 the serve override
        from filodb_tpu.query import cost_model as cm
        model = cm.model_for(ctx.dataset)
        d = model.decide(
            "pyramid",
            f"quantile:pw{cm.bucket(P * W)}",
            ("pyramid", "decode"),
            static_arm="pyramid" if static_serve else "decode",
            override="pyramid" if gate <= 0 else None,
        )
        model.defer(ctx, d)
        serve = d.arm == "pyramid"
    if not serve:
        raise sl._Bypass
    out = np.full((P, W), np.nan)
    samples = 0
    for i, p in enumerate(sparts):
        nodes = _partition_nodes(p, col, shard, decode_mode, acc)
        if not nodes:
            continue
        bundle = _NodeBundle(nodes)
        _interior, i0, i1 = sl._interior_fold(bundle, t0s, t1s)
        for k in range(W):
            sk = np.zeros(SKETCH_BUCKETS, np.int64)
            total = 0
            for c in range(i0[k], i1[k]):
                sk += _node_sketch(nodes[c], col, shard, p, acc)
                total += int(nodes[c].row[S_COUNT])
            for c in list(range(min(i0[k], len(nodes)))) \
                    + list(range(i1[k], len(nodes))):
                n = nodes[c]
                if n.end > t0s[k] and n.start <= t1s[k]:
                    for leaf in _leaf_nodes(n):
                        if leaf.end <= t0s[k] or leaf.start > t1s[k]:
                            continue
                        ch = _node_chunk(leaf, shard, p, acc)
                        fa = sl._chunk_fa(ch, col)
                        m = (fa.tv > t0s[k]) & (fa.tv <= t1s[k])
                        sk += _sketch_values(fa.vv[m]).astype(np.int64)
                        total += int(m.sum())
            if total:
                out[i, k] = sketch_quantile(q, sk)
            samples += total
    acc["samples"] = acc.get("samples", 0.0) + float(samples)
    return out


# ---------------------------------------------------------------------------
# entry point (called from sidecar_lane._execute for cold leaves)

def execute_cold(plan, ctx, psm, fn, parts, shard, decode_mode: bool,
                 approx: bool):
    """Pyramid-served evaluation of one cold-tier leaf.  Raises
    ``_Bypass`` (caught by ``try_execute``) when the backend publishes
    no pyramids or the parts aren't cold-tier partitions."""
    from filodb_tpu.core.store.objectstore import PAYLOAD_BYTES_DOWN
    from filodb_tpu.query.exec.transformers import steps_array
    from filodb_tpu.query.federation import ColdPartition
    from filodb_tpu.query.model import StepMatrix

    if getattr(shard, "pyramids", None) is None:
        raise sl._Bypass
    for p in parts:
        if not isinstance(p, ColdPartition):
            raise sl._Bypass
    # pyramid-vs-decode as a learned decision: composing stored roll-ups
    # is the static arm (it pages zero payload), but once settled wall
    # times show payload decode is cheaper for this partition-count class
    # (e.g. tiny scans on a warm ODP cache) the model may route around
    # the pyramid compose entirely
    from filodb_tpu.query import cost_model as cm
    _model = cm.model_for(ctx.dataset)
    _d = _model.decide("pyramid", f"cold:parts{cm.bucket(len(parts))}",
                       ("pyramid", "decode"), static_arm="pyramid")
    _model.defer(ctx, _d)
    if _d.arm == "decode":
        raise sl._Bypass
    steps = steps_array(psm.start, psm.step, psm.end)
    eval_steps = (steps - psm.offset).astype(np.int64)
    window = int(psm.window if psm.function else 300_000)
    t1s = np.minimum(eval_steps, int(plan.chunk_end))
    t0s = np.maximum(eval_steps - window, int(plan.chunk_start) - 1)
    by_schema: dict[str, list] = {}
    for p in parts:
        by_schema.setdefault(p.schema.name, []).append(p)
    mats = []
    acc: dict = {}
    pyr_b0 = pyr.PYR_BYTES_DOWN.value
    pay_b0 = PAYLOAD_BYTES_DOWN.value
    hits0, miss0 = shard.pyramids.hits, shard.pyramids.misses
    t_fold = time.perf_counter()
    for schema_name, sparts in by_schema.items():
        schema = sparts[0].schema
        col = plan._value_col_index(schema)
        if schema.data.columns[col].ctype not in _SCALAR_CTYPES:
            raise sl._Bypass
        counter = schema.data.columns[col].is_counter
        with span("decode", schema=schema_name, partitions=len(sparts),
                  pyramid=True):
            if fn == "quantile_over_time":
                out = _eval_cold_quantile(sparts, col,
                                          float(psm.params[0]), t0s, t1s,
                                          shard, decode_mode, acc, ctx)
            else:
                st = np.zeros((len(sparts), len(t0s), STATS_WIDTH),
                              np.float64)
                for i, p in enumerate(sparts):
                    nodes = _partition_nodes(p, col, shard, decode_mode,
                                             acc)
                    st[i] = _fold_nodes(nodes, col, t0s, t1s, shard, p,
                                        decode_mode, acc)
                acc["samples"] = acc.get("samples", 0.0) \
                    + float(st[:, :, S_COUNT].sum())
                out = sl.formula(fn, st, eval_steps.astype(np.float64),
                                 window, counter)
        keys = [p.part_key.range_vector_key for p in sparts]
        mats.append(StepMatrix(psm._out_keys(keys), out, steps))
    data = StepMatrix.concat(mats) if len(mats) > 1 else mats[0]
    decoded = len(acc.get("_decoded_ids", ()))
    nb = acc.get("nodes_bucket", 0)
    ns = acc.get("nodes_segment", 0)
    nc = acc.get("nodes_chunk", 0)
    ctx.stats.series_scanned += len(parts)
    ctx.stats.samples_scanned += int(acc.get("samples", 0.0))
    ctx.stats.sidecar_chunks += acc.get("sidecar_chunks", 0)
    ctx.stats.chunks_touched += decoded + acc.get("sidecar_chunks", 0)
    ctx.stats.decode_s += time.perf_counter() - t_fold
    # the pyramid summary cache is this lane's read cache — its hit/miss
    # ratio lands in the same counters the leaf batch cache feeds
    ctx.stats.cache_hits += shard.pyramids.hits - hits0
    ctx.stats.cache_misses += shard.pyramids.misses - miss0
    # flat numeric attribution (merge_counts folds dicts key-wise)
    pyr_bytes = max(0, pyr.PYR_BYTES_DOWN.value - pyr_b0)
    pay_bytes = max(0, PAYLOAD_BYTES_DOWN.value - pay_b0)
    for key, v in (("bucketNodes", nb), ("segmentNodes", ns),
                   ("chunkNodes", nc), ("decodeNodes", decoded),
                   ("pyramidBytes", pyr_bytes),
                   ("payloadBytes", pay_bytes)):
        ctx.stats.pyramid[key] = ctx.stats.pyramid.get(key, 0) + v
    pyr.PYR_NODES_BUCKET.inc(nb)
    pyr.PYR_NODES_SEGMENT.inc(ns)
    pyr.PYR_NODES_CHUNK.inc(nc)
    pyr.PYR_NODES_DECODE.inc(decoded)
    pyr.PYR_SERVED.inc()
    sl.SIDECAR_SERVED.inc()
    return data
