"""Jitted range-function kernels.

Counterpart of the reference's range-function library
(``query/src/main/scala/filodb/query/exec/rangefn/RangeFunction.scala:1-568``,
``AggrOverTimeFunctions.scala:1-970``, ``RateFunctions.scala:1-303``) — but
formulated as dense batched tensor programs instead of per-sample iterators:

- window boundaries: vectorized binary search over padded ts arrays
- windowed sums/averages/stddev/changes/resets: exclusive prefix sums, O(1)
  per step
- min/max over time: sparse-table range-min/max query, O(1) per step
- rate/increase/delta: first/last gathers + a prefix sum of counter-reset
  corrections, with Prometheus extrapolation semantics (reference
  ``RateFunctions.scala`` mirrors promql ``extrapolatedRate``)
- quantile_over_time / holt_winters: masked per-window evaluation, blocked
  over output steps to bound memory

All kernels take ``ts`` as int32 millis relative to the batch base (padding
= INT32_MAX) and are shape-polymorphic only through jit's compile cache —
batch builders bucket shapes to powers of two to keep cache hits high.

Output convention: [P, K] float matrix; NaN = "no result at this step" (maps
to a gap in the Prom JSON output).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# ---------------------------------------------------------------------------
# helpers

def fdtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _valid_mask(ts, counts):
    S = ts.shape[1]
    return jnp.arange(S)[None, :] < counts[:, None]


def _eprefix(x):
    """Exclusive prefix sum along the last axis: [..., S] -> [..., S+1]."""
    return jnp.concatenate(
        [jnp.zeros(x.shape[:-1] + (1,), x.dtype), jnp.cumsum(x, -1)], -1)


def window_bounds(ts, steps, window):
    """[lo, hi) sample index bounds of window (t-w, t] per series per step.

    ts: int32 [P, S] sorted, padded with INT32_MAX; steps: int32 [K];
    window: int32 scalar. Returns lo, hi int32 [P, K].
    """
    def one(tsp):
        hi = jnp.searchsorted(tsp, steps, side="right")
        lo = jnp.searchsorted(tsp, steps - window, side="right")
        return lo, hi

    lo, hi = jax.vmap(one)(ts)
    return lo.astype(jnp.int32), hi.astype(jnp.int32)


def _gather(x, idx):
    """x [P, S(+1)], idx [P, K] -> [P, K]."""
    return jnp.take_along_axis(x, idx, axis=1)


def _prev_valid_value(v, valid, pv):
    """(prev_valid_value, prev_exists) per position — comparisons against the
    previous VALID sample, skipping interior gaps."""
    pv_prev = jnp.concatenate(
        [jnp.full_like(pv[:, :1], -1), pv[:, :-1]], axis=1)
    prev_val = jnp.take_along_axis(v, jnp.maximum(pv_prev, 0), axis=1)
    return prev_val, pv_prev >= 0


def _counter_corrected(v, valid, pv=None):
    """Values plus cumulative reset correction (Prometheus counter semantics:
    on a drop, the previous value is added to all subsequent samples).
    Comparisons skip gap positions via the prev-valid index map."""
    if pv is None:
        S = v.shape[1]
        sidx = jnp.arange(S, dtype=jnp.int32)[None, :]
        pv = lax.cummax(jnp.where(valid, sidx, -1), axis=1)
    prev, prev_ok = _prev_valid_value(v, valid, pv)
    dropped = (v < prev) & valid & prev_ok
    correction = jnp.cumsum(jnp.where(dropped, prev, 0.0), axis=1)
    return v + correction


# ---------------------------------------------------------------------------
# sparse table (range min/max query)

def _build_sparse(v, op, identity, levels):
    P, S = v.shape
    tabs = [v]
    cur = v
    for j in range(1, levels):
        half = 1 << (j - 1)
        shifted = jnp.concatenate(
            [cur[:, half:], jnp.full((P, half), identity, v.dtype)], axis=1)
        cur = op(cur, shifted)
        tabs.append(cur)
    return jnp.stack(tabs)  # [L, P, S]


def _rmq(table, lo, hi, op, identity):
    """Range query over [lo, hi) using the sparse table. lo/hi [P, K]."""
    P = table.shape[1]
    w = hi - lo
    j = jnp.maximum(31 - lax.clz(jnp.maximum(w, 1)), 0)
    pw = jnp.left_shift(1, j)
    p_idx = jnp.arange(P)[:, None]
    a = table[j, p_idx, jnp.minimum(lo, table.shape[2] - 1)]
    b = table[j, p_idx, jnp.clip(hi - pw, 0, table.shape[2] - 1)]
    out = op(a, b)
    return jnp.where(w > 0, out, jnp.nan)


# ---------------------------------------------------------------------------
# the main range-function kernel family

SIMPLE_FNS = (
    "sum_over_time", "avg_over_time", "count_over_time", "min_over_time",
    "max_over_time", "stddev_over_time", "stdvar_over_time", "last_over_time",
    "present_over_time", "changes", "resets", "deriv", "irate", "idelta",
    "rate", "increase", "delta", "last_sample", "timestamp", "zscore",
    "absent_over_time",
)


@partial(jax.jit, static_argnames=("fn", "counter", "pre_corrected"))
def range_eval(fn: str, ts, vals, counts, steps, window, extra=0.0,
               counter: bool = False, pre_corrected: bool = False,
               raw=None):
    """Evaluate one range function at each step for each series.

    ts: int32 [P,S] relative ms; vals: float [P,S]; counts: int32 [P];
    steps: int32 [K]; window: int32 scalar ms; extra: scalar parameter
    (predict_linear horizon etc.). Returns float [P,K].

    ``pre_corrected``: values were counter-reset-corrected AND per-series
    rebased host-side in f64 (``SeriesBatch.delta_host``) — the in-kernel
    correction is skipped. ``raw`` [P,S] is the UNcorrected value tensor,
    consulted only where Prometheus' extrapolate-to-zero heuristic needs
    each window's raw first sample (precision there is moot, so the f32
    copy suffices). This is what keeps f32 device math exact at real
    counter magnitudes (a counter ≥2^24 otherwise loses every per-window
    delta to the f32 cast).
    """
    return _range_impl(fn, ts, vals, _valid_mask(ts, counts), steps, window,
                       extra, counter, pre_corrected, raw)


@partial(jax.jit, static_argnames=("fn", "counter", "pre_corrected"))
def range_eval_masked(fn: str, ts, vals, valid, steps, window, extra=0.0,
                      counter: bool = False, pre_corrected: bool = False,
                      raw=None):
    """Mask-aware variant: ``valid`` [P,S] may have interior gaps (device-
    decoded block-aligned pages). Gap positions must carry a timestamp ≤ the
    next valid sample's (monotone non-decreasing ts overall)."""
    return _range_impl(fn, ts, vals, valid, steps, window, extra, counter,
                       pre_corrected, raw)


def _range_impl(fn: str, ts, vals, valid, steps, window, extra, counter,
                pre_corrected: bool = False, raw=None):
    dt = fdtype()
    vals = vals.astype(dt)
    v = jnp.where(valid, vals, 0.0)
    S = ts.shape[1]
    lo, hi = window_bounds(ts, steps, window)
    vcount = _eprefix(valid.astype(dt))
    n = _gather(vcount, hi) - _gather(vcount, lo)
    has1 = n >= 1
    has2 = n >= 2
    nan = jnp.array(jnp.nan, dt)
    # valid-sample machinery (positions may be gaps, not just tail padding):
    # prev/next-valid index maps — only built for functions that gather
    # first/last samples (fn is static, so this prunes the compiled graph)
    pv = nv = first_idx = last_idx = None
    if fn in ("stddev_over_time", "stdvar_over_time", "zscore",
              "last_over_time", "last_sample", "timestamp", "changes",
              "resets", "irate", "idelta", "rate", "increase", "delta"):
        sidx = jnp.arange(S, dtype=jnp.int32)[None, :]
        pv = lax.cummax(jnp.where(valid, sidx, -1), axis=1)
        nv = lax.cummin(jnp.where(valid, sidx, S), axis=1, reverse=True)
        # first/last VALID sample index within [lo, hi)
        first_idx = jnp.clip(_gather(nv, jnp.minimum(lo, S - 1)), 0, S - 1)
        last_idx = jnp.clip(_gather(pv, jnp.maximum(hi - 1, 0)), 0, S - 1)

    if fn == "count_over_time":
        return jnp.where(has1, n, nan)
    if fn == "present_over_time":
        return jnp.where(has1, 1.0, nan).astype(dt)
    if fn == "absent_over_time":
        # per-series presence; the absent transformer combines across series
        return jnp.where(has1, nan, 1.0).astype(dt)

    if fn in ("sum_over_time", "avg_over_time"):
        csum = _eprefix(v)
        s = _gather(csum, hi) - _gather(csum, lo)
        if fn == "avg_over_time":
            return jnp.where(has1, s / jnp.maximum(n, 1.0), nan)
        return jnp.where(has1, s, nan)

    if fn in ("stddev_over_time", "stdvar_over_time", "zscore"):
        csum = _eprefix(v)
        csum2 = _eprefix(v * v)
        s = _gather(csum, hi) - _gather(csum, lo)
        s2 = _gather(csum2, hi) - _gather(csum2, lo)
        mean = s / jnp.maximum(n, 1.0)
        var = jnp.maximum(s2 / jnp.maximum(n, 1.0) - mean * mean, 0.0)
        if fn == "stdvar_over_time":
            return jnp.where(has1, var, nan)
        sd = jnp.sqrt(var)
        if fn == "stddev_over_time":
            return jnp.where(has1, sd, nan)
        last = _gather(v, last_idx)
        return jnp.where(has1, (last - mean) / sd, nan)

    if fn in ("min_over_time", "max_over_time"):
        S = ts.shape[1]
        levels = max(S.bit_length(), 1)
        if fn == "min_over_time":
            masked = jnp.where(valid, vals, jnp.inf)
            table = _build_sparse(masked, jnp.minimum, jnp.inf, levels)
            out = _rmq(table, lo, hi, jnp.minimum, jnp.inf)
        else:
            masked = jnp.where(valid, vals, -jnp.inf)
            table = _build_sparse(masked, jnp.maximum, -jnp.inf, levels)
            out = _rmq(table, lo, hi, jnp.maximum, -jnp.inf)
        return jnp.where(has1, out, nan)

    if fn in ("last_over_time", "last_sample", "timestamp"):
        if fn == "timestamp":
            t_last = _gather(ts, last_idx).astype(dt)
            return jnp.where(has1, t_last / 1000.0, nan)
        return jnp.where(has1, _gather(v, last_idx), nan)

    if fn in ("changes", "resets"):
        prev_val, prev_ok = _prev_valid_value(v, valid, pv)
        if fn == "changes":
            ind = (v != prev_val) & valid & prev_ok
        else:
            ind = (v < prev_val) & valid & prev_ok
        cind = _eprefix(ind.astype(dt))
        # count indicators whose predecessor is also in the window:
        # positions (first_idx, hi)
        start = jnp.minimum(first_idx + 1, hi)
        cnt = _gather(cind, hi) - _gather(cind, start)
        return jnp.where(has1, cnt, nan)

    if fn in ("irate", "idelta"):
        i1 = last_idx
        i0 = jnp.clip(_gather(pv, jnp.maximum(i1 - 1, 0)), 0, S - 1)
        v1, v0 = _gather(v, i1), _gather(v, i0)
        t1, t0 = _gather(ts, i1).astype(dt), _gather(ts, i0).astype(dt)
        dv = v1 - v0
        if fn == "irate":
            dv = jnp.where(v1 < v0, v1, dv)  # counter reset: instant rate from 0
            out = dv / jnp.maximum((t1 - t0) / 1000.0, 1e-10)
        else:
            out = dv
        return jnp.where(has2, out, nan)

    if fn == "deriv":
        return _linreg(ts, v, valid, lo, hi, steps, slope_only=True)

    if fn == "predict_linear":
        return _linreg(ts, v, valid, lo, hi, steps, slope_only=False,
                       horizon_s=extra)

    if fn in ("rate", "increase", "delta"):
        if pre_corrected or not (counter or fn in ("rate", "increase")):
            cv = v  # host pre-corrected values are already monotone
        else:
            cv = _counter_corrected(jnp.where(valid, vals, 0.0), valid, pv)
            cv = jnp.where(valid, cv, 0.0)
        v_first = _gather(cv, first_idx)
        v_last = _gather(cv, last_idx)
        if pre_corrected and raw is not None:
            # the extrapolate-to-zero heuristic needs each window's RAW
            # first sample — the rebased lane lost that magnitude, so
            # gather it from the raw reference tensor instead
            raw_first = _gather(
                jnp.where(valid, raw.astype(dt), 0.0), first_idx)
        else:
            raw_first = _gather(v, first_idx)
        t_first = _gather(ts, first_idx).astype(dt) / 1000.0
        t_last = _gather(ts, last_idx).astype(dt) / 1000.0
        result = v_last - v_first
        # Prometheus extrapolatedRate semantics
        range_start = (steps[None, :] - window).astype(dt) / 1000.0
        range_end = steps[None, :].astype(dt) / 1000.0
        sampled = t_last - t_first
        avg_dur = sampled / jnp.maximum(n - 1.0, 1.0)
        dur_start = t_first - range_start
        dur_end = range_end - t_last
        if fn in ("rate", "increase"):
            dur_to_zero = jnp.where(result > 0,
                                    sampled * raw_first / jnp.maximum(result, 1e-30),
                                    jnp.inf)
            dur_start = jnp.minimum(dur_start, dur_to_zero)
        threshold = avg_dur * 1.1
        extend = sampled
        extend = extend + jnp.where(dur_start < threshold, dur_start, avg_dur / 2.0)
        extend = extend + jnp.where(dur_end < threshold, dur_end, avg_dur / 2.0)
        factor = extend / jnp.maximum(sampled, 1e-10)
        result = result * factor
        if fn == "rate":
            result = result / (window.astype(dt) / 1000.0)
        return jnp.where(has2, result, nan)

    raise ValueError(f"unknown range function {fn}")


def _linreg(ts, v, valid, lo, hi, steps, slope_only: bool, horizon_s=0.0):
    """Least-squares slope/prediction over each window (deriv/predict_linear).

    Time is centered at the step timestamp to keep the normal equations
    well-conditioned in float32.
    """
    dt = fdtype()
    t_s = jnp.where(valid, ts, 0).astype(dt) / 1000.0
    c_n = _eprefix(valid.astype(dt))
    c_t = _eprefix(jnp.where(valid, t_s, 0.0))
    c_v = _eprefix(v)
    c_tt = _eprefix(jnp.where(valid, t_s * t_s, 0.0))
    c_tv = _eprefix(jnp.where(valid, t_s * v, 0.0))
    n = _gather(c_n, hi) - _gather(c_n, lo)
    St = _gather(c_t, hi) - _gather(c_t, lo)
    Sv = _gather(c_v, hi) - _gather(c_v, lo)
    Stt = _gather(c_tt, hi) - _gather(c_tt, lo)
    Stv = _gather(c_tv, hi) - _gather(c_tv, lo)
    c = steps[None, :].astype(dt) / 1000.0  # center at step time
    St_c = St - n * c
    Stt_c = Stt - 2.0 * c * St + n * c * c
    Stv_c = Stv - c * Sv
    denom = n * Stt_c - St_c * St_c
    slope = (n * Stv_c - St_c * Sv) / jnp.where(denom == 0, 1.0, denom)
    has2 = n >= 2  # n counts VALID samples (mask-aware)
    if slope_only:
        return jnp.where(has2 & (denom != 0), slope, jnp.nan)
    intercept = (Sv - slope * St_c) / jnp.maximum(n, 1.0)
    return jnp.where(has2 & (denom != 0),
                     intercept + slope * horizon_s, jnp.nan)


# ---------------------------------------------------------------------------
# blocked masked kernels (quantile_over_time, holt_winters / double exp)

@partial(jax.jit, static_argnames=("block",))
def quantile_over_time(q, ts, vals, counts, steps, window, block: int = 16):
    """phi-quantile over each window. Masked sort per window, blocked over
    steps to bound the [P, block, S] working set."""
    return _quantile_impl(q, ts, vals, _valid_mask(ts, counts), steps,
                          window, block)


@partial(jax.jit, static_argnames=("block",))
def quantile_over_time_masked(q, ts, vals, valid, steps, window,
                              block: int = 16):
    return _quantile_impl(q, ts, vals, valid, steps, window, block)


def _quantile_impl(q, ts, vals, valid, steps, window, block: int):
    dt = fdtype()
    vals = vals.astype(dt)
    lo, hi = window_bounds(ts, steps, window)
    vcount = _eprefix(valid.astype(dt))
    K = steps.shape[0]
    S = ts.shape[1]
    pad_k = (-K) % block
    lo_p = jnp.pad(lo, ((0, 0), (0, pad_k)))
    hi_p = jnp.pad(hi, ((0, 0), (0, pad_k)))
    nblocks = (K + pad_k) // block
    s_idx = jnp.arange(S)[None, None, :]

    def do_block(b):
        lo_b = lax.dynamic_slice_in_dim(lo_p, b * block, block, axis=1)
        hi_b = lax.dynamic_slice_in_dim(hi_p, b * block, block, axis=1)
        in_win = (s_idx >= lo_b[:, :, None]) & (s_idx < hi_b[:, :, None])
        masked = jnp.where(in_win & valid[:, None, :], vals[:, None, :], jnp.inf)
        srt = jnp.sort(masked, axis=-1)
        n = (jnp.take_along_axis(vcount, hi_b, axis=1)
             - jnp.take_along_axis(vcount, lo_b, axis=1)).astype(dt)
        pos = q * jnp.maximum(n - 1.0, 0.0)
        i0 = jnp.floor(pos).astype(jnp.int32)
        frac = pos - i0
        a = jnp.take_along_axis(srt, i0[:, :, None], axis=-1)[:, :, 0]
        bv = jnp.take_along_axis(
            srt, jnp.minimum(i0 + 1, S - 1)[:, :, None], axis=-1)[:, :, 0]
        out = a + (bv - a) * frac
        return jnp.where(n > 0, out, jnp.nan)

    blocks = lax.map(do_block, jnp.arange(nblocks))  # [nb, P, block]
    out = jnp.moveaxis(blocks, 0, 1).reshape(ts.shape[0], -1)
    return out[:, :K]


@jax.jit
def holt_winters(sf, tf, ts, vals, counts, steps, window):
    """Holt's double exponential smoothing per window (promql holt_winters).

    Sequential by nature: a scan over samples carrying (level, trend) per
    (series, step) window. O(S) scan with [P, K] state.
    """
    return _holt_impl(sf, tf, ts, vals, _valid_mask(ts, counts), steps,
                      window)


@jax.jit
def holt_winters_masked(sf, tf, ts, vals, valid, steps, window):
    return _holt_impl(sf, tf, ts, vals, valid, steps, window)


def _holt_impl(sf, tf, ts, vals, valid, steps, window):
    dt = fdtype()
    vals = vals.astype(dt)
    lo, hi = window_bounds(ts, steps, window)
    S = ts.shape[1]
    P, K = lo.shape

    def step_fn(carry, i):
        level, trend, cnt = carry
        in_win = (i >= lo) & (i < hi) & valid[:, i][:, None]
        x = vals[:, i][:, None]
        new_level1 = x  # first sample initializes level
        new_trend1 = jnp.zeros_like(x)
        new_trend2 = x - level  # second sample initializes trend
        new_level2 = x
        sm_level = sf * x + (1 - sf) * (level + trend)
        sm_trend = tf * (sm_level - level) + (1 - tf) * trend
        nl = jnp.where(cnt == 0, new_level1,
                       jnp.where(cnt == 1, new_level2, sm_level))
        nt = jnp.where(cnt == 0, new_trend1,
                       jnp.where(cnt == 1, new_trend2, sm_trend))
        level = jnp.where(in_win, nl, level)
        trend = jnp.where(in_win, nt, trend)
        cnt = jnp.where(in_win, cnt + 1, cnt)
        return (level, trend, cnt), None

    init = (jnp.zeros((P, K), dt), jnp.zeros((P, K), dt),
            jnp.zeros((P, K), jnp.int32))
    (level, trend, cnt), _ = lax.scan(step_fn, init, jnp.arange(S))
    return jnp.where(cnt >= 2, level, jnp.nan)
