"""Sidecar-served evaluation lane: range functions folded from chunk-level
aggregate summaries instead of decoded samples.

Chunks carry fixed-size per-column summaries computed once at seal time
(``memory/chunk.py``: count/sum/sumsq/min/max/first/last/resets/corr/changes
plus a mergeable log2 sketch — the Zarr chunk-level cumulative-sums shape from
PAPERS.md). For a window (t-w, t] the lane splits each partition's data into

    [left-edge chunk] [interior chunks ...] [right-edge chunk] [write buffer]

folds the interior chunks from their summaries in O(chunks), decodes only the
(at most two) edge chunks, folds the write-buffer tail in one batched native
call (``shard_buf_fold``), and merges the segments with Prometheus counter
-reset carry across segment boundaries. The per-window merged stats row then
feeds closed-form range-function formulas that mirror
``query/engine/kernels._range_impl`` operation for operation in float64.

Exactness gate: the lane serves only functions whose window decomposition is
exact over the summary algebra (sum/avg/count/min/max/stddev/stdvar/last/
present/absent/changes/resets/zscore/timestamp and the rate/increase/delta
family via first/last + per-chunk reset corrections). quantile_over_time is
served from the mergeable sketch only under ``FILODB_SIDECAR_APPROX=1``
(declared approximation). Anything else — at-modifier pins, histogram
columns, sample budgets, demand paging, out-of-order buffers — bypasses to
the decode lane and increments ``filodb_sidecar_bypassed_total``.

Provenance valve (``FILODB_SIDECARS``):
  ``1`` (default) serve from stored sidecars (computing them lazily for
        natively-sealed chunks); ``decode`` re-derives every summary from the
        decoded vectors, ignoring stored sidecars — byte-identical to ``1``
        because codecs are lossless and the summary fold is strictly
        sequential; ``0`` disables the lane entirely (kernel lane).
"""

from __future__ import annotations

import os
import time

import numpy as np

from filodb_tpu.core.schemas import ColumnType
from filodb_tpu.memory.chunk import (
    S_CHANGES,
    S_CORR,
    S_COUNT,
    S_FIRST_TS,
    S_FIRST_VAL,
    S_LAST_TS,
    S_LAST_VAL,
    S_MAX,
    S_MIN,
    S_RESETS,
    S_SUM,
    S_SUMSQ,
    STATS_WIDTH,
    ensure_summary,
    summarize_values,
)
from filodb_tpu.utils.metrics import Counter
from filodb_tpu.utils.tracing import span

SIDECAR_SERVED = Counter(
    "filodb_sidecar_served",
    help="leaf evaluations served from chunk aggregate sidecars")
SIDECAR_BYPASSED = Counter(
    "filodb_sidecar_bypassed",
    help="eligible-path evaluations that fell back to the decode lane")

# functions whose (t-w, t] evaluation is exact over the summary algebra
ELIGIBLE_FNS = frozenset((
    "count_over_time", "sum_over_time", "avg_over_time", "min_over_time",
    "max_over_time", "stddev_over_time", "stdvar_over_time", "zscore",
    "last_over_time", "present_over_time", "absent_over_time", "changes",
    "resets", "rate", "increase", "delta", "last_sample", "timestamp",
))

_SCALAR_CTYPES = (ColumnType.DOUBLE, ColumnType.LONG, ColumnType.INT)


def mode() -> str:
    """``1`` serve, ``decode`` recompute-from-vectors, ``0`` off."""
    v = os.environ.get("FILODB_SIDECARS", "1").strip().lower()
    if v in ("0", "off", "false"):
        return "0"
    if v == "decode":
        return "decode"
    return "1"


def approx_enabled() -> bool:
    return os.environ.get("FILODB_SIDECAR_APPROX", "0") == "1"


def _sealed_gate() -> int:
    """Amortization choke point for the sealed-chunk fold. The buffer
    tier folds in one batched C call regardless of partition count, and
    since the flat-batch fold (``_eval_sealed_batch``) the sealed
    interiors do too — the remaining per-partition Python cost is edge
    decodes only. The decode lane still amortizes better once
    ``sealed_partitions * windows`` dwarfs the samples skipped, so the
    gate survives, but 16x wider than the PR 15 per-partition fold
    needed (measured: gated_scan_small_chunks in benchmarks/sidecars.py
    stays ahead of decode through 64k partition-windows).
    0 disables the gate (always serve)."""
    try:
        return int(os.environ.get("FILODB_SIDECAR_SEALED_GATE", "65536"))
    except ValueError:
        return 65536


# Below this many sealed partition-windows the fold's fixed overhead is
# immaterial and the lane serves unconditionally (keeps small stores and
# tests deterministic). Above it, serve only when each partition-window
# skips enough interior samples to buy back its fixed cost.
_SEALED_FREE_PART_WINDOWS = 512
_SEALED_MIN_SKIPPED_SAMPLES = 1024


def _sealed_fold_pays(sparts, sealed_overlap, t0s, t1s, W: int) -> bool:
    """Decide whether the per-partition sealed fold beats full decode.

    Cost model: the fold costs ~a per sealed partition-window (python
    edge decode + segment merges); the decode lane costs ~b per sample
    in the window, batched. The fold's only edge is the interior samples
    it never touches, so it pays exactly when
    ``skipped_samples_per_partition_window * b > a`` — empirically about
    a thousand samples. Interior skip is estimated from the first sealed
    partition's chunk geometry (span and density), not by decoding."""
    n_sealed = int(sealed_overlap.sum())
    if n_sealed == 0:
        return True
    gate = _sealed_gate()
    if gate <= 0:
        return True
    if n_sealed * W > gate:
        return False
    if n_sealed * W <= _SEALED_FREE_PART_WINDOWS:
        return True
    chunks = sparts[int(np.argmax(sealed_overlap))].chunks[:8]
    spans = [c.end_time - c.start_time for c in chunks
             if c.end_time > c.start_time]
    if not spans:
        return False
    span = float(np.median(spans))
    density = float(np.median([c.num_rows for c in chunks])) / span
    window_ms = float((t1s - t0s).max())
    skipped = max(0.0, window_ms - 2.0 * span) * density
    return skipped >= _SEALED_MIN_SKIPPED_SAMPLES


def _sealed_arm(sparts, sealed_overlap, t0s, t1s, W: int, ctx) -> bool:
    """Sidecar-vs-decode as a learned decision ("sidecar" site): the
    geometry heuristic (:func:`_sealed_fold_pays`) stays the static arm,
    and once the cost model has settled wall times for BOTH arms of this
    partition-window signature class the predicted-cheaper arm wins.
    ``FILODB_SIDECAR_SEALED_GATE<=0`` remains a hard always-serve valve
    (override). The decision defers onto ``ctx`` and settles with the
    leaf's evaluation wall time back in the exec leaf."""
    n_sealed = int(sealed_overlap.sum())
    if n_sealed == 0:
        return True  # nothing sealed: the fold is trivially the buffer read
    static_serve = _sealed_fold_pays(sparts, sealed_overlap, t0s, t1s, W)
    if ctx is None:
        return static_serve
    from filodb_tpu.query import cost_model as cm
    model = cm.model_for(ctx.dataset)
    d = model.decide(
        "sidecar",
        f"fold:pw{cm.bucket(n_sealed * W)}",
        ("sidecar", "decode"),
        static_arm="sidecar" if static_serve else "decode",
        override="sidecar" if _sealed_gate() <= 0 else None,
    )
    model.defer(ctx, d)
    return d.arm == "sidecar"


def covers_fn(fn: str) -> bool:
    """Would the lane serve this range function (mesh prepare-stage
    precheck)? quantile only under declared approximation."""
    if mode() == "0":
        return False
    return fn in ELIGIBLE_FNS or (
        fn == "quantile_over_time" and approx_enabled())


class _Bypass(Exception):
    """Raised anywhere in the lane when exactness can't be guaranteed —
    the caller falls back to the decode lane."""


# ---------------------------------------------------------------------------
# per-series window folds (prefix-gather form, vectorized over windows)

def _eprefix(x: np.ndarray) -> np.ndarray:
    out = np.empty(len(x) + 1, np.float64)
    out[0] = 0.0
    np.cumsum(x, out=out[1:])
    return out


class _FoldArrays:
    """Prefix-sum bundle over one NaN-filtered value sequence, for O(1)
    per-window gathers (the host analog of the kernels' ``_eprefix``)."""

    __slots__ = ("tv", "vv", "ps", "ps2", "pr", "pcorr", "pchg")

    def __init__(self, tv: np.ndarray, vv: np.ndarray):
        self.tv = tv
        self.vv = vv
        self.ps = _eprefix(vv)
        self.ps2 = _eprefix(vv * vv)
        if len(vv) > 1:
            prev, cur = vv[:-1], vv[1:]
            drop = cur < prev
            ind = np.zeros(len(vv), np.float64)
            ind[1:] = drop
            self.pr = _eprefix(ind)
            ind2 = np.zeros(len(vv), np.float64)
            ind2[1:] = np.where(drop, prev, 0.0)
            self.pcorr = _eprefix(ind2)
            ind3 = np.zeros(len(vv), np.float64)
            ind3[1:] = cur != prev
            self.pchg = _eprefix(ind3)
        else:
            z = np.zeros(len(vv) + 1, np.float64)
            self.pr = self.pcorr = self.pchg = z


def _fold_windows(fa: _FoldArrays, t0s: np.ndarray,
                  t1s: np.ndarray) -> np.ndarray:
    """Stats rows [W, STATS_WIDTH] for windows (t0, t1] over one sequence."""
    W = len(t0s)
    out = np.zeros((W, STATS_WIDTH), np.float64)
    n = len(fa.tv)
    out[:, S_MIN:S_LAST_VAL + 1] = np.nan
    if n == 0:
        return out
    lo = np.searchsorted(fa.tv, t0s, side="right")
    hi = np.searchsorted(fa.tv, t1s, side="right")
    cnt = (hi - lo).astype(np.float64)
    have = hi > lo
    out[:, S_COUNT] = np.where(have, cnt, 0.0)
    out[:, S_SUM] = np.where(have, fa.ps[hi] - fa.ps[lo], 0.0)
    out[:, S_SUMSQ] = np.where(have, fa.ps2[hi] - fa.ps2[lo], 0.0)
    # reset/change indicators at position j compare vv[j] to vv[j-1]; only
    # pairs fully inside the window count: j in [lo+1, hi)
    lo1 = np.minimum(lo + 1, hi)
    out[:, S_RESETS] = fa.pr[hi] - fa.pr[lo1]
    out[:, S_CORR] = fa.pcorr[hi] - fa.pcorr[lo1]
    out[:, S_CHANGES] = fa.pchg[hi] - fa.pchg[lo1]
    fi = np.clip(lo, 0, n - 1)
    li = np.clip(hi - 1, 0, n - 1)
    out[:, S_FIRST_TS] = np.where(have, fa.tv[fi], np.nan)
    out[:, S_FIRST_VAL] = np.where(have, fa.vv[fi], np.nan)
    out[:, S_LAST_TS] = np.where(have, fa.tv[li], np.nan)
    out[:, S_LAST_VAL] = np.where(have, fa.vv[li], np.nan)
    # min/max via paired reduceat segments [lo0,hi0),[hi0,lo1),...; a NaN
    # sentinel makes hi == n addressable (odd/degenerate segments that touch
    # it are discarded or masked by ``have``)
    ext = np.append(fa.vv, np.nan)
    inds = np.empty(2 * W, np.int64)
    inds[0::2] = lo
    inds[1::2] = hi
    mn = np.minimum.reduceat(ext, inds)[0::2]
    mx = np.maximum.reduceat(ext, inds)[0::2]
    out[:, S_MIN] = np.where(have, mn, np.nan)
    out[:, S_MAX] = np.where(have, mx, np.nan)
    return out


def _valid_series(ts: np.ndarray, vals: np.ndarray):
    vals = np.asarray(vals, np.float64)
    ts = np.asarray(ts, np.int64)
    m = ~np.isnan(vals)
    return ts[m], vals[m]


def _merge_vec(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Merge stats rows of two consecutive-in-time segments, [W, 12] each.
    Counter-reset carry across the boundary follows the kernels'
    prev-valid-sample comparison: a drop from segment A's last sample to
    segment B's first counts as one reset with correction A.last."""
    an = a[:, S_COUNT] > 0
    bn = b[:, S_COUNT] > 0
    out = a.copy()
    only_b = ~an & bn
    out[only_b] = b[only_b]
    m = an & bn
    if m.any():
        A, B = a[m], b[m]
        R = A.copy()
        R[:, S_COUNT] = A[:, S_COUNT] + B[:, S_COUNT]
        R[:, S_SUM] = A[:, S_SUM] + B[:, S_SUM]
        R[:, S_SUMSQ] = A[:, S_SUMSQ] + B[:, S_SUMSQ]
        R[:, S_MIN] = np.minimum(A[:, S_MIN], B[:, S_MIN])
        R[:, S_MAX] = np.maximum(A[:, S_MAX], B[:, S_MAX])
        R[:, S_LAST_TS] = B[:, S_LAST_TS]
        R[:, S_LAST_VAL] = B[:, S_LAST_VAL]
        bdrop = B[:, S_FIRST_VAL] < A[:, S_LAST_VAL]
        R[:, S_RESETS] = A[:, S_RESETS] + bdrop + B[:, S_RESETS]
        R[:, S_CORR] = (A[:, S_CORR]
                        + np.where(bdrop, A[:, S_LAST_VAL], 0.0)) \
            + B[:, S_CORR]
        R[:, S_CHANGES] = A[:, S_CHANGES] \
            + (B[:, S_FIRST_VAL] != A[:, S_LAST_VAL]) + B[:, S_CHANGES]
        out[m] = R
    return out


# ---------------------------------------------------------------------------
# per-partition sealed-chunk bundles (summary matrices, cached by version)

class _ChunkBundle:
    __slots__ = ("starts", "ends", "stats", "chunks", "sketches")

    def __init__(self, starts, ends, stats, chunks, sketches):
        self.starts = starts
        self.ends = ends
        self.stats = stats  # [C, STATS_WIDTH] for count>0 chunks only
        self.chunks = chunks
        self.sketches = sketches


def _chunk_col_stats(ch, col: int, decode_mode: bool):
    """(stats row, sketch) of one sealed chunk's value column."""
    if decode_mode:
        cs = summarize_values(np.asarray(ch.decode_column(0), np.int64),
                              np.asarray(ch.decode_column(col), np.float64))
        return cs.stats, cs.sketch
    summary = ensure_summary(ch)
    cs = summary[col] if summary is not None and col < len(summary) else None
    if cs is None:
        raise _Bypass
    return cs.stats, cs.sketch


def _part_bundle(p, col: int, decode_mode: bool) -> _ChunkBundle:
    chs = p.chunks
    token = (len(chs), chs[-1].id if chs else 0, col, decode_mode)
    cache = getattr(p, "_sc_cache", None)
    if cache is not None and cache[0] == token:
        return cache[1]
    rows, sketches, keep = [], [], []
    for ch in chs:
        st, sk = _chunk_col_stats(ch, col, decode_mode)
        if st[S_COUNT] > 0:
            rows.append(st)
            sketches.append(sk)
            keep.append(ch)
    if rows:
        stats = np.vstack(rows)
        starts = stats[:, S_FIRST_TS].astype(np.int64)
        ends = stats[:, S_LAST_TS].astype(np.int64)
        # exactness requires time-ordered, non-overlapping chunks (valid
        # sample spans): out-of-order seals fall back to the decode lane
        if len(starts) > 1 and (np.any(np.diff(starts) <= 0)
                                or np.any(starts[1:] <= ends[:-1])):
            raise _Bypass
    else:
        stats = np.zeros((0, STATS_WIDTH), np.float64)
        starts = ends = np.zeros(0, np.int64)
    bundle = _ChunkBundle(starts, ends, stats, keep, sketches)
    try:
        p._sc_cache = (token, bundle)
    except AttributeError:
        pass
    return bundle


def _interior_fold(bundle: _ChunkBundle, t0s, t1s):
    """Merged stats rows [W, 12] of the interior chunk run per window, plus
    the [i0, i1) run bounds (for edge-chunk identification)."""
    C = len(bundle.starts)
    W = len(t0s)
    out = np.zeros((W, STATS_WIDTH), np.float64)
    out[:, S_MIN:S_LAST_VAL + 1] = np.nan
    if C == 0:
        z = np.zeros(W, np.int64)
        return out, z, z
    st = bundle.stats
    i0 = np.searchsorted(bundle.starts, t0s, side="right")
    i1 = np.searchsorted(bundle.ends, t1s, side="right")
    i1 = np.maximum(i1, i0)
    have = i1 > i0
    pc = _eprefix(st[:, S_COUNT])
    ps = _eprefix(st[:, S_SUM])
    ps2 = _eprefix(st[:, S_SUMSQ])
    pr = _eprefix(st[:, S_RESETS])
    pcorr = _eprefix(st[:, S_CORR])
    pchg = _eprefix(st[:, S_CHANGES])
    # chunk-boundary reset/change carry between consecutive kept chunks
    if C > 1:
        bdrop = st[1:, S_FIRST_VAL] < st[:-1, S_LAST_VAL]
        br = _eprefix(bdrop.astype(np.float64))
        bc = _eprefix(np.where(bdrop, st[:-1, S_LAST_VAL], 0.0))
        bg = _eprefix(
            (st[1:, S_FIRST_VAL] != st[:-1, S_LAST_VAL]).astype(np.float64))
    else:
        br = bc = bg = np.zeros(1, np.float64)
    out[:, S_COUNT] = pc[i1] - pc[i0]
    out[:, S_SUM] = ps[i1] - ps[i0]
    out[:, S_SUMSQ] = ps2[i1] - ps2[i0]
    # boundaries between chunks c,c+1 with both inside [i0, i1)
    blo = np.minimum(i0, len(br) - 1)
    bhi = np.clip(i1 - 1, blo, len(br) - 1)
    out[:, S_RESETS] = (pr[i1] - pr[i0]) + (br[bhi] - br[blo])
    out[:, S_CORR] = (pcorr[i1] - pcorr[i0]) + (bc[bhi] - bc[blo])
    out[:, S_CHANGES] = (pchg[i1] - pchg[i0]) + (bg[bhi] - bg[blo])
    fi = np.clip(i0, 0, C - 1)
    li = np.clip(i1 - 1, 0, C - 1)
    out[:, S_FIRST_TS] = np.where(have, st[fi, S_FIRST_TS], np.nan)
    out[:, S_FIRST_VAL] = np.where(have, st[fi, S_FIRST_VAL], np.nan)
    out[:, S_LAST_TS] = np.where(have, st[li, S_LAST_TS], np.nan)
    out[:, S_LAST_VAL] = np.where(have, st[li, S_LAST_VAL], np.nan)
    if C * W <= 1 << 22:
        sel = (np.arange(C)[:, None] >= i0[None, :]) \
            & (np.arange(C)[:, None] < i1[None, :])
        mn = np.where(sel, st[:, S_MIN][:, None], np.inf).min(axis=0)
        mx = np.where(sel, st[:, S_MAX][:, None], -np.inf).max(axis=0)
    else:  # very wide scans: per-window gather to bound memory
        mn = np.array([st[a:b, S_MIN].min() if b > a else np.inf
                       for a, b in zip(i0, i1)])
        mx = np.array([st[a:b, S_MAX].max() if b > a else -np.inf
                       for a, b in zip(i0, i1)])
    out[:, S_MIN] = np.where(have, mn, np.nan)
    out[:, S_MAX] = np.where(have, mx, np.nan)
    out[~have, S_COUNT] = 0.0
    return out, i0, i1


_CHUNK_FA = "_fold_arrays"


def _chunk_fa(ch, col: int) -> _FoldArrays:
    """Decoded + NaN-filtered fold arrays for an edge chunk, memoized on the
    (immutable) chunk object per column."""
    cache = ch.__dict__.get(_CHUNK_FA)
    if cache is None:
        object.__setattr__(ch, _CHUNK_FA, {})
        cache = ch.__dict__[_CHUNK_FA]
    fa = cache.get(col)
    if fa is None:
        tv, vv = _valid_series(ch.decode_column(0), ch.decode_column(col))
        fa = cache[col] = _FoldArrays(tv, vv)
    return fa


def _edge_stats(bundle: _ChunkBundle, col: int, edge_idx: np.ndarray,
                t0s, t1s, touched: set) -> np.ndarray:
    """Stats rows [W, 12] of the window∩chunk slice for per-window edge
    chunk indices (-1 = no edge chunk for that window)."""
    W = len(edge_idx)
    out = np.zeros((W, STATS_WIDTH), np.float64)
    out[:, S_MIN:S_LAST_VAL + 1] = np.nan
    for c in np.unique(edge_idx[edge_idx >= 0]):
        k = np.flatnonzero(edge_idx == c)
        fa = _chunk_fa(bundle.chunks[c], col)
        out[k] = _fold_windows(fa, t0s[k], t1s[k])
        touched.add(id(bundle.chunks[c]))
    return out


def eval_partition_windows(p, col: int, t0s, t1s, buf_rows, decode_mode: bool,
                           stats_acc: dict) -> np.ndarray:
    """General path for a partition whose sealed chunks overlap the windows:
    interior-from-summaries + decoded edges + buffer tail, merged in time
    order. ``buf_rows`` [W, 12] is the already-folded write-buffer segment.
    Returns merged stats rows [W, 12]."""
    bundle = _part_bundle(p, col, decode_mode)
    interior, i0, i1 = _interior_fold(bundle, t0s, t1s)
    C = len(bundle.starts)
    # overlap run [o0, o1): left edge = chunk straddling t0, right edge =
    # chunk straddling t1 (each at most one for non-overlapping chunks)
    o0 = np.searchsorted(bundle.ends, t0s, side="right")
    o1 = np.searchsorted(bundle.starts, t1s, side="right")
    left = np.where(o0 < i0, o0, -1)
    re_idx = o1 - 1
    right = np.where((re_idx >= i1) & (re_idx >= 0) & (re_idx < C)
                     & (re_idx != left), re_idx, -1)
    touched: set = set()
    lstats = _edge_stats(bundle, col, left, t0s, t1s, touched)
    rstats = _edge_stats(bundle, col, right, t0s, t1s, touched)
    merged = _merge_vec(_merge_vec(_merge_vec(lstats, interior), rstats),
                        buf_rows)
    # exactness: the buffer must strictly follow every sealed sample it is
    # merged after (out-of-order ingest violates the segment order)
    pre = _merge_vec(_merge_vec(lstats, interior), rstats)
    both = (pre[:, S_COUNT] > 0) & (buf_rows[:, S_COUNT] > 0)
    if np.any(buf_rows[both, S_FIRST_TS] <= pre[both, S_LAST_TS]):
        raise _Bypass
    stats_acc["sidecar_chunks"] = stats_acc.get("sidecar_chunks", 0) \
        + int((i1 - i0).sum())
    stats_acc["decoded_chunks"] = stats_acc.get("decoded_chunks", 0) \
        + len(touched)
    return merged


# ---------------------------------------------------------------------------
# range-function formulas over merged stats (mirrors kernels._range_impl)

def formula(fn: str, st: np.ndarray, steps_ms: np.ndarray, window_ms: int,
            counter: bool) -> np.ndarray:
    """st: [..., W, 12] merged stats; steps_ms: [W] absolute eval steps.
    Returns [..., W] float64 values with kernel-matching NaN gating."""
    n = st[..., S_COUNT]
    has1 = n >= 1
    nan = np.nan
    with np.errstate(divide="ignore", invalid="ignore"):
        if fn == "count_over_time":
            return np.where(has1, n, nan)
        if fn == "present_over_time":
            return np.where(has1, 1.0, nan)
        if fn == "absent_over_time":
            return np.where(has1, nan, 1.0)
        if fn == "sum_over_time":
            return np.where(has1, st[..., S_SUM], nan)
        if fn == "avg_over_time":
            return np.where(has1, st[..., S_SUM] / np.maximum(n, 1.0), nan)
        if fn in ("stddev_over_time", "stdvar_over_time", "zscore"):
            mean = st[..., S_SUM] / np.maximum(n, 1.0)
            var = np.maximum(
                st[..., S_SUMSQ] / np.maximum(n, 1.0) - mean * mean, 0.0)
            if fn == "stdvar_over_time":
                return np.where(has1, var, nan)
            sd = np.sqrt(var)
            if fn == "stddev_over_time":
                return np.where(has1, sd, nan)
            return np.where(has1, (st[..., S_LAST_VAL] - mean) / sd, nan)
        if fn == "min_over_time":
            return np.where(has1, st[..., S_MIN], nan)
        if fn == "max_over_time":
            return np.where(has1, st[..., S_MAX], nan)
        if fn in ("last_over_time", "last_sample"):
            return np.where(has1, st[..., S_LAST_VAL], nan)
        if fn == "timestamp":
            return np.where(has1, st[..., S_LAST_TS] / 1000.0, nan)
        if fn == "changes":
            return np.where(has1, st[..., S_CHANGES], nan)
        if fn == "resets":
            return np.where(has1, st[..., S_RESETS], nan)
        if fn in ("rate", "increase", "delta"):
            has2 = n >= 2
            corrected = counter or fn in ("rate", "increase")
            raw_first = st[..., S_FIRST_VAL]
            v_last = st[..., S_LAST_VAL]
            if corrected:
                v_last = v_last + st[..., S_CORR]
            result = v_last - raw_first
            t_first = st[..., S_FIRST_TS] / 1000.0
            t_last = st[..., S_LAST_TS] / 1000.0
            range_start = (steps_ms - window_ms) / 1000.0
            range_end = steps_ms / 1000.0
            sampled = t_last - t_first
            avg_dur = sampled / np.maximum(n - 1.0, 1.0)
            dur_start = t_first - range_start
            dur_end = range_end - t_last
            if fn in ("rate", "increase"):
                dur_to_zero = np.where(
                    result > 0,
                    sampled * raw_first / np.maximum(result, 1e-30), np.inf)
                dur_start = np.minimum(dur_start, dur_to_zero)
            threshold = avg_dur * 1.1
            extend = sampled \
                + np.where(dur_start < threshold, dur_start, avg_dur / 2.0) \
                + np.where(dur_end < threshold, dur_end, avg_dur / 2.0)
            result = result * (extend / np.maximum(sampled, 1e-10))
            if fn == "rate":
                result = result / (window_ms / 1000.0)
            return np.where(has2, result, nan)
    raise _Bypass


# ---------------------------------------------------------------------------
# leaf entry point

def try_execute(plan, ctx):
    """Attempt to serve a SelectRawPartitionsExec leaf's windowing stage from
    sidecars. Returns the PeriodicSamplesMapper-equivalent StepMatrix (the
    caller applies the remaining transformers), or None to fall back to the
    decode lane."""
    m = mode()
    if m == "0":
        return None
    from filodb_tpu.query.exec.transformers import (
        PeriodicSamplesMapper,
        steps_array,
    )
    if not plan.transformers \
            or not isinstance(plan.transformers[0], PeriodicSamplesMapper):
        return None
    psm = plan.transformers[0]
    fn = psm.function or "last_sample"
    approx = approx_enabled()
    if fn not in ELIGIBLE_FNS \
            and not (fn == "quantile_over_time" and approx):
        SIDECAR_BYPASSED.inc()
        return None
    if psm.at_ms is not None or (psm.params and fn != "quantile_over_time") \
            or ctx.budget is not None:
        SIDECAR_BYPASSED.inc()
        return None
    try:
        return _execute(plan, ctx, psm, fn, m == "decode", approx)
    except _Bypass:
        SIDECAR_BYPASSED.inc()
        # the decode lane serves this leaf now: any pending lane decision
        # whose chosen arm didn't run settles under "decode" instead, with
        # its prediction dropped from calibration
        from filodb_tpu.query.cost_model import CostModel
        CostModel.relabel_deferred(ctx, "sidecar", "decode")
        CostModel.relabel_deferred(ctx, "pyramid", "decode")
        return None


def _execute(plan, ctx, psm, fn, decode_mode: bool, approx: bool):
    from filodb_tpu.core.memstore.native_shard import NativeBackedPartition
    from filodb_tpu.core.memstore.partition import TimeSeriesPartition
    from filodb_tpu.query.exec.transformers import steps_array
    from filodb_tpu.query.model import QueryLimitExceeded, StepMatrix

    memstore = plan.store if plan.store is not None else ctx.memstore
    dataset = plan.dataset_name or ctx.dataset
    shard = memstore.get_shard(dataset, plan.shard)
    cfg = getattr(shard, "config", None)
    if cfg is None:
        raise _Bypass
    part_ids = shard.lookup_partitions(list(plan.filters), plan.chunk_start,
                                       plan.chunk_end)
    max_matches = getattr(cfg, "max_query_matches", 0)
    if max_matches and len(part_ids) > max_matches:
        raise QueryLimitExceeded(
            f"query matches {len(part_ids)} series on shard "
            f"{plan.shard} > limit {max_matches}")
    parts = [shard.partition(pid) for pid in part_ids]
    parts = [p for p in parts if p is not None]
    if not parts:
        raise _Bypass  # let the decode lane produce the canonical empty
    if any(type(p) is not TimeSeriesPartition
           and type(p) is not NativeBackedPartition for p in parts):
        # not warm memstore partitions: cold-tier leaves route to the
        # pyramid lane (stored segment/bucket aggregates, zero payload
        # paging); anything else — paged shells, duck-typed tier
        # partitions, backends without pyramids — bypasses inside it
        from filodb_tpu.query.engine import pyramid_lane
        return pyramid_lane.execute_cold(plan, ctx, psm, fn, parts,
                                         shard, decode_mode, approx)
    if getattr(cfg, "demand_paging_enabled", False):
        # the decode lane would pull cold chunks for partitions whose
        # resident data doesn't reach the query start — those windows
        # can't be folded from in-memory sidecars alone
        from filodb_tpu.core.memstore.odp import needs_paging
        for p in parts:
            if needs_paging(p, shard.index.start_time(p.part_id),
                            plan.chunk_start):
                raise _Bypass
    steps = steps_array(psm.start, psm.step, psm.end)
    eval_steps = (steps - psm.offset).astype(np.int64)
    window = int(psm.window if psm.function else 300_000)
    # decode-lane parity: build_batch only sees samples inside
    # [chunk_start, chunk_end], so windows clip to that range
    t1s = np.minimum(eval_steps, int(plan.chunk_end))
    t0s = np.maximum(eval_steps - window, int(plan.chunk_start) - 1)
    by_schema: dict[str, list] = {}
    for p in parts:
        by_schema.setdefault(p.schema.name, []).append(p)
    mats = []
    stats_acc: dict = {}
    t_fold = time.perf_counter()
    for schema_name, sparts in by_schema.items():
        schema = sparts[0].schema
        col = plan._value_col_index(schema)
        if schema.data.columns[col].ctype not in _SCALAR_CTYPES:
            raise _Bypass
        counter = schema.data.columns[col].is_counter
        with span("decode", schema=schema_name,
                  partitions=len(sparts), sidecar=True):
            if fn == "quantile_over_time":
                out = _eval_group_quantile(
                    sparts, col, float(psm.params[0]), t0s, t1s,
                    decode_mode, stats_acc, ctx)
            else:
                st = _eval_group_stats(sparts, col, t0s, t1s,
                                       decode_mode, stats_acc, ctx)
                stats_acc["samples"] = stats_acc.get("samples", 0.0) \
                    + float(st[:, :, S_COUNT].sum())
                out = formula(fn, st, eval_steps.astype(np.float64),
                              window, counter)
        keys = [p.part_key.range_vector_key for p in sparts]
        mats.append(StepMatrix(psm._out_keys(keys), out, steps))
    data = StepMatrix.concat(mats) if len(mats) > 1 else mats[0]
    ctx.stats.series_scanned += len(parts)
    # stats semantics in this lane: samples_scanned is the per-window
    # samples-ACCOUNTED figure (the number Prometheus reports as samples
    # processed — interior samples are folded, never materialized);
    # chunks_touched counts every chunk consulted, with the sidecar-folded
    # share broken out in sidecar_chunks; the whole fold (edge decodes +
    # summary reads) is this lane's decode stage, so its wall time lands
    # in decode_s.
    ctx.stats.samples_scanned += int(stats_acc.get("samples", 0.0))
    ctx.stats.sidecar_chunks += stats_acc.get("sidecar_chunks", 0)
    ctx.stats.chunks_touched += stats_acc.get("decoded_chunks", 0) \
        + stats_acc.get("sidecar_chunks", 0)
    ctx.stats.decode_s += time.perf_counter() - t_fold
    SIDECAR_SERVED.inc()
    return data


def _buf_rows_python(p, col: int, t0s, t1s) -> np.ndarray:
    b = p._buf
    n = b.n
    if n == 0:
        out = np.zeros((len(t0s), STATS_WIDTH), np.float64)
        out[:, S_MIN:S_LAST_VAL + 1] = np.nan
        return out
    ts = b.ts[:n]
    if n > 1 and np.any(np.diff(ts) < 0):
        raise _Bypass
    tv, vv = _valid_series(ts, b.cols[col - 1][:n])
    return _fold_windows(_FoldArrays(tv, vv), t0s, t1s)


def _eval_group_stats(sparts, col: int, t0s, t1s, decode_mode: bool,
                      stats_acc: dict, ctx=None) -> np.ndarray:
    """Merged stats tensor [P, W, 12] for one schema group."""
    from filodb_tpu.core.memstore.native_shard import NativeBackedPartition
    P, W = len(sparts), len(t0s)
    st = np.zeros((P, W, STATS_WIDTH), np.float64)
    # batched native buffer fold: one C call per shard core
    by_core: dict[int, list[int]] = {}
    cores = {}
    sealed_overlap = np.zeros(P, bool)
    buf_rows = [None] * P
    for i, p in enumerate(sparts):
        if isinstance(p, NativeBackedPartition):
            key = id(p._core)
            cores[key] = p._core
            by_core.setdefault(key, []).append(i)
        else:
            buf_rows[i] = _buf_rows_python(p, col, t0s, t1s)
            sealed_overlap[i] = any(
                c.end_time > t0s.min() and c.start_time <= t1s.max()
                for c in p.chunks)
    for key, idxs in by_core.items():
        core = cores[key]
        pids = np.array([sparts[i].part_id for i in idxs], np.int32)
        folded = core.buf_fold(pids, t0s, t1s, col - 1)
        if folded is None:  # pre-sidecar .so: python per-partition fallback
            for i in idxs:
                buf_rows[i] = _buf_rows_python(sparts[i], col, t0s, t1s)
                sealed_overlap[i] = bool(sparts[i].chunks) and any(
                    c.end_time > t0s.min() and c.start_time <= t1s.max()
                    for c in sparts[i].chunks)
            continue
        rows, flags = folded
        if np.any(flags & 1):
            raise _Bypass  # out-of-order buffer (or bad column)
        for j, i in enumerate(idxs):
            buf_rows[i] = rows[j]
            sealed_overlap[i] = bool(flags[j] & 2)
    if not _sealed_arm(sparts, sealed_overlap, t0s, t1s, W, ctx):
        raise _Bypass  # sealed fold wouldn't amortize — decode lane wins
    sealed_idx = []
    for i, p in enumerate(sparts):
        if sealed_overlap[i]:
            sealed_idx.append(i)
        else:
            st[i] = buf_rows[i]
    if sealed_idx:
        _eval_sealed_batch(sparts, sealed_idx, col, st, t0s, t1s,
                           buf_rows, decode_mode, stats_acc)
    return st


def _eval_sealed_batch(sparts, sealed_idx, col: int, st, t0s, t1s,
                       buf_rows, decode_mode: bool, stats_acc: dict):
    """Batched sealed fold: ONE flat interior fold across every sealed
    partition in the group, in place of a per-partition
    ``eval_partition_windows`` call.

    All partitions' kept chunk rows concatenate into one [Ctot, 12]
    array; per-partition searchsorted becomes one composite-key
    searchsorted (``pidx * span + (t - lo)`` — blocks are disjoint in
    key space, so the flat result is the block-local result plus the
    block offset), and the window sums become global-prefix-sum
    differences.  Only edge chunks (decoded slices) and chunkless
    partitions stay on per-partition code.  This is what moved the
    ``FILODB_SIDECAR_SEALED_GATE`` default from 4096 to 65536: the
    per-partition fixed cost the gate amortizes is now one numpy
    dispatch per GROUP, not per partition."""
    W = len(t0s)
    bundles, rows_idx = [], []
    for i in sealed_idx:
        b = _part_bundle(sparts[i], col, decode_mode)
        if len(b.starts) == 0:
            st[i] = buf_rows[i]
        else:
            bundles.append(b)
            rows_idx.append(i)
    S = len(bundles)
    if S == 0:
        return
    Cs = np.array([len(b.starts) for b in bundles], np.int64)
    offs = np.zeros(S + 1, np.int64)
    np.cumsum(Cs, out=offs[1:])
    Ctot = int(offs[-1])
    fstats = np.vstack([b.stats for b in bundles])
    fstarts = np.concatenate([b.starts for b in bundles])
    fends = np.concatenate([b.ends for b in bundles])
    # composite keys: disjoint per-partition blocks on a shared time axis
    lo = min(int(fstarts.min()), int(t0s.min()), int(t1s.min()))
    hi = max(int(fends.max()), int(t0s.max()), int(t1s.max()))
    span = np.int64(hi - lo + 2)
    base = np.arange(S, dtype=np.int64)[:, None] * span
    ks = (np.repeat(np.arange(S, dtype=np.int64), Cs) * span
          + (fstarts - lo))
    ke = (np.repeat(np.arange(S, dtype=np.int64), Cs) * span
          + (fends - lo))
    q0 = (base + (t0s[None, :] - lo)).ravel()
    q1 = (base + (t1s[None, :] - lo)).ravel()
    i0 = np.searchsorted(ks, q0, side="right").reshape(S, W) - offs[:-1, None]
    i1 = np.searchsorted(ke, q1, side="right").reshape(S, W) - offs[:-1, None]
    i1 = np.maximum(i1, i0)
    A = (offs[:-1, None] + i0)
    B = (offs[:-1, None] + i1)
    have = i1 > i0
    interior = np.zeros((S, W, STATS_WIDTH), np.float64)
    interior[:, :, S_MIN:S_LAST_VAL + 1] = np.nan
    pc = _eprefix(fstats[:, S_COUNT])
    ps = _eprefix(fstats[:, S_SUM])
    ps2 = _eprefix(fstats[:, S_SUMSQ])
    pr = _eprefix(fstats[:, S_RESETS])
    pcorr = _eprefix(fstats[:, S_CORR])
    pchg = _eprefix(fstats[:, S_CHANGES])
    interior[:, :, S_COUNT] = pc[B] - pc[A]
    interior[:, :, S_SUM] = ps[B] - ps[A]
    interior[:, :, S_SUMSQ] = ps2[B] - ps2[A]
    # chunk-boundary reset/change carry: pair j = boundary between flat
    # rows j, j+1 — zeroed across block seams so global prefixes stay
    # per-partition exact
    if Ctot > 1:
        same_block = np.ones(Ctot - 1, bool)
        same_block[offs[1:-1] - 1] = False
        pdrop = same_block \
            & (fstats[1:, S_FIRST_VAL] < fstats[:-1, S_LAST_VAL])
        br = _eprefix(pdrop.astype(np.float64))
        bc = _eprefix(np.where(pdrop, fstats[:-1, S_LAST_VAL], 0.0))
        bg = _eprefix((same_block
                       & (fstats[1:, S_FIRST_VAL]
                          != fstats[:-1, S_LAST_VAL])).astype(np.float64))
    else:
        br = bc = bg = np.zeros(1, np.float64)
    blo = np.minimum(A, len(br) - 1)
    bhi = np.clip(B - 1, blo, len(br) - 1)
    interior[:, :, S_RESETS] = (pr[B] - pr[A]) + (br[bhi] - br[blo])
    interior[:, :, S_CORR] = (pcorr[B] - pcorr[A]) + (bc[bhi] - bc[blo])
    interior[:, :, S_CHANGES] = (pchg[B] - pchg[A]) + (bg[bhi] - bg[blo])
    lo_row = offs[:-1, None]
    hi_row = offs[1:, None] - 1
    fi = np.clip(A, lo_row, hi_row)
    li = np.clip(B - 1, lo_row, hi_row)
    for slot in (S_FIRST_TS, S_FIRST_VAL):
        interior[:, :, slot] = np.where(have, fstats[fi, slot], np.nan)
    for slot in (S_LAST_TS, S_LAST_VAL):
        interior[:, :, slot] = np.where(have, fstats[li, slot], np.nan)
    # min/max over flat runs [A, B): one reduceat per extreme, with a
    # sentinel row so empty runs (masked by ``have``) index in bounds
    ridx = np.empty(2 * S * W, np.int64)
    ridx[0::2] = A.ravel()
    ridx[1::2] = B.ravel()
    mn_ext = np.append(fstats[:, S_MIN], np.inf)
    mx_ext = np.append(fstats[:, S_MAX], -np.inf)
    mn = np.minimum.reduceat(mn_ext, ridx)[0::2].reshape(S, W)
    mx = np.maximum.reduceat(mx_ext, ridx)[0::2].reshape(S, W)
    interior[:, :, S_MIN] = np.where(have, mn, np.nan)
    interior[:, :, S_MAX] = np.where(have, mx, np.nan)
    interior[~have, S_COUNT] = 0.0
    # edges stay per-partition (decoded slices are inherently per-chunk)
    o0 = np.searchsorted(ke, q0, side="right").reshape(S, W) - offs[:-1, None]
    o1 = np.searchsorted(ks, q1, side="right").reshape(S, W) - offs[:-1, None]
    left = np.where(o0 < i0, o0, -1)
    re_idx = o1 - 1
    right = np.where((re_idx >= i1) & (re_idx >= 0)
                     & (re_idx < Cs[:, None]) & (re_idx != left),
                     re_idx, -1)
    touched: set = set()
    zero = np.zeros((W, STATS_WIDTH), np.float64)
    zero[:, S_MIN:S_LAST_VAL + 1] = np.nan
    lstats = np.broadcast_to(zero, (S, W, STATS_WIDTH)).copy()
    rstats = np.broadcast_to(zero, (S, W, STATS_WIDTH)).copy()
    for j in range(S):
        if np.any(left[j] >= 0):
            lstats[j] = _edge_stats(bundles[j], col, left[j], t0s, t1s,
                                    touched)
        if np.any(right[j] >= 0):
            rstats[j] = _edge_stats(bundles[j], col, right[j], t0s, t1s,
                                    touched)
    flat = lambda a: a.reshape(S * W, STATS_WIDTH)  # noqa: E731
    pre = _merge_vec(_merge_vec(flat(lstats), flat(interior)),
                     flat(rstats))
    bufs = np.stack([buf_rows[i] for i in rows_idx]) \
        .reshape(S * W, STATS_WIDTH)
    merged = _merge_vec(pre, bufs)
    both = (pre[:, S_COUNT] > 0) & (bufs[:, S_COUNT] > 0)
    if np.any(bufs[both, S_FIRST_TS] <= pre[both, S_LAST_TS]):
        raise _Bypass  # out-of-order ingest across the seal boundary
    for j, i in enumerate(rows_idx):
        st[i] = merged[j * W:(j + 1) * W]
    stats_acc["sidecar_chunks"] = stats_acc.get("sidecar_chunks", 0) \
        + int((i1 - i0).sum())
    stats_acc["decoded_chunks"] = stats_acc.get("decoded_chunks", 0) \
        + len(touched)


def _eval_group_quantile(sparts, col: int, q: float, t0s, t1s,
                         decode_mode: bool, stats_acc: dict,
                         ctx=None) -> np.ndarray:
    """Approximate quantile_over_time from mergeable sketches (declared
    approximation: FILODB_SIDECAR_APPROX=1). Interior chunks contribute
    their stored sketches; edge/buffer slices are sketched from values."""
    from filodb_tpu.memory.chunk import SKETCH_BUCKETS, _sketch_values
    from filodb_tpu.query.engine.aggregations import sketch_quantile
    P, W = len(sparts), len(t0s)
    gate = _sealed_gate()
    static_serve = not (gate > 0 and P * W > gate)
    serve = static_serve
    if ctx is not None:
        # learned sidecar-vs-decode for the sketch-merge path, same
        # decision site as the stats fold (valve override preserved)
        from filodb_tpu.query import cost_model as cm
        model = cm.model_for(ctx.dataset)
        d = model.decide(
            "sidecar",
            f"quantile:pw{cm.bucket(P * W)}",
            ("sidecar", "decode"),
            static_arm="sidecar" if static_serve else "decode",
            override="sidecar" if gate <= 0 else None,
        )
        model.defer(ctx, d)
        serve = d.arm == "sidecar"
    if not serve:
        raise _Bypass  # per-window sketch merge wouldn't amortize
    out = np.full((P, W), np.nan)
    samples = 0
    for i, p in enumerate(sparts):
        bundle = _part_bundle(p, col, decode_mode)
        _, i0, i1 = _interior_fold(bundle, t0s, t1s)
        b = p._buf
        n = b.n
        btv = bvv = None
        if n:
            btv, bvv = _valid_series(b.ts[:n], b.cols[col - 1][:n])
        for k in range(W):
            sk = np.zeros(SKETCH_BUCKETS, np.int64)
            total = 0
            for c in range(i0[k], i1[k]):
                s = bundle.sketches[c]
                if s is None:
                    raise _Bypass
                sk += s
                total += int(bundle.stats[c, S_COUNT])
            for c in list(range(min(i0[k], len(bundle.chunks)))) \
                    + list(range(i1[k], len(bundle.chunks))):
                ch = bundle.chunks[c]
                if ch.end_time > t0s[k] and ch.start_time <= t1s[k]:
                    fa = _chunk_fa(ch, col)
                    m = (fa.tv > t0s[k]) & (fa.tv <= t1s[k])
                    sk += _sketch_values(fa.vv[m]).astype(np.int64)
                    total += int(m.sum())
            if btv is not None:
                m = (btv > t0s[k]) & (btv <= t1s[k])
                sk += _sketch_values(bvv[m]).astype(np.int64)
                total += int(m.sum())
            if total:
                out[i, k] = sketch_quantile(q, sk)
            samples += total
    stats_acc["sidecar_chunks"] = stats_acc.get("sidecar_chunks", 0)
    stats_acc["samples"] = stats_acc.get("samples", 0.0) + float(samples)
    return out
