"""The TPU execution engine.

Replaces the reference's per-sample sliding-window machinery
(``query/src/main/scala/filodb/query/exec/PeriodicSamplesMapper.scala``,
``rangefn/RangeFunction.scala``, ``rangefn/AggrOverTimeFunctions.scala``) with
a dense, batched formulation designed for XLA/TPU:

1. Selected partitions' chunks are decoded into a padded ``SeriesBatch``:
   ``ts[P, S]`` (int32 millis relative to a base), ``vals[P, S]`` and
   per-series counts. Padding sits at +INT32_MAX so binary search never
   selects it.
2. A one-time ``precompute`` pass builds exclusive prefix sums (values,
   squares, counter-reset corrections, change/reset indicators) and sparse
   min/max tables — O(P·S).
3. Each output step's window reduces to O(1) gathers: window boundaries come
   from a vectorized binary search, windowed sums from prefix-sum differences,
   min/max from the sparse tables, rate/increase from first/last gathers with
   Prometheus counter-reset correction + extrapolation.

Total work is O(P·(S + K·log S)) with perfect batching across series — no
data-dependent control flow, fully jittable, shardable over a device mesh.
"""
