"""Query result model.

Counterpart of reference ``core/src/main/scala/filodb.core/query/``
(``RangeVector.scala:27,121,315``, ``QueryContext.scala:44``, ``ResultTypes``):
but column-oriented — the unit of data flowing through the exec tree is a
``StepMatrix``: a batch of series keys plus a dense [P, K] value matrix (or
[P, K, B] for histogram-valued vectors) over shared step timestamps. NaN marks
"no sample". This is the TPU-first replacement for per-row RangeVector
iterators; a ``StepMatrix`` converts to per-series (ts, value) pairs only at
the API boundary.
"""

from __future__ import annotations

import time as _time
import uuid
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL


@dataclass(frozen=True)
class RangeVectorKey:
    """Series identity: a frozen label set (reference ``RangeVectorKey``)."""

    labels: tuple[tuple[str, str], ...]

    @staticmethod
    def of(labels: dict[str, str]) -> "RangeVectorKey":
        return RangeVectorKey(tuple(sorted(labels.items())))

    @property
    def label_map(self) -> dict[str, str]:
        return dict(self.labels)

    def without(self, names) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels
                                    if k not in ns))

    def only(self, names) -> "RangeVectorKey":
        ns = set(names)
        return RangeVectorKey(tuple((k, v) for k, v in self.labels if k in ns))

    def drop_metric(self) -> "RangeVectorKey":
        # hot on the query path (every output key of every range function);
        # memoized per instance
        cached = self.__dict__.get("_no_metric")
        if cached is None:
            cached = self.without((METRIC_LABEL,))
            object.__setattr__(self, "_no_metric", cached)
        return cached

    def __hash__(self) -> int:
        # dict-key hot (label-aligning thousands of series per query, e.g.
        # the extent-merge path); the dataclass-generated hash recomputes
        # the labels-tuple hash on every call — memoize per instance
        h = self.__dict__.get("_hash")
        if h is None:
            h = hash(self.labels)
            object.__setattr__(self, "_hash", h)
        return h

    def __str__(self) -> str:
        return "{" + ",".join(f"{k}={v}" for k, v in self.labels) + "}"


@dataclass
class StepMatrix:
    """A batch of series sharing step timestamps.

    values: float64 [P, K]; histogram results use values [P, K, B] + les [B].
    """

    keys: list[RangeVectorKey]
    values: np.ndarray
    steps_ms: np.ndarray  # int64 [K] epoch millis
    les: np.ndarray | None = None

    @property
    def num_series(self) -> int:
        return len(self.keys)

    @property
    def num_steps(self) -> int:
        return len(self.steps_ms)

    @property
    def is_histogram(self) -> bool:
        return self.values.ndim == 3

    def compact(self) -> "StepMatrix":
        """Drop series with no samples at all.

        On device-resident values compaction is DEFERRED to
        ``materialize()``: the boolean row mask needs host arrays, and
        fetching here would cost one device→host round trip per query —
        through the axon tunnel that is ~75-90ms, which single-handedly
        capped the batched TPU query path at ~13 q/s. The flag rides along
        so whichever boundary materializes (including the coalesced
        batch-fetch in ``query_range_many``) applies the same mask."""
        if self.num_series == 0:
            return self
        if not isinstance(self.values, np.ndarray):
            self._pending_compact = True
            return self
        keep = self._keep_mask()
        if keep.all():
            return self
        keys = [k for k, m in zip(self.keys, keep) if m]
        return StepMatrix(keys, self.values[keep], self.steps_ms, self.les)

    def derive(self, keys, values, les=None) -> "StepMatrix":
        """Copy-construct a result whose rows still correspond 1:1 to (a
        subset/permutation of) this matrix's rows. Deferred compaction
        carries over: the all-NaN row mask is recomputed from the NEW
        values at materialize(), so reorder/slice/elementwise transforms
        stay correct."""
        out = StepMatrix(keys, values, self.steps_ms, les)
        if getattr(self, "_pending_compact", False):
            out._pending_compact = True
        return out

    def _keep_mask(self) -> np.ndarray:
        if self.is_histogram:
            return ~np.all(np.isnan(self.values[:, :, -1]), axis=1)
        return ~np.all(np.isnan(self.values), axis=1)

    @staticmethod
    def empty(steps_ms: np.ndarray | None = None) -> "StepMatrix":
        steps = steps_ms if steps_ms is not None else np.array([], np.int64)
        return StepMatrix([], np.zeros((0, len(steps))), steps)

    def materialize(self) -> "StepMatrix":
        """Force device-resident values to host numpy (API boundary), then
        apply any compaction deferred while values lived on device (row
        drops mutate in place — callers hold references to this object)."""
        if not isinstance(self.values, np.ndarray):
            self.values = np.asarray(self.values)
        if getattr(self, "_pending_compact", False):
            self._pending_compact = False
            keep = self._keep_mask()
            if not keep.all():
                self.keys = [k for k, m in zip(self.keys, keep) if m]
                self.values = self.values[keep]
        return self

    @staticmethod
    def concat(parts: list["StepMatrix"]) -> "StepMatrix":
        parts = [p for p in parts if p.num_series > 0]
        if not parts:
            return StepMatrix.empty()
        if len(parts) == 1:
            return parts[0]  # keep possibly-device values intact
        keys = [k for p in parts for k in p.keys]
        if any(not isinstance(p.values, np.ndarray) for p in parts):
            # device-resident parts stay on device: a host concat here would
            # force one blocking fetch per scatter-gather leaf (≈90ms each
            # through the axon tunnel); the service boundary materializes once
            import jax.numpy as jnp
            values = jnp.concatenate([jnp.asarray(p.values) for p in parts],
                                     axis=0)
        else:
            values = np.concatenate([p.values for p in parts], axis=0)
        out = StepMatrix(keys, values, parts[0].steps_ms, parts[0].les)
        if any(getattr(p, "_pending_compact", False) for p in parts):
            # deferred compaction survives concatenation (row-preserving
            # transforms use derive()) so the materialize boundary still
            # applies the row mask
            out._pending_compact = True
        return out


@dataclass
class ScalarResult:
    """A per-step scalar (time(), scalar(v), scalar literals)."""

    values: np.ndarray  # [K]
    steps_ms: np.ndarray


@dataclass
class QueryError:
    message: str
    query_id: str = ""


@dataclass
class QueryStats:
    series_scanned: int = 0
    samples_scanned: int = 0
    result_series: int = 0
    wall_time_s: float = 0.0
    cpu_prep_s: float = 0.0
    device_time_s: float = 0.0
    # distributed observability: leaf/decode/reduce attribution merged
    # across remote children by the gather's settle() fold
    chunks_touched: int = 0
    cache_hits: int = 0
    cache_misses: int = 0
    wire_bytes: int = 0
    admission_wait_s: float = 0.0
    decode_s: float = 0.0
    reduce_s: float = 0.0
    # chunk-window folds served from aggregate sidecars without decoding
    # (engine/sidecar_lane.py); decoded edge chunks land in chunks_touched
    sidecar_chunks: int = 0
    # tiered federation (query/federation.py): per-tier attribution of a
    # federated query — {tier: {subqueries, series, samples, chunks,
    # bytes, decodeMs, wallMs}} recorded by TierExec at the routing root;
    # empty for non-federated queries
    tiers: dict = field(default_factory=dict)
    # pyramid-lane attribution (query/engine/pyramid_lane.py): flat
    # numeric counters {bucketNodes, segmentNodes, chunkNodes,
    # decodeNodes, pyramidBytes, payloadBytes} for cold-tier folds
    # served from stored aggregate levels; empty otherwise
    pyramid: dict = field(default_factory=dict)

    def merge_counts(self, other: "QueryStats") -> None:
        """Fold a remote child's stats into this one (count/duration
        accumulators only; wall_time_s/result_series are root-owned)."""
        self.series_scanned += other.series_scanned
        self.samples_scanned += other.samples_scanned
        self.cpu_prep_s += other.cpu_prep_s
        self.device_time_s += other.device_time_s
        self.chunks_touched += other.chunks_touched
        self.cache_hits += other.cache_hits
        self.cache_misses += other.cache_misses
        self.wire_bytes += other.wire_bytes
        self.admission_wait_s += other.admission_wait_s
        self.decode_s += other.decode_s
        self.reduce_s += other.reduce_s
        self.sidecar_chunks += other.sidecar_chunks
        for tier, bucket in other.tiers.items():
            mine = self.tiers.setdefault(tier, {})
            for k, v in bucket.items():
                mine[k] = mine.get(k, 0) + v
        for k, v in other.pyramid.items():
            self.pyramid[k] = self.pyramid.get(k, 0) + v


@dataclass
class TraceContext:
    """Distributed-trace propagation context: rides ``QueryContext`` over
    the plan-shipping wire so remote executors join the root's trace
    (``utils/tracing.py``). ``sampled`` gates remote span collection."""

    trace_id: str = ""
    parent_span_id: int = 0
    sampled: bool = False


@dataclass
class QueryResult:
    result: StepMatrix
    stats: QueryStats = field(default_factory=QueryStats)
    query_id: str = ""
    # partial scatter-gather: some children were lost below the failure
    # threshold (reference HA semantics: degrade, don't fail); the Prom
    # JSON encoder surfaces these as "partial" + "warnings" fields
    partial: bool = False
    warnings: list[str] = field(default_factory=list)
    # remote span-tree ship-back: a sampled executor fills this with
    # Span.as_dict() dicts; the dispatching root grafts them (node-tagged)
    # under its dispatch span and strips them before returning upward
    spans: list = field(default_factory=list)


@dataclass
class PlannerParams:
    """Reference ``PlannerParams`` (spread, sample limits...)."""

    # per-query spread override (reference QueryActor spread overrides,
    # ``QueryActor.scala:56-70``); None = planner default
    spread: "int | None" = None
    sample_limit: int = 1_000_000
    enforce_sample_limit: bool = True
    shard_overrides: list[int] | None = None
    process_failure: bool = True
    # partial scatter-gather tolerance: when True, a gather tolerates
    # child failures up to max_partial_fraction of its children and marks
    # the result partial; above the threshold the query fails. None defers
    # to the process-wide resilience config defaults.
    allow_partial: bool | None = None
    max_partial_fraction: float | None = None
    # per-query scan-time cost budget (utils/governor.QueryBudget); rides
    # the wire with the QueryContext so a distributed query shares one
    # budget across its remote leaves. None = no budget.
    budget: "object | None" = None


@dataclass
class QueryContext:
    """Reference ``QueryContext.scala:44``."""

    query_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])
    submit_time_ms: int = field(
        default_factory=lambda: int(_time.time() * 1000))
    origin: str = ""
    planner_params: PlannerParams = field(default_factory=PlannerParams)
    # distributed tracing: set by traced_query() when the query is sampled
    # (or joins an active trace); remote executors check trace.sampled
    trace: "TraceContext | None" = None


class QueryLimitExceeded(RuntimeError):
    pass
