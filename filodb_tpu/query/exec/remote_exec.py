"""Remote HTTP exec: run a sub-query on another cluster via its Prom API.

Counterpart of reference ``PromQlRemoteExec.scala:1-247`` / ``RemoteExec``:
cross-cluster federation and HA routing ship PromQL text (not plans) to a
remote endpoint's ``query_range`` API and convert the JSON matrix back into
the internal result form.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.query.exec.plan import ExecPlan
from filodb_tpu.query.exec.transformers import steps_array
from filodb_tpu.query.model import RangeVectorKey, StepMatrix
from filodb_tpu.utils.resilience import (
    FaultInjector,
    RemoteQueryError,
    breaker_for,
)


@dataclass
class PromQlRemoteExec(ExecPlan):
    endpoint: str = ""        # e.g. http://host:port/promql/timeseries
    promql: str = ""
    start: int = 0            # ms
    step: int = 60_000
    end: int = 0
    timeout_s: float = 30.0   # cap; the query Deadline shortens it

    def do_execute(self, ctx) -> StepMatrix:
        qs = urllib.parse.urlencode({
            "query": self.promql,
            "start": self.start // 1000,
            "end": self.end // 1000,
            "step": max(self.step // 1000, 1),
        })
        url = f"{self.endpoint}/api/v1/query_range?{qs}"
        breaker = breaker_for(self.endpoint)
        # calling() guarantees the breaker sees exactly one outcome per
        # admitted call — a half-open probe can never stay pending
        with breaker.calling(transport_errors=(urllib.error.URLError,
                                               ConnectionError,
                                               OSError)) as outcome:
            deadline = getattr(ctx, "deadline", None)
            timeout = deadline.timeout(cap=self.timeout_s,
                                       what=f"remote exec {self.endpoint}") \
                if deadline is not None else self.timeout_s
            try:
                FaultInjector.fire("promql.remote", endpoint=self.endpoint)
                with urllib.request.urlopen(url, timeout=timeout) as r:
                    body = json.load(r)
            except urllib.error.HTTPError as e:
                # tag with the endpoint instead of leaking a raw urllib
                # traceback; an HTTP status is the remote ANSWERING — the
                # transport is healthy, so the breaker closes
                outcome.success()
                raise RemoteQueryError(
                    f"remote query to {self.endpoint} failed: "
                    f"HTTP {e.code} {e.reason}") from e
            except json.JSONDecodeError as e:
                # malformed body off a half-dead peer poisons the exchange
                # the same way a reset does
                outcome.failure()
                raise RemoteQueryError(
                    f"remote query to {self.endpoint} returned malformed "
                    f"JSON: {e}") from e
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                outcome.failure()
                reason = getattr(e, "reason", e)
                raise ConnectionError(
                    f"remote query to {self.endpoint} unreachable: "
                    f"{reason}") from e
        if body.get("status") != "success":
            raise RemoteQueryError(
                f"remote query to {self.endpoint} failed: {body}")
        return self._from_matrix_json(body["data"])

    def _from_matrix_json(self, data) -> StepMatrix:
        steps = steps_array(self.start, self.step, self.end)
        idx = {int(t): i for i, t in enumerate(steps)}
        keys, rows = [], []
        for series in data.get("result", []):
            labels = {("_metric_" if k == "__name__" else k): v
                      for k, v in series.get("metric", {}).items()}
            row = np.full(len(steps), np.nan)
            for t, v in series.get("values", []):
                ms = int(float(t) * 1000)
                i = idx.get(ms)
                if i is not None:
                    row[i] = float(v)
            keys.append(RangeVectorKey.of(labels))
            rows.append(row)
        values = np.stack(rows) if rows else np.zeros((0, len(steps)))
        return StepMatrix(keys, values, steps)

    def __repr__(self):
        return f"PromQlRemoteExec({self.endpoint!r}, {self.promql!r})"
