"""Binary join and set operator exec nodes.

Counterpart of reference ``BinaryJoinExec.scala:1-210`` (hash join on label
subsets, one-to-one / group_left / group_right cardinalities) and
``SetOperatorExec.scala:1-281`` (and/or/unless). Label matching happens on
host (small), value computation is a vectorized elementwise kernel over
gathered row indices.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query.engine.instantfns import apply_binary_op
from filodb_tpu.query.exec.plan import ExecContext, NonLeafExecPlan
from filodb_tpu.query.model import RangeVectorKey, StepMatrix


def _join_key(key: RangeVectorKey, on, ignoring) -> RangeVectorKey:
    if on is not None:
        return key.only(on)
    return key.without(tuple(ignoring) + (METRIC_LABEL,))


@dataclass
class BinaryJoinExec(NonLeafExecPlan):
    lhs_plans: list = field(default_factory=list)
    rhs_plans: list = field(default_factory=list)
    op: str = "+"
    cardinality: str = "one-to-one"
    on: tuple[str, ...] | None = None
    ignoring: tuple[str, ...] = ()
    include: tuple[str, ...] = ()
    bool_mode: bool = False

    def children(self):
        return self.lhs_plans + self.rhs_plans

    def do_execute(self, ctx: ExecContext) -> StepMatrix:
        lhs = StepMatrix.concat(
            [p.dispatcher.dispatch(p, ctx).result for p in self.lhs_plans])
        rhs = StepMatrix.concat(
            [p.dispatcher.dispatch(p, ctx).result for p in self.rhs_plans])
        steps = lhs.steps_ms if lhs.num_steps else rhs.steps_ms
        if lhs.num_series == 0 or rhs.num_series == 0:
            return StepMatrix([], np.zeros((0, len(steps))), steps)

        flipped = self.cardinality == "one-to-many"  # group_right
        many, one = (rhs, lhs) if flipped else (lhs, rhs)

        one_index: dict[RangeVectorKey, int] = {}
        for i, k in enumerate(one.keys):
            jk = _join_key(k, self.on, self.ignoring)
            if jk in one_index:
                side = "right" if not flipped else "left"
                raise ValueError(
                    f"multiple matches on {side} side for {jk} "
                    f"(many-to-many not allowed for {self.op})")
            one_index[jk] = i

        if self.cardinality == "one-to-one":
            seen: dict[RangeVectorKey, int] = {}
            for k in many.keys:
                jk = _join_key(k, self.on, self.ignoring)
                seen[jk] = seen.get(jk, 0) + 1
                if seen[jk] > 1:
                    raise ValueError(
                        f"multiple matches on left side for {jk} "
                        f"(use group_left/group_right)")

        many_idx, one_idx, out_keys = [], [], []
        for i, k in enumerate(many.keys):
            jk = _join_key(k, self.on, self.ignoring)
            j = one_index.get(jk)
            if j is None:
                continue
            many_idx.append(i)
            one_idx.append(j)
            out_keys.append(self._result_key(k, one.keys[j]))
        if not many_idx:
            return StepMatrix([], np.zeros((0, len(steps))), steps)

        mv = jnp.asarray(many.values[np.array(many_idx)])
        ov = jnp.asarray(one.values[np.array(one_idx)])
        l_v, r_v = (ov, mv) if flipped else (mv, ov)
        if self.op in ("==", "!=", ">", "<", ">=", "<=") and not self.bool_mode:
            cond = apply_binary_op(self.op, l_v, r_v, bool_mode=True) == 1.0
            out = np.asarray(jnp.where(cond, mv, jnp.nan))
        else:
            out = np.asarray(apply_binary_op(self.op, l_v, r_v,
                                             self.bool_mode))
        return StepMatrix(out_keys, out, steps).compact()

    def _result_key(self, many_key: RangeVectorKey,
                    one_key: RangeVectorKey) -> RangeVectorKey:
        if self.cardinality == "one-to-one":
            if self.on is not None:
                return many_key.only(self.on)
            return many_key.without(tuple(self.ignoring) + (METRIC_LABEL,))
        # group_left/right: keys of the "many" side (metric dropped) plus
        # include labels copied from the "one" side
        lm = many_key.without((METRIC_LABEL,)).label_map
        one_lm = one_key.label_map
        for lbl in self.include:
            if lbl in one_lm:
                lm[lbl] = one_lm[lbl]
            else:
                lm.pop(lbl, None)
        return RangeVectorKey.of(lm)

    def __repr__(self):
        return (f"BinaryJoinExec(op={self.op}, card={self.cardinality}, "
                f"on={self.on}, ignoring={self.ignoring})")


@dataclass
class SetOperatorExec(NonLeafExecPlan):
    """and / or / unless (reference ``SetOperatorExec.scala``). Presence is
    per-step: `and` keeps lhs samples where a matching rhs series has a
    sample at the same step."""

    lhs_plans: list = field(default_factory=list)
    rhs_plans: list = field(default_factory=list)
    op: str = "and"
    on: tuple[str, ...] | None = None
    ignoring: tuple[str, ...] = ()

    def children(self):
        return self.lhs_plans + self.rhs_plans

    def do_execute(self, ctx: ExecContext) -> StepMatrix:
        lhs = StepMatrix.concat(
            [p.dispatcher.dispatch(p, ctx).result for p in self.lhs_plans])
        rhs = StepMatrix.concat(
            [p.dispatcher.dispatch(p, ctx).result for p in self.rhs_plans])
        steps = lhs.steps_ms if lhs.num_steps else rhs.steps_ms
        K = len(steps)

        # per join-key presence masks of rhs, per step
        rhs_present: dict[RangeVectorKey, np.ndarray] = {}
        for i, k in enumerate(rhs.keys):
            jk = _join_key(k, self.on, self.ignoring)
            m = ~np.isnan(rhs.values[i])
            if jk in rhs_present:
                rhs_present[jk] |= m
            else:
                rhs_present[jk] = m

        if self.op == "and":
            keys, vals = [], []
            for i, k in enumerate(lhs.keys):
                jk = _join_key(k, self.on, self.ignoring)
                m = rhs_present.get(jk)
                if m is None:
                    continue
                keys.append(k)
                vals.append(np.where(m, lhs.values[i], np.nan))
            out = (np.stack(vals) if vals else np.zeros((0, K)))
            return StepMatrix(keys, out, steps).compact()

        if self.op == "unless":
            keys, vals = [], []
            for i, k in enumerate(lhs.keys):
                jk = _join_key(k, self.on, self.ignoring)
                m = rhs_present.get(jk)
                v = lhs.values[i] if m is None else np.where(m, np.nan,
                                                             lhs.values[i])
                keys.append(k)
                vals.append(v)
            out = (np.stack(vals) if vals else np.zeros((0, K)))
            return StepMatrix(keys, out, steps).compact()

        if self.op == "or":
            lhs_present: dict[RangeVectorKey, np.ndarray] = {}
            for i, k in enumerate(lhs.keys):
                jk = _join_key(k, self.on, self.ignoring)
                m = ~np.isnan(lhs.values[i])
                if jk in lhs_present:
                    lhs_present[jk] |= m
                else:
                    lhs_present[jk] = m
            keys = list(lhs.keys)
            vals = [lhs.values[i] for i in range(lhs.num_series)]
            for i, k in enumerate(rhs.keys):
                jk = _join_key(k, self.on, self.ignoring)
                lm = lhs_present.get(jk)
                if lm is None:
                    keys.append(k)
                    vals.append(rhs.values[i])
                else:
                    # rhs samples only at steps where no lhs series present
                    v = np.where(lm, np.nan, rhs.values[i])
                    if not np.isnan(v).all():
                        keys.append(k)
                        vals.append(v)
            out = (np.stack(vals) if vals else np.zeros((0, K)))
            return StepMatrix(keys, out, steps).compact()

        raise ValueError(f"unknown set op {self.op}")

    def __repr__(self):
        return f"SetOperatorExec(op={self.op})"
