"""RangeVectorTransformers: per-plan post-processing stages.

Counterpart of reference ``RangeVectorTransformer.scala:1-489`` +
``PeriodicSamplesMapper.scala`` + ``HistogramQuantileMapper.scala`` — but
operating on whole StepMatrix batches; each transformer is host orchestration
around jitted kernels.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query.engine import kernels
from filodb_tpu.query.engine.aggregations import (
    aggregate as agg_kernel,
    histogram_quantile,
    quantile_across,
    topk_mask,
)
from filodb_tpu.query.engine.batch import TS_PAD, SeriesBatch
from filodb_tpu.query.engine.instantfns import apply_binary_op, apply_instant_fn
from filodb_tpu.query.model import RangeVectorKey, ScalarResult, StepMatrix


_GID_CACHE: dict = {}


class RangeVectorTransformer:
    def apply(self, data: StepMatrix) -> StepMatrix:  # pragma: no cover
        raise NotImplementedError


def steps_array(start: int, step: int, end: int) -> np.ndarray:
    """Step timestamps [start, end] inclusive (epoch ms)."""
    if step <= 0:
        return np.array([end], dtype=np.int64)
    return np.arange(start, end + 1, step, dtype=np.int64)


@dataclass
class PeriodicSamplesMapper(RangeVectorTransformer):
    """THE hot windowing operator (reference ``PeriodicSamplesMapper.scala``):
    evaluates a range function (or instant-vector last-sample materialization)
    at each step. Operates on a SeriesBatch via the kernel library — O(P·(S+K))
    instead of per-sample sliding windows."""

    start: int
    step: int
    end: int
    window: int = 0
    function: str | None = None  # None => instant last-sample semantics
    params: tuple = ()
    offset: int = 0
    at_ms: "int | None" = None  # @ modifier: pin evaluation time
    is_counter: bool = False
    keep_metric: bool = False

    def eval_batch(self, batch: SeriesBatch,
                   keys: list[RangeVectorKey]) -> StepMatrix:
        steps = steps_array(self.start, self.step, self.end)
        if self.at_ms is not None:
            eval_steps = np.full(len(steps), self.at_ms - self.offset,
                                 np.int64)
        else:
            eval_steps = steps - self.offset
        rel_steps = (eval_steps - batch.base_ts).astype(np.int32)
        fn = self.function or "last_sample"
        window = self.window if self.function else 300_000  # staleness lookback
        steps_j = jnp.asarray(rel_steps)
        win_j = jnp.asarray(np.int32(window))

        if getattr(batch, "masked", False):
            # device-decoded masked batch (engine/device_batch.py)
            ts_j, vals_j, valid_j = batch.device_arrays()
            if batch.is_histogram:
                import jax

                def per_bucket_m(vb):
                    return kernels.range_eval_masked(
                        fn, ts_j, vb, valid_j, steps_j, win_j,
                        counter=self.is_counter)

                out = jax.vmap(per_bucket_m, in_axes=2, out_axes=2)(vals_j)
                out = np.asarray(out)[: batch.num_series]
                return StepMatrix(self._out_keys(keys), out, steps,
                                  batch.les)
            if fn == "quantile_over_time":
                out = kernels.quantile_over_time_masked(
                    self.params[0], ts_j, vals_j, valid_j, steps_j, win_j)
            elif fn == "holt_winters":
                out = kernels.holt_winters_masked(
                    self.params[0], self.params[1], ts_j, vals_j, valid_j,
                    steps_j, win_j)
            elif fn == "predict_linear":
                out = kernels.range_eval_masked(
                    fn, ts_j, vals_j, valid_j, steps_j, win_j,
                    extra=float(self.params[0]))
            else:
                out = kernels.range_eval_masked(
                    fn, ts_j, vals_j, valid_j, steps_j, win_j,
                    counter=self.is_counter)
            out = out[: batch.num_series]  # stays on device (lazy transfer)
            if fn == "timestamp":
                out = out + batch.base_ts / 1000.0
            return StepMatrix(self._out_keys(keys), out, steps)

        # delta-family fns run on f64-host-corrected, per-series-rebased
        # values (SeriesBatch.delta_host): the f32 device cast then only
        # sees window-scale magnitudes, keeping rate() exact for counters
        # beyond 2^24 (VERDICT r3 #2; reference RateFunctions.scala runs
        # in double throughout). Which fns get the reset CORRECTION
        # mirrors the kernels exactly: rate/increase always, delta only on
        # counter schemas, irate's reset handling is arithmetically
        # equivalent under correction; idelta/deriv are defined on raw
        # values (idelta must keep its negative delta across a reset), so
        # they take the rebase-only lane.
        delta_fns = ("rate", "increase", "delta", "irate", "idelta", "deriv")
        pre_corrected = fn in delta_fns and not batch.is_histogram
        if pre_corrected:
            corrected = fn in ("rate", "increase", "irate") \
                or (fn == "delta" and self.is_counter)
            ts_j, vals_j, counts_j, raw_j = batch.delta_arrays(
                counter=corrected)
            if fn not in ("rate", "increase"):
                raw_j = None  # only the extrapolation clamp consumes it
        else:
            ts_j, vals_j, counts_j = batch.device_arrays()
            raw_j = None

        if batch.is_histogram:
            # apply the range function per bucket: vmap over B
            import jax

            def per_bucket(vb):
                return kernels.range_eval(fn, ts_j, vb, counts_j, steps_j,
                                          win_j, counter=self.is_counter)

            out = jax.vmap(per_bucket, in_axes=2, out_axes=2)(vals_j)
            out = np.asarray(out)[: batch.num_series]
            m = StepMatrix(self._out_keys(keys), out, steps, batch.les)
            return m

        if fn == "quantile_over_time":
            out = kernels.quantile_over_time(self.params[0], ts_j, vals_j,
                                             counts_j, steps_j, win_j)
        elif fn == "holt_winters":
            sf, tf = self.params
            out = kernels.holt_winters(sf, tf, ts_j, vals_j, counts_j,
                                       steps_j, win_j)
        elif fn == "predict_linear":
            out = kernels.range_eval("predict_linear", ts_j, vals_j, counts_j,
                                     steps_j, win_j,
                                     extra=float(self.params[0]))
        else:
            out = kernels.range_eval(fn, ts_j, vals_j, counts_j, steps_j,
                                     win_j, counter=self.is_counter,
                                     pre_corrected=pre_corrected,
                                     raw=raw_j)
        # keep the result on device: downstream aggregation consumes it
        # without a host round trip; the query service materializes the
        # final result once (StepMatrix tolerates device values)
        out = out[: batch.num_series]
        if fn == "timestamp":
            # kernel returned relative seconds; rebase to epoch
            out = out + batch.base_ts / 1000.0
        return StepMatrix(self._out_keys(keys), out, steps)

    def _out_keys(self, keys):
        if self.function and not self.keep_metric:
            return [k.drop_metric() for k in keys]
        return list(keys)

    # matrix-in/matrix-out path (subqueries)
    def apply(self, data: StepMatrix) -> StepMatrix:
        """Apply the range function over an already-evaluated inner matrix
        (subquery): inner steps act as samples."""
        steps = steps_array(self.start, self.step, self.end)
        P = data.num_series
        if P == 0:
            return StepMatrix([], np.zeros((0, len(steps))), steps)
        data.materialize()
        # compact per-series NaN samples into padded ts/vals arrays
        inner_ts = data.steps_ms  # [S]
        S = len(inner_ts)
        base = int(inner_ts[0]) if S else 0
        ts_arr = np.full((P, max(S, 1)), TS_PAD, np.int32)
        vals_arr = np.zeros((P, max(S, 1)), np.float64)
        counts = np.zeros(P, np.int32)
        for i in range(P):
            valid = ~np.isnan(data.values[i])
            n = int(valid.sum())
            counts[i] = n
            ts_arr[i, :n] = (inner_ts[valid] - base).astype(np.int32)
            vals_arr[i, :n] = data.values[i][valid]
        batch = SeriesBatch(base, ts_arr, vals_arr, counts,
                            list(range(P)), data.les)
        return self.eval_batch(batch, data.keys)


@dataclass
class AggregateMapReduce(RangeVectorTransformer):
    """Label-grouped aggregation (reference ``AggregateMapReduce`` +
    RowAggregators), lowered to segment reductions."""

    op: str
    params: tuple = ()
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()

    def bind(self, ctx) -> None:
        # exec-context hook (ExecPlan.execute / leaf chains call bind before
        # apply): gives the aggregation access to the query's cost budget
        self._ctx = ctx

    def group_keys(self, keys: list[RangeVectorKey]) -> list[RangeVectorKey]:
        if self.by:
            return [k.only(self.by) for k in keys]
        if self.without:
            return [k.without(self.without).drop_metric() for k in keys]
        return [RangeVectorKey(()) for _ in keys]

    def _group_ids(self, keys):
        # group-id computations repeat across queries over cached batches
        # (the keys list object is stable); memoize on list identity. Entries
        # hold the keys list itself so the id can't be recycled while cached.
        ck = (id(keys), self.by, self.without)
        hit = _GID_CACHE.get(ck)
        if hit is not None and hit[0] is keys:
            return hit[1], hit[2]
        gkeys = self.group_keys(keys)
        uniq: dict[RangeVectorKey, int] = {}
        gids = np.empty(len(gkeys), np.int32)
        for i, gk in enumerate(gkeys):
            gids[i] = uniq.setdefault(gk, len(uniq))
        out_keys = list(uniq.keys())
        if len(_GID_CACHE) >= 128:
            _GID_CACHE.pop(next(iter(_GID_CACHE)))
        _GID_CACHE[ck] = (keys, gids, out_keys)
        return gids, out_keys

    def apply(self, data: StepMatrix) -> StepMatrix:
        if data.num_series == 0:
            return data
        gids, out_keys = self._group_ids(data.keys)
        G = len(out_keys)
        # scan-time group-cardinality budget: checked BEFORE the aggregation
        # kernel runs, so a runaway group-by fails (or truncates) without
        # paying for the full reduction
        ctx = getattr(self, "_ctx", None)
        budget = getattr(ctx, "budget", None) if ctx is not None else None
        if budget is not None and budget.check_cardinality(ctx, G):
            limit = int(budget.max_group_cardinality)
            idx = np.nonzero(gids < limit)[0]
            data = StepMatrix([data.keys[i] for i in idx],
                              np.asarray(data.values)[idx],
                              data.steps_ms, data.les)
            gids = gids[idx]
            out_keys = out_keys[:limit]
            G = limit
        v = jnp.asarray(data.values)
        g = jnp.asarray(gids)

        if self.op in ("sum", "avg", "count", "min", "max", "stddev",
                       "stdvar", "group"):
            # results stay device-resident (lazy): the exec tree may layer
            # further device transforms, and the service boundary
            # materializes exactly once — no per-node tunnel fetches
            if data.is_histogram:  # hist sum aggregates per bucket
                import jax
                out = jax.vmap(
                    lambda vb: agg_kernel(self.op, vb, g, G),
                    in_axes=2, out_axes=2)(v)
                return StepMatrix(out_keys, out, data.steps_ms, data.les)
            out = agg_kernel(self.op, v, g, G)
            return StepMatrix(out_keys, out, data.steps_ms)

        if self.op in ("topk", "bottomk"):
            k = int(self.params[0])
            mask = np.asarray(topk_mask(v, g, G, k, self.op == "bottomk"))
            vals = np.where(mask, data.values, np.nan)
            return StepMatrix(list(data.keys), vals, data.steps_ms).compact()

        if self.op == "quantile":
            out = quantile_across(float(self.params[0]), v, g, G)
            return StepMatrix(out_keys, out, data.steps_ms)

        if self.op == "count_values":
            label = str(self.params[0])
            # host-side: distinct values become output series. One
            # vectorized np.unique over (group, value, step) triples —
            # the former Python triple loop was O(groups × steps × uniques)
            vals = np.asarray(data.values)
            K = data.num_steps
            mask = ~np.isnan(vals)
            g = np.broadcast_to(gids[:, None], vals.shape)[mask]
            s = np.broadcast_to(np.arange(K)[None, :], vals.shape)[mask]
            v = vals[mask]
            triples = np.stack([g.astype(np.float64), v,
                                s.astype(np.float64)], axis=1)
            uniq, counts = np.unique(triples, axis=0, return_counts=True)
            # distinct (group, value) pairs become the output rows
            pairs, row_of = np.unique(uniq[:, :2], axis=0,
                                      return_inverse=True)
            values = np.full((len(pairs), K), np.nan)
            values[row_of, uniq[:, 2].astype(np.int64)] = counts
            keys = [RangeVectorKey(tuple(sorted(
                list(out_keys[int(gi)].labels) + [(label, _fmt_value(val))])))
                for gi, val in pairs]
            return StepMatrix(keys, values, data.steps_ms)

        raise ValueError(f"unknown aggregation {self.op}")


def _fmt_value(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(v)


# ---------------------------------------------------------------------------
# two-phase aggregation pushdown (map stage on children, reduce at the root)

# reserved label carrying a partial component name ("sum" / "sumsq" /
# "count") from the map stage to the root reduce; never a real series label
AGG_PART_LABEL = "__agg_part__"

# ops whose partials re-reduce with op-correct semantics at the root.
# quantile and count_values need every raw series at once — they stay on
# the declared bypass list (full-gather path).
AGG_PUSHDOWN_OPS = frozenset((
    "sum", "min", "max", "count", "avg", "group", "stddev", "stdvar",
    "topk", "bottomk"))
AGG_PUSHDOWN_BYPASS = frozenset(("quantile", "count_values"))


def _grouped(op: str, v, g, num_groups: int, is_hist: bool):
    """agg_kernel, vmapped over the bucket axis for histogram matrices."""
    if is_hist:
        import jax
        return jax.vmap(lambda vb: agg_kernel(op, vb, g, num_groups),
                        in_axes=2, out_axes=2)(v)
    return agg_kernel(op, v, g, num_groups)


def _part_key(gk: RangeVectorKey, comp: str) -> RangeVectorKey:
    return RangeVectorKey(tuple(sorted(gk.labels
                                       + ((AGG_PART_LABEL, comp),))))


@dataclass
class AggregatePartialMapper(RangeVectorTransformer):
    """Map stage of two-phase aggregation pushdown (the reference runs
    ``AggregateMapReduce`` on each leaf node): emits per-group PARTIAL rows
    so remote children ship one row per group instead of one per series.

    sum/min/max/count/group emit the local aggregate directly (count
    re-reduces via sum at the root); avg ships (sum, count) and
    stddev/stdvar ship (sum, sum-of-squares, count) as component rows
    tagged with ``AGG_PART_LABEL``; topk/bottomk emit the shard's k
    candidate series per group — exact after the root re-rank, because each
    step's global top-k is a subset of the union of per-shard top-k's."""

    op: str
    params: tuple = ()
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()

    def apply(self, data: StepMatrix) -> StepMatrix:
        if data.num_series == 0:
            return data
        amr = AggregateMapReduce(self.op, self.params, self.by, self.without)
        if self.op in ("sum", "min", "max", "count", "group", "topk",
                       "bottomk"):
            return amr.apply(data)
        if self.op == "avg":
            comps = ("sum", "count")
        elif self.op in ("stddev", "stdvar"):
            comps = ("sum", "sumsq", "count")
        else:
            raise ValueError(f"aggregation {self.op!r} is not "
                             f"pushdown-capable")
        gids, out_keys = amr._group_ids(data.keys)
        G = len(out_keys)
        v = jnp.asarray(data.values)
        g = jnp.asarray(gids)
        hist = data.is_histogram
        keys: list[RangeVectorKey] = []
        parts = []
        for comp in comps:
            if comp == "sumsq":
                part = _grouped("sum", v * v, g, G, hist)
            else:
                part = _grouped(comp, v, g, G, hist)
            parts.append(part)
            keys.extend(_part_key(gk, comp) for gk in out_keys)
        return StepMatrix(keys, jnp.concatenate(parts, axis=0),
                          data.steps_ms, data.les)


def _reduce_by_key(m: StepMatrix, op: str) -> StepMatrix:
    """Merge rows with identical keys using ``op`` (root combine of
    pushdown partials: group labels are already reduced on partial rows,
    so grouping is plain full-key identity)."""
    uniq: dict[RangeVectorKey, int] = {}
    gids = np.empty(m.num_series, np.int32)
    for i, k in enumerate(m.keys):
        gids[i] = uniq.setdefault(k, len(uniq))
    G = len(uniq)
    if G == m.num_series:
        return m  # all keys distinct: nothing to merge
    out = _grouped(op, jnp.asarray(m.values), jnp.asarray(gids), G,
                   m.is_histogram)
    return StepMatrix(list(uniq), np.asarray(out), m.steps_ms, m.les)


def _split_components(m: StepMatrix, comps: tuple[str, ...]):
    """Partial rows → (base keys, one aligned [G, K] array per component)."""
    rows: dict[str, dict[RangeVectorKey, np.ndarray]] = {c: {} for c in comps}
    for i, k in enumerate(m.keys):
        lm = dict(k.labels)
        comp = lm.pop(AGG_PART_LABEL, None)
        if comp not in rows:
            raise ValueError(f"partial aggregate row lacks a valid "
                             f"{AGG_PART_LABEL} component: {k}")
        rows[comp][RangeVectorKey(tuple(sorted(lm.items())))] = m.values[i]
    keys = list(rows[comps[0]])
    arrs = []
    for c in comps:
        if set(rows[c]) != set(keys):
            raise ValueError("misaligned partial aggregate components")
        arrs.append(np.stack([rows[c][k] for k in keys]) if keys
                    else m.values[:0])
    return keys, arrs


class PartialAggregateFolder:
    """Root reduce stage of two-phase pushdown: folds per-child partial
    matrices AS THEY ARRIVE — the accumulator stays at O(groups) rows, so
    peak root memory no longer scales with fan-out × cardinality — then
    finalizes multi-component ops (avg, stddev/stdvar)."""

    # how partial rows combine across children, per original op
    _COMBINE = {"sum": "sum", "count": "sum", "min": "min", "max": "max",
                "group": "group", "avg": "sum", "stddev": "sum",
                "stdvar": "sum"}

    def __init__(self, op: str, params=(), by=(), without=()):
        self.op = op
        self.params = params
        self.by = by
        self.without = without
        self._acc: StepMatrix | None = None

    def fold(self, m: StepMatrix) -> None:
        if m is None or m.num_series == 0:
            return
        m.materialize()  # partial rows are tiny; fold on host
        if self._acc is None or self._acc.num_series == 0:
            self._acc = m
            return
        both = StepMatrix.concat([self._acc, m])
        if self.op in ("topk", "bottomk"):
            # re-rank the accumulated candidate union after every fold so
            # the accumulator stays at ≤ groups × k live rows
            self._acc = AggregateMapReduce(
                self.op, self.params, self.by, self.without).apply(both)
        else:
            self._acc = _reduce_by_key(both, self._COMBINE[self.op])

    def finalize(self) -> StepMatrix:
        acc = self._acc
        if acc is None:
            return StepMatrix.empty()
        acc.materialize()
        if self.op == "avg":
            keys, (s, cnt) = _split_components(acc, ("sum", "count"))
            with np.errstate(invalid="ignore", divide="ignore"):
                out = np.where(np.nan_to_num(cnt) > 0, s / cnt, np.nan)
            return StepMatrix(keys, out, acc.steps_ms, acc.les)
        if self.op in ("stddev", "stdvar"):
            keys, (s, s2, cnt) = _split_components(
                acc, ("sum", "sumsq", "count"))
            # the sum-of-squares difference cancels catastrophically in
            # low precision; do the root math in float64 (the kernel-dtype
            # partials still bound equivalence to ~kernel tolerance)
            s, s2, cnt = (x.astype(np.float64) for x in (s, s2, cnt))
            with np.errstate(invalid="ignore", divide="ignore"):
                mean = s / cnt
                var = np.maximum(s2 / cnt - mean * mean, 0.0)
                out = np.where(np.nan_to_num(cnt) > 0,
                               var if self.op == "stdvar" else np.sqrt(var),
                               np.nan)
            return StepMatrix(keys, out, acc.steps_ms, acc.les)
        return acc


@dataclass
class InstantVectorFunctionMapper(RangeVectorTransformer):
    function: str
    args: tuple = ()

    def apply(self, data: StepMatrix) -> StepMatrix:
        if self.function == "hist_to_prom_vectors":
            # first-class histogram → le-labelled bucket series (reference
            # HistToPromSeriesMapper)
            if not data.is_histogram:
                return data
            from filodb_tpu.http.promjson import _flatten_histograms
            return _flatten_histograms(data)
        if self.function in ("histogram_quantile", "histogram_max_quantile"):
            q = float(self.args[0])
            if data.is_histogram:
                out = histogram_quantile(
                    q, jnp.asarray(data.values), jnp.asarray(data.les))
                keys = [k.drop_metric() for k in data.keys]
                return data.derive(keys, out)
            return self._bucket_quantile(q, data)
        vals = jnp.asarray(data.values)
        if self.function in ("hour", "minute", "month", "year", "day_of_month",
                             "day_of_week", "day_of_year", "days_in_month"):
            out = apply_instant_fn(self.function, vals)
        else:
            params = tuple(float(a) for a in self.args)
            out = apply_instant_fn(self.function, vals, params=params)
        keys = [k.drop_metric() for k in data.keys]
        return data.derive(keys, out, data.les)

    def _bucket_quantile(self, q: float, data: StepMatrix) -> StepMatrix:
        """histogram_quantile over prom-style `le`-labelled bucket series
        (reference ``HistogramQuantileMapper.scala:1-149``)."""
        data.materialize()  # host loop over bucket groups below
        groups: dict[RangeVectorKey, list[tuple[float, int]]] = {}
        for i, k in enumerate(data.keys):
            lm = k.label_map
            le = lm.get("le")
            if le is None:
                continue
            gk = k.without(("le", METRIC_LABEL))
            groups.setdefault(gk, []).append((float(le), i))
        if not groups:
            return StepMatrix([], np.zeros((0, data.num_steps)),
                              data.steps_ms)
        # groups sharing one bucket scheme evaluate as ONE batched
        # [G, K, B] quantile call (per-group device calls previously cost
        # ~90% of flat-histogram query time at fleet scale)
        by_les: dict[tuple, list] = {}
        for gk, buckets in groups.items():
            buckets.sort()
            by_les.setdefault(tuple(b[0] for b in buckets),
                              []).append((gk, [b[1] for b in buckets]))
        out_keys = []
        outs = []
        for les_t, members in by_les.items():
            les = np.array(les_t)
            h = data.values[np.array([idx for _, idx in members])]  # [G,B,K]
            # make cumulative counts monotonic across buckets (prom tolerates
            # slight non-monotonicity from scrapes)
            h = np.maximum.accumulate(np.nan_to_num(h, nan=0.0), axis=1)
            res = np.asarray(histogram_quantile(
                q, jnp.asarray(h.transpose(0, 2, 1)),
                jnp.asarray(les)))  # [G, K]
            out_keys.extend(gk for gk, _ in members)
            outs.extend(res)
        return StepMatrix(out_keys, np.stack(outs), data.steps_ms)


@dataclass
class ScalarOperationMapper(RangeVectorTransformer):
    """vector-scalar binary op (reference ``ScalarOperationMapper``)."""

    op: str
    scalar: "ScalarResult | float"
    scalar_is_lhs: bool = True
    bool_mode: bool = False

    _COMPARISONS = ("==", "!=", ">", "<", ">=", "<=")

    def apply(self, data: StepMatrix) -> StepMatrix:
        v = jnp.asarray(data.values)
        if v.size == 0:
            # no series: comparing/combining an empty vector with a
            # scalar is the empty vector (broadcast_to would reject
            # shaping a stepped scalar to the (0, 0) values array)
            return data.derive([k.drop_metric() for k in data.keys], v)
        if isinstance(self.scalar, ScalarResult):
            sc = jnp.asarray(self.scalar.values)[None, :]
        else:
            sc = jnp.asarray(float(self.scalar))
        sc = jnp.broadcast_to(sc, v.shape)
        lhs, rhs = (sc, v) if self.scalar_is_lhs else (v, sc)
        if self.op in self._COMPARISONS and not self.bool_mode:
            # comparison filtering keeps the *vector* sample values
            cond = ~jnp.isnan(apply_binary_op(self.op, lhs, rhs,
                                              bool_mode=True)) \
                & (apply_binary_op(self.op, lhs, rhs, bool_mode=True) == 1.0)
            out = jnp.where(cond, v, jnp.nan)
        else:
            out = apply_binary_op(self.op, lhs, rhs, self.bool_mode)
        keys = [k.drop_metric() for k in data.keys]
        return data.derive(keys, out)


@dataclass
class MiscellaneousFunctionMapper(RangeVectorTransformer):
    function: str
    args: tuple = ()

    def apply(self, data: StepMatrix) -> StepMatrix:
        if self.function == "label_replace":
            dst, repl, src, regex = self.args[:4]
            pat = re.compile(f"^(?:{regex})$")
            keys = []
            for k in data.keys:
                lm = k.label_map
                m = pat.match(lm.get(src, ""))
                if m:
                    val = m.expand(_dollar_to_backslash(repl))
                    if val:
                        lm[dst] = val
                    else:
                        lm.pop(dst, None)
                keys.append(RangeVectorKey.of(lm))
            return data.derive(keys, data.values, data.les)
        if self.function == "label_join":
            dst, sep, *srcs = self.args
            keys = []
            for k in data.keys:
                lm = k.label_map
                lm[dst] = sep.join(lm.get(s, "") for s in srcs)
                keys.append(RangeVectorKey.of(lm))
            return data.derive(keys, data.values, data.les)
        raise ValueError(f"unknown misc function {self.function}")


def _dollar_to_backslash(repl: str) -> str:
    # promql uses $1; python re.expand uses \1
    return re.sub(r"\$(\d+|\{\w+\})", lambda m: "\\" +
                  m.group(1).strip("{}"), repl)


@dataclass
class SortFunctionMapper(RangeVectorTransformer):
    descending: bool = False

    def apply(self, data: StepMatrix) -> StepMatrix:
        if data.num_series == 0:
            return data
        # sort by value at the last step with any data (prom: instant sort)
        v = np.nan_to_num(data.values[:, -1], nan=-np.inf if not
                          self.descending else np.inf)
        order = np.argsort(-v if self.descending else v, kind="stable")
        return data.derive([data.keys[i] for i in order],
                           data.values[order], data.les)


@dataclass
class AbsentFunctionMapper(RangeVectorTransformer):
    filters: tuple = ()
    start: int = 0
    step: int = 1000
    end: int = 0

    def apply(self, data: StepMatrix) -> StepMatrix:
        steps = steps_array(self.start, self.step, self.end)
        if data.num_series == 0:
            present = np.zeros(len(steps), bool)
        else:
            present = ~np.all(np.isnan(data.values), axis=0)
        out = np.where(present, np.nan, 1.0)[None, :]
        labels = {}
        from filodb_tpu.core.filters import Equals
        for f in self.filters:
            if isinstance(f.filter, Equals) and f.column != METRIC_LABEL:
                labels[f.column] = f.filter.value
        if not np.isnan(out).all():
            return StepMatrix([RangeVectorKey.of(labels)], out, steps)
        return StepMatrix([], np.zeros((0, len(steps))), steps)


@dataclass
class LimitFunctionMapper(RangeVectorTransformer):
    limit: int = 1000

    def apply(self, data: StepMatrix) -> StepMatrix:
        if data.num_series <= self.limit:
            return data
        return data.derive(data.keys[: self.limit],
                           data.values[: self.limit], data.les)
