"""ExecPlan tree: physical query execution.

Counterpart of reference ``query/src/main/scala/filodb/query/exec/``.
"""
