"""ExecPlan tree and dispatchers.

Counterpart of reference ``ExecPlan.scala:41,94`` (execute = doExecute →
transformer chain → materialization with limits), ``NonLeafExecPlan`` scatter-
gather, ``PlanDispatcher.scala:20,31`` / ``InProcessPlanDispatcher``,
``MultiSchemaPartitionsExec``/``SelectRawPartitionsExec`` leaves,
``DistConcatExec``, reduce-aggregate execs, ``BinaryJoinExec``,
``SetOperatorExec``, ``StitchRvsExec``, scalar execs.

Distribution note: cross-node aggregation is two-phase, like the reference's
``AggregateMapReduce``-on-leaf design — the planner pushes a map stage
(``AggregatePartialMapper``) into each per-shard/remote child so peers ship
one partial row per group instead of one per series, and
``ReduceAggregateExec`` folds those partials incrementally at the root with
op-correct merge semantics (``quantile``/``count_values`` bypass to the
full-gather path; see ``doc/dist_agg.md``). The device mesh path
(``filodb_tpu/parallel``) additionally reduces on device via collectives.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.core.schemas import ColumnType
from filodb_tpu.query.engine import sidecar_lane
from filodb_tpu.query.engine.batch import build_batch
from filodb_tpu.query.exec.transformers import (
    RangeVectorTransformer,
    steps_array,
)
from filodb_tpu.query.model import (
    QueryContext,
    QueryLimitExceeded,
    QueryResult,
    QueryStats,
    RangeVectorKey,
    ScalarResult,
    StepMatrix,
)
from filodb_tpu.utils.tracing import activate, current_span, current_trace, span


class PlanDispatcher:
    """Ships a plan to where its data lives (reference ``PlanDispatcher``)."""

    def dispatch(self, plan: "ExecPlan", ctx: "ExecContext") -> QueryResult:
        raise NotImplementedError


class InProcessPlanDispatcher(PlanDispatcher):
    """Executes against the local memstore (reference
    ``InProcessPlanDispatcher.scala``)."""

    # stateless: serializes as a bare tag. (Deliberately NOT on the base
    # class — stateful dispatchers like NodeDispatcher must fail at encode
    # time, not silently drop their state.)
    __wire_fields__ = ()

    def dispatch(self, plan, ctx):
        return plan.execute(ctx)


@dataclass
class ExecContext:
    """Execution-time context: data source + query session state."""

    memstore: object  # TimeSeriesMemStore
    dataset: str
    qcontext: QueryContext = field(default_factory=QueryContext)
    stats: QueryStats = field(default_factory=QueryStats)
    # per-query deadline (utils.resilience.Deadline); every downstream
    # socket/HTTP timeout on the distributed path derives from it
    deadline: object = None
    # partial scatter-gather state, accumulated by NonLeafExecPlan.gather
    partial: bool = False
    warnings: list[str] = field(default_factory=list)
    # per-query scan-time cost budget (utils/governor.QueryBudget); checked
    # incrementally in leaf scans and transformers, not just on the final
    # matrix. Defaults from the QueryContext so remote executors pick the
    # root's budget off the wire.
    budget: object = None

    def __post_init__(self):
        if self.budget is None:
            self.budget = getattr(self.qcontext.planner_params,
                                  "budget", None)


def apply_result_budget(data: StepMatrix, ctx: "ExecContext") -> StepMatrix:
    """Enforce the result-bytes budget on a materialized matrix. In
    ``degrade="partial"`` mode the matrix is truncated to the series rows
    that fit the byte budget (the breach is already recorded on ``ctx`` as
    partial + warning); ``degrade="error"`` raises from the check itself."""
    budget = getattr(ctx, "budget", None)
    if budget is None or not isinstance(data.values, np.ndarray) \
            or data.num_series == 0:
        return data
    nbytes = int(data.values.nbytes)
    if not budget.check_result_bytes(ctx, nbytes):
        return data
    per_row = max(1, nbytes // data.num_series)
    keep = max(1, int(budget.max_result_bytes) // per_row)
    if keep >= data.num_series:
        return data
    return StepMatrix(list(data.keys[:keep]), data.values[:keep],
                      data.steps_ms, data.les)


@dataclass
class ExecPlan:
    """A node of the physical plan tree."""

    transformers: list[RangeVectorTransformer] = field(default_factory=list,
                                                      kw_only=True)
    dispatcher: PlanDispatcher = field(
        default_factory=InProcessPlanDispatcher, kw_only=True)

    def execute(self, ctx: ExecContext) -> QueryResult:
        # span per exec node (reference: Kamon "execute-plan" spans,
        # ExecPlan.scala:101); free when no trace is active on this thread
        from filodb_tpu.utils.tracing import span
        with span(type(self).__name__):
            data = self.do_execute(ctx)
            for t in self.transformers:
                if hasattr(t, "bind"):
                    t.bind(ctx)
                with span(type(t).__name__):
                    data = t.apply(data)
        # limits are enforced on the POST-compaction series count on every
        # path; device-resident results defer compaction to materialize(),
        # so their enforcement happens at the service boundary instead
        if isinstance(data.values, np.ndarray) \
                and not getattr(data, "_pending_compact", False):
            self._enforce_limits(data, ctx.qcontext)
            data = apply_result_budget(data, ctx)
        return QueryResult(data, ctx.stats, ctx.qcontext.query_id,
                           partial=ctx.partial, warnings=list(ctx.warnings))

    def do_execute(self, ctx: ExecContext) -> StepMatrix:
        raise NotImplementedError

    def add_transformer(self, t: RangeVectorTransformer) -> "ExecPlan":
        self.transformers.append(t)
        return self

    @staticmethod
    def _enforce_limits(data: StepMatrix, qcontext) -> None:
        pp = qcontext.planner_params
        if pp.enforce_sample_limit:
            samples = data.num_series * data.num_steps
            if samples > pp.sample_limit:
                raise QueryLimitExceeded(
                    f"result samples {samples} > limit {pp.sample_limit}")

    def children(self) -> list["ExecPlan"]:
        return []

    def tree_str(self, indent: int = 0) -> str:
        lines = [" " * indent + repr(self)]
        for t in self.transformers:
            lines.append(" " * (indent + 2) + f"~> {type(t).__name__}")
        for c in self.children():
            lines.append(c.tree_str(indent + 2))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# leaves

@dataclass
class SelectRawPartitionsExec(ExecPlan):
    """Leaf: select partitions on one shard, decode chunks into a batch, and
    run the transformer chain (reference ``MultiSchemaPartitionsExec`` →
    ``SelectRawPartitionsExec``: schema discovery happens here at runtime)."""

    shard: int = 0
    filters: tuple[ColumnFilter, ...] = ()
    chunk_start: int = 0  # ms; already includes lookback extension
    chunk_end: int = 0
    value_column: str | None = None
    # overrides for leaves that read a different store (downsample plans)
    store: object = None
    dataset_name: str | None = None

    def do_execute(self, ctx: ExecContext) -> StepMatrix:
        # sidecar lane: serve the windowing stage from chunk aggregate
        # summaries when the range function decomposes exactly over them
        # (engine/sidecar_lane.py); falls through to the decode lane on any
        # eligibility miss
        # one "scan" span per leaf regardless of which lane serves it —
        # a sidecar bypass mid-fold falls through to the decode scan
        # inside the SAME span, so distributed trace trees keep exactly
        # one scan per shard
        with span("scan", shard=self.shard):
            t_scan = time.perf_counter()
            data = sidecar_lane.try_execute(self, ctx)
            outs = None if data is not None else self._scan_batches(ctx)
            # settle any lane decisions (sidecar/pyramid/paging) the scan
            # deferred onto the context with the arm's observed wall time
            from filodb_tpu.query.cost_model import CostModel
            CostModel.settle_deferred(ctx, time.perf_counter() - t_scan)
        if data is not None:
            with span("reduce"):
                t0 = time.perf_counter()
                for t in self.transformers[1:]:
                    if hasattr(t, "bind"):
                        t.bind(ctx)
                    data = t.apply(data)
                ctx.stats.reduce_s += time.perf_counter() - t0
            return data
        if outs is None:
            return StepMatrix.empty()
        with span("reduce"):
            t0 = time.perf_counter()
            data = self._apply_transformers(outs, ctx)
            ctx.stats.reduce_s += time.perf_counter() - t0
        return data

    def _scan_batches(self, ctx: ExecContext) -> list | None:
        memstore = self.store if self.store is not None else ctx.memstore
        dataset = self.dataset_name or ctx.dataset
        shard = memstore.get_shard(dataset, self.shard)
        part_ids = shard.lookup_partitions(list(self.filters),
                                           self.chunk_start, self.chunk_end)
        max_matches = getattr(shard.config, "max_query_matches", 0)
        if max_matches and len(part_ids) > max_matches:
            # query-size guardrail (reference
            # ensureQueriedDataSizeWithinLimitApprox, OnDemandPagingShard)
            raise QueryLimitExceeded(
                f"query matches {len(part_ids)} series on shard "
                f"{self.shard} > limit {max_matches}")
        parts = [shard.partition(pid) for pid in part_ids]
        parts = [p for p in parts if p is not None]
        ctx.stats.series_scanned += len(parts)
        if not parts:
            return None
        # multi-schema: group by schema, batch per schema
        # (reference MultiSchemaPartitionsExec discovers the schema here)
        by_schema: dict[str, list] = {}
        for p in parts:
            by_schema.setdefault(p.schema.name, []).append(p)
        outs = []
        version = shard.data_version
        leaf_scanned = 0  # budget is per leaf: identical local or remote
        for schema_name, sparts in by_schema.items():
            schema = sparts[0].schema
            col = self._value_col_index(schema)
            cache_key = (schema_name, str(self.filters), self.chunk_start,
                         self.chunk_end, col, tuple(p.part_id for p in sparts))
            cached = shard.batch_cache.get(cache_key)
            if cached is not None and cached[0] == version:
                _, batch, keys, is_counter = cached
                ctx.stats.cache_hits += 1
            else:
                ctx.stats.cache_misses += 1
                # chunk accounting is best-effort: downsample-store
                # PagedReadablePartition duck-types only the read API
                ctx.stats.chunks_touched += sum(
                    len(p.chunks_in_range(self.chunk_start, self.chunk_end,
                                          include_buffer=False))
                    for p in sparts if hasattr(p, "chunks_in_range"))
                t0 = time.perf_counter()
                with span("decode", schema=schema_name,
                          partitions=len(sparts)):
                    # on-demand paging: pull cold chunks for partitions whose
                    # in-memory data doesn't reach back to the query start
                    # (skipped on cache hits — resident data didn't change)
                    extra_chunks = None
                    if shard.config.demand_paging_enabled:
                        from filodb_tpu.core.memstore.odp import (
                            page_partitions,
                        )
                        extra_chunks = page_partitions(
                            shard, sparts, self.chunk_start, self.chunk_end,
                            shard.odp_cache)
                    if self._use_device_path(shard, schema, col):
                        from filodb_tpu.query.engine.device_batch import (
                            build_device_batch,
                        )
                        batch = build_device_batch(sparts, self.chunk_start,
                                                   self.chunk_end, col,
                                                   extra_chunks=extra_chunks)
                    else:
                        batch = build_batch(sparts, self.chunk_start,
                                            self.chunk_end, col,
                                            extra_chunks=extra_chunks)
                ctx.stats.decode_s += time.perf_counter() - t0
                # duck-typed partitions (downsample PagedReadablePartition,
                # cold-tier ColdPartition) count the chunks each read
                # instead — per-tier attribution needs real chunk counts
                ctx.stats.chunks_touched += sum(
                    getattr(p, "chunks_read", 0) for p in sparts
                    if not hasattr(p, "chunks_in_range"))
                keys = [p.part_key.range_vector_key for p in sparts]
                is_counter = schema.data.columns[col].is_counter
                if len(shard.batch_cache) >= shard.batch_cache_cap:
                    shard.batch_cache.pop(next(iter(shard.batch_cache)))
                shard.batch_cache[cache_key] = (version, batch, keys,
                                                is_counter)
            scanned = int(batch.counts.sum())
            ctx.stats.samples_scanned += scanned
            leaf_scanned += scanned
            outs.append((batch, keys, is_counter))
            # incremental scan-time budget: stop scanning further schema
            # groups once the samples budget is breached — partial mode
            # keeps what was already scanned, error mode raises here. The
            # count is LEAF-local, not query-cumulative, so a distributed
            # query degrades identically whether its leaves run in-process
            # (shared stats) or on remote peers (per-peer stats).
            if ctx.budget is not None and ctx.budget.check_samples(
                    ctx, leaf_scanned):
                break
        return outs

    def _apply_transformers(self, outs: list, ctx: ExecContext) -> StepMatrix:
        # the first transformer must be the windowing mapper — it consumes the
        # batch directly; the rest apply to the concatenated step matrix
        from filodb_tpu.query.exec.transformers import PeriodicSamplesMapper
        if not self.transformers or not isinstance(self.transformers[0],
                                                   PeriodicSamplesMapper):
            raise ValueError("leaf transformer chain must start with "
                             "PeriodicSamplesMapper")
        psm, rest = self.transformers[0], self.transformers[1:]
        mats = []
        for batch, keys, is_counter in outs:
            psm.is_counter = is_counter
            mats.append(psm.eval_batch(batch, keys))
        data = StepMatrix.concat(mats) if len(mats) > 1 else mats[0]
        for t in rest:
            if hasattr(t, "bind"):
                t.bind(ctx)
            data = t.apply(data)
        return data

    def execute(self, ctx: ExecContext) -> QueryResult:
        data = self.do_execute(ctx)
        # same post-compaction rule as ExecPlan.execute: device-resident
        # results with deferred compaction enforce at the service boundary
        if isinstance(data.values, np.ndarray) \
                and not getattr(data, "_pending_compact", False):
            self._enforce_limits(data, ctx.qcontext)
            data = apply_result_budget(data, ctx)
        return QueryResult(data, ctx.stats, ctx.qcontext.query_id,
                           partial=ctx.partial, warnings=list(ctx.warnings))

    def _use_device_path(self, shard, schema, col) -> bool:
        """Decode-on-device path: enabled per store config, for scalar float
        columns (histogram columns use the host-decoded path)."""
        if not getattr(shard.config, "device_pages", False):
            return False
        return schema.data.columns[col].ctype in (ColumnType.DOUBLE,
                                                  ColumnType.HISTOGRAM)

    def _value_col_index(self, schema) -> int:
        if self.value_column:
            for i, c in enumerate(schema.data.columns):
                if c.name == self.value_column:
                    return i
        return schema.data.value_column

    def __repr__(self):
        f = ",".join(str(x) for x in self.filters)
        return (f"SelectRawPartitionsExec(shard={self.shard}, filters=[{f}], "
                f"range=[{self.chunk_start},{self.chunk_end}])")


@dataclass
class EmptyResultExec(ExecPlan):
    start: int = 0
    step: int = 1000
    end: int = 0

    def do_execute(self, ctx) -> StepMatrix:
        steps = steps_array(self.start, self.step, self.end)
        return StepMatrix([], np.zeros((0, len(steps))), steps)

    def __repr__(self):
        return "EmptyResultExec"


# ---------------------------------------------------------------------------
# non-leaves

def plan_shards(plan: ExecPlan) -> list[int]:
    """All shard numbers a subtree reads — names the lost data in partial-
    result warnings."""
    out = set()
    shard = getattr(plan, "shard", None)
    if shard is not None:
        out.add(shard)
    for c in plan.children():
        out.update(plan_shards(c))
    return sorted(out)


@dataclass
class NonLeafExecPlan(ExecPlan):
    children_plans: list[ExecPlan] = field(default_factory=list)

    def children(self):
        return self.children_plans

    # child failures tolerated as partial results: transport-level losses
    # (dead peer, reset connection, open breaker, socket timeout). A
    # deterministic remote error or limit violation still fails the query.
    TOLERABLE = (ConnectionError, OSError, TimeoutError)

    def gather(self, ctx) -> list[StepMatrix]:
        """Dispatch children concurrently and tolerate per-child failure
        below the configured threshold (reference: HA scatter-gather
        routes around lost peers instead of failing the query)."""
        mats: list[StepMatrix] = []
        self.gather_each(ctx, mats.append)
        return mats

    def gather_each(self, ctx, fold) -> None:
        """Streaming gather: dispatch children concurrently and feed each
        successful child's matrix to ``fold`` as it becomes available
        instead of holding all gathered matrices. Children settle in child
        order (deterministic downstream row order — topk tie-breaks and
        concat layout must not depend on completion timing), so an
        out-of-order remote completion buffers in its future until its
        predecessors settle; the common case folds one child at a time."""
        from filodb_tpu.utils.resilience import (
            DeadlineExceeded,
            FaultInjector,
            config,
        )
        children = self.children_plans
        if ctx.deadline is not None:
            ctx.deadline.check(type(self).__name__ + ".gather")

        rc = config()
        pp = ctx.qcontext.planner_params
        allow_partial = pp.allow_partial if pp.allow_partial is not None \
            else rc.allow_partial
        max_frac = pp.max_partial_fraction \
            if pp.max_partial_fraction is not None \
            else rc.partial_max_fraction
        failures: list[tuple[int, list[int], Exception]] = []

        # gather workers run on pool threads that don't inherit the caller's
        # thread-local trace; capture the handle (and the open span to parent
        # under) here and adopt it inside run() — a no-op on the calling
        # thread, where the trace is already active
        trace = current_trace()
        parent_span = current_span()

        def run(i, c):
            FaultInjector.fire("gather.child", index=i,
                               shards=plan_shards(c), plan=c)
            if trace is not None:
                with activate(trace, parent_span):
                    return c.dispatcher.dispatch(c, ctx)
            return c.dispatcher.dispatch(c, ctx)

        def settle(i, ok, payload):
            if ok:
                result = payload
                # a remote subtree may itself be partial: merge upward.
                # An in-process child shares THIS ctx, so its warnings are
                # already here — only genuinely new ones are added.
                if getattr(result, "partial", False):
                    ctx.partial = True
                    ctx.warnings.extend(w for w in result.warnings
                                        if w not in ctx.warnings)
                # remote children carry their own stats object; fold its
                # scan/decode/cache/wire counters upward (in-process children
                # share THIS ctx.stats — merging would double-count)
                stats = getattr(result, "stats", None)
                if stats is not None and stats is not ctx.stats:
                    ctx.stats.merge_counts(stats)
                fold(result.result)
                return
            err = payload
            if isinstance(err, DeadlineExceeded) or not allow_partial \
                    or not isinstance(err, self.TOLERABLE):
                raise err
            failures.append((i, plan_shards(children[i]), err))

        pending: dict[int, tuple[bool, object]] = {}
        next_i = 0

        def offer(i, ok, payload):
            nonlocal next_i
            pending[i] = (ok, payload)
            while next_i in pending:
                settle(next_i, *pending.pop(next_i))
                next_i += 1

        # concurrency pays only when children leave the process; local
        # children keep the serial path (no thread hop on the hot path)
        n_remote = sum(1 for c in children
                       if not isinstance(c.dispatcher,
                                         InProcessPlanDispatcher))
        if n_remote and len(children) > 1:
            from concurrent.futures import ThreadPoolExecutor, as_completed
            # per-gather pool: a shared bounded pool deadlocks on nested
            # gathers (parents hold workers while waiting on children).
            # Remote transport connections are pooled process-wide (keyed
            # by peer), so short-lived workers don't cost redials.
            with ThreadPoolExecutor(
                    max_workers=min(n_remote, 16),
                    thread_name_prefix="gather") as ex:
                # only remote children go to the pool: in-process children
                # execute against THIS ctx, whose stats/warnings mutations
                # are not thread-safe — they run on the calling thread
                # (below) while the remote dispatches are in flight
                futs = {ex.submit(run, i, c): i
                        for i, c in enumerate(children)
                        if not isinstance(c.dispatcher,
                                          InProcessPlanDispatcher)}
                remote_idx = set(futs.values())
                for i, c in enumerate(children):
                    if i in remote_idx:
                        continue
                    try:
                        outcome = (True, run(i, c))
                    except Exception as e:  # noqa: BLE001 — sorted in settle
                        outcome = (False, e)
                    offer(i, *outcome)
                for f in as_completed(futs):
                    i = futs[f]
                    try:
                        outcome = (True, f.result())
                    except Exception as e:  # noqa: BLE001 — sorted in settle
                        outcome = (False, e)
                    offer(i, *outcome)
        else:
            for i, c in enumerate(children):
                try:
                    outcome = (True, run(i, c))
                except Exception as e:  # noqa: BLE001 — sorted in settle
                    outcome = (False, e)
                offer(i, *outcome)

        if failures:
            if len(failures) / len(children) > max_frac:
                lost = sorted({s for _, shards, _ in failures
                               for s in shards})
                raise failures[0][2].__class__(
                    f"{len(failures)}/{len(children)} scatter-gather "
                    f"children failed (> partial threshold {max_frac}); "
                    f"lost shards {lost}: {failures[0][2]}")
            ctx.partial = True
            for i, shards, err in failures:
                ctx.warnings.append(
                    f"partial result: child {i} "
                    f"(shards {shards or 'n/a'}) lost: "
                    f"{type(err).__name__}: {err}")


@dataclass
class DistConcatExec(NonLeafExecPlan):
    """Concatenate child results (reference ``LocalPartitionDistConcatExec``)."""

    def do_execute(self, ctx) -> StepMatrix:
        return StepMatrix.concat(self.gather(ctx))

    def __repr__(self):
        return f"DistConcatExec({len(self.children_plans)} children)"


@dataclass
class ReduceAggregateExec(NonLeafExecPlan):
    """Root reduce stage of the aggregation (see module docstring).

    Single-phase form (``pushdown=False``): gather raw per-series child
    matrices and run the whole ``AggregateMapReduce`` at the root.
    Two-phase form (``pushdown=True``): children carry an
    ``AggregatePartialMapper`` in their transformer chains and ship one
    (partial) row per group; this node folds those partials incrementally
    as children arrive and finalizes multi-component ops (avg, stddev,
    stdvar) once — peak root memory scales with group count, not series
    cardinality."""

    op: str = "sum"
    params: tuple = ()
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()
    pushdown: bool = False

    def do_execute(self, ctx) -> StepMatrix:
        from filodb_tpu.query.exec.transformers import (
            AggregateMapReduce,
            PartialAggregateFolder,
        )
        if self.pushdown:
            folder = PartialAggregateFolder(self.op, self.params, self.by,
                                            self.without)
            self.gather_each(ctx, folder.fold)
            with span("reduce", op=self.op):
                t0 = time.perf_counter()
                out = folder.finalize()
                ctx.stats.reduce_s += time.perf_counter() - t0
            return out
        data = StepMatrix.concat(self.gather(ctx))
        amr = AggregateMapReduce(self.op, self.params, self.by, self.without)
        amr.bind(ctx)  # group-cardinality budget sees the query's ctx
        with span("reduce", op=self.op):
            t0 = time.perf_counter()
            out = amr.apply(data)
            ctx.stats.reduce_s += time.perf_counter() - t0
        return out

    def __repr__(self):
        pd = ", pushdown" if self.pushdown else ""
        return (f"ReduceAggregateExec(op={self.op}, by={self.by}, "
                f"without={self.without}{pd}, "
                f"{len(self.children_plans)} children)")


@dataclass
class StitchRvsExec(NonLeafExecPlan):
    """Stitch children evaluated over adjacent time ranges
    (reference ``StitchRvsExec.scala:1-127``)."""

    def do_execute(self, ctx) -> StepMatrix:
        mats = [m for m in self.gather(ctx) if m.num_steps > 0]
        if not mats:
            return StepMatrix.empty()
        mats.sort(key=lambda m: int(m.steps_ms[0]) if m.num_steps else 0)
        all_keys: dict[RangeVectorKey, int] = {}
        for m in mats:
            for k in m.keys:
                all_keys.setdefault(k, len(all_keys))
        steps = np.concatenate([m.steps_ms for m in mats])
        # dedupe overlapping steps, keeping the first occurrence
        uniq_steps, first_idx = np.unique(steps, return_index=True)
        P, K = len(all_keys), len(uniq_steps)
        les = next((m.les for m in mats if m.les is not None), None)
        shape = (P, K) if les is None else (P, K, mats[0].values.shape[2])
        out = np.full(shape, np.nan)
        col = 0
        for m in mats:
            kk = m.num_steps
            cols_global = np.searchsorted(uniq_steps, m.steps_ms)
            rows = np.array([all_keys[k] for k in m.keys], dtype=np.int64)
            if len(rows):
                cur = out[rows[:, None], cols_global[None, :]]
                new = m.values
                take_new = np.isnan(cur) & ~np.isnan(new)
                out[rows[:, None], cols_global[None, :]] = np.where(
                    take_new, new, cur)
            col += kk
        return StepMatrix(list(all_keys.keys()), out,
                          uniq_steps.astype(np.int64), les)

    def __repr__(self):
        return f"StitchRvsExec({len(self.children_plans)} children)"


# ---------------------------------------------------------------------------
# scalar plans

@dataclass
class ScalarFixedDoubleExec(ExecPlan):
    value: float = 0.0
    start: int = 0
    step: int = 1000
    end: int = 0

    def execute_scalar(self, ctx) -> ScalarResult:
        steps = steps_array(self.start, self.step, self.end)
        return ScalarResult(np.full(len(steps), self.value), steps)

    def do_execute(self, ctx) -> StepMatrix:
        s = self.execute_scalar(ctx)
        return StepMatrix([RangeVectorKey(())], s.values[None, :], s.steps_ms)

    def __repr__(self):
        return f"ScalarFixedDoubleExec({self.value})"


@dataclass
class TimeScalarGeneratorExec(ExecPlan):
    function: str = "time"
    start: int = 0
    step: int = 1000
    end: int = 0

    def execute_scalar(self, ctx) -> ScalarResult:
        steps = steps_array(self.start, self.step, self.end)
        if self.function == "time":
            return ScalarResult(steps / 1000.0, steps)
        raise ValueError(f"unknown scalar generator {self.function}")

    def do_execute(self, ctx) -> StepMatrix:
        s = self.execute_scalar(ctx)
        return StepMatrix([RangeVectorKey(())], s.values[None, :], s.steps_ms)

    def __repr__(self):
        return f"TimeScalarGeneratorExec({self.function})"


@dataclass
class ScalarVaryingExec(ExecPlan):
    """scalar(vector): per-step scalar; NaN unless exactly one series."""

    inner: ExecPlan | None = None
    start: int = 0
    step: int = 1000
    end: int = 0

    def execute_scalar(self, ctx) -> ScalarResult:
        data = self.inner.dispatcher.dispatch(self.inner, ctx).result
        if data.num_series == 0:
            # no matching series: still emit NaN per step (an empty inner
            # matrix may carry no steps at all)
            steps = (data.steps_ms if data.num_steps
                     else steps_array(self.start, self.step, self.end))
            return ScalarResult(np.full(len(steps), np.nan), steps)
        present = ~np.isnan(data.values)
        cnt = present.sum(axis=0)
        vals = np.where(cnt == 1, np.nansum(data.values, axis=0), np.nan)
        return ScalarResult(vals, data.steps_ms)

    def do_execute(self, ctx) -> StepMatrix:
        s = self.execute_scalar(ctx)
        return StepMatrix([RangeVectorKey(())], s.values[None, :], s.steps_ms)

    def __repr__(self):
        return "ScalarVaryingExec"


@dataclass
class ScalarBinaryOperationExec(ExecPlan):
    """scalar OP scalar, possibly nested (reference
    ``ScalarBinaryOperationExec``)."""

    op: str = "+"
    lhs: object = 0.0  # float | ExecPlan with execute_scalar
    rhs: object = 0.0
    start: int = 0
    step: int = 1000
    end: int = 0

    def execute_scalar(self, ctx) -> ScalarResult:
        from filodb_tpu.query.engine.instantfns import apply_binary_op
        import jax.numpy as jnp
        steps = steps_array(self.start, self.step, self.end)

        def ev(x):
            if isinstance(x, (int, float)):
                return np.full(len(steps), float(x))
            return x.execute_scalar(ctx).values

        out = np.asarray(apply_binary_op(self.op, jnp.asarray(ev(self.lhs)),
                                         jnp.asarray(ev(self.rhs))))
        return ScalarResult(out, steps)

    def do_execute(self, ctx) -> StepMatrix:
        s = self.execute_scalar(ctx)
        return StepMatrix([RangeVectorKey(())], s.values[None, :], s.steps_ms)

    def __repr__(self):
        return f"ScalarBinaryOperationExec({self.op})"


@dataclass
class VectorFromScalarExec(ExecPlan):
    """vector(scalar) (reference ``VectorFunctionMapper``)."""

    inner: ExecPlan | None = None

    def do_execute(self, ctx) -> StepMatrix:
        s = self.inner.execute_scalar(ctx)
        return StepMatrix([RangeVectorKey(())], s.values[None, :], s.steps_ms)

    def __repr__(self):
        return "VectorFromScalarExec"
