"""LogicalPlanParser: reconstruct PromQL text from a LogicalPlan.

Counterpart of reference ``coordinator/src/main/scala/filodb.coordinator/
queryplanner/LogicalPlanParser.scala``: planners that route sub-plans to
remote clusters over the HTTP API must re-render the plan as a query string
(``PromQlRemoteExec`` carries PromQL, not serialized plans, across cluster
boundaries).
"""

from __future__ import annotations

from filodb_tpu.core.filters import (
    Equals,
    EqualsRegex,
    In,
    NotEquals,
    NotEqualsRegex,
)
from filodb_tpu.core.partkey import METRIC_LABEL
from filodb_tpu.query import logical as lp


def _dur(ms: int) -> str:
    if ms % 3_600_000 == 0:
        return f"{ms // 3_600_000}h"
    if ms % 60_000 == 0:
        return f"{ms // 60_000}m"
    if ms % 1000 == 0:
        return f"{ms // 1000}s"
    return f"{ms}ms"


def _q(v: str) -> str:
    """Quote a label value/pattern as re-parseable PromQL."""
    return '"' + v.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n").replace("\t", "\\t") + '"'


def _selector(filters, column=None) -> str:
    metric = ""
    matchers = []
    for f in filters:
        flt = f.filter
        if f.column == METRIC_LABEL and isinstance(flt, Equals):
            metric = flt.value
            continue
        if isinstance(flt, Equals):
            matchers.append(f'{f.column}={_q(flt.value)}')
        elif isinstance(flt, NotEquals):
            matchers.append(f'{f.column}!={_q(flt.value)}')
        elif isinstance(flt, EqualsRegex):
            matchers.append(f'{f.column}=~{_q(flt.pattern)}')
        elif isinstance(flt, NotEqualsRegex):
            matchers.append(f'{f.column}!~{_q(flt.pattern)}')
        elif isinstance(flt, In):
            import re as _re
            # regex-escape each value: the rendered =~ must match the
            # literal strings, not treat '.' or '|' inside them as regex
            vals = "|".join(_re.escape(v) for v in sorted(flt.values))
            matchers.append(f'{f.column}=~{_q(vals)}')
    body = metric
    if column:
        body += f"::{column}"
    if matchers:
        body += "{" + ",".join(matchers) + "}"
    return body or "{}"


def _offset_suffix(offset: int) -> str:
    return f" offset {_dur(offset)}" if offset else ""


def _at_suffix(at_ms) -> str:
    return f" @ {at_ms // 1000}" if at_ms is not None else ""


def to_promql(plan: lp.LogicalPlan) -> str:
    """Render a LogicalPlan back to PromQL."""
    if isinstance(plan, lp.PeriodicSeries):
        return _selector(plan.raw.filters, plan.raw.column) \
            + _offset_suffix(plan.offset) + _at_suffix(plan.at_ms)
    if isinstance(plan, lp.PeriodicSeriesWithWindowing):
        sel = _selector(plan.raw.filters, plan.raw.column)
        rng = (f"{sel}[{_dur(plan.window)}]{_offset_suffix(plan.offset)}"
               f"{_at_suffix(plan.at_ms)}")
        args = [rng]
        if plan.function == "quantile_over_time":
            args = [str(plan.params[0]), rng]
        elif plan.function in ("holt_winters", "predict_linear"):
            args = [rng] + [_num(p) for p in plan.params]
        return f"{plan.function}({', '.join(args)})"
    if isinstance(plan, lp.SubqueryWithWindowing):
        inner = to_promql(plan.inner)
        sub = (f"{inner}[{_dur(plan.subquery_window)}:"
               f"{_dur(plan.subquery_step)}]{_offset_suffix(plan.offset)}")
        args = [sub]
        if plan.function == "quantile_over_time":
            args = [str(plan.params[0]), sub]
        elif plan.function in ("holt_winters", "predict_linear"):
            args = [sub] + [_num(p) for p in plan.params]
        return f"{plan.function}({', '.join(args)})"
    if isinstance(plan, lp.TopLevelSubquery):
        return to_promql(plan.inner)
    if isinstance(plan, lp.Aggregate):
        inner = to_promql(plan.vector)
        clause = ""
        if plan.by:
            clause = f" by ({', '.join(plan.by)})"
        elif plan.without:
            clause = f" without ({', '.join(plan.without)})"
        if plan.op in ("topk", "bottomk", "quantile", "count_values"):
            p = plan.params[0]
            pstr = f'"{p}"' if isinstance(p, str) else _num(p)
            return f"{plan.op}({pstr}, {inner}){clause}"
        return f"{plan.op}({inner}){clause}"
    if isinstance(plan, lp.BinaryJoin):
        l, r = to_promql(plan.lhs), to_promql(plan.rhs)
        mods = []
        if plan.bool_mode:
            mods.append("bool")
        if plan.on is not None:
            mods.append(f"on ({', '.join(plan.on)})")
        elif plan.ignoring:
            mods.append(f"ignoring ({', '.join(plan.ignoring)})")
        if plan.cardinality == "many-to-one":
            mods.append(f"group_left ({', '.join(plan.include)})"
                        if plan.include else "group_left")
        elif plan.cardinality == "one-to-many":
            mods.append(f"group_right ({', '.join(plan.include)})"
                        if plan.include else "group_right")
        mod = (" " + " ".join(mods)) if mods else ""
        return f"({l} {plan.op}{mod} {r})"
    if isinstance(plan, lp.ScalarVectorBinaryOperation):
        s = to_promql(plan.scalar)
        v = to_promql(plan.vector)
        b = "bool " if plan.bool_mode else ""
        if plan.scalar_is_lhs:
            return f"({s} {plan.op} {b}{v})"
        return f"({v} {plan.op} {b}{s})"
    if isinstance(plan, lp.ApplyInstantFunction):
        inner = to_promql(plan.vector)
        args = [_num(a) if isinstance(a, (int, float)) else str(a)
                for a in plan.args]
        if plan.function == "histogram_quantile":
            return f"histogram_quantile({args[0]}, {inner})"
        all_args = ", ".join([inner] + args)
        return f"{plan.function}({all_args})"
    if isinstance(plan, lp.ApplyMiscellaneousFunction):
        inner = to_promql(plan.vector)
        args = ", ".join(f'"{a}"' for a in plan.args)
        return f"{plan.function}({inner}, {args})" if args \
            else f"{plan.function}({inner})"
    if isinstance(plan, lp.ApplySortFunction):
        fn = "sort_desc" if plan.descending else "sort"
        return f"{fn}({to_promql(plan.vector)})"
    if isinstance(plan, lp.ApplyAbsentFunction):
        return f"absent({to_promql(plan.vector)})"
    if isinstance(plan, lp.ApplyLimitFunction):
        return f"limit({plan.limit}, {to_promql(plan.vector)})"
    if isinstance(plan, lp.ScalarFixedDoublePlan):
        return _num(plan.value)
    if isinstance(plan, lp.ScalarTimeBasedPlan):
        return f"{plan.function}()"
    if isinstance(plan, lp.ScalarVaryingDoublePlan):
        return f"scalar({to_promql(plan.vector)})"
    if isinstance(plan, lp.ScalarBinaryOperation):
        l = _num(plan.lhs) if isinstance(plan.lhs, (int, float)) \
            else to_promql(plan.lhs)
        r = _num(plan.rhs) if isinstance(plan.rhs, (int, float)) \
            else to_promql(plan.rhs)
        return f"({l} {plan.op} {r})"
    if isinstance(plan, lp.VectorPlan):
        return f"vector({to_promql(plan.scalar)})"
    if isinstance(plan, lp.RawSeries):
        return _selector(plan.filters, plan.column)
    raise ValueError(f"cannot render {type(plan).__name__} to PromQL")


def _num(x) -> str:
    f = float(x)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)
