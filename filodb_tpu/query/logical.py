"""LogicalPlan algebra.

Counterpart of reference ``query/src/main/scala/filodb/query/LogicalPlan.scala:6-509``
and ``PlanEnums.scala``: the planner-facing description of a query, produced by
the PromQL front end and materialized into ExecPlans by the planners.

Times are epoch millis throughout (reference uses millis too); windows/offsets
are millis.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from filodb_tpu.core.filters import ColumnFilter

# --- enums (reference PlanEnums.scala) -------------------------------------

AGGREGATION_OPERATORS = {
    "sum", "avg", "count", "min", "max", "stddev", "stdvar", "topk",
    "bottomk", "quantile", "count_values", "group",
}

RANGE_FUNCTIONS = {
    "rate", "increase", "delta", "idelta", "irate", "resets", "changes",
    "deriv", "predict_linear", "holt_winters", "avg_over_time",
    "min_over_time", "max_over_time", "sum_over_time", "count_over_time",
    "stddev_over_time", "stdvar_over_time", "quantile_over_time",
    "last_over_time", "present_over_time", "absent_over_time", "timestamp",
    "zscore",
}

INSTANT_FUNCTIONS = {
    "abs", "ceil", "clamp", "clamp_max", "clamp_min", "exp", "floor",
    "histogram_quantile", "ln", "log10", "log2", "round", "sgn", "sqrt",
    "day_of_month", "day_of_week", "day_of_year", "days_in_month", "hour",
    "minute", "month", "year", "sin", "cos", "tan", "asin", "acos", "atan",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "deg", "rad",
    "histogram_max_quantile", "hist_to_prom_vectors",
}

MISC_FUNCTIONS = {"label_replace", "label_join", "sort", "sort_desc",
                  "absent", "scalar", "vector", "time", "pi"}


class LogicalPlan:
    """Base of the plan algebra."""

    def is_raw_series(self) -> bool:
        return isinstance(self, RawSeries)


# --- leaf / series plans ----------------------------------------------------


@dataclass(frozen=True)
class RawSeries(LogicalPlan):
    """Select raw chunks for matching series over [start-lookback, end]
    (reference ``RawSeries``)."""

    filters: tuple[ColumnFilter, ...]
    range_start: int  # ms
    range_end: int    # ms
    lookback: int = 0
    offset: int = 0
    column: str | None = None  # explicit value column (::sum etc.)


@dataclass(frozen=True)
class RawChunkMeta(LogicalPlan):
    """Chunk metadata debug query (reference ``RawChunkMeta``)."""

    filters: tuple[ColumnFilter, ...]
    range_start: int
    range_end: int
    column: str = ""


# --- periodic (step) plans --------------------------------------------------


@dataclass(frozen=True)
class PeriodicSeries(LogicalPlan):
    """Instant-vector materialization at each step: latest sample within
    the staleness lookback (reference ``PeriodicSeries``)."""

    raw: RawSeries
    start: int
    step: int
    end: int
    offset: int = 0
    at_ms: int | None = None  # @ modifier: fixed evaluation time


@dataclass(frozen=True)
class PeriodicSeriesWithWindowing(LogicalPlan):
    """Range function over a window at each step
    (reference ``PeriodicSeriesWithWindowing``)."""

    raw: RawSeries
    start: int
    step: int
    end: int
    window: int
    function: str  # one of RANGE_FUNCTIONS
    params: tuple = ()
    offset: int = 0
    at_ms: int | None = None  # @ modifier: fixed evaluation time


@dataclass(frozen=True)
class SubqueryWithWindowing(LogicalPlan):
    """Range function applied over a subquery's inner plan
    (reference ``SubqueryWithWindowing:199``)."""

    inner: LogicalPlan
    start: int
    step: int
    end: int
    function: str
    params: tuple
    subquery_window: int
    subquery_step: int
    offset: int = 0


@dataclass(frozen=True)
class TopLevelSubquery(LogicalPlan):
    """Top-level subquery sampling (reference ``TopLevelSubquery:239``)."""

    inner: LogicalPlan
    start: int
    step: int
    end: int
    original_step: int = 0


# --- transforms -------------------------------------------------------------


@dataclass(frozen=True)
class Aggregate(LogicalPlan):
    op: str
    vector: LogicalPlan
    params: tuple = ()
    by: tuple[str, ...] = ()
    without: tuple[str, ...] = ()


@dataclass(frozen=True)
class BinaryJoin(LogicalPlan):
    lhs: LogicalPlan
    op: str
    rhs: LogicalPlan
    cardinality: str = "one-to-one"  # one-to-one|many-to-one|one-to-many|many-to-many
    on: tuple[str, ...] | None = None
    ignoring: tuple[str, ...] = ()
    include: tuple[str, ...] = ()  # group_left/right labels
    bool_mode: bool = False


@dataclass(frozen=True)
class ScalarVectorBinaryOperation(LogicalPlan):
    op: str
    scalar: LogicalPlan  # scalar-producing plan
    vector: LogicalPlan
    scalar_is_lhs: bool = True
    bool_mode: bool = False


@dataclass(frozen=True)
class ApplyInstantFunction(LogicalPlan):
    vector: LogicalPlan
    function: str
    args: tuple = ()  # scalar plans or literals


@dataclass(frozen=True)
class ApplyMiscellaneousFunction(LogicalPlan):
    vector: LogicalPlan
    function: str  # label_replace | label_join | ...
    args: tuple = ()


@dataclass(frozen=True)
class ApplySortFunction(LogicalPlan):
    vector: LogicalPlan
    descending: bool = False


@dataclass(frozen=True)
class ApplyAbsentFunction(LogicalPlan):
    vector: LogicalPlan
    filters: tuple[ColumnFilter, ...]
    start: int
    step: int
    end: int


@dataclass(frozen=True)
class ApplyLimitFunction(LogicalPlan):
    vector: LogicalPlan
    limit: int


# --- scalar plans -----------------------------------------------------------


@dataclass(frozen=True)
class ScalarFixedDoublePlan(LogicalPlan):
    value: float
    start: int = 0
    step: int = 0
    end: int = 0


@dataclass(frozen=True)
class ScalarTimeBasedPlan(LogicalPlan):
    function: str  # time | pi | scalar fns of time: hour, month...
    start: int = 0
    step: int = 0
    end: int = 0


@dataclass(frozen=True)
class ScalarVaryingDoublePlan(LogicalPlan):
    """scalar(vector) — per-step scalar from a 1-series vector."""

    vector: LogicalPlan
    function: str = "scalar"


@dataclass(frozen=True)
class ScalarBinaryOperation(LogicalPlan):
    op: str
    lhs: LogicalPlan | float
    rhs: LogicalPlan | float
    start: int = 0
    step: int = 0
    end: int = 0


@dataclass(frozen=True)
class VectorPlan(LogicalPlan):
    """vector(scalar) — 1-series vector from a scalar."""

    scalar: LogicalPlan


# --- metadata plans ---------------------------------------------------------


@dataclass(frozen=True)
class LabelValues(LogicalPlan):
    label: str
    filters: tuple[ColumnFilter, ...] = ()
    start: int = 0
    end: int = 0


@dataclass(frozen=True)
class LabelNames(LogicalPlan):
    filters: tuple[ColumnFilter, ...] = ()
    start: int = 0
    end: int = 0


@dataclass(frozen=True)
class SeriesKeysByFilters(LogicalPlan):
    filters: tuple[ColumnFilter, ...]
    start: int = 0
    end: int = 0


# --- utilities --------------------------------------------------------------


def leaf_raw_series(plan: LogicalPlan) -> list[RawSeries]:
    """All RawSeries leaves of a plan tree."""
    out: list[RawSeries] = []

    def walk(p):
        if isinstance(p, RawSeries):
            out.append(p)
            return
        for f in getattr(p, "__dataclass_fields__", {}):
            v = getattr(p, f)
            if isinstance(v, LogicalPlan):
                walk(v)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, LogicalPlan):
                        walk(x)

    walk(plan)
    return out
