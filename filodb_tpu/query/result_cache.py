"""Step-aligned range-query splitting with an immutable-extent result cache.

The Cortex/Thanos query-frontend pattern, built into ``QueryService``: a
range query's step grid is split at step-aligned *extent* boundaries, each
extent evaluated as an independent sub-query, and per-extent result
matrices cached keyed on a canonical (time-blanked) logical-plan signature
plus the extent bounds. Because a sub-query's logical plan keeps its
``lookback``/``window``/``offset`` fields and the planner widens the chunk
scan by them at materialization (``SingleClusterPlanner._leaves``), range
functions (``rate``, ``increase``, ``*_over_time``) are exact at extent
seams — no samples are missing from any window that straddles a boundary.

Invalidation is the core trick: extents that end at or before the dataset's
**mutable horizon** (min over local shards of the max ingested timestamp,
minus a configurable out-of-order allowance) can never be changed by
further ingest, so they are cached with NO version stamp — ingest cannot
orphan them. Only the head extent past the horizon carries the dataset's
``data_version`` and is recomputed whenever ingest has advanced. This is
what makes the cache effective under live ingestion, where the exact-match
rendered-response cache (``filodb_tpu/http/server.py``) has ~0% hit rate
(its stamp bumps on every row).

Each extent is evaluated on its FULL aligned grid (``extent_steps`` steps),
cached once, and sliced to the requested sub-range at merge time. Partial
head/edge extents would otherwise produce a different step count every
dashboard refresh — a fresh XLA compile per refresh on the batched kernel
path — while full extents give every sub-query the same shape and let
queries with different (same-phase) starts share entries.

Splicing is *semantics-preserving*, not bit-identical: the windowed kernels
are prefix-sum based, so evaluating the same step over a different chunk
batch can differ in the last ulp. Absent-series fill is NaN, which matches
the aggregation kernels' ``cnt == 0 → NaN`` convention exactly.

Anything the splitter can't prove safe bypasses the cache wholesale:
instant queries (step 0), subqueries, ``absent()``/``absent_over_time``,
``sort``/``limit`` (cross-extent ordering), ``@`` modifiers and negative
offsets (extent immutability undecidable), metadata plans, and any result
that comes back partial or with warnings (PR 1 degraded scatter-gather) is
never stored.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict

import numpy as np

from filodb_tpu.query import logical as lp
from filodb_tpu.query.model import (
    QueryContext,
    QueryResult,
    QueryStats,
    StepMatrix,
)
from filodb_tpu.utils.metrics import Gauge, get_counter
from filodb_tpu.utils.tracing import span

cache_hits = get_counter("filodb_result_cache_hits")
cache_misses = get_counter("filodb_result_cache_misses")
cache_partial_hits = get_counter("filodb_result_cache_partial_hits")
cache_evictions = get_counter("filodb_result_cache_evictions")
cache_bytes = Gauge("filodb_result_cache_bytes")

# Predicted recompute wall time below which an extent admits at low
# priority (it's cheaper to recompute than the cache space it occupies).
_CHEAP_RECOMPUTE_S = 0.002


@dataclasses.dataclass
class ResultCacheConfig:
    """``result_cache`` config block (``filodb_tpu.config.DEFAULTS``)."""

    enabled: bool = True
    # extent length in steps; dashboards advancing one step per refresh
    # recompute only the head extent plus at most one partial edge extent
    extent_steps: int = 32
    # byte budget for cached matrices (LRU beyond it)
    max_bytes: int = 256 * 1024 * 1024
    # how far behind the max ingested timestamp a row may still arrive;
    # extents ending earlier than (max_ts - allowance) are immutable
    ooo_allowance_ms: int = 300_000

    @staticmethod
    def from_dict(d: dict) -> "ResultCacheConfig":
        known = {f.name for f in dataclasses.fields(ResultCacheConfig)}
        return ResultCacheConfig(**{k: v for k, v in d.items() if k in known})


# Plan node types that make a query unsplittable. Subqueries re-sample the
# inner plan on their own grid; absent() needs the whole range to decide
# emptiness; sort/limit order or truncate series by values across the whole
# range, which splicing would not preserve.
_BYPASS_NODES = (
    lp.SubqueryWithWindowing,
    lp.TopLevelSubquery,
    lp.ApplyAbsentFunction,
    lp.ApplySortFunction,
    lp.ApplyLimitFunction,
    lp.RawChunkMeta,
    lp.LabelValues,
    lp.LabelNames,
    lp.SeriesKeysByFilters,
)


def splittable_grid(plan: lp.LogicalPlan) -> tuple[int, int, int] | None:
    """The single (start, step, end) grid every periodic node of ``plan``
    evaluates on, or None when the plan must bypass the splitter."""
    grids: list[tuple[int, int, int]] = []
    ok = True

    def walk(p):
        nonlocal ok
        if not ok:
            return
        if isinstance(p, _BYPASS_NODES):
            ok = False
            return
        if isinstance(p, lp.RawSeries):
            # a bare selector (no periodic sampling) returns raw samples;
            # its output is not on a step grid
            ok = False
            return
        if isinstance(p, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing)):
            if p.at_ms is not None or p.offset < 0 or p.raw.offset < 0 \
                    or p.step <= 0 or p.end < p.start:
                # @ fixes evaluation time (extent immutability is about the
                # evaluation window, which @ decouples from the grid);
                # negative offsets read the future relative to the extent
                ok = False
                return
            grids.append((p.start, p.step, p.end))
            return
        if dataclasses.is_dataclass(p):
            for f in dataclasses.fields(p):
                v = getattr(p, f.name)
                if isinstance(v, lp.LogicalPlan):
                    walk(v)
                elif isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, lp.LogicalPlan):
                            walk(x)

    walk(plan)
    if not ok or not grids:
        return None
    g0 = grids[0]
    if any(g != g0 for g in grids):
        return None
    return g0


def retime_extent(plan: lp.LogicalPlan, start: int, end: int):
    """Rebind a splittable plan tree onto the [start, end] extent grid.

    Periodic nodes keep step/window/lookback/offset — only the evaluation
    range moves, so the planner re-widens the chunk scan per extent and
    window functions stay exact at seams. With ``start == end == 0`` this
    doubles as the canonical plan *signature*: two queries that differ only
    in evaluation range retime to equal (hashable, frozen) trees.
    """
    if isinstance(plan, (lp.PeriodicSeries, lp.PeriodicSeriesWithWindowing)):
        raw = dataclasses.replace(plan.raw, range_start=start, range_end=end)
        return dataclasses.replace(plan, raw=raw, start=start, end=end)
    if not dataclasses.is_dataclass(plan):
        return plan
    changes = {}
    for f in dataclasses.fields(plan):
        v = getattr(plan, f.name)
        if f.name == "start" and isinstance(v, int):
            changes[f.name] = start
        elif f.name == "end" and isinstance(v, int):
            changes[f.name] = end
        elif isinstance(v, lp.LogicalPlan):
            changes[f.name] = retime_extent(v, start, end)
        elif isinstance(v, tuple) and any(isinstance(x, lp.LogicalPlan)
                                          for x in v):
            changes[f.name] = tuple(
                retime_extent(x, start, end) if isinstance(x, lp.LogicalPlan)
                else x for x in v)
    return dataclasses.replace(plan, **changes) if changes else plan


def plan_signature(plan: lp.LogicalPlan):
    """Canonical, hashable signature: the plan with its evaluation range
    blanked. Selectors, functions, windows, offsets, steps all remain."""
    return retime_extent(plan, 0, 0)


def split_extents(start: int, step: int, end: int, extent_steps: int
                  ) -> list[tuple[int, int]]:
    """Split the inclusive step grid {start + k*step <= end} at absolute
    extent boundaries (multiples of ``extent_steps * step``), returning
    [(first_step, last_step)] per extent. Boundaries are absolute — NOT
    relative to ``start`` — so a dashboard window sliding one step per
    refresh keeps hitting the same interior extents."""
    extent_ms = extent_steps * step
    last = start + ((end - start) // step) * step
    out: list[tuple[int, int]] = []
    cur = start
    while cur <= last:
        bound = (cur // extent_ms + 1) * extent_ms  # exclusive
        k = (bound - 1 - cur) // step
        ext_last = min(cur + k * step, last)
        out.append((cur, ext_last))
        cur = ext_last + step
    return out


def _matrix_nbytes(m: StepMatrix) -> int:
    n = int(m.values.nbytes) + int(m.steps_ms.nbytes)
    if m.les is not None:
        n += int(np.asarray(m.les).nbytes)
    # label tuples are shared/interned; charge a flat overhead per key
    return n + 64 * len(m.keys) + 256


class ResultCache:
    """Byte-budgeted LRU of per-extent result matrices.

    Entries: (signature, full_extent_start, full_extent_end) →
    (stamp, StepMatrix), the full aligned extent grid regardless of how
    much of it the triggering query needed.
    ``stamp`` is None for immutable extents (never orphaned by ingest) and
    the dataset ``data_version`` for the mutable head (self-invalidates on
    any applied write). Stored matrices are host-resident and compacted;
    ``execute`` copies values out at merge time, so cached arrays are never
    aliased into mutable results.
    """

    def __init__(self, config: ResultCacheConfig | None = None):
        self.config = config or ResultCacheConfig()
        self._lru: "OrderedDict[tuple, tuple[int | None, StepMatrix]]" = \
            OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        # low-priority admissions: extents that are cheap to recompute
        # (pyramid-served, or predicted-cheap by the cost model) evict
        # before any payload-decoding entry under byte pressure
        self._cheap: set = set()

    @staticmethod
    def from_config(cfg) -> "ResultCache | None":
        """Build from a ``result_cache`` config dict (or passthrough an
        existing instance); None when disabled."""
        if cfg is None or cfg is False:
            return None
        if isinstance(cfg, ResultCache):
            return cfg
        if isinstance(cfg, ResultCacheConfig):
            conf = cfg
        elif isinstance(cfg, dict):
            conf = ResultCacheConfig.from_dict(cfg)
        elif cfg is True:
            conf = ResultCacheConfig()
        else:
            raise TypeError(f"bad result_cache config: {cfg!r}")
        return ResultCache(conf) if conf.enabled else None

    # ---- LRU ------------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def _get(self, key: tuple, stamp: int | None) -> StepMatrix | None:
        with self._lock:
            entry = self._lru.get(key)
            if entry is None or entry[0] != stamp:
                return None
            self._lru.move_to_end(key)
            return entry[1]

    def _put(self, key: tuple, stamp: int | None, m: StepMatrix,
             cheap: bool = False) -> None:
        nb = _matrix_nbytes(m)
        if nb > self.config.max_bytes:
            return  # larger than the whole budget: don't thrash
        with self._lock:
            old = self._lru.pop(key, None)
            if old is not None:
                self._bytes -= _matrix_nbytes(old[1])
            self._cheap.discard(key)
            self._lru[key] = (stamp, m)
            self._bytes += nb
            if cheap:
                self._cheap.add(key)
            while self._bytes > self.config.max_bytes and self._lru:
                # cheap-to-recompute entries go first (oldest cheap entry),
                # then plain LRU order — a payload-decoding extent outlives
                # every pyramid-served one under byte pressure
                ev_key = None
                if self._cheap:
                    ev_key = next((k for k in self._lru if k in self._cheap),
                                  None)
                if ev_key is None:
                    ev_key, (_, ev) = self._lru.popitem(last=False)
                else:
                    _, ev = self._lru.pop(ev_key)
                self._cheap.discard(ev_key)
                self._bytes -= _matrix_nbytes(ev)
                cache_evictions.inc()
            cache_bytes.set(self._bytes)

    def clear(self) -> None:
        with self._lock:
            self._lru.clear()
            self._cheap.clear()
            self._bytes = 0
            cache_bytes.set(0)

    # ---- split / execute / merge ----------------------------------------

    def execute(self, svc, plan: lp.LogicalPlan,
                qcontext: QueryContext | None = None) -> QueryResult | None:
        """Answer ``plan`` by extent splitting, or None to signal the
        caller to take the uncached path (bypass)."""
        qcontext = qcontext or QueryContext()
        pp = qcontext.planner_params
        if pp.shard_overrides or pp.spread is not None:
            return None  # per-query routing overrides change what's read
        grid = splittable_grid(plan)
        if grid is None:
            return None
        start, step, end = grid
        shards = svc.memstore.shards_for(svc.dataset)
        if len(shards) < getattr(svc, "num_shards", 1):
            # remote shards: local versions/horizons can't witness their
            # ingest (same rule as http.server.service_version)
            return None
        extents = split_extents(start, step, end, self.config.extent_steps)
        # Read version BEFORE evaluating the head extent: rows ingested
        # while we compute make the stored stamp stale, so the entry
        # self-invalidates instead of serving a pre-ingest result.
        version = sum(s.data_version for s in shards)
        max_ts = min((s.max_ingested_ts for s in shards), default=-1)
        horizon = max_ts - self.config.ooo_allowance_ms
        # standing-query hook (rules/manager.py): recording rules write
        # series AT timestamps at/below the ingest horizon, i.e. inside
        # the "immutable" region. Clamp immutability to what the rules
        # have verifiably written so an extent of a rule-output series is
        # never frozen before the rule's write lands; extents past the
        # clamp carry a version stamp and self-invalidate on the write.
        floor = getattr(svc, "rules_horizon_floor", None)
        if floor is not None:
            horizon = min(horizon, floor() if callable(floor) else floor)
        sig = plan_signature(plan)
        # tiered federation: the signature stays tier-INVARIANT (the grid
        # splits before tier routing, so a repeat query hits the same key
        # no matter which tier serves an extent), but tier MEMBERSHIP is
        # part of it — a TieredPlanner folds its cold/ds index versions
        # in, so settled extents don't outlive part-key index growth in
        # the colder tiers (e.g. the downsampler publishing a window that
        # was queried before it landed).
        tok = getattr(svc.planner, "version_token", None)
        if tok is not None:
            sig = (sig, tok())

        extent_ms = self.config.extent_steps * step
        t0 = time.perf_counter()
        parts: list[tuple[int, int, StepMatrix]] = []
        stats = QueryStats()
        hits = misses = 0
        with span("cache", extents=len(extents)) as sp:
            for es, ee in extents:
                # evaluate/cache the FULL aligned extent grid [fs, fe] (same
                # step phase as the query), slice to [es, ee] below: every
                # sub-query then has exactly extent_steps steps, so the
                # batched kernels compile once and stay warm
                lo = (es // extent_ms) * extent_ms
                fs = lo + ((start - lo) % step)
                fe = fs + ((lo + extent_ms - 1 - fs) // step) * step
                key = (sig, fs, fe)
                stamp = None if fe <= horizon else version
                m = self._get(key, stamp)
                if m is not None:
                    hits += 1
                else:
                    misses += 1
                    sub = retime_extent(plan, fs, fe)
                    # origin rides along so rule-driven sub-queries admit
                    # under the governor's RULES class, not EXPENSIVE
                    r = svc._execute_uncached(
                        sub, QueryContext(planner_params=pp,
                                          origin=qcontext.origin),
                        materialize=True)
                    if r.partial or r.warnings:
                        # degraded extents must not be cached OR spliced
                        # into a result that looks whole; surrender to the
                        # uncached path so partial semantics match it
                        cache_misses.inc(misses)
                        cache_hits.inc(hits)
                        return svc._execute_uncached(plan, qcontext)
                    # admission priority by recompute cost, not byte size:
                    # the "cache" decision site learns each signature's
                    # recompute wall time; predicted-cheap extents — and
                    # pyramid-served ones, whose windows re-fold from
                    # stored roll-ups without paging payload — admit at
                    # low priority and evict first
                    from filodb_tpu.query import cost_model as cm
                    model = cm.model_for(svc.dataset)
                    d = model.classify(
                        "cache", sig, _CHEAP_RECOMPUTE_S,
                        below_arm="cheap", above_arm="keep",
                        static_arm="keep")
                    model.record_actual(d, r.stats.wall_time_s)
                    cheap = d.arm == "cheap" or bool(r.stats.pyramid)
                    self._put(key, stamp, r.result, cheap=cheap)
                    m = r.result
                    # fold the full expanded counters (incl. per-tier
                    # federation buckets), not just the scan totals
                    stats.merge_counts(r.stats)
                parts.append((es, ee, _slice_steps(m, fs, step, es, ee)))
            cache_hits.inc(hits)
            cache_misses.inc(misses)
            if 0 < hits < len(extents):
                cache_partial_hits.inc()
            merged = _merge_extents(parts, step)
            if sp is not None:
                sp.tags.update(hits=hits, misses=misses,
                               bytes=self._bytes)
        if merged is None:
            # non-uniform histogram buckets across extents — rare enough
            # to just evaluate whole
            return svc._execute_uncached(plan, qcontext)
        from filodb_tpu.query.exec.plan import ExecPlan
        ExecPlan._enforce_limits(merged, qcontext)
        stats.cache_hits += hits
        stats.cache_misses += misses
        stats.result_series = merged.num_series
        stats.wall_time_s = time.perf_counter() - t0
        return QueryResult(merged, stats, qcontext.query_id)


def _slice_steps(m: StepMatrix, fs: int, step: int, es: int, ee: int
                 ) -> StepMatrix:
    """View of a full-extent matrix restricted to grid points [es, ee].

    Rows left all-NaN by the slice are dropped: the single-shot path
    compacts them at materialize, and per-step-selective functions (topk)
    can emit a series solely for steps outside the requested sub-range."""
    if m.num_series == 0:
        return m
    i0 = (es - fs) // step
    i1 = (ee - fs) // step
    if i0 == 0 and i1 == len(m.steps_ms) - 1:
        return m
    vals = m.values[:, i0:i1 + 1]
    axes = tuple(range(1, vals.ndim))
    keep = ~np.all(np.isnan(vals), axis=axes)
    keys = m.keys
    if not keep.all():
        vals = vals[keep]
        keys = [k for k, kp in zip(keys, keep) if kp]
    return StepMatrix(keys, vals, m.steps_ms[i0:i1 + 1], m.les)


def _merge_extents(parts: list[tuple[int, int, StepMatrix]], step: int
                   ) -> StepMatrix | None:
    """Splice per-extent matrices back into one grid-spanning matrix.

    Series are aligned by label key across extents; a series absent from an
    extent (no samples in its widened window) fills with NaN, which is
    exactly what the single-shot evaluation produces for it there. Returns
    None when histogram bucket layouts disagree across extents (unmergeable
    — caller falls back to whole evaluation)."""
    if len(parts) == 1:
        es, ee, m = parts[0]
        # copy out: cached arrays (or slices of them) must never be
        # aliased into a result a caller might mutate
        return StepMatrix(list(m.keys), np.array(m.values),
                          np.array(m.steps_ms), m.les)
    key_index: dict = {}
    order: list = []
    les = None
    nbuckets = 0
    dtype = None
    for _, _, m in parts:
        if m.keys != order:  # common case: every extent has the same keys
            for k in m.keys:
                if k not in key_index:
                    key_index[k] = len(order)
                    order.append(k)
        if m.num_series and dtype is None:
            dtype = m.values.dtype
        if m.num_series and m.is_histogram:
            if les is None:
                les = m.les
                nbuckets = m.values.shape[2]
            elif m.les is None or len(m.les) != len(les) \
                    or not np.array_equal(np.asarray(m.les),
                                          np.asarray(les)):
                return None
    steps_full = np.concatenate([
        np.arange(es, ee + 1, step, dtype=np.int64) for es, ee, _ in parts])
    if not order:
        return StepMatrix.empty()
    shape = (len(order), len(steps_full), nbuckets) if nbuckets \
        else (len(order), len(steps_full))
    out = np.full(shape, np.nan, dtype=dtype or np.float64)
    off = 0
    for es, ee, m in parts:
        k = (ee - es) // step + 1
        if m.num_series:
            if bool(nbuckets) != m.is_histogram:
                return None  # scalar/histogram mix across extents
            if m.keys == order:
                out[:, off:off + k] = m.values
            else:
                rows = np.fromiter((key_index[key] for key in m.keys),
                                   dtype=np.intp, count=len(m.keys))
                out[rows, off:off + k] = m.values
        off += k
    return StepMatrix(order, out, steps_full, les)
