"""Online per-(dataset, plan-signature-class) cost model.

Every either/or planning decision in the query path — sidecar fold vs
payload decode, pyramid compose vs chunk fallback, aggregate pushdown vs
local evaluation, mesh lane routing, cold-tier paging granularity,
governor admission classing, result-cache admission — historically ran on
a static constant or a hand-tuned valve. This module closes the loop from
settled :class:`~filodb_tpu.query.model.QueryStats` back to those
decisions: each site asks :meth:`CostModel.decide` for the
predicted-cheaper arm, then settles the observed wall time back with
:meth:`CostModel.record_actual` (directly, or via :meth:`CostModel.defer`
when the settle point is downstream of the decision point — filolint
DC601 enforces that pairing).

Estimator per (site, signature-class, arm): an EWMA point estimate with
the same warmup semantics as PR 14's lane router (first two samples
replace outright, then ``est += alpha * (v - est)``) plus a bounded
reservoir of recent samples for percentile queries (governor Retry-After,
debug surfaces). Signature classes are caller-bucketed feature strings
(``"b16"``, ``"span4096"``) or hashed canonical plan signatures; the
table is LRU-bounded over signature classes so adversarial cardinality
cannot grow memory without bound.

Safety invariant — *cold model == static behavior, bit for bit*: a site
departs from its ``static_arm`` only when ``FILODB_ADAPTIVE`` is not
``"0"`` AND every competing arm has at least ``min_samples``
observations. Natural traffic settles only the arm actually taken, so
the non-taken arm never warms up on its own and existing behavior is
preserved until both-arm evidence exists (shadow probes, oracle replay in
``benchmarks/adaptive.py``, or a restored persisted model).

Models persist through the metastore (``write_cost_model`` /
``read_cost_model``) so restarts keep learned estimates.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from filodb_tpu.utils.metrics import get_counter, get_gauge
from filodb_tpu.utils.tracing import FlightRecorder

__all__ = [
    "SITES",
    "Decision",
    "CostModel",
    "bucket",
    "enabled",
    "model_for",
    "models",
    "reset_models",
    "signature_key",
]

# The known decision sites. Metrics are pre-created per site at import so
# scrapes expose every series from process start (PR206 parity).
SITES = ("sidecar", "pyramid", "pushdown", "lane", "paging", "admit", "cache")

_SOURCES = ("static", "model", "override")

_decided = {
    (s, src): get_counter("filodb_costmodel_decisions", {"site": s, "source": src})
    for s in SITES
    for src in _SOURCES
}
_settled = {s: get_counter("filodb_costmodel_settled", {"site": s}) for s in SITES}
_calib_gauge = {
    s: get_gauge("filodb_costmodel_calibration_error", {"site": s}) for s in SITES
}
_signatures_gauge = get_gauge("filodb_costmodel_signatures")
_evicted = get_counter("filodb_costmodel_evictions")

# EWMA weight for calibration error and arm estimates (matches the PR 14
# lane router so the generalized "lane" site reproduces its routing).
_ALPHA = 0.3


def enabled() -> bool:
    """Adaptive routing valve. Default on; ``FILODB_ADAPTIVE=0`` pins
    every decision site to its static arm regardless of model warmth."""
    return os.environ.get("FILODB_ADAPTIVE", "1") != "0"


def bucket(n: int) -> int:
    """Power-of-two bucket for signature features, so nearby workload
    sizes share one signature class instead of fragmenting the table."""
    n = int(n)
    b = 1
    while b < n and b < (1 << 20):
        b <<= 1
    return b


def signature_key(signature: object) -> str:
    """Stable signature-class key. Short strings pass through (readable in
    ``coststats``); everything else hashes its canonical ``repr`` —
    ``hash()`` is seed-randomized across processes and would break
    persistence."""
    if isinstance(signature, str) and len(signature) <= 64:
        return signature
    import hashlib

    return hashlib.blake2b(repr(signature).encode(), digest_size=8).hexdigest()


@dataclass
class Decision:
    """One routed decision: which arm a site took and why. Carried to the
    settle point (possibly via :meth:`CostModel.defer`) so the observed
    actual lands on the arm that actually ran."""

    site: str
    signature: str
    arm: str
    static_arm: str
    source: str  # "static" | "model" | "override"
    predicted: float | None = None
    alternatives: dict[str, float | None] = field(default_factory=dict)
    # Arm key the actual settles under when it differs from the routing
    # arm (admission classing settles the query's wall time, not the
    # class label's "cost").
    settle_arm: str | None = None


class _ArmStat:
    __slots__ = ("n", "est", "samples")

    def __init__(self, reservoir: int):
        self.n = 0
        self.est = 0.0
        self.samples: deque = deque(maxlen=reservoir)

    def record(self, v: float) -> None:
        self.n += 1
        if self.n <= 2:
            self.est = v
        else:
            self.est += _ALPHA * (v - self.est)
        self.samples.append(v)


class CostModel:
    """Per-dataset online cost model: EWMA + percentile reservoir per
    (site, signature-class, arm), LRU-bounded over signature classes."""

    def __init__(
        self,
        dataset: str = "",
        min_samples: int = 8,
        max_signatures: int = 4096,
        reservoir: int = 64,
    ):
        self.dataset = dataset
        self.min_samples = max(1, int(min_samples))
        self.max_signatures = max(16, int(max_signatures))
        self.reservoir = max(8, int(reservoir))
        self._lock = threading.RLock()
        # (site, sig) -> {arm: _ArmStat}, LRU over keys
        self._stats: OrderedDict[tuple[str, str], dict[str, _ArmStat]] = OrderedDict()
        self._calib: dict[str, float] = {}  # site -> EWMA |pred-actual|/actual
        self._ring = FlightRecorder(capacity=128)
        self._dirty = False

    def configure(
        self,
        min_samples: int | None = None,
        max_signatures: int | None = None,
        reservoir: int | None = None,
        ring_capacity: int | None = None,
    ) -> None:
        with self._lock:
            if min_samples is not None:
                self.min_samples = max(1, int(min_samples))
            if max_signatures is not None:
                self.max_signatures = max(16, int(max_signatures))
            if reservoir is not None:
                self.reservoir = max(8, int(reservoir))
            if ring_capacity is not None:
                self._ring.resize(int(ring_capacity))

    # -- estimate bookkeeping ----------------------------------------------

    def _entry(self, site: str, sig: str, create: bool) -> dict[str, _ArmStat] | None:
        key = (site, sig)
        arms = self._stats.get(key)
        if arms is None:
            if not create:
                return None
            arms = self._stats[key] = {}
            while len(self._stats) > self.max_signatures:
                self._stats.popitem(last=False)
                _evicted.inc()
            _signatures_gauge.set(float(len(self._stats)))
        else:
            self._stats.move_to_end(key)
        return arms

    def observe(self, site: str, signature: object, arm: str, actual_s: float) -> None:
        """Settle an observed cost directly (no prior Decision)."""
        sig = signature_key(signature)
        with self._lock:
            arms = self._entry(site, sig, create=True)
            stat = arms.get(arm)
            if stat is None:
                stat = arms[arm] = _ArmStat(self.reservoir)
            stat.record(float(actual_s))
            self._dirty = True

    def estimate(self, site: str, signature: object, arm: str) -> float | None:
        """Warm EWMA estimate, or None below ``min_samples``."""
        sig = signature_key(signature)
        with self._lock:
            arms = self._entry(site, sig, create=False)
            if not arms:
                return None
            stat = arms.get(arm)
            if stat is None or stat.n < self.min_samples:
                return None
            return stat.est

    def samples(self, site: str, signature: object, arm: str) -> int:
        sig = signature_key(signature)
        with self._lock:
            arms = self._stats.get((site, sig))
            stat = arms.get(arm) if arms else None
            return stat.n if stat is not None else 0

    def percentile(
        self, site: str, signature: object, arm: str, q: float
    ) -> float | None:
        """Reservoir percentile, or None below ``min_samples``."""
        sig = signature_key(signature)
        with self._lock:
            arms = self._stats.get((site, sig))
            stat = arms.get(arm) if arms else None
            if stat is None or stat.n < self.min_samples or not stat.samples:
                return None
            xs = sorted(stat.samples)
            i = min(len(xs) - 1, max(0, int(round(q * (len(xs) - 1)))))
            return xs[i]

    # -- decisions ----------------------------------------------------------

    def decide(
        self,
        site: str,
        signature: object,
        arms: tuple[str, ...],
        static_arm: str,
        override: str | None = None,
        require_all: bool = True,
        min_samples: int | None = None,
    ) -> Decision:
        """Route one decision. Returns the ``static_arm`` unless adaptive
        routing is enabled AND the competing arms are warm (all of them
        when ``require_all``, any subset otherwise — the lane router keeps
        PR 14's min-over-known semantics via ``require_all=False``)."""
        sig = signature_key(signature)
        if override is not None:
            ctr = _decided.get((site, "override"))
            if ctr is not None:
                ctr.inc()
            return Decision(site, sig, override, static_arm, "override")
        need = self.min_samples if min_samples is None else max(1, int(min_samples))
        ests: dict[str, float | None] = {}
        with self._lock:
            table = self._entry(site, sig, create=False) or {}
            for arm in arms:
                stat = table.get(arm)
                ests[arm] = stat.est if stat is not None and stat.n >= need else None
        known = {a: e for a, e in ests.items() if e is not None}
        use_model = (
            enabled()
            and known
            and (len(known) == len(arms) or not require_all)
        )
        if use_model:
            arm = min(known, key=known.get)
            src = "model"
        else:
            arm, src = static_arm, "static"
        ctr = _decided.get((site, src))
        if ctr is not None:
            ctr.inc()
        return Decision(site, sig, arm, static_arm, src, ests.get(arm), ests)

    def classify(
        self,
        site: str,
        signature: object,
        threshold_s: float,
        below_arm: str,
        above_arm: str,
        static_arm: str,
        settle_arm: str = "wall",
    ) -> Decision:
        """Threshold classing (governor CHEAP/EXPENSIVE): the arm is
        picked by comparing the predicted wall time for this signature
        class against ``threshold_s``, not by comparing arm costs. The
        settle lands under ``settle_arm`` so the prediction keeps
        learning from whichever class the query was given."""
        sig = signature_key(signature)
        est = self.estimate(site, sig, settle_arm)
        if enabled() and est is not None:
            arm = below_arm if est < threshold_s else above_arm
            src = "model"
        else:
            arm, src = static_arm, "static"
        ctr = _decided.get((site, src))
        if ctr is not None:
            ctr.inc()
        return Decision(
            site, sig, arm, static_arm, src, est, {settle_arm: est}, settle_arm
        )

    def record_actual(self, decision: Decision, actual_s: float,
                      observe: bool = True) -> None:
        """Settle a decision with its observed cost; feeds the estimator,
        per-site calibration error, and the prediction-vs-actual ring.
        ``observe=False`` skips the estimator update for call sites that
        already fed the sample through :meth:`observe` (the lane router
        mirrors every serve)."""
        arm = decision.settle_arm or decision.arm
        if observe:
            self.observe(decision.site, decision.signature, arm, actual_s)
        ctr = _settled.get(decision.site)
        if ctr is not None:
            ctr.inc()
        pred = decision.predicted
        if pred is not None and actual_s > 0:
            err = abs(pred - actual_s) / max(actual_s, 1e-9)
            with self._lock:
                prev = self._calib.get(decision.site)
                cur = err if prev is None else prev + _ALPHA * (err - prev)
                self._calib[decision.site] = cur
            g = _calib_gauge.get(decision.site)
            if g is not None:
                g.set(cur)
        self._ring.record(
            {
                "site": decision.site,
                "signature": decision.signature,
                "arm": arm,
                "source": decision.source,
                "predicted_s": pred,
                "actual_s": float(actual_s),
            }
        )

    # -- deferred settle ----------------------------------------------------

    def defer(self, carrier: object, decision: Decision) -> None:
        """Attach a decision to a context object whose settle point is
        downstream (e.g. the sidecar gate decides inside the lane but the
        wall time is only known back in the exec leaf)."""
        pend = getattr(carrier, "_cost_decisions", None)
        if pend is None:
            pend = []
            try:
                setattr(carrier, "_cost_decisions", pend)
            except (AttributeError, TypeError):  # frozen carrier: drop
                return
        pend.append((self, decision))

    @staticmethod
    def relabel_deferred(carrier: object, site: str, arm: str) -> None:
        """Re-label pending decisions for ``site`` whose chosen arm did
        NOT run (e.g. the sidecar fold bypassed mid-flight and the decode
        lane served instead): the settle moves to the arm that actually
        ran and the prediction is dropped so calibration error only
        measures honest predictions."""
        pend = getattr(carrier, "_cost_decisions", None)
        if not pend:
            return
        for _, d in pend:
            if d.site == site and d.arm != arm:
                d.settle_arm = arm
                d.predicted = None

    @staticmethod
    def settle_deferred(carrier: object, actual_s: float) -> None:
        """Settle every decision deferred onto ``carrier``; no-op when
        none are pending."""
        pend = getattr(carrier, "_cost_decisions", None)
        if not pend:
            return
        try:
            delattr(carrier, "_cost_decisions")
        except (AttributeError, TypeError):
            pass
        for model, decision in pend:
            model.record_actual(decision, actual_s)

    # -- debug / persistence ------------------------------------------------

    def calibration(self) -> dict[str, float]:
        with self._lock:
            return dict(self._calib)

    def recent(self, limit: int = 0) -> list[dict]:
        entries = list(reversed(self._ring.snapshot()))
        return entries[:limit] if limit and limit > 0 else entries

    def snapshot(self) -> dict:
        """Structured dump for ``filo-cli coststats`` and
        ``/api/v1/debug/costmodel``."""
        with self._lock:
            rows = []
            for (site, sig), arms in self._stats.items():
                for arm, stat in arms.items():
                    xs = sorted(stat.samples)
                    rows.append(
                        {
                            "site": site,
                            "signature": sig,
                            "arm": arm,
                            "n": stat.n,
                            "estimate_s": stat.est,
                            "p50_s": xs[len(xs) // 2] if xs else None,
                            "p90_s": xs[min(len(xs) - 1, int(0.9 * len(xs)))]
                            if xs
                            else None,
                            "warm": stat.n >= self.min_samples,
                        }
                    )
            return {
                "dataset": self.dataset,
                "enabled": enabled(),
                "min_samples": self.min_samples,
                "signatures": len(self._stats),
                "max_signatures": self.max_signatures,
                "calibration_error": dict(self._calib),
                "estimates": rows,
                "recent": self.recent(32),
            }

    def to_bytes(self) -> bytes:
        with self._lock:
            entries = [
                {
                    "site": site,
                    "sig": sig,
                    "arm": arm,
                    "n": stat.n,
                    "est": stat.est,
                    "samples": list(stat.samples),
                }
                for (site, sig), arms in self._stats.items()
                for arm, stat in arms.items()
            ]
            doc = {
                "version": 1,
                "dataset": self.dataset,
                "min_samples": self.min_samples,
                "calibration": dict(self._calib),
                "entries": entries,
            }
        return json.dumps(doc, sort_keys=True).encode()

    def from_bytes(self, raw: bytes) -> bool:
        try:
            doc = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            return False
        if not isinstance(doc, dict) or doc.get("version") != 1:
            return False
        with self._lock:
            self._stats.clear()
            for e in doc.get("entries", ()):
                try:
                    arms = self._entry(str(e["site"]), str(e["sig"]), create=True)
                    stat = _ArmStat(self.reservoir)
                    stat.n = int(e["n"])
                    stat.est = float(e["est"])
                    stat.samples.extend(float(x) for x in e.get("samples", ()))
                    arms[str(e["arm"])] = stat
                except (KeyError, TypeError, ValueError):
                    continue
            self._calib = {
                str(k): float(v)
                for k, v in (doc.get("calibration") or {}).items()
                if isinstance(v, (int, float))
            }
            _signatures_gauge.set(float(len(self._stats)))
            self._dirty = False
        return True

    def save(self, meta_store) -> None:
        """Persist learned estimates through the metastore (no-op when the
        store lacks blob support)."""
        write = getattr(meta_store, "write_cost_model", None)
        if write is None:
            return
        write(self.dataset, self.to_bytes())
        with self._lock:
            self._dirty = False

    def load(self, meta_store) -> bool:
        read = getattr(meta_store, "read_cost_model", None)
        if read is None:
            return False
        raw = read(self.dataset)
        if not raw:
            return False
        return self.from_bytes(raw)

    @property
    def dirty(self) -> bool:
        return self._dirty


# ---------------------------------------------------------------------------
# per-dataset registry

_models: dict[str, CostModel] = {}
_models_lock = threading.Lock()


def model_for(dataset: str) -> CostModel:
    """Process-global per-dataset model (decision sites deep in the query
    path reach it by dataset name rather than by plumbing a handle)."""
    with _models_lock:
        m = _models.get(dataset)
        if m is None:
            m = _models[dataset] = CostModel(dataset)
        return m


def models() -> dict[str, CostModel]:
    with _models_lock:
        return dict(_models)


def reset_models() -> None:
    """Test hook: drop all learned state."""
    with _models_lock:
        _models.clear()
