"""Query engine: logical plans, exec plans, and the TPU compute kernels.

Counterpart of reference ``query/`` (LogicalPlan/ExecPlan/range functions) —
redesigned so the hot path (windowed range functions + label aggregation) runs
as jitted JAX kernels over dense batched tensors instead of per-sample
iterators.
"""
