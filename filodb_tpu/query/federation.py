"""Tiered federation: one PromQL query across memstore, downsample tier,
and object-store history.

Counterpart of the reference deployment posture where a raw cluster, a
downsample cluster and a long-term store answer one query (reference
``LongTimeRangePlanner.scala`` generalized to three tiers; ROADMAP open
item 3). The pieces composed here all pre-exist:

- ``route_tiers`` decomposes a query grid into per-tier step ranges at
  step boundaries, honoring the max lookback window so no tier is asked
  for steps whose window reaches below its data floor (seam semantics:
  every step lands in exactly ONE tier — the newest tier whose floor
  covers the step's full lookback window).
- ``ColdTierStore`` is a memstore-shaped facade over the RAW dataset's
  persisted chunks (the object-store history tier): the part-key index
  bootstraps from ``scan_part_keys`` and chunk payloads page in through
  the per-shard :class:`DemandPagedChunkCache` — on an
  ``ObjectStoreColumnStore`` backend that is a CRC-verified coalesced
  ranged GET per segment run.
- ``TierExec`` wraps each tier's exec subtree and attributes
  chunks/bytes/decode to ``QueryStats.tiers[tier]`` (PR 10 machinery:
  a ``tier=...`` span per sub-query), so a federated query's time
  budget is provable from ``?stats=all``.

The planner that glues these together is
:class:`filodb_tpu.coordinator.tiered_planner.TieredPlanner`; settled
per-extent results of federated queries land in the PR 2 result cache
keyed by the tier-invariant plan signature (the cache splits the grid
BEFORE tier routing, so a repeat dashboard query over old data hits warm
without touching the object store).
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from filodb_tpu.core.memstore.index import PartKeyIndex
from filodb_tpu.core.memstore.odp import DemandPagedChunkCache
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, Schemas
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.query.exec.plan import ExecContext, NonLeafExecPlan
from filodb_tpu.query.model import QueryStats, StepMatrix
from filodb_tpu.utils.metrics import Counter
from filodb_tpu.utils.tracing import span, tag

MEMSTORE = "memstore"
OBJECTSTORE = "objectstore"
DOWNSAMPLE = "downsample"

# federated (multi-tier) query + per-tier sub-query counters; the scrape
# breadth test asserts these families (tests/test_metrics_scrape.py)
fed_queries = Counter("filodb_federation_queries")
fed_sub_memstore = Counter("filodb_federation_subqueries",
                           {"tier": MEMSTORE})
fed_sub_objectstore = Counter("filodb_federation_subqueries",
                              {"tier": OBJECTSTORE})
fed_sub_downsample = Counter("filodb_federation_subqueries",
                             {"tier": DOWNSAMPLE})
_SUB_COUNTERS = {MEMSTORE: fed_sub_memstore,
                 OBJECTSTORE: fed_sub_objectstore,
                 DOWNSAMPLE: fed_sub_downsample}


# ---------------------------------------------------------------------------
# tier routing

@dataclass(frozen=True)
class TierRange:
    """One tier's slice of a query grid: step instants
    ``start, start+step, ..., end`` (both inclusive, ms)."""

    tier: str
    start: int
    end: int


def _first_covered_step(start: int, step: int, end: int, lookback: int,
                        floor: int) -> int:
    """First grid instant whose full lookback window sits at/above
    ``floor`` (>= semantics: a step at exactly ``floor + lookback`` is
    covered). Returns ``end + step`` when no grid instant qualifies."""
    b = start
    while b - lookback < floor and b <= end:
        b += step
    return b


def route_tiers(start: int, step: int, end: int, lookback: int,
                mem_floor: int, raw_floor: int | None) -> list[TierRange]:
    """Decompose a query grid into per-tier step ranges, oldest tier
    first.

    Seam semantics: each step goes to the NEWEST tier whose data floor
    covers the step's full lookback window ``[t - lookback, t]``; the
    returned ranges are disjoint, adjacent, and cover every grid step —
    no double-counted or dropped steps at tier seams. ``raw_floor`` is
    the earliest raw (object-store) data; ``None`` means there is no
    downsample tier and the object-store tier extends to the range
    start. ``mem_floor`` below ``raw_floor`` is clamped (memory never
    retains more than the durable store)."""
    step = max(step, 1)
    if raw_floor is not None and mem_floor < raw_floor:
        mem_floor = raw_floor
    b_mem = _first_covered_step(start, step, end, lookback, mem_floor)
    b_os = start if raw_floor is None else \
        _first_covered_step(start, step, end, lookback, raw_floor)
    out = []
    if b_os > start:
        out.append(TierRange(DOWNSAMPLE, start, b_os - step))
    if b_mem > b_os:
        out.append(TierRange(OBJECTSTORE, b_os, b_mem - step))
    if b_mem <= end:
        out.append(TierRange(MEMSTORE, b_mem, end))
    return out


# ---------------------------------------------------------------------------
# cold tier: object-store-resident raw history

class _TierCounter:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class _ColdShardStats:
    """Duck-types the ShardStats surface the ODP cache touches."""

    def __init__(self):
        self.chunks_paged_in = _TierCounter()
        self.partitions_paged_in = _TierCounter()


class ColdPartition:
    """Read-only partition over object-store-resident raw chunks.

    Nothing is resident (``chunks`` is empty) — every read pages through
    the shard's :class:`DemandPagedChunkCache`, which on a covered
    repeat serves from the LRU without touching the store."""

    chunks = ()  # resident set for the ODP cache: always empty

    def __init__(self, part_id, part_key, schema, shard):
        self.part_id = part_id
        self.part_key = part_key
        self.schema = schema
        self._shard = shard
        # chunk accounting for QueryStats (leaf scans fold this in —
        # duck-typed partitions have no chunks_in_range)
        self.chunks_read = 0

    def read_samples(self, start, end, col=None, extra_chunks=None):
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        from filodb_tpu.query import cost_model as cm

        # paging granularity as a learned decision ("paging" site):
        # "exact" pages precisely the queried window (static arm);
        # "wide" doubles it so adjacent dashboard panels and step-scrolled
        # repeats hit the ODP range memo instead of paying another store
        # round trip. Decided and settled inline — the arm's cost is the
        # load itself.
        span = max(1, int(end) - int(start))
        model = cm.model_for(self._shard.dataset)
        d = model.decide("paging", f"page:span{cm.bucket(span // 60_000)}",
                         ("exact", "wide"), static_arm="exact")
        load_start, load_end = start, end
        if d.arm == "wide":
            load_start, load_end = start - span // 2, end + span // 2
        t0 = time.perf_counter()
        chunks = self._shard.odp_cache.get_or_load(self._shard, self,
                                                   load_start, load_end)
        model.record_actual(d, time.perf_counter() - t0)
        self.chunks_read = len(chunks)
        tmp = TimeSeriesPartition(self.part_id, self.part_key, self.schema)
        tmp.chunks = list(chunks)
        return tmp.read_samples(start, end, col)


class ColdTierShard:
    """Shard facade over the RAW dataset's persisted part keys + chunks
    (compare ``DownsampledTimeSeriesShard``, which does the same for the
    ds dataset but without demand paging)."""

    def __init__(self, dataset: str, shard: int, column_store,
                 schemas: Schemas, odp_max_chunks: int = 10_000,
                 refresh_s: float = 60.0):
        self.dataset = dataset
        self.shard_num = shard
        self.column_store = column_store
        self.schemas = schemas
        self.index = PartKeyIndex()
        self.config = StoreConfig(demand_paging_enabled=False)
        self.odp_cache = DemandPagedChunkCache(max_chunks=odp_max_chunks)
        # pyramid-lane summary cache; None when the backend publishes no
        # pyramid objects (the lane then bypasses to demand paging)
        from filodb_tpu.core.store.pyramid import make_pyramid_cache
        self.pyramids = make_pyramid_cache(column_store, dataset, shard)
        self.stats = _ColdShardStats()
        # leaf-exec batch cache protocol (see TimeSeriesShard.batch_cache)
        self.batch_cache: dict = {}
        self.batch_cache_cap = 64
        self.refresh_s = refresh_s
        self._known: dict = {}
        self._parts: dict = {}
        self._refreshed_at = float("-inf")

    @property
    def data_version(self) -> int:
        return len(self._known)

    def refresh_index(self) -> int:
        """Bootstrap/refresh the index from the raw dataset's persisted
        part keys; periodic re-refresh picks up newly flushed series."""
        n = 0
        for rec in self.column_store.scan_part_keys(self.dataset,
                                                    self.shard_num):
            if rec.part_key in self._known:
                pid = self._known[rec.part_key]
                self.index.update_end_time(pid, rec.end_time)
                continue
            pid = len(self._known)
            self._known[rec.part_key] = pid
            self.index.add_part_key(pid, rec.part_key, rec.start_time,
                                    rec.end_time)
            self._parts[pid] = ColdPartition(
                pid, rec.part_key, self.schemas[rec.part_key.schema], self)
            n += 1
        self._refreshed_at = time.monotonic()
        return n

    def _maybe_refresh(self) -> None:
        if time.monotonic() - self._refreshed_at > self.refresh_s:
            self.refresh_index()

    def lookup_partitions(self, filters, start, end):
        self._maybe_refresh()
        return self.index.part_ids_from_filters(filters, start, end)

    def partition(self, pid):
        return self._parts.get(pid)

    def label_values(self, label, filters=None, start=0, end=2**62):
        self._maybe_refresh()
        return self.index.label_values(label, filters, start, end)

    def label_names(self):
        self._maybe_refresh()
        return self.index.label_names()

    @property
    def num_partitions(self):
        return len(self._known)


class ColdTierStore:
    """Memstore-shaped facade over object-store-resident raw history for
    the exec layer: leaves read it via the ``store`` override exactly
    like the downsample store."""

    def __init__(self, column_store, dataset: str, num_shards: int,
                 schemas: Schemas | None = None,
                 odp_max_chunks: int = 10_000, refresh_s: float = 60.0):
        self.column_store = column_store
        self.dataset = dataset
        self.schemas = schemas or DEFAULT_SCHEMAS
        self._shards = {
            s: ColdTierShard(dataset, s, column_store, self.schemas,
                             odp_max_chunks=odp_max_chunks,
                             refresh_s=refresh_s)
            for s in range(num_shards)}

    def get_shard(self, dataset: str, shard: int):
        return self._shards[shard]

    def shards_for(self, dataset: str):
        return [self._shards[s] for s in sorted(self._shards)]

    def cache_chunks(self) -> int:
        return sum(len(s.odp_cache) for s in self._shards.values())

    def clear_caches(self) -> None:
        """Drop ODP + batch + pyramid caches (benchmarks force cold
        reads)."""
        for s in self._shards.values():
            s.odp_cache.clear()
            s.batch_cache.clear()
            if s.pyramids is not None:
                s.pyramids.clear()

    def tier_stats(self) -> dict:
        """{series, bytes, segments} for the status route; bytes/segments
        come from the backend when it can introspect them
        (``ObjectStoreColumnStore.dataset_stats``)."""
        for s in self._shards.values():
            s._maybe_refresh()
        series = sum(s.num_partitions for s in self._shards.values())
        out = {"series": series, "bytes": None, "segments": None}
        ds_stats = getattr(self.column_store, "dataset_stats", None)
        if ds_stats is not None:
            st = ds_stats(self.dataset)
            out["bytes"] = st.get("bytes")
            out["segments"] = st.get("segments")
        return out

    def label_values(self, dataset, label, filters=None, start=0, end=2**62):
        out = set()
        for s in self.shards_for(dataset):
            out.update(s.label_values(label, filters, start, end))
        return sorted(out)

    def label_names(self, dataset):
        out = set()
        for s in self.shards_for(dataset):
            out.update(s.label_names())
        return sorted(out)

    # ----------------------------------------------------- approx lane
    def _merged_sketches(self):
        """(TopKSketch, HLLSketch) merged over every shard's pyramid
        footers: bucket roll-ups where present, segment pyramids for the
        seqs no bucket covers — a summary-only scan, zero payloads."""
        from filodb_tpu.memory.sketches import HLLSketch, TopKSketch
        topk = TopKSketch(capacity=256)
        hll = HLLSketch()
        for s in self._shards.values():
            if s.pyramids is None:
                raise RuntimeError(
                    "approximate scans need a pyramid-publishing "
                    "backend (ObjectStoreColumnStore)")
            idx = getattr(self.column_store, "pyramid_index", None)
            seqs, buckets = idx(self.dataset, s.shard_num)
            covered: set[int] = set()
            for bkt, rec in buckets.items():
                bp = s.pyramids.bucket(int(bkt), int(rec["seq"]))
                if bp is None:
                    continue
                covered.update(int(q) for q in bp["covers"])
                topk.merge(bp["topk"])
                hll.merge(bp["hll"])
            for seq in seqs:
                if seq in covered:
                    continue
                sp = s.pyramids.segment(seq)
                if sp is None:
                    continue
                topk.merge(sp["topk"])
                hll.merge(sp["hll"])
        return topk, hll

    def approx_topk(self, k: int = 10) -> list[dict]:
        """Sketch-served ``topk(k, max per series)`` over the ENTIRE cold
        history — O(pyramid objects), no chunk payload bytes. Declared
        approximation: only served under ``FILODB_SIDECAR_APPROX=1``."""
        from filodb_tpu.core.store.localstore import _pk_from_blob
        from filodb_tpu.query.engine.sidecar_lane import approx_enabled
        if not approx_enabled():
            raise RuntimeError(
                "approx_topk requires FILODB_SIDECAR_APPROX=1")
        for s in self._shards.values():
            s._maybe_refresh()
        topk, _hll = self._merged_sketches()
        out = []
        for blob, v in topk.top(k):
            pk = _pk_from_blob(blob)
            out.append({"labels": pk.label_map, "value": v})
        return out

    def approx_cardinality(self) -> float:
        """HyperLogLog series-count estimate from pyramid footers (σ ≈
        3.25%); same approx declaration as :meth:`approx_topk`."""
        from filodb_tpu.query.engine.sidecar_lane import approx_enabled
        if not approx_enabled():
            raise RuntimeError(
                "approx_cardinality requires FILODB_SIDECAR_APPROX=1")
        for s in self._shards.values():
            s._maybe_refresh()
        _topk, hll = self._merged_sketches()
        return hll.estimate()


# ---------------------------------------------------------------------------
# per-tier execution + attribution

def _tier_bucket() -> dict:
    return {"subqueries": 0, "series": 0, "samples": 0, "chunks": 0,
            "bytes": 0, "decodeMs": 0.0, "wallMs": 0.0}


@dataclass
class TierExec(NonLeafExecPlan):
    """Wrap one tier's exec subtree: executes the child under a
    ``tier=...`` span with a FRESH stats object, then folds the counts
    into the query's stats twice — once merged (totals stay correct)
    and once into the per-tier attribution bucket
    ``QueryStats.tiers[tier]``.

    Execution goes through the standard ``gather`` (single child), so
    tier sub-query dispatch stays inside the exec machinery that the
    governor ``admit()`` gate at ``_execute_uncached`` covers — filolint
    CP502 proves no federation path dispatches outside it. A cold tier
    lost to a transport fault re-raises from here and is tolerated by
    the stitching parent as a partial result, never wrong data."""

    tier: str = ""

    def do_execute(self, ctx: ExecContext) -> StepMatrix:
        from filodb_tpu.core.store.objectstore import BYTES_DOWN
        sub = ExecContext(ctx.memstore, ctx.dataset, ctx.qcontext,
                          stats=QueryStats(), deadline=ctx.deadline,
                          budget=ctx.budget)
        _SUB_COUNTERS.get(self.tier, fed_queries).inc()
        bytes0 = BYTES_DOWN.value
        t0 = time.perf_counter()
        with span("tier", tier=self.tier):
            mats = self.gather(sub)
            tag("series", sub.stats.series_scanned)
            tag("chunks", sub.stats.chunks_touched)
        wall_s = time.perf_counter() - t0
        ctx.partial = ctx.partial or sub.partial
        for w in sub.warnings:
            if w not in ctx.warnings:
                ctx.warnings.append(w)
        ctx.stats.merge_counts(sub.stats)
        b = ctx.stats.tiers.setdefault(self.tier, _tier_bucket())
        b["subqueries"] += 1
        b["series"] += sub.stats.series_scanned
        b["samples"] += sub.stats.samples_scanned
        b["chunks"] += sub.stats.chunks_touched
        # bytes moved for this tier: object-store ranged-GET payloads
        # (single-process counter delta — concurrent queries can only
        # over-attribute, never lose bytes) plus remote-child wire bytes
        b["bytes"] += max(0, BYTES_DOWN.value - bytes0) \
            + sub.stats.wire_bytes
        b["decodeMs"] += sub.stats.decode_s * 1000.0
        b["wallMs"] += wall_s * 1000.0
        # pyramid-lane level attribution rides per-tier too, so
        # ?stats=all shows WHICH levels served a cold sub-query
        for k, v in sub.stats.pyramid.items():
            b[k] = b.get(k, 0) + v
        if not mats:
            return StepMatrix.empty()
        return mats[0]

    def __repr__(self):
        return f"TierExec({self.tier})"


# ---------------------------------------------------------------------------
# status introspection (shared by both HTTP fronts + filo-cli tiers)

def tier_status(name: str, svc) -> dict:
    """Per-dataset tier snapshot: retention boundaries and per-tier
    series/bytes. Works for any service — non-federated datasets report
    the memstore tier only."""
    tiers = []
    mem_series = 0
    mem_bytes = 0
    for sh in svc.memstore.shards_for(name):
        card = getattr(sh, "cardinality", None)
        if card is not None:
            mem_series += card.cardinality([]).active_ts
        st = getattr(sh, "stats", None)
        if st is not None and hasattr(st, "encoded_bytes"):
            mem_bytes += st.encoded_bytes.value
    mem_tier = {"tier": MEMSTORE, "series": mem_series,
                "bytes": mem_bytes, "floorMs": None, "ceilMs": None}
    out = {"federated": False, "tiers": tiers}
    planner = getattr(svc, "planner", None)
    detail = getattr(planner, "tier_detail", None)
    if detail is not None:
        d = detail()
        out["federated"] = True
        out["memFloorMs"] = d["memFloorMs"]
        out["rawFloorMs"] = d["rawFloorMs"]
        mem_tier["floorMs"] = d["memFloorMs"]
        tiers.extend(d["tiers"])
    tiers.append(mem_tier)
    return out
