"""FiloClient: programmatic client for a running server.

Counterpart of reference ``coordinator/src/main/scala/filodb.coordinator/
client/Client.scala:106,126`` (``LocalClient``/``ClusterClient`` ask
facades + ``QueryCommands``/``ClusterOps``): query and cluster operations
against a server's HTTP API. Results come back as parsed structures; range
queries can also be requested as numpy matrices.
"""

from __future__ import annotations

import http.client
import json
import urllib.parse
from dataclasses import dataclass, field

import numpy as np


class FiloClientError(RuntimeError):
    pass


@dataclass
class FiloClient:
    host: str = "127.0.0.1"
    port: int = 8080
    dataset: str = "timeseries"
    timeout_s: float = 60.0
    # persistent keep-alive connection (NOT thread-safe: share a client
    # across threads and requests interleave — use one client per thread,
    # as the serving benchmark and reference Client facades do)
    _conn: http.client.HTTPConnection | None = field(
        default=None, repr=False, compare=False)

    # -- http plumbing --

    def _request(self, path_qs: str) -> tuple[int, bytes]:
        """One GET over the cached keep-alive connection; reconnects once
        on a stale socket (server restarted / idle timeout)."""
        for attempt in (0, 1):
            if self._conn is None:
                self._conn = http.client.HTTPConnection(
                    self.host, self.port, timeout=self.timeout_s)
            try:
                self._conn.request("GET", path_qs)
                resp = self._conn.getresponse()
                body = resp.read()
                if resp.will_close:
                    self._conn.close()
                    self._conn = None
                return resp.status, body
            except (http.client.HTTPException, ConnectionError, OSError):
                self._conn.close()
                self._conn = None
                if attempt:
                    raise
        raise AssertionError("unreachable")

    def _get(self, path: str, **params) -> dict:
        qs = urllib.parse.urlencode(params, doseq=True)
        status, raw = self._request(path + (f"?{qs}" if qs else ""))
        try:
            body = json.loads(raw)
        except Exception as e:
            if status >= 400:
                raise FiloClientError(f"HTTP {status}") from e
            raise
        if status >= 400:
            raise FiloClientError(
                body.get("error", str(body)) if isinstance(body, dict)
                else str(body))
        if isinstance(body, dict) and body.get("status") == "error":
            raise FiloClientError(body.get("error", "unknown error"))
        return body

    def _api(self, endpoint: str) -> str:
        return f"/promql/{self.dataset}/api/v1/{endpoint}"

    # -- queries --

    def query_range(self, promql: str, start: int, end: int,
                    step: int = 60) -> list[dict]:
        """Prom matrix result: [{"metric": {...}, "values": [[ts, v], ...]}]."""
        body = self._get(self._api("query_range"), query=promql, start=start,
                         end=end, step=step)
        return body["data"]["result"]

    def query_range_matrix(self, promql: str, start: int, end: int,
                           step: int = 60):
        """(labels list, values float[P, K] with NaN gaps, steps int64[K])."""
        result = self.query_range(promql, start, end, step)
        steps = np.arange(start, end + 1, step, dtype=np.int64)
        idx = {int(t): i for i, t in enumerate(steps)}
        values = np.full((len(result), len(steps)), np.nan)
        labels = []
        for i, series in enumerate(result):
            labels.append(series["metric"])
            for t, v in series["values"]:
                j = idx.get(int(float(t)))
                if j is not None:
                    values[i, j] = float(v)
        return labels, values, steps

    def query(self, promql: str, time: int) -> list[dict]:
        body = self._get(self._api("query"), query=promql, time=time)
        return body["data"]["result"]

    def series(self, match: str, start: int, end: int) -> list[dict]:
        return self._get(self._api("series"), **{"match[]": match},
                         start=start, end=end)["data"]

    def label_names(self) -> list[str]:
        return self._get(self._api("labels"))["data"]

    def label_values(self, label: str) -> list[str]:
        return self._get(self._api(f"label/{label}/values"))["data"]

    # -- cluster ops (reference ClusterOps) --

    def cluster_status(self) -> list[dict]:
        return self._get(f"/api/v1/cluster/{self.dataset}/status")["data"]

    def stop_shards(self, shards: list[int]) -> list[int]:
        return self._get(f"/api/v1/cluster/{self.dataset}/stopshards",
                         shards=",".join(map(str, shards)))["data"]

    def start_shards(self, shards: list[int], node: str | None = None
                     ) -> list[int]:
        params = {"shards": ",".join(map(str, shards))}
        if node:
            params["node"] = node
        return self._get(f"/api/v1/cluster/{self.dataset}/startshards",
                         **params)["data"]

    def health(self) -> bool:
        try:
            return self._get("/__health").get("status") == "healthy"
        except (FiloClientError, OSError):
            return False
