"""Evicted-part-key Bloom filter.

Counterpart of the reference's evicted-partkey bloom filter
(``core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:457``):
when a seemingly-new series key arrives at ingest, a positive bloom answer
means the key MAY have been evicted before — the shard then restores the
series' identity (original startTime, dedup floor) instead of minting a
fresh one. False positives only cost an index lookup; false negatives are
bounded by the configured rate.

numpy bit array + double hashing (Kirsch–Mitzenmacher): k indexes derived
from two independent 64-bit halves of blake2b, so adds and membership tests
are a handful of vectorized ops.
"""

from __future__ import annotations

import hashlib
import math

import numpy as np


class BloomFilter:
    """Fixed-capacity bloom filter over byte strings."""

    def __init__(self, capacity: int, fp_rate: float = 0.01):
        capacity = max(capacity, 1)
        m = int(-capacity * math.log(fp_rate) / (math.log(2) ** 2))
        self.nbits = max(64, 1 << (m - 1).bit_length())  # pow2 for masking
        self.k = max(1, round(m / capacity * math.log(2)))
        self._bits = np.zeros(self.nbits // 64, np.uint64)
        self.count = 0

    def _indexes(self, key: bytes) -> np.ndarray:
        d = hashlib.blake2b(key, digest_size=16).digest()
        h1 = int.from_bytes(d[:8], "little")
        h2 = int.from_bytes(d[8:], "little") | 1
        idx = (h1 + np.arange(self.k, dtype=np.uint64) * np.uint64(h2 % 2**63)) \
            & np.uint64(self.nbits - 1)
        return idx

    def add(self, key: bytes) -> None:
        idx = self._indexes(key)
        np.bitwise_or.at(self._bits, (idx >> np.uint64(6)).astype(np.int64),
                         np.uint64(1) << (idx & np.uint64(63)))
        self.count += 1

    def __contains__(self, key: bytes) -> bool:
        idx = self._indexes(key)
        word = self._bits[(idx >> np.uint64(6)).astype(np.int64)]
        bit = np.uint64(1) << (idx & np.uint64(63))
        return bool(np.all(word & bit))

    def state(self) -> dict:
        """Snapshot-serializable state."""
        return {"nbits": int(self.nbits), "k": int(self.k),
                "count": int(self.count),
                "bits": self._bits.tobytes().hex()}

    @staticmethod
    def from_state(st: dict) -> "BloomFilter":
        bf = BloomFilter.__new__(BloomFilter)
        bf.nbits = st["nbits"]
        bf.k = st["k"]
        bf.count = st["count"]
        bf._bits = np.frombuffer(bytes.fromhex(st["bits"]),
                                 np.uint64).copy()
        return bf
