"""Node-level resource governor: admission control, query cost budgets, and
memory-pressure load shedding.

Counterpart of the reference's multi-tenant protection layer: `sample-limit`
and queried-data-size checks bound what one query may scan
(``QueryContext.scala`` / ``PlannerParams``), cardinality quotas bound what
one tenant may ingest, and the coordinator sheds load instead of letting a
hot node fall over. Here those properties live in one node-local governor:

- :class:`ResourceGovernor` — a bounded-concurrency admission gate with a
  deadline-aware wait queue in front of every query entry point (HTTP,
  remote exec, batcher). Over-capacity requests queue until their deadline
  budget says they cannot finish, then are shed with
  :class:`QueryRejected` (HTTP 503 + ``Retry-After``).
- :class:`QueryBudget` — per-query scan-time limits (samples scanned,
  result bytes, group-by cardinality) checked *incrementally* inside leaf
  scans and transformers, not only on the final matrix. ``degrade="partial"``
  returns what was scanned so far flagged ``partial=True`` (PR 1 plumbing);
  ``degrade="error"`` raises :class:`QueryBudgetExceeded` (HTTP 422).
  Budgets ride ``PlannerParams`` over the wire so a distributed query
  shares one budget across its remote leaves.
- :class:`MemoryWatchdog` — samples utilization sources (write-buffer-pool
  occupancy, result-cache bytes) and drives the node through
  ``ok -> degraded -> critical``: degraded evicts caches and tightens
  admission capacity; critical sheds gateway ingest and rejects new
  expensive queries while cheap/instant queries stay alive.

Every transition and rejection is a ``filodb_governor_*`` metric.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from filodb_tpu.query.model import QueryLimitExceeded
from filodb_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    get_counter,
    get_gauge,
)

# ---------------------------------------------------------------------------
# states

OK, DEGRADED, CRITICAL = "ok", "degraded", "critical"
_STATE_VALUE = {OK: 0, DEGRADED: 1, CRITICAL: 2}

# admission cost classes: "cheap" (instant/metadata — stays admissible under
# CRITICAL) vs "expensive" (range scans — shed first under pressure) vs
# "rules" (background standing-query evaluation — strictly lowest priority:
# capped by ``rules_max_inflight``, never queued, shed the moment the node
# leaves OK; a shed evaluation just retries on a later tick)
CHEAP, EXPENSIVE, RULES = "cheap", "expensive", "rules"


# ---------------------------------------------------------------------------
# errors


class QueryRejected(RuntimeError):
    """The admission gate shed this query (HTTP 503 + ``Retry-After``).

    Deliberately NOT a ``ConnectionError``/``TimeoutError``: a peer that
    sheds is *healthy* — scatter-gather must not treat it as a lost child
    and circuit breakers must not count it as a transport failure.
    """

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 reason: str = "capacity"):
        super().__init__(msg)
        self.retry_after_s = retry_after_s
        self.reason = reason


class QueryBudgetExceeded(QueryLimitExceeded):
    """A scan-time cost budget was breached in ``degrade="error"`` mode
    (maps to HTTP 422 through the existing ``QueryLimitExceeded`` arm)."""


# ---------------------------------------------------------------------------
# metrics — pre-created at import so the scrape families render even before
# any traffic moves them

_state_gauge = Gauge("filodb_governor_state")
_inflight_gauge = Gauge("filodb_governor_inflight")
_queue_depth_gauge = Gauge("filodb_governor_queue_depth")
_memory_util_gauge = Gauge("filodb_governor_memory_utilization")
_admitted = Counter("filodb_governor_admitted")
_rejected = {r: Counter("filodb_governor_rejected", {"reason": r})
             for r in ("capacity", "deadline", "queue_full", "critical",
                       "tenant", "rules")}
_transitions = {s: Counter("filodb_governor_transitions", {"to": s})
                for s in (OK, DEGRADED, CRITICAL)}
_budget_exceeded = Counter("filodb_governor_budget_exceeded")
_queue_wait = Histogram("filodb_governor_queue_wait_seconds")

# per-tenant families (tenant = "_ws_" or "_ws_/_ns_" shard-key prefix);
# untagged series pre-created so the families render before any tenant
# config exists — runtime series carry {"tenant": ...} tags
_tenant_inflight = Gauge("filodb_tenant_inflight")
_tenant_admitted = Counter("filodb_tenant_admitted")
_tenant_rejected = Counter("filodb_tenant_rejected")
_tenant_dropped = Counter("filodb_tenant_ingest_dropped")
_tenant_series = Gauge("filodb_tenant_series")
_tenant_quota = Gauge("filodb_tenant_quota")


# ---------------------------------------------------------------------------
# config (process-wide singleton; overridable via config.py "governor" block)


@dataclass
class GovernorConfig:
    admission_capacity: int = 32       # concurrent queries when OK
    admission_queue_limit: int = 128   # waiters beyond that -> queue_full
    max_queue_wait_s: float = 5.0      # hard cap on time spent queued
    queue_headroom_s: float = 0.05     # deadline slack a queued query keeps
    retry_after_s: float = 1.0         # advisory Retry-After on sheds
    degraded_capacity_factor: float = 0.5
    degraded_threshold: float = 0.75   # max source utilization -> degraded
    critical_threshold: float = 0.92   # max source utilization -> critical
    watchdog_interval_s: float = 0.5
    # concurrent standing-query (rule) evaluations; rule evals are their
    # own admission class so a pathological rule cannot starve
    # interactive queries (they never queue and shed outside OK)
    rules_max_inflight: int = 2
    # budget limits; 0 = unlimited (no budget attached to queries)
    max_samples_scanned: int = 0
    max_result_bytes: int = 0
    max_group_cardinality: int = 0
    budget_degrade: str = "partial"    # "partial" | "error"
    # per-tenant admission classes + cardinality quotas, keyed on the
    # shard-key prefix: {"ws": {...}} or {"ws/ns": {...}} with
    #   max_inflight:  concurrent queries for this tenant (0 = unlimited)
    #   max_series:    active-series cardinality quota per shard (0 = off)
    # one tenant's flood sheds ONLY that tenant: its queries reject with
    # reason="tenant" without consuming the shared admission queue, and
    # its over-quota series drop at ingest (QuotaExceededError)
    tenants: dict = field(default_factory=dict)


_config = GovernorConfig()


def config() -> GovernorConfig:
    return _config


def configure(**kw) -> GovernorConfig:
    """Apply server-config overrides (``config.py`` ``governor`` block)."""
    for k, v in kw.items():
        if hasattr(_config, k):
            setattr(_config, k, v)
    return _config


# Optional live Retry-After source (coordinator/adaptive_planner.py): maps
# a shed reason to an advisory delay learned from settled per-class
# latency percentiles. Returning None (or raising nothing useful) falls
# back to the static ``retry_after_s`` constant, so a cold model keeps
# today's behavior bit-for-bit.
_retry_after_provider = None


def set_retry_after_provider(fn) -> None:
    global _retry_after_provider
    _retry_after_provider = fn


def _advised_retry_after(reason: str, static_s: float) -> float:
    fn = _retry_after_provider
    if fn is None:
        return static_s
    try:
        v = fn(reason)
    except Exception:
        return static_s
    if v is None:
        return static_s
    try:
        v = float(v)
    except (TypeError, ValueError):
        return static_s
    # clamp: advisory backoff should never be absurd even if the model is
    return min(max(v, 0.05), 60.0)


# ---------------------------------------------------------------------------
# query budget


@dataclass
class QueryBudget:
    """Per-query scan-time cost limits; 0 means unlimited for that axis.

    Wire-serializable (registered in ``coordinator/wire.py``) and carried on
    ``PlannerParams.budget`` so remote leaves enforce the same budget.
    """

    max_samples_scanned: int = 0
    max_result_bytes: int = 0
    max_group_cardinality: int = 0
    degrade: str = "partial"

    def breach(self, ctx, what: str, limit: int, actual: int) -> bool:
        """Record a budget breach. ``degrade="error"`` raises; partial mode
        flags ``ctx`` partial with a warning and returns True so the caller
        stops scanning and returns what it has."""
        _budget_exceeded.inc()
        msg = (f"query budget exceeded: {what} {actual} > {limit}; "
               f"returning partial data")
        if self.degrade == "error":
            raise QueryBudgetExceeded(
                f"query budget exceeded: {what} {actual} > limit {limit}")
        if ctx is not None:
            ctx.partial = True
            if msg not in ctx.warnings:
                ctx.warnings.append(msg)
        return True

    def check_samples(self, ctx, samples_scanned: int) -> bool:
        """True when the samples budget is breached (and recorded)."""
        lim = self.max_samples_scanned
        if lim and samples_scanned > lim:
            return self.breach(ctx, "samples scanned", lim, samples_scanned)
        return False

    def check_result_bytes(self, ctx, nbytes: int) -> bool:
        lim = self.max_result_bytes
        if lim and nbytes > lim:
            return self.breach(ctx, "result bytes", lim, nbytes)
        return False

    def check_cardinality(self, ctx, groups: int) -> bool:
        lim = self.max_group_cardinality
        if lim and groups > lim:
            return self.breach(ctx, "group cardinality", lim, groups)
        return False


def default_budget() -> QueryBudget | None:
    """Budget from the governor config, or None when every axis is
    unlimited (the common case: budgets are opt-in, existing queries see
    no behavior change)."""
    c = _config
    if not (c.max_samples_scanned or c.max_result_bytes
            or c.max_group_cardinality):
        return None
    return QueryBudget(max_samples_scanned=c.max_samples_scanned,
                       max_result_bytes=c.max_result_bytes,
                       max_group_cardinality=c.max_group_cardinality,
                       degrade=c.budget_degrade)


# ---------------------------------------------------------------------------
# per-tenant isolation (keyed on the _ws_/_ns_ shard-key prefix)


def tenant_of(labels: dict) -> str:
    """Tenant id from a shard-key label map: ``"ws/ns"`` when both are
    present, ``"ws"`` with only a workspace, ``""`` for untenanted data."""
    ws = labels.get("_ws_", "")
    ns = labels.get("_ns_", "")
    return f"{ws}/{ns}" if ws and ns else ws


def tenant_limits(tenant: str) -> dict | None:
    """The configured class for a tenant: exact ``ws/ns`` match first,
    then the ``ws`` prefix; None when the tenant is unclassed."""
    if not tenant or not _config.tenants:
        return None
    tc = _config.tenants.get(tenant)
    if tc is None and "/" in tenant:
        tc = _config.tenants.get(tenant.split("/", 1)[0])
    return tc


def tenant_account_key(tenant: str) -> str:
    """Inflight-accounting key for a tenant: the configured class key when
    one matches (so a ``ws``-scoped cap aggregates across all of that
    workspace's namespaces), else the tenant itself."""
    if not tenant or not _config.tenants or tenant in _config.tenants:
        return tenant
    if "/" in tenant:
        ws = tenant.split("/", 1)[0]
        if ws in _config.tenants:
            return ws
    return tenant


def apply_tenant_quotas(tracker) -> None:
    """Push configured per-tenant cardinality quotas into a shard's
    :class:`CardinalityTracker` (called at shard construction, so every
    shard enforces the same quotas at ingest)."""
    for tenant, tc in _config.tenants.items():
        quota = int(tc.get("max_series", 0) or 0)
        if quota <= 0:
            continue
        tracker.set_quota(tenant.split("/"), quota)
        get_gauge("filodb_tenant_quota", {"tenant": tenant}).set(quota)


def record_tenant_drop(labels: dict) -> None:
    """Count one quota-dropped ingest record against its tenant."""
    tenant = tenant_of(labels)
    _tenant_dropped.inc()
    if tenant:
        get_counter("filodb_tenant_ingest_dropped",
                    {"tenant": tenant}).inc()


def register_tenant_series_gauges(shards_fn) -> None:
    """Per-tenant active-series gauges (``filodb_tenant_series{tenant=}``)
    computed at scrape time by summing each configured tenant's
    cardinality-tree counts over ``shards_fn()`` (the node's live shards) —
    no update path, never stale."""
    from filodb_tpu.utils.metrics import GaugeFn
    for tenant in _config.tenants:
        prefix = tenant.split("/")

        def fn(prefix=prefix):
            total = 0
            for sh in shards_fn() or []:
                total += sh.cardinality.cardinality(prefix).active_ts
            return total

        GaugeFn("filodb_tenant_series", fn, {"tenant": tenant})


# ---------------------------------------------------------------------------
# admission gate


class ResourceGovernor:
    """Bounded-concurrency admission gate with a deadline-aware wait queue.

    Capacity shrinks by ``degraded_capacity_factor`` when the watchdog moves
    the node out of OK; under CRITICAL, new ``EXPENSIVE`` work is shed
    outright while ``CHEAP`` (instant/metadata) queries keep flowing.
    Admission never deadlocks: every wait is bounded by the caller's
    deadline and ``max_queue_wait_s``, and slots are always released via
    the :meth:`admit` context manager.
    """

    def __init__(self, cfg: GovernorConfig | None = None):
        self.cfg = cfg or _config
        self._cond = threading.Condition()
        self._inflight = 0
        self._waiters = 0
        self._rules_inflight = 0
        self._tenant_inflight: dict[str, int] = {}
        self._state = OK
        _state_gauge.set(_STATE_VALUE[OK])
        _inflight_gauge.set(0)
        _queue_depth_gauge.set(0)

    # -- state ------------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    def set_state(self, new: str) -> bool:
        """Move to ``new`` state; returns True when this was a transition."""
        if new not in _STATE_VALUE:
            raise ValueError(f"unknown governor state {new!r}")
        with self._cond:
            if new == self._state:
                return False
            self._state = new
            _state_gauge.set(_STATE_VALUE[new])
            _transitions[new].inc()
            self._cond.notify_all()
        return True

    def capacity(self) -> int:
        cap = max(1, int(self.cfg.admission_capacity))
        if self._state != OK:
            cap = max(1, int(cap * self.cfg.degraded_capacity_factor))
        return cap

    @property
    def inflight(self) -> int:
        return self._inflight

    # -- admission --------------------------------------------------------

    def _reject(self, reason: str, detail: str) -> None:
        _rejected[reason].inc()
        raise QueryRejected(f"query shed ({reason}): {detail}",
                            retry_after_s=_advised_retry_after(
                                reason, self.cfg.retry_after_s),
                            reason=reason)

    @contextmanager
    def admit(self, deadline=None, cost: str = EXPENSIVE,
              tenant: str = ""):
        """Admit one query; blocks while at capacity until a slot frees or
        the wait budget (deadline minus headroom, capped at
        ``max_queue_wait_s``) runs out, then sheds with
        :class:`QueryRejected`. ``tenant`` (the ``_ws_/_ns_`` shard-key
        prefix) gates against that tenant's configured ``max_inflight``
        BEFORE the shared queue — a flooding tenant sheds itself without
        occupying capacity others are waiting for."""
        tenant = tenant_account_key(tenant)
        self._acquire(deadline, cost, tenant)
        try:
            yield self
        finally:
            self._release(tenant, cost)

    def _tenant_gate(self, tenant: str) -> None:
        """Per-tenant concurrency cap; caller holds ``_cond``. Rejects
        immediately (no queueing) — the shed is the isolation mechanism."""
        tc = tenant_limits(tenant)
        if tc is None:
            return
        cap = int(tc.get("max_inflight", 0) or 0)
        if cap and self._tenant_inflight.get(tenant, 0) >= cap:
            get_counter("filodb_tenant_rejected",
                        {"tenant": tenant}).inc()
            _tenant_rejected.inc()
            self._reject("tenant",
                         f"tenant {tenant} at max_inflight={cap}")

    def _acquire(self, deadline, cost: str, tenant: str = "") -> None:
        cfg = self.cfg
        t0 = time.monotonic()
        with self._cond:
            self._tenant_gate(tenant)
            if cost == RULES:
                # background standing-query work: strictly lowest
                # priority. Shed the moment the node leaves OK, cap
                # concurrent evaluations, and never occupy the wait
                # queue — interactive queries own it. A shed evaluation
                # retries on a later tick with nothing lost.
                if self._state != OK:
                    self._reject("rules",
                                 f"rule evaluation shed: node {self._state}")
                cap = max(1, int(self.cfg.rules_max_inflight))
                if self._rules_inflight >= cap:
                    self._reject("rules",
                                 f"rule evaluations at max_inflight={cap}")
                if self._inflight >= self.capacity() or self._waiters:
                    self._reject("rules",
                                 "no spare capacity for rule evaluation")
                self._admit_locked(t0, tenant, cost)
                return
            if self._state == CRITICAL and cost == EXPENSIVE:
                self._reject("critical",
                             "node under memory pressure; only cheap "
                             "queries admitted")
            if self._inflight < self.capacity() and self._waiters == 0:
                self._admit_locked(t0, tenant, cost)
                return
            if self._waiters >= cfg.admission_queue_limit:
                self._reject("queue_full",
                             f"admission queue full "
                             f"({self._waiters} waiting)")
            self._waiters += 1
            _queue_depth_gauge.set(self._waiters)
            try:
                while True:
                    if self._state == CRITICAL and cost == EXPENSIVE:
                        self._reject("critical",
                                     "node went critical while queued")
                    if self._inflight < self.capacity():
                        self._admit_locked(t0, tenant, cost)
                        return
                    budget = cfg.max_queue_wait_s - (time.monotonic() - t0)
                    if deadline is not None:
                        budget = min(budget, deadline.remaining()
                                     - cfg.queue_headroom_s)
                    if budget <= 0:
                        reason = "deadline" if deadline is not None \
                            else "capacity"
                        self._reject(reason,
                                     f"no capacity within wait budget "
                                     f"(inflight={self._inflight}, "
                                     f"capacity={self.capacity()})")
                    self._cond.wait(timeout=min(budget, 0.25))
            finally:
                self._waiters -= 1
                _queue_depth_gauge.set(self._waiters)

    def _admit_locked(self, t0: float, tenant: str = "",
                      cost: str = EXPENSIVE) -> None:
        self._inflight += 1
        _inflight_gauge.set(self._inflight)
        _admitted.inc()
        _queue_wait.observe(time.monotonic() - t0)
        if cost == RULES:
            self._rules_inflight += 1
        if tenant:
            n = self._tenant_inflight.get(tenant, 0) + 1
            self._tenant_inflight[tenant] = n
            get_gauge("filodb_tenant_inflight", {"tenant": tenant}).set(n)
            get_counter("filodb_tenant_admitted", {"tenant": tenant}).inc()
            _tenant_admitted.inc()

    def _release(self, tenant: str = "", cost: str = EXPENSIVE) -> None:
        with self._cond:
            self._inflight = max(0, self._inflight - 1)
            _inflight_gauge.set(self._inflight)
            if cost == RULES:
                self._rules_inflight = max(0, self._rules_inflight - 1)
            if tenant:
                n = max(0, self._tenant_inflight.get(tenant, 0) - 1)
                self._tenant_inflight[tenant] = n
                get_gauge("filodb_tenant_inflight",
                          {"tenant": tenant}).set(n)
            self._cond.notify()


# ---------------------------------------------------------------------------
# memory watchdog


class MemoryWatchdog:
    """Periodically samples utilization sources (0..1 each) and drives the
    governor's state machine; the max over sources decides the state.

    Sources are callables returning a fraction or None (subject torn down).
    ``on_degraded`` callbacks fire on every upward transition out of OK —
    standalone wires result-cache eviction there.
    """

    def __init__(self, gov: ResourceGovernor | None = None,
                 interval_s: float | None = None, clock=time.monotonic):
        self.gov = gov or governor()
        self.interval_s = interval_s if interval_s is not None \
            else self.gov.cfg.watchdog_interval_s
        self.clock = clock
        self.sources: list[tuple[str, "callable"]] = []
        self.on_degraded: list["callable"] = []
        self._stop = threading.Event()
        self._thread = None

    def add_source(self, name: str, fn) -> "MemoryWatchdog":
        self.sources.append((name, fn))
        return self

    def utilization(self) -> float:
        worst = 0.0
        for _name, fn in self.sources:
            try:
                v = fn()
            except Exception:
                continue
            if v is not None:
                worst = max(worst, float(v))
        return worst

    def sample(self) -> str:
        """One observation: read sources, map to a state, apply it."""
        util = self.utilization()
        _memory_util_gauge.set(util)
        cfg = self.gov.cfg
        if util >= cfg.critical_threshold:
            new = CRITICAL
        elif util >= cfg.degraded_threshold:
            new = DEGRADED
        else:
            new = OK
        prev = self.gov.state
        if self.gov.set_state(new) and _STATE_VALUE[new] > _STATE_VALUE[prev]:
            for cb in self.on_degraded:
                try:
                    cb(new)
                except Exception:
                    pass
        return new

    def start(self) -> "MemoryWatchdog":
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                self.sample()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="governor-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        # a stopped watchdog leaves no stale pressure behind (tests share
        # the process-global governor)
        self.gov.set_state(OK)


# ---------------------------------------------------------------------------
# process-global governor singleton

_governor: ResourceGovernor | None = None
_governor_lock = threading.Lock()


def governor() -> ResourceGovernor:
    global _governor
    with _governor_lock:
        if _governor is None:
            _governor = ResourceGovernor(_config)
        return _governor


def reset() -> None:
    """Fresh governor + default config (tests)."""
    global _governor, _retry_after_provider
    with _governor_lock:
        _config.__dict__.update(GovernorConfig().__dict__)
        _governor = ResourceGovernor(_config)
        _retry_after_provider = None
