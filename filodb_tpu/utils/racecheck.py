"""Debug runtime shared-state race sanitizer (``FILODB_RACECHECK=1``).

The static LD103 pass flags attributes written both under and outside a
lock, but only within one class's lexical scope — it cannot see a shard
map mutated from the heartbeat thread through one lock and from a
migration worker through another, or a rules-state dict written with no
lock at all from a path the class never declared. This module covers
that gap at runtime with an Eraser-style lockset algorithm:

- :func:`register` marks an object as *shared state*; every subsequent
  attribute write to it records which checked locks (from
  :mod:`~filodb_tpu.utils.lockcheck`, by creation site) the writing
  thread held.
- Per ``(label, attribute)`` cell the tracker intersects the guard sets
  across writes. Once two or more distinct threads have written the
  cell and the intersection is empty, there is no single lock that
  protects it: the write is flagged **guard-free** (the current writer
  held no checked lock at all) or **mixed-guard** (writers hold locks,
  but disjoint ones).
- :func:`tracked_dict` wraps a dict in a recording subclass so keyed
  state (the metrics registry, rules group state) gets the same
  treatment per key. Plain ``dict`` subclassing keeps wire encoding
  (``isinstance(obj, dict)``) and every read path untouched.

Tracking patches ``__setattr__`` on the *original* class — never swaps
``obj.__class__`` — because the wire registry checks exact class
identity on encode (``registry().get(name) is not cls``) and
``MigrationManifest`` is wire-registered shared state.

Known gaps, accepted by design (mirroring lockcheck): objects created
before :func:`install` are untracked; in-place mutations of list/set
attribute *values* are invisible (only the attribute rebind is seen) —
keyed container state should go through :func:`tracked_dict`; guard
identity is lockcheck's creation-site key, so locks created before
lockcheck installed are invisible as guards.

Usage in tests::

    with lockcheck.session():
        with racecheck.session():
            ... run chaos scenario ...
        assert racecheck.violations() == []

Setting ``FILODB_RACECHECK=1`` before importing ``filodb_tpu`` installs
the tracker process-wide (and lockcheck with it — the guard sets come
from lockcheck's held stack).
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import weakref
from dataclasses import dataclass, field

from filodb_tpu.utils import lockcheck

__all__ = [
    "RaceViolation",
    "Violation",
    "enabled_by_env",
    "install",
    "installed",
    "register",
    "reset",
    "session",
    "tracked_dict",
    "uninstall",
    "violations",
]

_ENV_FLAG = "FILODB_RACECHECK"


@dataclass(frozen=True)
class Violation:
    kind: str        # "guard-free" | "mixed-guard"
    thread: str
    detail: str

    def render(self) -> str:
        return f"[{self.kind}] thread={self.thread}: {self.detail}"


class RaceViolation(RuntimeError):
    pass


@dataclass
class _Cell:
    """Lockset state for one (label, attr) pair."""
    candidates: frozenset | None = None   # None until the first write
    writers: set = field(default_factory=set)          # thread idents
    examples: dict = field(default_factory=dict)       # guards -> site


@dataclass
class _State:
    strict: bool = False
    cells: dict = field(default_factory=dict)   # (label, attr) -> _Cell
    violations: list = field(default_factory=list)
    reported: set = field(default_factory=set)
    lock: object = None
    installed_lockcheck: bool = False

    def __post_init__(self):
        # a REAL lock: while lockcheck is installed, threading.Lock()
        # returns a checked wrapper, and the tracker's own bookkeeping
        # must not appear in the held stack it samples
        self.lock = lockcheck._real_lock()


_state: _State | None = None
# id(obj) -> label for registered objects; populated only while
# installed, cleaned up by weakref.finalize so a recycled id cannot
# alias a dead object's label
_labels: dict[int, str] = {}
# class -> (had_own_setattr, original_setattr_descriptor, call_target)
_patched: dict[type, tuple] = {}


def _write_site() -> str:
    f = sys._getframe(2)
    this = __file__
    while f is not None:
        fn = f.f_code.co_filename
        if fn != this and "threading" not in fn:
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _current_guards() -> frozenset:
    return frozenset(site for site, _ in lockcheck._held())


def _record_write(label: str, attr: str) -> None:
    st = _state
    if st is None:
        return
    guards = _current_guards()
    site = _write_site()
    ident = threading.get_ident()
    tname = threading.current_thread().name
    raise_v = None
    with st.lock:
        cell = st.cells.setdefault((label, attr), _Cell())
        cell.writers.add(ident)
        cell.examples.setdefault(guards, site)
        if cell.candidates is None:
            cell.candidates = guards
        else:
            cell.candidates = cell.candidates & guards
        if len(cell.writers) >= 2 and not cell.candidates:
            kind = "guard-free" if not guards else "mixed-guard"
            key = (label, attr, kind)
            if key not in st.reported:
                st.reported.add(key)
                others = "; ".join(
                    f"{{{', '.join(sorted(g)) or 'no lock'}}} at {s}"
                    for g, s in cell.examples.items())
                held = ", ".join(sorted(guards)) or "no lock"
                v = Violation(
                    kind, tname,
                    f"write to {label}.{attr} at {site} under [{held}] "
                    f"has no lock in common with the other "
                    f"{len(cell.writers)} writer thread(s): {others}")
                st.violations.append(v)
                if st.strict:
                    raise_v = v
    if raise_v is not None:
        raise RaceViolation(raise_v.render())


# --------------------------------------------------------------------------
# attribute tracking

def _patch_class(cls: type) -> None:
    if cls in _patched:
        return
    had_own = "__setattr__" in cls.__dict__
    original_descriptor = cls.__dict__.get("__setattr__")
    call_target = cls.__setattr__   # resolved through the MRO

    def _tracked_setattr(self, name, value, _orig=call_target):
        _orig(self, name, value)
        label = _labels.get(id(self))
        if label is not None and not name.startswith("__"):
            _record_write(label, name)

    _patched[cls] = (had_own, original_descriptor)
    cls.__setattr__ = _tracked_setattr


def _unpatch_all() -> None:
    for cls, (had_own, original) in _patched.items():
        if had_own:
            cls.__setattr__ = original
        else:
            try:
                del cls.__setattr__
            except AttributeError:
                pass
    _patched.clear()


def register(obj, label: str):
    """Mark ``obj`` as tracked shared state; returns ``obj`` so it can
    wrap an assignment. No-op (and free) when the tracker is not
    installed — product code calls this unconditionally."""
    if _state is None:
        return obj
    _patch_class(type(obj))
    oid = id(obj)
    _labels[oid] = label
    try:
        weakref.finalize(obj, _labels.pop, oid, None)
    except TypeError:
        pass   # non-weakref-able objects just keep the label entry
    return obj


class _TrackedDict(dict):
    """Dict subclass recording per-key writes. Stays a real ``dict`` so
    wire encoding and every structural read path are untouched."""

    __slots__ = ("_racecheck_label",)

    def __init__(self, label: str, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._racecheck_label = label

    def _note(self, key) -> None:
        _record_write(self._racecheck_label, f"[{key!r}]")

    def __setitem__(self, key, value):
        super().__setitem__(key, value)
        self._note(key)

    def __delitem__(self, key):
        super().__delitem__(key)
        self._note(key)

    def setdefault(self, key, default=None):
        present = key in self
        out = super().setdefault(key, default)
        if not present:
            self._note(key)
        return out

    def pop(self, key, *default):
        present = key in self
        out = super().pop(key, *default)
        if present:
            self._note(key)
        return out

    def popitem(self):
        key, value = super().popitem()
        self._note(key)
        return key, value

    def update(self, *args, **kwargs):
        snapshot = dict(*args, **kwargs)
        super().update(snapshot)
        for key in snapshot:
            self._note(key)

    def clear(self):
        keys = list(self)
        super().clear()
        for key in keys:
            self._note(key)


def tracked_dict(label: str, initial=None):
    """A recording dict labeled ``label`` — or a plain dict when the
    tracker is not installed, so product code pays nothing."""
    if _state is None:
        return dict(initial or {})
    return _TrackedDict(label, initial or {})


# --------------------------------------------------------------------------
# lifecycle

def installed() -> bool:
    return _state is not None


def enabled_by_env() -> bool:
    return os.environ.get(_ENV_FLAG, "") not in ("", "0", "false")


_saved_metrics_lock = None


def _wrap_metrics_registry() -> None:
    """The metric registry dict and its module lock are created at
    import time, before any fixture can install the tracker; swap the
    dict for a recording one AND re-create the lock through the (now
    lockcheck-patched) factory — otherwise every registry write would
    look guard-free, since a pre-install real lock is invisible to the
    held-stack sampling. Both are swapped back at uninstall."""
    global _saved_metrics_lock
    from filodb_tpu.utils import metrics
    if not isinstance(metrics._registry, _TrackedDict):
        metrics._registry = _TrackedDict("metrics.registry",
                                         metrics._registry)
        _saved_metrics_lock = metrics._lock
        metrics._lock = threading.Lock()


def _unwrap_metrics_registry() -> None:
    global _saved_metrics_lock
    from filodb_tpu.utils import metrics
    if isinstance(metrics._registry, _TrackedDict):
        metrics._registry = dict(metrics._registry)
        if _saved_metrics_lock is not None:
            metrics._lock = _saved_metrics_lock
            _saved_metrics_lock = None


def install(strict: bool = False) -> None:
    """Start tracking registered shared objects. Installs lockcheck too
    if absent (guard sets come from its held-lock stack); that piggyback
    install is torn down again by :func:`uninstall`. Idempotent."""
    global _state
    if _state is not None:
        _state.strict = strict
        return
    st = _State(strict=strict)
    if not lockcheck.installed():
        lockcheck.install(strict=False)
        st.installed_lockcheck = True
    _state = st
    _wrap_metrics_registry()


def uninstall() -> None:
    global _state
    st = _state
    _state = None
    _unpatch_all()
    _labels.clear()
    _unwrap_metrics_registry()
    if st is not None and st.installed_lockcheck:
        lockcheck.uninstall()


def reset() -> None:
    """Clear cells and recorded violations (tracker stays installed,
    registrations stay live)."""
    st = _state
    if st is None:
        return
    with st.lock:
        st.cells.clear()
        st.violations.clear()
        st.reported.clear()


def violations() -> list[Violation]:
    st = _state
    if st is None:
        return []
    with st.lock:
        return list(st.violations)


@contextlib.contextmanager
def session(strict: bool = False):
    """Install for the duration of a block. Non-strict by default so a
    chaos scenario runs to completion and the test asserts
    ``violations() == []`` at teardown (strict raises inside worker
    threads, which surfaces as an unrelated secondary failure)."""
    fresh = _state is None
    install(strict=strict)
    if not fresh:
        reset()
    try:
        yield
    finally:
        if fresh:
            uninstall()
        # else: leave the process-wide (env-driven) install in place
