"""Built-in sampling profiler.

Counterpart of reference ``standalone/src/main/java/filodb/standalone/
SimpleProfiler.java:36`` (558-line stack-sampling profiler started by
FiloServer): samples all thread stacks at a fixed interval, aggregates hot
frames, and periodically logs a top-N report. Pure stdlib
(``sys._current_frames``).
"""

from __future__ import annotations

import logging
import sys
import threading
import time
import traceback
from collections import Counter

log = logging.getLogger(__name__)


class SimpleProfiler:
    def __init__(self, sample_interval_s: float = 0.01,
                 report_interval_s: float = 60.0, top_n: int = 20):
        self.sample_interval_s = sample_interval_s
        self.report_interval_s = report_interval_s
        self.top_n = top_n
        self._counts: Counter = Counter()
        self._samples = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "SimpleProfiler":
        if self._thread:
            return self
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="simple-profiler")
        self._thread.start()
        return self

    def _loop(self):
        last_report = time.monotonic()
        me = threading.get_ident()
        while not self._stop.wait(self.sample_interval_s):
            for tid, frame in sys._current_frames().items():
                if tid == me:
                    continue
                stack = traceback.extract_stack(frame, limit=1)
                if stack:
                    f = stack[-1]
                    self._counts[f"{f.filename}:{f.lineno} {f.name}"] += 1
            self._samples += 1
            if time.monotonic() - last_report >= self.report_interval_s:
                log.info("profiler report:\n%s", self.report())
                last_report = time.monotonic()

    def report(self, top_n: int | None = None) -> str:
        total = sum(self._counts.values()) or 1
        lines = [f"{n:6d} ({100.0 * n / total:5.1f}%)  {frame}"
                 for frame, n in self._counts.most_common(top_n or self.top_n)]
        return "\n".join(lines)

    def stop(self) -> str:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        return self.report()
