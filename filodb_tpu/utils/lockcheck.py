"""Debug runtime lock-order validator (``FILODB_LOCKCHECK=1``).

The static pass (:mod:`filodb_tpu.analysis.lockdiscipline`) approximates
lock identity lexically, so it cannot order two locks created at the
same site or see cross-object call chains. This module covers that gap
at runtime, ThreadSanitizer-style but at lock granularity:

- :func:`install` replaces ``threading.Lock``/``threading.RLock`` with
  checked wrappers. Each wrapper is keyed by its CREATION SITE
  (``file:line``), so every ``with self._lock:`` across all instances
  of a class maps to one graph node — the same approximation the static
  pass uses, which is what makes an A→B vs B→A report meaningful.
- Each thread keeps its held-lock stack; acquiring lock B while holding
  A adds the edge ``site(A) → site(B)`` to a global order graph. An
  acquisition whose edge closes a cycle records a
  :class:`LockOrderViolation` (and raises, unless ``strict=False``).
- Registered blocking calls (``time.sleep``, ``queue.Queue.get``,
  ``threading.Thread.join``) made while ANY checked lock is held record
  a :class:`BlockingUnderLockViolation`.

Known gaps, accepted by design: locks created BEFORE :func:`install`
(module import order) and locks captured by value at class-definition
time (``field(default_factory=threading.Lock)``) are not wrapped; the
static pass still sees those. Same-site edges (two instances of one
class) are skipped for cycle purposes — instance order is not expressible
at site granularity — but still count as "a lock is held" for blocking
checks.

Usage in tests::

    with lockcheck.session():
        ... run chaos scenario ...
    assert lockcheck.violations() == []

Setting ``FILODB_LOCKCHECK=1`` before importing ``filodb_tpu`` installs
the checker for the whole process (see ``filodb_tpu/__init__``).
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from dataclasses import dataclass, field

__all__ = [
    "BlockingUnderLockViolation",
    "LockOrderViolation",
    "Violation",
    "enabled_by_env",
    "install",
    "installed",
    "reset",
    "session",
    "uninstall",
    "violations",
]

_ENV_FLAG = "FILODB_LOCKCHECK"


@dataclass(frozen=True)
class Violation:
    kind: str        # "lock-order-cycle" | "blocking-under-lock"
    thread: str
    detail: str

    def render(self) -> str:
        return f"[{self.kind}] thread={self.thread}: {self.detail}"


class LockOrderViolation(RuntimeError):
    pass


class BlockingUnderLockViolation(RuntimeError):
    pass


@dataclass
class _State:
    strict: bool = True
    # creation-site graph: src site -> {dst site -> example detail}
    edges: dict = field(default_factory=dict)
    violations: list = field(default_factory=list)
    lock: threading.Lock = field(default_factory=threading.Lock)
    # sites already reported, so one bad shape doesn't flood the list
    reported: set = field(default_factory=set)


_state: _State | None = None
_tls = threading.local()

_real_lock = threading.Lock
_real_rlock = threading.RLock
_real_sleep = time.sleep
_real_queue_get = queue.Queue.get
_real_thread_join = threading.Thread.join


def _held() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _creation_site() -> str:
    """First stack frame outside this module and outside ``threading`` —
    the line that called ``threading.Lock()``."""
    import sys
    f = sys._getframe(2)
    this = __file__
    while f is not None:
        fn = f.f_code.co_filename
        if fn != this and "threading" not in fn:
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "<unknown>"


def _record_violation(exc_cls, kind: str, detail: str,
                      dedupe_key) -> None:
    st = _state
    if st is None:
        return
    with st.lock:
        if dedupe_key in st.reported:
            return
        st.reported.add(dedupe_key)
        v = Violation(kind, threading.current_thread().name, detail)
        st.violations.append(v)
    if st.strict:
        raise exc_cls(v.render())


def _check_cycle(new_site: str) -> None:
    """Before pushing ``new_site``, add edges held→new and verify the
    graph stays acyclic. DFS from new_site back to any held site."""
    st = _state
    held = _held()
    if st is None or not held:
        return
    srcs = {s for s, _ in held if s != new_site}
    if not srcs:
        return
    with st.lock:
        for src in srcs:
            st.edges.setdefault(src, set()).add(new_site)
        # reachability: new_site ->* src means src -> new_site closed a
        # cycle
        seen = set()
        frontier = [new_site]
        path_hit = None
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            if cur in srcs and cur != new_site:
                path_hit = cur
                break
            frontier.extend(st.edges.get(cur, ()))
    if path_hit is not None:
        _record_violation(
            LockOrderViolation, "lock-order-cycle",
            f"acquiring lock created at {new_site} while holding "
            f"{path_hit} closes an order cycle "
            f"({path_hit} -> {new_site} and {new_site} ->* {path_hit} "
            f"both observed)",
            ("cycle", new_site, path_hit))


def _push(site: str, obj) -> None:
    _held().append((site, id(obj)))


def _pop(obj) -> None:
    stack = _held()
    for i in range(len(stack) - 1, -1, -1):
        if stack[i][1] == id(obj):
            del stack[i]
            return


class _CheckedLockBase:
    """Delegating wrapper over a real lock primitive. Implements enough
    of the lock protocol for ``threading.Condition(lock)`` to accept it
    (``_release_save``/``_acquire_restore``/``_is_owned`` on the RLock
    variant)."""

    def __init__(self, inner):
        self._inner = inner
        self._site = _creation_site()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            _check_cycle(self._site)
        got = self._inner.acquire(blocking, timeout)
        if got:
            _push(self._site, self)
        return got

    def release(self):
        self._inner.release()
        _pop(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        # delegate the rest of the primitive's surface (e.g. the
        # _at_fork_reinit hook concurrent.futures registers on a
        # module-level lock) straight to the wrapped lock
        return getattr(self._inner, name)

    def __repr__(self):
        return f"<checked {self._inner!r} from {self._site}>"


class _CheckedLock(_CheckedLockBase):
    pass


class _CheckedRLock(_CheckedLockBase):
    # Condition integration: these mirror RLock's private protocol
    def _release_save(self):
        # full release (all recursion levels); Condition.wait calls this
        state = self._inner._release_save() \
            if hasattr(self._inner, "_release_save") else None
        _pop(self)
        return state

    def _acquire_restore(self, state):
        if hasattr(self._inner, "_acquire_restore"):
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        _push(self._site, self)

    def _is_owned(self):
        if hasattr(self._inner, "_is_owned"):
            return self._inner._is_owned()
        return any(lid == id(self) for _, lid in _held())


def _checked_lock_factory():
    if _state is None:
        return _real_lock()
    return _CheckedLock(_real_lock())


def _checked_rlock_factory():
    if _state is None:
        return _real_rlock()
    return _CheckedRLock(_real_rlock())


def _holding_any() -> bool:
    return bool(_held())


def _blocking(desc: str) -> None:
    if _state is None or not _holding_any():
        return
    held = ", ".join(dict.fromkeys(s for s, _ in _held()))
    _record_violation(
        BlockingUnderLockViolation, "blocking-under-lock",
        f"{desc} while holding lock(s) created at {held}",
        ("blocking", desc, held))


def _checked_sleep(secs):
    _blocking(f"time.sleep({secs})")
    _real_sleep(secs)


def _checked_queue_get(self, block=True, timeout=None):
    if block:
        _blocking("queue.Queue.get(block=True)")
    return _real_queue_get(self, block, timeout)


def _checked_thread_join(self, timeout=None):
    _blocking(f"Thread.join({self.name})")
    return _real_thread_join(self, timeout)


# --------------------------------------------------------------------------
# lifecycle

def installed() -> bool:
    return _state is not None


def enabled_by_env() -> bool:
    return os.environ.get(_ENV_FLAG, "") not in ("", "0", "false")


def install(strict: bool = True) -> None:
    """Patch the lock factories and blocking calls. Idempotent; locks
    created before this call stay unchecked."""
    global _state
    if _state is not None:
        _state.strict = strict
        return
    _state = _State(strict=strict)
    threading.Lock = _checked_lock_factory
    threading.RLock = _checked_rlock_factory
    time.sleep = _checked_sleep
    queue.Queue.get = _checked_queue_get
    threading.Thread.join = _checked_thread_join


def uninstall() -> None:
    global _state
    threading.Lock = _real_lock
    threading.RLock = _real_rlock
    time.sleep = _real_sleep
    queue.Queue.get = _real_queue_get
    threading.Thread.join = _real_thread_join
    _state = None


def reset() -> None:
    """Clear the order graph and recorded violations (checker stays
    installed)."""
    st = _state
    if st is None:
        return
    with st.lock:
        st.edges.clear()
        st.violations.clear()
        st.reported.clear()


def violations() -> list[Violation]:
    st = _state
    if st is None:
        return []
    with st.lock:
        return list(st.violations)


@contextlib.contextmanager
def session(strict: bool = False):
    """Install for the duration of a block and yield the live violation
    list via :func:`violations`. Non-strict by default so a test can run
    the whole scenario and assert ``violations() == []`` at the end
    (strict mode raises inside worker threads, which usually surfaces as
    an unrelated secondary failure)."""
    fresh = _state is None
    install(strict=strict)
    if not fresh:
        reset()
    try:
        yield
    finally:
        if fresh:
            uninstall()
        # else: leave the process-wide install (env-driven) in place
