"""Resilience primitives for the distributed query path.

Counterpart of the reference's fault-tolerance substrate: Akka supervision +
phi-accrual failure detection tolerate lost peers (``ShardManager.scala:28``),
``HighAvailabilityPlanner`` routes around known failures, and queries carry
a submit-time deadline. Here the same properties are provided as explicit,
injectable primitives threaded through the exec tree:

- :class:`Deadline` — one per query; every downstream socket/HTTP timeout on
  the distributed path derives from it instead of a hard-coded constant.
- :class:`RetryPolicy` — exponential backoff + jitter with a retry budget;
  clock and sleep are injectable so tests never sleep on the wall clock.
- :class:`CircuitBreaker` — per-peer closed/open/half-open breaker; open
  peers are skipped (the scatter-gather treats them as lost children).
- :class:`FaultInjector` — a process-global registry of named fault sites;
  tests arm connection errors, slow responses and malformed frames at
  instrumented call sites to exercise the failure paths deterministically.

Metrics exported through ``utils.metrics``: ``filodb_query_retries_total``,
``filodb_breaker_state`` (0=closed, 1=half-open, 2=open, per peer) and
``filodb_partial_results_total``.
"""

from __future__ import annotations

import random
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from filodb_tpu.utils.metrics import Gauge, get_counter

# ---------------------------------------------------------------------------
# errors


class DeadlineExceeded(TimeoutError):
    """The query's deadline expired (reference: query timeout in
    ``QueryContext``/actor ask timeouts)."""


class CircuitOpenError(ConnectionError):
    """The peer's circuit breaker is open — the call was skipped without
    dialing. Subclasses ConnectionError so scatter-gather treats a skipped
    peer exactly like a lost one (partial result below the threshold)."""


class RemoteQueryError(RuntimeError):
    """A remote endpoint answered with an error (tagged with the endpoint,
    not a raw transport traceback)."""


# ---------------------------------------------------------------------------
# deadline


@dataclass
class Deadline:
    """Absolute per-query deadline on an injectable monotonic clock.

    Created once per query (``QueryService``), carried on ``ExecContext``;
    every socket/HTTP timeout on the distributed path is derived from the
    remaining time via :meth:`timeout`.
    """

    deadline_s: float  # absolute instant on ``clock``
    clock: "callable" = time.monotonic

    @classmethod
    def after(cls, timeout_s: float, clock=time.monotonic) -> "Deadline":
        return cls(clock() + timeout_s, clock)

    def remaining(self) -> float:
        return self.deadline_s - self.clock()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0

    def timeout(self, cap: float | None = None, what: str = "") -> float:
        """Remaining seconds, optionally capped — the value to hand to a
        socket/HTTP call. Raises :class:`DeadlineExceeded` when nothing
        remains, so an exhausted query fails before dialing."""
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"query deadline exceeded{' before ' + what if what else ''}"
                f" ({-rem:.3f}s past)")
        return min(rem, cap) if cap is not None else rem

    def check(self, what: str = "") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"query deadline exceeded{' in ' + what if what else ''}")


# ---------------------------------------------------------------------------
# retry

_retries_total = get_counter("filodb_query_retries")


@dataclass
class RetryPolicy:
    """Exponential backoff + full jitter with a total-sleep budget.

    ``sleep``/``rng`` are injectable: deterministic tests pass a recording
    sleep and a fixed rng, so no test ever waits on the wall clock.
    """

    max_attempts: int = 3
    base_backoff_s: float = 0.05
    max_backoff_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.5          # fraction of the backoff randomized
    budget_s: float | None = None  # cap on total sleep across attempts
    sleep: "callable" = time.sleep
    rng: "callable" = random.random

    def backoff(self, attempt: int) -> float:
        """Backoff before retry number ``attempt`` (1-based)."""
        raw = min(self.base_backoff_s * (self.multiplier ** (attempt - 1)),
                  self.max_backoff_s)
        return raw * (1.0 - self.jitter + self.jitter * self.rng())

    def call(self, fn, retry_on: tuple = (ConnectionError, OSError),
             deadline: Deadline | None = None, on_retry=None, site: str = ""):
        """Run ``fn`` with retries. Retries stop when attempts or the sleep
        budget are exhausted, or when the deadline can no longer cover the
        next backoff."""
        slept = 0.0
        attempt = 1
        while True:
            try:
                return fn()
            except retry_on as e:
                if isinstance(e, (CircuitOpenError, DeadlineExceeded)):
                    raise  # never retry a skip/timeout decision
                delay = self.backoff(attempt)
                out_of_attempts = attempt >= self.max_attempts
                out_of_budget = (self.budget_s is not None
                                 and slept + delay > self.budget_s)
                out_of_time = (deadline is not None
                               and deadline.remaining() <= delay)
                if out_of_attempts or out_of_budget or out_of_time:
                    raise
                _retries_total.inc()
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep(delay)
                slept += delay
                attempt += 1


# ---------------------------------------------------------------------------
# circuit breaker

CLOSED, HALF_OPEN, OPEN = "closed", "half-open", "open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """Per-peer closed/open/half-open breaker.

    Closed: calls flow; consecutive failures >= ``failure_threshold`` opens
    it. Open: calls are skipped (:class:`CircuitOpenError`) until
    ``reset_timeout_s`` elapses, then one probe is admitted (half-open).
    Half-open: the probe's success closes the breaker; its failure re-opens
    it for another ``reset_timeout_s``.
    """

    def __init__(self, key: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 10.0, clock=time.monotonic):
        self.key = key
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._state = CLOSED
        self._opened_at = 0.0
        self._probing = False
        self._gauge = Gauge("filodb_breaker_state", {"peer": key})

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state_locked()

    def _effective_state_locked(self) -> str:
        if self._state == OPEN and \
                self.clock() - self._opened_at >= self.reset_timeout_s:
            self._state = HALF_OPEN
            self._probing = False
            self._gauge.set(_STATE_VALUE[HALF_OPEN])
        return self._state

    @property
    def is_open(self) -> bool:
        return self.state == OPEN

    def allow(self) -> bool:
        """Whether a call may proceed now. In half-open, only a single
        probe is admitted until it reports back."""
        with self._lock:
            st = self._effective_state_locked()
            if st == CLOSED:
                return True
            if st == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def guard(self) -> None:
        if not self.allow():
            raise CircuitOpenError(
                f"circuit breaker open for peer {self.key}")

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            if self._state != CLOSED:
                self._state = CLOSED
                self._gauge.set(_STATE_VALUE[CLOSED])

    def force_open(self) -> None:
        """Open immediately — used by the cluster failure detector when a
        peer is declared down, so queries skip it without paying a connect
        timeout first."""
        with self._lock:
            self._state = OPEN
            self._opened_at = self.clock()
            self._probing = False
            self._gauge.set(_STATE_VALUE[OPEN])

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if self._state == HALF_OPEN \
                    or self._failures >= self.failure_threshold:
                self._state = OPEN
                self._opened_at = self.clock()
                self._gauge.set(_STATE_VALUE[OPEN])

    def cancel_probe(self) -> None:
        """The admitted call produced no transport verdict (deadline
        expired before dialing, fault injected at an off-path site):
        free the half-open probe slot so a later call may probe again.
        Without this, an exception that bypasses record_success/
        record_failure would leave ``_probing`` set and wedge the
        breaker half-open forever."""
        with self._lock:
            self._probing = False

    @contextmanager
    def calling(self, transport_errors: tuple = (ConnectionError, OSError)):
        """Admit one call (:meth:`guard`) and guarantee exactly one
        outcome on every exit path: clean exit records success, a
        ``transport_errors`` exception records failure (except
        :class:`CircuitOpenError`/:class:`DeadlineExceeded` — a skip or
        deadline verdict says nothing about the peer's health), and any
        other exception releases the probe slot without a verdict.

        The yielded handle lets the body record an outcome explicitly
        first (e.g. an HTTP error status means the peer ANSWERED —
        transport healthy — even though the call raises); whichever of
        success/failure/release happens first wins.
        """
        self.guard()
        outcome = _BreakerOutcome(self)
        try:
            yield outcome
        except transport_errors as e:
            if not isinstance(e, (CircuitOpenError, DeadlineExceeded)):
                outcome.failure()
            raise
        else:
            outcome.success()
        finally:
            outcome.release()


class _BreakerOutcome:
    """One-shot outcome handle yielded by :meth:`CircuitBreaker.calling`."""

    def __init__(self, breaker: CircuitBreaker):
        self._breaker = breaker
        self._done = False

    def success(self) -> None:
        if not self._done:
            self._done = True
            self._breaker.record_success()

    def failure(self) -> None:
        if not self._done:
            self._done = True
            self._breaker.record_failure()

    def release(self) -> None:
        if not self._done:
            self._done = True
            self._breaker.cancel_probe()


_breakers: dict[str, CircuitBreaker] = {}
_breakers_lock = threading.Lock()


def breaker_for(key: str, **defaults) -> CircuitBreaker:
    """Process-global per-peer breaker registry (one breaker per peer,
    shared by every dispatcher/connection that talks to it)."""
    with _breakers_lock:
        b = _breakers.get(key)
        if b is None:
            cfg = dict(config().breaker_defaults)
            cfg.update(defaults)
            b = _breakers[key] = CircuitBreaker(key, **cfg)
        return b


def reset_breakers() -> None:
    """Drop all breaker state (tests)."""
    with _breakers_lock:
        _breakers.clear()


# ---------------------------------------------------------------------------
# peer latency (EWMA) — replica read routing

_peer_latency: dict[str, float] = {}
_peer_latency_lock = threading.Lock()
PEER_LATENCY_ALPHA = 0.3  # weight of the newest sample


def record_peer_latency(key: str, seconds: float) -> None:
    """Fold one observed dispatch round-trip into the peer's EWMA. Keys
    match the breaker registry ("host:port" for remote peers, the node
    name for in-process members); the replica read path orders candidates
    by this value (coordinator/replication.py)."""
    with _peer_latency_lock:
        prev = _peer_latency.get(key)
        _peer_latency[key] = seconds if prev is None else \
            prev + PEER_LATENCY_ALPHA * (seconds - prev)


def peer_latency(key: str) -> float | None:
    """Current EWMA dispatch latency for a peer; None before any sample."""
    with _peer_latency_lock:
        return _peer_latency.get(key)


def reset_peer_latency() -> None:
    """Drop all latency estimates (tests)."""
    with _peer_latency_lock:
        _peer_latency.clear()


# ---------------------------------------------------------------------------
# process-wide resilience config (defaults; overridable via config.py)


@dataclass
class ResilienceConfig:
    query_timeout_s: float = 30.0
    retry_max_attempts: int = 2        # 1 retry on a fresh socket
    retry_base_backoff_s: float = 0.02
    retry_max_backoff_s: float = 1.0
    breaker_failure_threshold: int = 5
    breaker_reset_s: float = 10.0
    partial_max_fraction: float = 0.5  # children allowed to fail per gather
    allow_partial: bool = True

    @property
    def breaker_defaults(self) -> dict:
        return {"failure_threshold": self.breaker_failure_threshold,
                "reset_timeout_s": self.breaker_reset_s}


_config = ResilienceConfig()


def config() -> ResilienceConfig:
    return _config


def configure(**kw) -> ResilienceConfig:
    """Apply server-config overrides (``config.py`` ``resilience`` block)."""
    for k, v in kw.items():
        if hasattr(_config, k):
            setattr(_config, k, v)
    return _config


def default_retry_policy(**kw) -> RetryPolicy:
    c = _config
    base = dict(max_attempts=c.retry_max_attempts,
                base_backoff_s=c.retry_base_backoff_s,
                max_backoff_s=c.retry_max_backoff_s)
    base.update(kw)
    return RetryPolicy(**base)


# ---------------------------------------------------------------------------
# fault injection


@dataclass
class Fault:
    """One armed fault: raise ``error`` and/or delay, ``times`` times, at a
    named site, optionally filtered by a ``match`` predicate over the
    site's context kwargs."""

    error: "BaseException | type | None" = None
    delay_s: float = 0.0
    times: int | None = None      # None = unlimited
    match: "callable | None" = None
    sleep: "callable" = time.sleep
    fired: int = 0                # observability for tests

    def _applies(self, ctx: dict) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        return self.match is None or bool(self.match(ctx))


class FaultInjector:
    """Process-global registry of named fault sites.

    Production code calls ``FaultInjector.fire("site", **ctx)`` at
    instrumented points — a cheap no-op dict lookup unless a test armed a
    fault there. Instrumented sites:

    - ``gather.child``      (ctx: index, shards, plan) — scatter-gather child
    - ``remote.dispatch``   (ctx: host, port)  — plan shipping send
    - ``remote.connect``    (ctx: host, port)  — socket establishment
    - ``promql.remote``     (ctx: endpoint)    — cross-cluster HTTP exec
    - ``store.call``        (ctx: host, port, op) — remote column store
    - ``node.dispatch``     (ctx: node)        — in-cluster node dispatch
    - ``shard.ingest``      (ctx: dataset, shard, offset) — per-container
      shard ingest (stall/error injection for freshness-alert tests)
    - ``replica.tail``      (ctx: node, dataset, shard) — follower tail
      loop top (``coordinator/replication.py``)
    - ``replica.dispatch``  (ctx: node, shard) — per-candidate replica
      read dispatch (hedging/failover tests)
    - ``objectstore.put``   (ctx: key)         — object-store segment upload
    - ``migration.*``       (ctx: dataset, shard, source, dest, phase) —
      live-migration kill-points, one per state transition
      (``coordinator/migration.py`` ``KILL_POINTS``)
    - ``rules.eval``        (ctx: group, start, end) — standing-query group
      evaluation start (``rules/manager.py``)
    - ``rules.write``       (ctx: group, rule, count) — rule-output write,
      fired before the sink append so a kill leaves the watermark unmoved
    """

    _faults: dict[str, list[Fault]] = {}
    _lock = threading.Lock()

    @classmethod
    def arm(cls, site: str, error=None, delay_s: float = 0.0,
            times: int | None = None, match=None,
            sleep=time.sleep) -> Fault:
        f = Fault(error=error, delay_s=delay_s, times=times, match=match,
                  sleep=sleep)
        with cls._lock:
            cls._faults.setdefault(site, []).append(f)
        return f

    @classmethod
    def fire(cls, site: str, **ctx) -> None:
        if not cls._faults:  # hot path: nothing armed anywhere
            return
        with cls._lock:
            faults = list(cls._faults.get(site, ()))
        for f in faults:
            if not f._applies(ctx):
                continue
            f.fired += 1
            if f.delay_s:
                f.sleep(f.delay_s)
            if f.error is not None:
                err = f.error
                if isinstance(err, type):
                    err = err(f"fault injected at {site}")
                raise err

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._faults.clear()

    @classmethod
    def armed(cls) -> bool:
        return bool(cls._faults)
