"""Minimal DNS SRV resolver over stdlib sockets (RFC 1035 + RFC 2782).

Counterpart of reference ``akka-bootstrapper/.../DnsSrvClusterSeedDiscovery
.scala:1-122`` (which leans on dnsjava). This image has no dnspython, so the
wire format is spoken directly: one UDP query (QTYPE=SRV), answer parsing
with full name-compression support, answers ordered by (priority, -weight)
per RFC 2782. TCP fallback on truncation is intentionally omitted — seed
lists are small.
"""

from __future__ import annotations

import os
import secrets
import socket
import struct
from dataclasses import dataclass

QTYPE_SRV = 33
QCLASS_IN = 1


class DnsError(RuntimeError):
    pass


@dataclass(frozen=True)
class SrvRecord:
    target: str
    port: int
    priority: int
    weight: int


def encode_qname(name: str) -> bytes:
    out = bytearray()
    for label in name.rstrip(".").split("."):
        raw = label.encode("idna") if not label.isascii() else label.encode()
        if not 0 < len(raw) < 64:
            raise DnsError(f"bad label in {name!r}")
        out.append(len(raw))
        out += raw
    out.append(0)
    return bytes(out)


def build_query(name: str, txid: int) -> bytes:
    # header: id, flags=RD, qdcount=1
    header = struct.pack(">HHHHHH", txid, 0x0100, 1, 0, 0, 0)
    return header + encode_qname(name) + struct.pack(">HH", QTYPE_SRV,
                                                     QCLASS_IN)


def read_name(msg: bytes, off: int, depth: int = 0) -> tuple[str, int]:
    """Decode a (possibly compressed) domain name; returns (name, next_off).
    ``next_off`` is the offset after the name AT THIS POSITION (a pointer
    consumes 2 bytes regardless of where it lands)."""
    if depth > 16:
        raise DnsError("compression loop")
    labels = []
    while True:
        if off >= len(msg):
            raise DnsError("truncated name")
        n = msg[off]
        if n == 0:
            return ".".join(labels), off + 1
        if n & 0xC0 == 0xC0:  # compression pointer
            if off + 2 > len(msg):
                raise DnsError("truncated pointer")
            ptr = struct.unpack(">H", msg[off:off + 2])[0] & 0x3FFF
            if ptr >= off:
                raise DnsError("forward pointer")
            suffix, _ = read_name(msg, ptr, depth + 1)
            return ".".join(labels + ([suffix] if suffix else [])), off + 2
        if n & 0xC0:
            raise DnsError("bad label type")
        off += 1
        labels.append(msg[off:off + n].decode("ascii", "replace"))
        off += n


def parse_srv_response(msg: bytes, txid: int) -> list[SrvRecord]:
    if len(msg) < 12:
        raise DnsError("short response")
    rid, flags, qd, an, _, _ = struct.unpack(">HHHHHH", msg[:12])
    if rid != txid:
        raise DnsError("transaction id mismatch")
    rcode = flags & 0xF
    if rcode == 3:  # NXDOMAIN
        return []
    if rcode != 0:
        raise DnsError(f"server rcode {rcode}")
    off = 12
    for _ in range(qd):  # skip question section
        _, off = read_name(msg, off)
        off += 4
    out = []
    for _ in range(an):
        _, off = read_name(msg, off)
        if off + 10 > len(msg):
            raise DnsError("truncated answer")
        rtype, rclass, _ttl, rdlen = struct.unpack(">HHIH",
                                                   msg[off:off + 10])
        off += 10
        rdata_end = off + rdlen
        if rdata_end > len(msg):
            raise DnsError("truncated rdata")
        if rtype == QTYPE_SRV and rclass == QCLASS_IN:
            if rdlen < 7:
                raise DnsError("short SRV rdata")
            prio, weight, port = struct.unpack(">HHH", msg[off:off + 6])
            target, _ = read_name(msg, off + 6)
            out.append(SrvRecord(target, port, prio, weight))
        off = rdata_end
    out.sort(key=lambda r: (r.priority, -r.weight))
    return out


def system_resolver() -> tuple[str, int]:
    """First nameserver from /etc/resolv.conf (127.0.0.53 systemd stub is
    fine — it speaks real DNS)."""
    try:
        with open("/etc/resolv.conf") as f:
            for line in f:
                parts = line.split()
                if len(parts) >= 2 and parts[0] == "nameserver":
                    return parts[1], 53
    except OSError:
        pass
    return "127.0.0.1", 53


def resolve_srv(name: str, server: str | None = None, port: int | None = None,
                timeout: float = 2.0) -> list[SrvRecord]:
    """Resolve SRV records for ``name`` (e.g. ``_filodb._tcp.example.com``).

    ``server``/``port`` override the system resolver (tests point this at a
    stub). Env override: ``FILODB_DNS_SERVER=host[:port]``."""
    if server is None:
        env = os.environ.get("FILODB_DNS_SERVER")
        if env:
            host, _, p = env.partition(":")
            try:
                server, port = host, int(p) if p else 53
            except ValueError as e:
                raise DnsError(f"bad FILODB_DNS_SERVER {env!r}") from e
        else:
            server, sys_port = system_resolver()
            port = port or sys_port
    txid = secrets.randbelow(1 << 16)
    query = build_query(name, txid)
    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
        s.settimeout(timeout)
        s.sendto(query, (server, port or 53))
        msg, _ = s.recvfrom(4096)
    return parse_srv_response(msg, txid)
