"""Minimal in-process metrics registry.

Counterpart of the reference's Kamon counters/gauges/histograms
(``TimeSeriesShardStats``, ``KamonLogger.scala``): a process-wide registry that
the HTTP server exposes in Prometheus text exposition format (the reference's
"metrics sink" concept, ``README.md:860-876``).

Updates are thread-safe: ``Counter.inc``, ``Gauge.set``, and
``Histogram.observe`` synchronize on a per-metric lock, since updates race
across gather workers, the write-behind uploader, and rules threads.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict

log = logging.getLogger("filodb.metrics")

_registry: dict[str, "Metric"] = {}
_lock = threading.Lock()

# GaugeFn callbacks whose first failure has already been logged (keyed by
# metric key) — one log line per broken callback, not one per scrape
_scrape_error_logged: set[str] = set()


class Metric:
    def __init__(self, name: str, tags: dict[str, str] | None = None,
                 help: str | None = None):
        self.name = name
        self.tags = tags or {}
        self.help = help or name
        self._mlock = threading.Lock()
        key = self._key()
        with _lock:
            _registry[key] = self

    def _key(self) -> str:
        t = ",".join(f"{k}={v}" for k, v in sorted(self.tags.items()))
        return f"{self.name}{{{t}}}"


class Counter(Metric):
    def __init__(self, name: str, tags: dict[str, str] | None = None,
                 help: str | None = None):
        super().__init__(name, tags, help)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        with self._mlock:
            self.value += n


class Gauge(Metric):
    def __init__(self, name: str, tags: dict[str, str] | None = None,
                 help: str | None = None):
        super().__init__(name, tags, help)
        self.value = 0.0

    def set(self, v: float) -> None:
        with self._mlock:
            self.value = v


class GaugeFn(Metric):
    """Gauge whose value is computed at scrape time from a callback —
    used for state that lives elsewhere (index sizes, pool sizes, arena
    stats) so scrapes never go stale and no update path is needed. A
    callback returning ``None`` (e.g. its subject was torn down) drops
    the series from the exposition instead of rendering NaN."""

    def __init__(self, name: str, fn, tags: dict[str, str] | None = None,
                 help: str | None = None):
        super().__init__(name, tags, help)
        self.fn = fn

    @property
    def value(self) -> float | None:
        try:
            v = self.fn()
            return None if v is None else float(v)
        except Exception:
            SCRAPE_ERRORS.inc()
            key = self._key()
            with _lock:
                first = key not in _scrape_error_logged
                if first:
                    _scrape_error_logged.add(key)
            if first:
                log.warning("metric scrape callback failed: %s", key,
                            exc_info=True)
            return float("nan")


class Histogram(Metric):
    """Fixed-boundary histogram; default bounds suit latency seconds,
    pass ``bounds`` for other units (e.g. query ranges in minutes)."""

    BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
              1.0, 2.5, 5.0, 10.0)

    def __init__(self, name: str, tags: dict[str, str] | None = None,
                 bounds: tuple | None = None, help: str | None = None):
        super().__init__(name, tags, help)
        self.bounds = tuple(bounds) if bounds is not None else self.BOUNDS
        self.buckets = defaultdict(int)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        with self._mlock:
            self.count += 1
            self.sum += v
            for b in self.bounds:
                if v <= b:
                    self.buckets[b] += 1

    def time(self):
        return _Timer(self)


class _Timer:
    def __init__(self, hist: Histogram):
        self.hist = hist

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0)


# broken scrape callbacks are counted, not silently masked as nan: a
# dashboard watching this family catches a dead gauge the first scrape
SCRAPE_ERRORS = Counter("filodb_metric_scrape_errors")


def get_counter(name: str, tags: dict[str, str] | None = None,
                help: str | None = None) -> Counter:
    """Idempotent counter lookup: error-path call sites (flush loops,
    protocol handlers) increment per-(name, tags) counters without each
    having to hold a module-level instance — re-registering would reset the
    running value."""
    t = ",".join(f"{k}={v}" for k, v in sorted((tags or {}).items()))
    key = f"{name}{{{t}}}"
    with _lock:
        m = _registry.get(key)
    if isinstance(m, Counter):
        return m
    return Counter(name, tags, help)


def get_gauge(name: str, tags: dict[str, str] | None = None,
              help: str | None = None) -> Gauge:
    """Idempotent gauge lookup (per-(name, tags)) — the gauge analog of
    :func:`get_counter`, for dynamically-tagged series (per-tenant,
    per-migration) where re-registering would drop the live value."""
    t = ",".join(f"{k}={v}" for k, v in sorted((tags or {}).items()))
    key = f"{name}{{{t}}}"
    with _lock:
        m = _registry.get(key)
    if isinstance(m, Gauge):
        return m
    return Gauge(name, tags, help)


def escape_label_value(v) -> str:
    """Prometheus text-exposition label-value escaping: a backslash,
    double quote, or newline in a tag value would otherwise corrupt the
    whole scrape body."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_prometheus() -> str:
    """Expose all metrics in Prometheus text format, series grouped per
    family under ``# HELP``/``# TYPE`` headers (the help string defaults to
    the family name unless the metric was created with ``help=``)."""
    with _lock:
        metrics = list(_registry.values())
    families: dict[tuple[str, str], list[Metric]] = {}
    for m in metrics:
        if isinstance(m, Counter):
            fam = (f"{m.name}_total", "counter")
        elif isinstance(m, (Gauge, GaugeFn)):
            fam = (m.name, "gauge")
        elif isinstance(m, Histogram):
            fam = (m.name, "histogram")
        else:
            continue
        families.setdefault(fam, []).append(m)
    lines = []
    for (fam, typ), members in families.items():
        help_text = " ".join(str(members[0].help).split())
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} {typ}")
        for m in members:
            tagstr = ",".join(f'{k}="{escape_label_value(v)}"'
                              for k, v in sorted(m.tags.items()))
            tagstr = f"{{{tagstr}}}" if tagstr else ""
            if isinstance(m, Counter):
                lines.append(f"{m.name}_total{tagstr} {m.value}")
            elif isinstance(m, (Gauge, GaugeFn)):
                v = m.value
                if v is None:
                    continue  # subject gone (GaugeFn over a dead shard)
                lines.append(f"{m.name}{tagstr} {v}")
            elif isinstance(m, Histogram):
                for b in m.bounds:
                    t = (tagstr[:-1] + f',le="{b}"}}' if tagstr
                         else f'{{le="{b}"}}')
                    lines.append(f"{m.name}_bucket{t} {m.buckets.get(b, 0)}")
                t = tagstr[:-1] + ',le="+Inf"}' if tagstr else '{le="+Inf"}'
                lines.append(f"{m.name}_bucket{t} {m.count}")
                lines.append(f"{m.name}_count{tagstr} {m.count}")
                lines.append(f"{m.name}_sum{tagstr} {m.sum}")
    return "\n".join(lines) + "\n"
