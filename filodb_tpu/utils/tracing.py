"""Span tracing for the query path.

Counterpart of the reference's Kamon spans around exec-plan execution
(``query/src/main/scala/filodb/query/exec/ExecPlan.scala:101`` "execute-
plan" spans, ``OnDemandPagingShard.scala:48`` ``startODPSpan``): nested,
timed spans collected per query. There is no Kamon/zipkin here; traces are
in-process objects surfaced through the debug HTTP endpoint
(``/promql/{ds}/api/v1/debug/trace``), the slow-query log, and tests.

Zero-cost when inactive: ``span()`` checks a thread-local and no-ops unless
a trace was explicitly started on this thread, so the hot path pays one
attribute lookup per instrumentation point.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

_local = threading.local()


@dataclass
class Span:
    name: str
    start_s: float
    duration_s: float = 0.0
    depth: int = 0
    tags: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        d = {"name": self.name, "depth": self.depth,
             "duration_ms": round(self.duration_s * 1000, 3)}
        if self.tags:
            d["tags"] = {k: v for k, v in self.tags.items()}
        return d


@dataclass
class Trace:
    spans: list[Span] = field(default_factory=list)
    _depth: int = 0

    def as_dicts(self) -> list[dict]:
        return [s.as_dict() for s in self.spans]

    def find(self, name: str) -> list[Span]:
        return [s for s in self.spans if s.name == name]


def current_trace() -> Trace | None:
    return getattr(_local, "trace", None)


@contextmanager
def start_trace():
    """Activate tracing on this thread for the duration of the block."""
    prev = getattr(_local, "trace", None)
    trace = Trace()
    _local.trace = trace
    try:
        yield trace
    finally:
        _local.trace = prev


@contextmanager
def span(name: str, **tags):
    """Record a nested span if a trace is active; otherwise free."""
    trace = getattr(_local, "trace", None)
    if trace is None:
        yield None
        return
    s = Span(name, time.perf_counter(), depth=trace._depth, tags=tags)
    trace.spans.append(s)
    trace._depth += 1
    try:
        yield s
    finally:
        trace._depth -= 1
        s.duration_s = time.perf_counter() - s.start_s


def tag(key: str, value) -> None:
    """Attach a tag to the innermost open span, if tracing."""
    trace = getattr(_local, "trace", None)
    if trace is None or not trace.spans:
        return
    for s in reversed(trace.spans):
        if s.depth == trace._depth - 1:
            s.tags[key] = value
            return
