"""Distributed span tracing, per-query stage metrics, and the slow-query
flight recorder.

Counterpart of the reference's Kamon spans around exec-plan execution
(``query/src/main/scala/filodb/query/exec/ExecPlan.scala:101`` "execute-
plan" spans, ``OnDemandPagingShard.scala:48`` ``startODPSpan``): nested,
timed spans collected per query. There is no Kamon/zipkin here; traces are
in-process objects that cross the wire as plain span dicts:

- A ``TraceContext`` (``query/model.py``) rides ``QueryContext`` through the
  plan-shipping path; ``PlanExecutorServer`` activates a trace for sampled
  queries and ships the remote span tree + expanded ``QueryStats`` back in
  the result frame, where the root grafts it — node-tagged — under the
  dispatching span (:func:`graft_spans`).
- Gather worker threads adopt the caller's trace via :func:`activate`
  (span appends are guarded by a per-trace lock), so fanned-out dispatch
  spans are no longer dropped by the thread-local.
- :func:`traced_query` head-samples queries at ``sample_rate`` and tail-
  captures any query slower than ``slow_query_threshold_ms`` into a bounded
  ring buffer (the flight recorder), surfaced at
  ``/promql/{ds}/api/v1/debug/slow_queries`` on both HTTP fronts and via
  ``filo-cli slowlog``. ``/promql/{ds}/api/v1/debug/trace`` runs one query
  fully traced and records it in the same ring.
- :func:`traced_operation` reuses the machinery for background work (rules
  ticks, objectstore uploads, migration phases); slow operations land in
  the same recorder.
- Completed query traces feed per-stage ``filodb_query_stage_seconds``
  histograms (:func:`observe_stage_times`).

Zero-cost when inactive: ``span()`` checks a thread-local and no-ops unless
a trace was explicitly started on (or handed to) this thread, so the
unsampled hot path pays one attribute lookup per instrumentation point.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

from filodb_tpu.utils.metrics import Histogram, get_counter

_local = threading.local()
_span_ids = itertools.count(1)

# ---------------------------------------------------------------------------
# configuration

@dataclass
class TracingConfig:
    sample_rate: float = 0.0            # head-sampling fraction [0, 1]
    slow_query_threshold_ms: float = 500.0  # tail capture; 0 disables
    slowlog_capacity: int = 128         # flight-recorder ring size
    slow_ingest_threshold_ms: float = 250.0  # ingest-ring capture; 0 off
    ingest_slowlog_capacity: int = 128  # ingest flight-recorder ring size


_config = TracingConfig()


def configure(**overrides) -> TracingConfig:
    """Apply tracing config at boot (``config.py`` "tracing" block)."""
    global _config
    _config = TracingConfig(**overrides)
    _recorder.resize(_config.slowlog_capacity)
    _ingest_recorder.resize(_config.ingest_slowlog_capacity)
    return _config


def config() -> TracingConfig:
    return _config


def should_sample(trace_id: str, rate: float | None = None) -> bool:
    """Deterministic head-sampling verdict for a trace id: the same id
    always samples the same way at a given rate, so retries and tests are
    reproducible across processes."""
    r = _config.sample_rate if rate is None else rate
    if r <= 0.0:
        return False
    if r >= 1.0:
        return True
    h = int.from_bytes(
        hashlib.blake2b(trace_id.encode(), digest_size=8).digest(), "big")
    return (h % 10_000) < int(r * 10_000)


# ---------------------------------------------------------------------------
# spans

@dataclass
class Span:
    name: str
    start_s: float
    duration_s: float = 0.0
    depth: int = 0
    tags: dict = field(default_factory=dict)
    span_id: int = 0
    parent_id: int = 0

    def as_dict(self) -> dict:
        d = {"name": self.name, "depth": self.depth,
             "duration_ms": round(self.duration_s * 1000, 3),
             "span_id": self.span_id, "parent_id": self.parent_id}
        if self.tags:
            d["tags"] = {k: v for k, v in self.tags.items()}
        return d


@dataclass
class Trace:
    spans: list[Span] = field(default_factory=list)
    _depth: int = 0  # legacy field; per-thread depth now lives in _local
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False, compare=False)

    def as_dicts(self) -> list[dict]:
        with self._lock:
            return [s.as_dict() for s in self.spans]

    def find(self, name: str) -> list[Span]:
        with self._lock:
            return [s for s in self.spans if s.name == name]


def current_trace() -> Trace | None:
    return getattr(_local, "trace", None)


def current_span() -> Span | None:
    """Innermost span open on this thread (the adopted parent when none)."""
    stack = getattr(_local, "stack", None)
    if stack:
        return stack[-1]
    return getattr(_local, "base", None)


def _push_state(trace, base):
    prev = (getattr(_local, "trace", None), getattr(_local, "stack", None),
            getattr(_local, "base", None))
    _local.trace, _local.stack, _local.base = trace, [], base
    return prev


def _pop_state(prev):
    _local.trace, _local.stack, _local.base = prev


@contextmanager
def start_trace():
    """Activate tracing on this thread for the duration of the block."""
    trace = Trace()
    prev = _push_state(trace, None)
    try:
        yield trace
    finally:
        _pop_state(prev)


@contextmanager
def activate(trace: Trace, parent: Span | None = None):
    """Adopt an existing trace on this thread (gather-worker handoff).
    New root-level spans opened here parent under ``parent``. A no-op when
    the trace is already active on this thread."""
    if getattr(_local, "trace", None) is trace:
        yield trace
        return
    prev = _push_state(trace, parent)
    try:
        yield trace
    finally:
        _pop_state(prev)


@contextmanager
def span(name: str, **tags):
    """Record a nested span if a trace is active; otherwise free."""
    trace = getattr(_local, "trace", None)
    if trace is None:
        yield None
        return
    stack = _local.stack
    parent = stack[-1] if stack else getattr(_local, "base", None)
    s = Span(name, time.perf_counter(),
             depth=parent.depth + 1 if parent is not None else 0,
             tags=tags, span_id=next(_span_ids),
             parent_id=parent.span_id if parent is not None else 0)
    with trace._lock:
        trace.spans.append(s)
    stack.append(s)
    try:
        yield s
    finally:
        stack.pop()
        s.duration_s = time.perf_counter() - s.start_s


def tag(key: str, value) -> None:
    """Attach a tag to the innermost span open on this thread, if tracing."""
    if getattr(_local, "trace", None) is None:
        return
    stack = getattr(_local, "stack", None)
    if stack:
        stack[-1].tags[key] = value


def graft_spans(span_dicts: list, parent: Span | None = None,
                **extra_tags) -> None:
    """Append a remote span tree (a list of ``Span.as_dict()`` dicts, as
    shipped in ``QueryResult.spans``) to the current trace under ``parent``.
    Top-level remote spans get ``extra_tags`` (e.g. ``node="host:port"``).
    Span ids are remapped to this process's id space so parent links stay
    unambiguous when several peers graft concurrently."""
    trace = getattr(_local, "trace", None)
    if trace is None or not span_dicts:
        return
    base_depth = parent.depth + 1 if parent is not None else 0
    base_parent = parent.span_id if parent is not None else 0
    remap: dict[int, int] = {}
    spans = []
    for d in span_dicts:
        if not isinstance(d, dict) or "name" not in d:
            continue
        sid = next(_span_ids)
        old = d.get("span_id", 0)
        if old:
            remap[old] = sid
        pid = remap.get(d.get("parent_id", 0), 0)
        tags = dict(d.get("tags") or {})
        if not pid:
            pid = base_parent
            tags.update(extra_tags)
        spans.append(Span(d["name"], 0.0,
                          duration_s=float(d.get("duration_ms", 0.0)) / 1000,
                          depth=base_depth + int(d.get("depth", 0)),
                          tags=tags, span_id=sid, parent_id=pid))
    with trace._lock:
        trace.spans.extend(spans)


# ---------------------------------------------------------------------------
# per-stage histograms derived from spans

_STAGES = ("parse", "plan-materialize", "exec-dispatch", "dispatch",
           "mesh-execute", "scan", "decode", "reduce", "odp-page", "cache")
_stage_hists = {}
for _s in _STAGES:
    _stage_hists[_s] = Histogram("filodb_query_stage_seconds",
                                 tags={"stage": _s},
                                 help="query stage latency derived from "
                                      "trace spans")
del _s

_sampled = get_counter("filodb_queries_sampled")
_recorded = get_counter("filodb_slow_queries_recorded")
_ingest_recorded = get_counter("filodb_ingest_slow_recorded")


def observe_stage_times(spans: list[Span]) -> None:
    """Feed ``filodb_query_stage_seconds{stage=...}`` from a completed
    trace. Only whitelisted stage names are observed, bounding label
    cardinality against arbitrary exec-plan class names."""
    for s in spans:
        h = _stage_hists.get(s.name)
        if h is not None:
            h.observe(s.duration_s)


# ---------------------------------------------------------------------------
# flight recorder

class FlightRecorder:
    """Bounded ring buffer of slow/sampled query and operation records."""

    def __init__(self, capacity: int = 128):
        self._rlock = threading.Lock()
        self._ring: deque = deque(maxlen=max(1, int(capacity)))

    def record(self, entry: dict) -> None:
        with self._rlock:
            self._ring.append(entry)

    def snapshot(self) -> list[dict]:
        with self._rlock:
            return list(self._ring)

    def resize(self, capacity: int) -> None:
        with self._rlock:
            self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    def clear(self) -> None:
        with self._rlock:
            self._ring.clear()

    def __len__(self) -> int:
        with self._rlock:
            return len(self._ring)


_recorder = FlightRecorder()

# Separate ring for the ingest pipeline (gateway drain, shard ingest,
# flush, object-store upload): ingest stalls must stay visible even while
# a slow-query storm is churning the query ring, and vice versa.
_ingest_recorder = FlightRecorder()

# traced_operation kinds that belong to the ingest pipeline and therefore
# record into the ingest ring under slow_ingest_threshold_ms
_INGEST_KINDS = frozenset({"gateway", "ingest", "flush", "objectstore"})


def flight_recorder() -> FlightRecorder:
    return _recorder


def ingest_recorder() -> FlightRecorder:
    return _ingest_recorder


def slow_queries(limit: int = 0) -> list[dict]:
    """Flight-recorder entries, newest first."""
    entries = list(reversed(_recorder.snapshot()))
    return entries[:limit] if limit and limit > 0 else entries


def slow_ingest(limit: int = 0) -> list[dict]:
    """Ingest flight-recorder entries, newest first."""
    entries = list(reversed(_ingest_recorder.snapshot()))
    return entries[:limit] if limit and limit > 0 else entries


class _QueryRecord:
    """Handle yielded by :func:`traced_query`; call :meth:`observe` with the
    QueryResult so its stats land in the flight-recorder entry."""

    __slots__ = ("result",)

    def __init__(self):
        self.result = None

    def observe(self, result) -> None:
        self.result = result


def _stats_dict(result) -> dict:
    stats = getattr(result, "stats", None)
    if stats is None:
        return {}
    try:
        return dataclasses.asdict(stats)
    except TypeError:
        return {}


def _finish_query(rec, trace, start_idx, t0, sampled, info) -> None:
    cfg = _config
    duration_ms = (time.perf_counter() - t0) * 1000
    section = []
    if trace is not None:
        with trace._lock:
            section = list(trace.spans[start_idx:])
        observe_stage_times(section)
    if cfg.slow_query_threshold_ms <= 0 \
            or duration_ms <= cfg.slow_query_threshold_ms:
        return
    entry = {"kind": "query", "when": time.time(),
             "duration_ms": round(duration_ms, 3), "sampled": sampled}
    entry.update(info)
    entry["stats"] = _stats_dict(rec.result)
    entry["spans"] = [s.as_dict() for s in section]
    _recorder.record(entry)
    _recorded.inc()


@contextmanager
def traced_query(qcontext, **info):
    """Per-query tracing driver for the query-service entry points.

    Joins an already-active trace (debug endpoint, rules tick) or head-
    samples a fresh one at ``sample_rate``; either way the ``qcontext``
    gets a sampled ``TraceContext`` so remote executors ship their span
    trees back. On exit, feeds stage histograms and tail-captures slow
    queries into the flight recorder (unsampled slow queries record stats
    with an empty span list — set ``sample_rate`` to 1.0 to retain full
    trees for every slow query)."""
    from filodb_tpu.query.model import TraceContext
    rec = _QueryRecord()
    t0 = time.perf_counter()
    outer = getattr(_local, "trace", None)
    if outer is not None:
        if getattr(qcontext, "trace", None) is None:
            qcontext.trace = TraceContext(trace_id=qcontext.query_id,
                                          sampled=True)
        start_idx = len(outer.spans)
        try:
            yield rec
        finally:
            _finish_query(rec, outer, start_idx, t0, True, info)
        return
    if should_sample(qcontext.query_id):
        _sampled.inc()
        qcontext.trace = TraceContext(trace_id=qcontext.query_id,
                                      sampled=True)
        with start_trace() as trace:
            try:
                yield rec
            finally:
                _finish_query(rec, trace, 0, t0, True, info)
    else:
        try:
            yield rec
        finally:
            _finish_query(rec, None, 0, t0, False, info)


def record_slow(kind: str, duration_ms: float, spans: list | None = None,
                stats: dict | None = None, **info) -> None:
    """Record an already-measured slow item (batched query paths that
    cannot wrap :func:`traced_query` around each query)."""
    cfg = _config
    if cfg.slow_query_threshold_ms <= 0 \
            or duration_ms <= cfg.slow_query_threshold_ms:
        return
    entry = {"kind": kind, "when": time.time(),
             "duration_ms": round(duration_ms, 3),
             "sampled": bool(spans)}
    entry.update(info)
    entry["stats"] = stats or {}
    entry["spans"] = spans or []
    _recorder.record(entry)
    _recorded.inc()


@contextmanager
def traced_operation(kind: str, **tags):
    """Trace a background operation (rules tick, gateway drain, shard
    ingest, flush, objectstore upload, migration phase). Operations are
    low-frequency, so they always trace. Slow runs land in a flight
    recorder: ingest-pipeline kinds (``_INGEST_KINDS``) over
    ``slow_ingest_threshold_ms`` go to the ingest ring, everything else
    over ``slow_query_threshold_ms`` to the query ring — so an ingest
    stall stays visible through a slow-query storm and vice versa."""
    if getattr(_local, "trace", None) is not None:
        with span(kind, **tags) as s:
            yield s
        return
    t0 = time.perf_counter()
    with start_trace() as trace:
        with span(kind, **tags) as s:
            yield s
    duration_ms = (time.perf_counter() - t0) * 1000
    cfg = _config
    if kind in _INGEST_KINDS:
        recorder, threshold, counter = (
            _ingest_recorder, cfg.slow_ingest_threshold_ms,
            _ingest_recorded)
    else:
        recorder, threshold, counter = (
            _recorder, cfg.slow_query_threshold_ms, _recorded)
    if threshold > 0 and duration_ms > threshold:
        entry = {"kind": kind, "when": time.time(),
                 "duration_ms": round(duration_ms, 3), "sampled": True}
        entry.update(tags)
        entry["spans"] = trace.as_dicts()
        recorder.record(entry)
        counter.inc()
