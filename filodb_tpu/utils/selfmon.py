"""Self-monitoring: the node's own metric registry as a first-class dataset.

Counterpart of the reference's "monitor FiloDB with a TSDB" deployment
pattern (``PAPER.md``: production FiloDB clusters are watched by pointing a
time-series database at FiloDB's Kamon metrics) — here the node points at
itself.  :class:`MetaMonitor` samples the in-process metric registry
(``utils/metrics.py``) every N seconds, converts each family to gauge
series tagged with node/instance labels, and writes them through the
*normal* ingest path (a rules-style sink: WAL ``LogSink`` in standalone,
``MemstoreSink`` in tests) into a dedicated ``_meta`` dataset.  PromQL,
the result cache, and standing rules/alerts then work over the system's
own telemetry with zero special cases — the default alert group in
``standalone.py`` (ingest lag, breaker open) evaluates against ``_meta``
like any user rule group.

Also home to the end-to-end freshness probe: gateways stamp a sampled
subset of outgoing containers (:class:`E2EStamps`), and the shard-side
ingest worker observes wall-clock deltas into ``filodb_ingest_e2e_seconds``
once the stamped offset is actually queryable in the shard.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque

from filodb_tpu.core.partkey import METRIC_LABEL, PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer
from filodb_tpu.utils import metrics
from filodb_tpu.utils.metrics import Counter, Gauge, GaugeFn, Histogram

log = logging.getLogger("filodb.selfmon")

TICKS = Counter("filodb_selfmon_ticks")
ERRORS = Counter("filodb_selfmon_errors")
SAMPLES = Counter("filodb_selfmon_samples")
SERIES = Gauge("filodb_selfmon_series")
TICK_SECONDS = Histogram("filodb_selfmon_tick_seconds")

# end-to-end ingest freshness: gateway-stamp wall time -> queryable in shard
INGEST_E2E = Histogram(
    "filodb_ingest_e2e_seconds",
    bounds=(0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
            10.0, 30.0, 60.0),
    help="gateway-stamped record to queryable-in-shard, sampled")


def registry_samples(base_labels: dict[str, str],
                     include_buckets: bool = False):
    """Convert the live metric registry to ``(labels, value)`` gauge samples.

    Families follow exposition naming (counters get ``_total``, histograms
    contribute ``_count``/``_sum`` and optionally per-``le`` buckets).
    ``base_labels`` (node/instance/shard-key labels) win on collision: a
    metric tag that would shadow one is remapped to ``exported_<key>``,
    Prometheus-federation style.  ``GaugeFn`` callbacks returning ``None``
    (subject torn down) or NaN (broken callback) are skipped — a NaN
    sample would poison range aggregations over ``_meta``.
    """
    with metrics._lock:
        members = list(metrics._registry.values())
    out = []

    def emit(name: str, tags: dict, value: float) -> None:
        labels = dict(base_labels)
        labels[METRIC_LABEL] = name
        for k, v in tags.items():
            if k in labels:
                k = "exported_" + k
            labels[k] = str(v)
        out.append((labels, float(value)))

    for m in members:
        if isinstance(m, Counter):
            emit(m.name + "_total", m.tags, m.value)
        elif isinstance(m, Histogram):
            emit(m.name + "_count", m.tags, m.count)
            emit(m.name + "_sum", m.tags, m.sum)
            if include_buckets:
                for b in m.bounds:
                    emit(m.name + "_bucket", {**m.tags, "le": str(b)},
                         m.buckets.get(b, 0))
        elif isinstance(m, (Gauge, GaugeFn)):
            v = m.value
            if v is None or v != v:
                continue
            emit(m.name, m.tags, v)
    return out


class MetaMonitor:
    """Background sampler feeding the ``_meta`` dataset.

    ``sink`` is a rules-style sink (``rules.manager.LogSink`` /
    ``MemstoreSink``): ``write(container) -> (count, offsets)``.  Using the
    same sink abstraction as recording rules means ``_meta`` rides the WAL,
    replay, and checkpoint machinery unchanged.
    """

    def __init__(self, sink, interval_s: float = 15.0, *,
                 node: str = "node0", instance: str = "filodb",
                 dataset: str = "_meta", include_buckets: bool = False,
                 workspace: str = "_system", namespace: str = "selfmon"):
        self.sink = sink
        self.interval_s = max(0.05, float(interval_s))
        self.dataset = dataset
        self.include_buckets = include_buckets
        self.base_labels = {"_ws_": workspace, "_ns_": namespace,
                            "node": node, "instance": instance}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run,
                                        name="filodb-selfmon", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        # first tick immediately so tests (and freshly booted nodes) see
        # _meta series without waiting a full interval
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval_s)

    # -- one sample pass ---------------------------------------------------

    def tick(self) -> int:
        """Sample the registry once and write one container to the sink.
        Returns the number of series written (0 on error — selfmon must
        never take down the node it is watching)."""
        with TICK_SECONDS.time():
            try:
                ts_ms = int(time.time() * 1000)
                samples = registry_samples(self.base_labels,
                                           self.include_buckets)
                cont = RecordContainer()
                for labels, v in samples:
                    cont.add(IngestRecord(PartKey.create("gauge", labels),
                                          ts_ms, (v,)))
                if len(cont):
                    self.sink.write(cont)
                TICKS.inc()
                SAMPLES.inc(len(samples))
                SERIES.set(float(len(samples)))
                return len(samples)
            except Exception:
                ERRORS.inc()
                log.warning("selfmon tick failed", exc_info=True)
                return 0


class E2EStamps:
    """Sampled gateway->shard freshness stamps.

    The gateway stamps every Nth drained container per (dataset, shard)
    with its wall-clock send time keyed by log offset; the shard-side
    ingest worker calls :meth:`observe` after committing an offset, which
    pops every stamp at-or-below it and records the wall-clock delta into
    ``filodb_ingest_e2e_seconds``.  Bounded deques keep an ingest stall
    from accumulating stamps without limit (oldest stamps drop first —
    under a stall the *surviving* samples still show the tail latency).
    """

    def __init__(self, sample_every: int = 32, max_pending: int = 256):
        self.sample_every = max(1, int(sample_every))
        self.max_pending = max_pending
        self._lock = threading.Lock()
        self._counts: dict[tuple, int] = {}
        self._pending: dict[tuple, deque] = {}

    def maybe_stamp(self, dataset: str, shard: int, offset: int) -> None:
        key = (dataset, shard)
        with self._lock:
            n = self._counts.get(key, 0)
            self._counts[key] = n + 1
            if n % self.sample_every:
                return
            dq = self._pending.get(key)
            if dq is None:
                dq = self._pending[key] = deque(maxlen=self.max_pending)
            dq.append((offset, time.time()))

    def observe(self, dataset: str, shard: int, offset: int) -> None:
        key = (dataset, shard)
        now = time.time()
        deltas = []
        with self._lock:
            dq = self._pending.get(key)
            if not dq:
                return
            while dq and dq[0][0] <= offset:
                _, t0 = dq.popleft()
                deltas.append(now - t0)
        for d in deltas:
            INGEST_E2E.observe(max(0.0, d))


# process-wide stamp tracker shared by gateway (producer side) and the
# cluster ingest workers (consumer side)
STAMPS = E2EStamps()
