"""Rule definitions: recording and alerting rules parsed from the
``rules:`` config block.

Mirrors the Prometheus rule-file shape (groups of rules with a shared
evaluation ``interval``), restricted to what the standing-query engine
supports: intervals must be whole seconds (the range-query grid is epoch
seconds) and each rule is exactly one of ``record:`` or ``alert:``.
Durations accept either Prometheus duration strings (via
``parse_duration_ms``) or bare numbers meaning seconds.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from filodb_tpu.promql.parser import parse_duration_ms

# record-rule output metric names must round-trip through the selector
# lexer; single colons are the conventional level:metric:operation form
# (``job:http_requests:rate5m``).  ``::`` is reserved by the parser's
# metric::column extension and is rejected up front.
_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_:]*$")

# group names are interpolated into recovery selectors as label values
# (and so are alert names, which additionally must be valid metric names
# per _NAME_RE, matching Prometheus); restrict both to a charset that
# can never break the selector lexer — no quotes, backslashes, or braces
_GROUP_NAME_RE = re.compile(r"^[A-Za-z0-9_.:/\- ]+$")

# synthetic series owned by the manager; a recording rule shadowing one
# would corrupt alert-state recovery
_RESERVED_NAMES = {"ALERTS", "ALERTS_FOR_STATE", "FILODB_RULES_WATERMARK"}

# labels a rule may not override: output identity, alert state, and the
# recovery scope stamp are assigned by the evaluator itself
_RESERVED_LABELS = {"__name__", "_metric_", "alertstate", "_group_"}


@dataclass(frozen=True)
class RecordingRule:
    """``record: <name>`` — expr output written back as series ``name``."""

    record: str
    expr: str
    labels: tuple[tuple[str, str], ...] = ()

    @property
    def name(self) -> str:
        return self.record


@dataclass(frozen=True)
class AlertingRule:
    """``alert: <name>`` — expr output drives inactive→pending→firing."""

    alert: str
    expr: str
    for_ms: int = 0
    labels: tuple[tuple[str, str], ...] = ()
    annotations: tuple[tuple[str, str], ...] = ()

    @property
    def name(self) -> str:
        return self.alert


@dataclass(frozen=True)
class RuleGroup:
    """A set of rules sharing one evaluation interval and watermark."""

    name: str
    interval_ms: int
    dataset: str
    rules: tuple = field(default_factory=tuple)

    @property
    def interval_s(self) -> int:
        return self.interval_ms // 1000


def _duration_ms(value, what: str) -> int:
    if isinstance(value, bool):
        raise ValueError(f"rules: {what} must be a duration, got {value!r}")
    if isinstance(value, (int, float)):
        return int(value * 1000)
    if isinstance(value, str):
        ms = parse_duration_ms(value)
        if ms == 0 and value not in ("0", "0s", "0ms"):
            raise ValueError(f"rules: unparseable duration {value!r} "
                             f"for {what}")
        return ms
    raise ValueError(f"rules: {what} must be a duration, got {value!r}")


def _label_pairs(raw, what: str) -> tuple[tuple[str, str], ...]:
    if not raw:
        return ()
    if not isinstance(raw, dict):
        raise ValueError(f"rules: {what} must be a mapping")
    for k in raw:
        if k in _RESERVED_LABELS:
            raise ValueError(f"rules: {what} may not set reserved "
                             f"label {k!r}")
    return tuple(sorted((str(k), str(v)) for k, v in raw.items()))


def _load_rule(raw: dict, group: str):
    if not isinstance(raw, dict):
        raise ValueError(f"rules: group {group!r}: rule must be a mapping")
    has_record = "record" in raw
    has_alert = "alert" in raw
    if has_record == has_alert:
        raise ValueError(f"rules: group {group!r}: rule must have exactly "
                         f"one of record:/alert:")
    expr = raw.get("expr")
    if not expr or not isinstance(expr, str):
        raise ValueError(f"rules: group {group!r}: rule needs a non-empty "
                         f"expr:")
    labels = _label_pairs(raw.get("labels"), f"group {group!r} labels")
    if has_record:
        name = str(raw["record"])
        if not _NAME_RE.match(name) or "::" in name:
            raise ValueError(f"rules: invalid record name {name!r}")
        if name in _RESERVED_NAMES:
            raise ValueError(f"rules: record name {name!r} is reserved")
        if "for" in raw or "annotations" in raw:
            raise ValueError(f"rules: record rule {name!r} may not set "
                             f"for:/annotations:")
        return RecordingRule(record=name, expr=expr, labels=labels)
    name = str(raw["alert"])
    if not _NAME_RE.match(name):
        # alert names become the alertname label value AND the recovery
        # selector; Prometheus applies the same metric-name restriction
        raise ValueError(f"rules: invalid alert name {name!r}")
    for_ms = _duration_ms(raw.get("for", 0), f"alert {name!r} for:")
    if for_ms < 0:
        raise ValueError(f"rules: alert {name!r} for: must be >= 0")
    ann = raw.get("annotations") or {}
    if not isinstance(ann, dict):
        raise ValueError(f"rules: alert {name!r} annotations must be a "
                         f"mapping")
    return AlertingRule(
        alert=name, expr=expr, for_ms=for_ms, labels=labels,
        annotations=tuple(sorted((str(k), str(v)) for k, v in ann.items())))


def load_groups(block, default_dataset: str) -> list[RuleGroup]:
    """Parse the ``rules.groups`` config list into validated RuleGroups."""
    groups_raw = (block or {}).get("groups", [])
    if not isinstance(groups_raw, list):
        raise ValueError("rules: groups must be a list")
    out: list[RuleGroup] = []
    seen: set[str] = set()
    for g in groups_raw:
        if not isinstance(g, dict) or not g.get("name"):
            raise ValueError("rules: each group needs a name:")
        name = str(g["name"])
        if not _GROUP_NAME_RE.match(name):
            raise ValueError(f"rules: invalid group name {name!r} (group "
                             f"names appear in recovery selectors)")
        if name in seen:
            raise ValueError(f"rules: duplicate group name {name!r}")
        seen.add(name)
        interval_ms = _duration_ms(g.get("interval", "60s"),
                                   f"group {name!r} interval:")
        if interval_ms < 1000 or interval_ms % 1000:
            raise ValueError(f"rules: group {name!r} interval must be a "
                             f"whole number of seconds >= 1s")
        rules = tuple(_load_rule(r, name) for r in g.get("rules", []))
        rule_names = [r.name for r in rules]
        if len(rule_names) != len(set(rule_names)):
            raise ValueError(f"rules: duplicate rule name in group {name!r}")
        out.append(RuleGroup(name=name, interval_ms=interval_ms,
                             dataset=str(g.get("dataset", default_dataset)),
                             rules=rules))
    return out
