"""Alert notification egress: webhook POST on alert state transitions.

The RuleManager's group commit produces :class:`AlertEvent` records
(pending / firing / resolved). :class:`WebhookNotifier` ships them to an
Alertmanager-style webhook — asynchronously, through a bounded queue and
a single daemon worker, so the hand-off from the evaluation thread is a
non-blocking ``put_nowait``. The blocking POST (plus
:class:`~filodb_tpu.utils.resilience.RetryPolicy` backoff) happens only
on the worker thread, never under the manager's state or eval lock —
the lock-discipline pass (LD101) and the runtime checker both verify
this placement.

Delivery semantics: at-most-once. A full queue drops the batch and
counts ``filodb_alerts_notifications_dropped_total`` (alerts state
itself is durable in the alert series; notifications are a best-effort
side channel, the reference's Alertmanager-push posture). Exhausted
retries count ``filodb_alerts_notification_failures_total``.
"""

from __future__ import annotations

import json
import logging
import queue
import threading
import urllib.request
from dataclasses import dataclass, field

from filodb_tpu.utils.metrics import Counter
from filodb_tpu.utils.resilience import FaultInjector, RetryPolicy

log = logging.getLogger("filodb.rules.notify")

notifications_sent = Counter("filodb_alerts_notifications")
notification_failures = Counter("filodb_alerts_notification_failures")
notifications_dropped = Counter("filodb_alerts_notifications_dropped")

PENDING, FIRING, RESOLVED = "pending", "firing", "resolved"


@dataclass(frozen=True)
class AlertEvent:
    """One alert state transition, as committed by a group tick."""

    group: str
    alertname: str
    state: str                    # pending | firing | resolved
    labels: tuple                 # sorted ((k, v), ...) incl. alertname
    annotations: tuple            # ((k, v), ...) from the rule
    value: float                  # rule value at the transition step
    active_since_ms: int          # when the alert became active
    ts_ms: int                    # evaluation step of the transition

    def payload(self) -> dict:
        """Alertmanager-webhook-style single-alert body."""
        return {
            "status": ("resolved" if self.state == RESOLVED
                       else "firing"),
            "labels": dict(self.labels),
            "annotations": dict(self.annotations),
            "startsAt": self.active_since_ms / 1000.0,
            "value": self.value,
            "state": self.state,
            "group": self.group,
            "evaluatedAt": self.ts_ms / 1000.0,
        }


@dataclass
class _Batch:
    events: list


class WebhookNotifier:
    """Bounded-queue webhook shipper with retrying daemon worker.

    ``post`` is injectable for tests (defaults to a urllib POST with
    ``timeout_s``); the retry policy's ``sleep`` is injectable through
    :class:`RetryPolicy` itself, so no test waits on the wall clock.
    """

    def __init__(self, url: str, timeout_s: float = 5.0,
                 retry_policy: RetryPolicy | None = None,
                 queue_depth: int = 256, post=None):
        self.url = url
        self.timeout_s = timeout_s
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=4, base_backoff_s=0.1, max_backoff_s=2.0)
        self._post = post or self._http_post
        self._q: queue.Queue = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run,
                                        name="alert-notifier",
                                        daemon=True)
        self._worker.start()

    # ------------------------------------------------------- producer
    def submit(self, events: list[AlertEvent]) -> bool:
        """Enqueue a transition batch. NON-BLOCKING by contract: the
        caller is the rules evaluation thread and must never wait on
        notification egress. Returns False (and counts drops) when the
        queue is full."""
        if not events:
            return True
        try:
            self._q.put_nowait(_Batch(list(events)))
            return True
        except queue.Full:
            notifications_dropped.inc(len(events))
            log.warning("alert notifier queue full; dropped %d "
                        "event(s)", len(events))
            return False

    # -------------------------------------------------------- worker
    def _http_post(self, body: bytes) -> None:
        req = urllib.request.Request(
            self.url, data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            if r.status >= 300:
                raise ConnectionError(
                    f"webhook returned HTTP {r.status}")

    def _ship(self, batch: _Batch) -> None:
        body = json.dumps({
            "version": "4",
            "alerts": [e.payload() for e in batch.events],
        }).encode()
        FaultInjector.fire("rules.notify", url=self.url,
                           count=len(batch.events))
        self.retry_policy.call(
            lambda: self._post(body),
            retry_on=(ConnectionError, OSError, TimeoutError),
            site="rules.notify")
        notifications_sent.inc(len(batch.events))

    def _run(self) -> None:
        while True:
            batch = self._q.get()
            if batch is None:
                return
            try:
                self._ship(batch)
            except Exception:
                notification_failures.inc(len(batch.events))
                log.warning("alert notification delivery failed "
                            "(%d event(s))", len(batch.events),
                            exc_info=True)
            finally:
                self._q.task_done()

    def close(self, timeout_s: float = 2.0) -> None:
        """Stop the worker after draining what's already queued."""
        self._q.put(None)
        self._worker.join(timeout=timeout_s)


def events_from_transitions(group: str, rule_annotations: tuple,
                            changes: list) -> list[AlertEvent]:
    """Build events from ``(labels_key, state, value, active_since, ts)``
    tuples staged by the alert state machine."""
    return [AlertEvent(group=group,
                       alertname=dict(k).get("alertname", ""),
                       state=state, labels=k,
                       annotations=rule_annotations,
                       value=value, active_since_ms=since, ts_ms=ts)
            for k, state, value, since, ts in changes]
