"""Standing-query evaluation: incremental recording rules and alerts.

The reference deployment's dashboard workload is dominated by re-polling
the same PromQL; recording/alerting rules (``prometheus/rules``) convert
that into amortized streaming work at write time. Here the evaluation
loop is driven by shard ingest progress: a group's clock is the result
cache's horizon (``min(shard.max_ingested_ts) − ooo_allowance`` — the
point behind which extents are immutable), and each tick evaluates every
rule only over newly-completed step-aligned extents. Evaluation goes
through ``QueryService.query_range`` so the per-extent matrices land in
and are served from the extent result cache, and the recording outputs
are written back as first-class series through the normal ingest path —
they shard, flush, upload, downsample, and migrate like any other
series, and they pass the same per-tenant cardinality quotas as gateway
ingest (rules are not a quota bypass).

Crash-safety contract (proven by the chaos tests):

- Re-evaluating a step is idempotent: shards drop per-partition samples
  at ``ts <= last`` as out-of-order, so a crashed-then-retried write can
  never double-count.
- The group watermark is a COMMIT RECORD, not in-memory state: after all
  rules' outputs for a window are handed to the sink, the manager writes
  one ``FILODB_RULES_WATERMARK{group=...}`` sample at the window's last
  step (value = that step, epoch seconds). Restart recovery reads the
  marker back (``max_over_time`` so selector lookback cannot overstate
  it) and resumes from the step after it — anything written past the
  marker before the crash is simply re-evaluated and deduplicated, so
  there is no skipped extent and no double-write.
- Alert state (inactive→pending→firing per group-key, with ``for:``
  hysteresis) is recomputed from the synthetic ``ALERTS_FOR_STATE``
  series at the recovered watermark; in-memory state only commits
  together with the watermark.

Rule evaluations admit through the governor as their own cost class
(``origin="rules"`` on the QueryContext → ``RULES``), gated by
``rules_max_inflight`` and shed before interactive queries under
pressure; a shed tick leaves the watermark unmoved and retries next
tick.

Cache-consistency hook: rule outputs are written at timestamps at or
below the ingest horizon — inside the region the result cache treats as
immutable. The manager therefore publishes ``svc.rules_horizon_floor``
(min over groups of the last step whose outputs are known VISIBLE in the
memstore); the cache clamps its immutability horizon to that floor so an
extent of a rule-output series can never be frozen before the rule's
write lands. The floor is a plain int republished at every commit and
read lock-free — the cache's per-query call never blocks behind an
in-flight evaluation. A group that has not yet recovered contributes a
BOUNDED conservative floor (recovery and catch-up never write below
``horizon − (max_catchup_steps+1)·interval``) instead of an open-ended
sentinel, so a group stuck before its first recovery costs cache
efficiency over a bounded window only; ``filodb_rules_unrecovered_groups``
surfaces how many groups are still pinning it.

Locking: ``_eval_lock`` serializes ticks; ``_lock`` guards group state
and is held only for brief snapshot reads and commit writes, never
across query evaluation or sink writes — so ``/api/v1/rules`` and
``/api/v1/alerts`` snapshots and interactive queries cannot stall
behind a slow evaluation or a post-restart catch-up.
"""

from __future__ import annotations

import logging
import math
import threading
import time
from dataclasses import dataclass, field, replace

import numpy as np

from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData
from filodb_tpu.query.model import QueryContext
from filodb_tpu.rules import notify
from filodb_tpu.rules.model import AlertingRule, RecordingRule, RuleGroup
from filodb_tpu.utils import governor as governor_mod
from filodb_tpu.utils import racecheck
from filodb_tpu.utils.metrics import Counter, Gauge, Histogram, get_gauge
from filodb_tpu.utils.resilience import FaultInjector
from filodb_tpu.utils.tracing import traced_operation

log = logging.getLogger("filodb.rules")

WATERMARK_METRIC = "FILODB_RULES_WATERMARK"
ALERTS_METRIC = "ALERTS"
ALERTS_FOR_STATE_METRIC = "ALERTS_FOR_STATE"

_UNRECOVERED = -(1 << 62)

# families pre-registered at import (standalone imports this module
# unconditionally) so dashboards see stable zeros before any rule runs
rules_groups = Gauge("filodb_rules_groups")
rules_evals = Counter("filodb_rules_evals")
rules_eval_failures = Counter("filodb_rules_eval_failures")
rules_evals_shed = Counter("filodb_rules_evals_shed")
rules_steps_evaluated = Counter("filodb_rules_steps_evaluated")
rules_steps_skipped = Counter("filodb_rules_steps_skipped")
rules_samples_written = Counter("filodb_rules_samples_written")
rules_eval_seconds = Histogram("filodb_rules_eval_seconds")
rules_last_eval_ts = Gauge("filodb_rules_last_eval_ts")
rules_unrecovered_groups = Gauge("filodb_rules_unrecovered_groups")
# untagged family anchor — runtime series carry {group=...} tags
rules_watermark_lag = Gauge("filodb_rules_watermark_lag_seconds")
alerts_firing = Gauge("filodb_alerts_firing")
alerts_pending = Gauge("filodb_alerts_pending")
alerts_transitions = Counter("filodb_alerts_transitions")


def _q(value: str) -> str:
    """Quote a string as a PromQL label-value literal. Group and alert
    names are charset-validated at config load, but selector fragments
    are still escaped here so a lexer-breaking name can never turn into
    a silently never-recovering group."""
    return '"' + value.replace("\\", "\\\\").replace('"', '\\"') + '"'


class LogSink:
    """Route rule outputs into the per-shard replay logs — the gateway
    path. Writes become visible once the shards' ingestion pipelines
    consume the appended offsets; ``write`` returns those offsets so the
    manager can track visibility for the cache horizon floor."""

    def __init__(self, logs, num_shards: int, spread: int = 1):
        self.logs = logs
        self.num_shards = num_shards
        self.spread = spread

    def write(self, container: RecordContainer):
        count = 0
        offsets: dict[int, int] = {}
        for shard, cont in route_container(container, self.num_shards,
                                           self.spread).items():
            offsets[shard] = self.logs[shard].append(cont)
            count += len(cont)
        return count, offsets


class MemstoreSink:
    """Ingest rule outputs directly into local shards (embedded servers,
    tests, benchmarks). Synchronous: visible as soon as ``write``
    returns. Offsets are allocated above both the shard's latest
    ingested offset and its flush watermarks, so direct writes are never
    mistaken for recovery replay and skipped."""

    def __init__(self, memstore, dataset: str, num_shards: int,
                 spread: int = 0):
        self.memstore = memstore
        self.dataset = dataset
        self.num_shards = num_shards
        self.spread = spread

    def write(self, container: RecordContainer):
        count = 0
        for shard_num, cont in route_container(container, self.num_shards,
                                               self.spread).items():
            shard = self.memstore.get_shard(self.dataset, shard_num)
            offset = max(shard.latest_offset,
                         max(shard.group_watermarks, default=-1)) + 1
            count += self.memstore.ingest(self.dataset, shard_num,
                                          SomeData(cont, offset))
        return count, {}


@dataclass
class AlertState:
    """One active alert instance (pending or firing)."""

    active_since_ms: int
    firing: bool
    value: float


@dataclass
class _GroupState:
    last_step: int | None = None          # committed watermark (epoch ms)
    visible_step: int = _UNRECOVERED      # watermark known shard-visible
    pending_offsets: dict = field(default_factory=dict)
    pending_step: int | None = None
    # rule name -> {label tuple -> AlertState}
    alert_states: dict = field(default_factory=dict)
    last_error: str = ""
    last_eval_wall: float = 0.0
    last_eval_duration: float = 0.0


class RuleManager:
    """Evaluates one dataset's rule groups against its QueryService.

    ``sink`` is a :class:`LogSink` (WAL path) or :class:`MemstoreSink`
    (direct). ``ooo_allowance_ms`` defaults to the service's result-cache
    allowance so the rules horizon and the cache horizon agree exactly.
    """

    def __init__(self, svc, sink, groups: list[RuleGroup],
                 ooo_allowance_ms: int | None = None,
                 max_catchup_steps: int = 512,
                 default_labels: dict[str, str] | None = None,
                 notifier=None):
        self.svc = svc
        self.sink = sink
        # WebhookNotifier (or anything with submit(events)); transition
        # events are handed off AFTER the state-lock commit — the
        # hand-off is non-blocking and the POST runs on the notifier's
        # own worker (lock-discipline pass verifies the placement)
        self._notifier = notifier
        self.groups = list(groups)
        if ooo_allowance_ms is None:
            rc = getattr(svc, "result_cache", None)
            ooo_allowance_ms = (rc.config.ooo_allowance_ms
                                if rc is not None else 300_000)
        self.ooo_allowance_ms = ooo_allowance_ms
        self.max_catchup_steps = max(1, int(max_catchup_steps))
        self.default_labels = dict(default_labels
                                   or {"_ws_": "default", "_ns_": "default"})
        # group states are committed under _lock from the tick thread
        # and snapshotted from API/recovery threads; the race sanitizer
        # (when armed) verifies every write actually holds a common lock
        self._state = racecheck.tracked_dict("RuleManager._state", {
            g.name: racecheck.register(
                _GroupState(), f"RuleManager.state[{g.name}]")
            for g in self.groups})
        # _lock guards group state for brief commits/snapshots only;
        # _eval_lock serializes ticks so queries and sink writes run
        # without blocking state readers
        self._lock = threading.RLock()
        self._eval_lock = threading.Lock()
        self._floor = (1 << 62) if not self.groups else _UNRECOVERED
        self._stalled_ticks = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        rules_groups.set(rules_groups.value + len(self.groups))
        # pre-register watermark lag at 0 per group so the family scrapes
        # from boot (the metrics-parity gate lists it)
        for g in self.groups:
            get_gauge("filodb_rules_watermark_lag_seconds",
                      {"group": g.name}).set(0.0)
        # cache-consistency hook: clamp the result cache's immutability
        # horizon to what the rules have verifiably written (module doc)
        svc.rules_horizon_floor = self.horizon_floor

    # ------------------------------------------------------------ clock

    def horizon_ms(self) -> int | None:
        """Ingest-progress clock: the result cache's horizon."""
        shards = self.svc.memstore.shards_for(self.svc.dataset)
        if not shards:
            return None
        max_ts = min((s.max_ingested_ts for s in shards), default=-1)
        if max_ts < 0:
            return None
        return max_ts - self.ooo_allowance_ms

    def horizon_floor(self) -> int:
        """Min over groups of the last shard-visible committed step.

        Lock-free: the value is republished as a plain int at every
        commit (a single attribute store/load is atomic in CPython), so
        the result cache's per-query call can never block behind an
        in-flight evaluation or catch-up."""
        return self._floor

    def _publish_floor(self, horizon: int) -> None:
        """Recompute and publish the cache floor. A group that has not
        recovered yet contributes ``horizon − (max_catchup_steps+1)·
        interval`` — recovery's lookback and the catch-up cap both bound
        how far back its writes can land — rather than the far-negative
        sentinel, so the cache regression before first recovery covers a
        bounded window only."""
        floor = 1 << 62
        unrecovered = 0
        with self._lock:
            for g in self.groups:
                st = self._state[g.name]
                if st.last_step is None:
                    unrecovered += 1
                    floor = min(floor, horizon - (self.max_catchup_steps
                                                  + 1) * g.interval_ms)
                else:
                    floor = min(floor, st.visible_step)
                    # how far the group's evaluation trails the ingest
                    # clock — the per-group freshness gauge
                    get_gauge("filodb_rules_watermark_lag_seconds",
                              {"group": g.name}).set(
                        max(0.0, (horizon - st.last_step) / 1000.0))
        self._floor = floor
        rules_unrecovered_groups.set(unrecovered)

    def _note_no_horizon_locked(self) -> None:
        """No ingest progress yet: nothing to evaluate or recover, but
        surface unrecovered groups so a floor stuck at the sentinel is
        visible instead of a silent cache-efficiency drain. Caller holds
        ``_eval_lock`` (guards ``_stalled_ticks``)."""
        with self._lock:
            unrecovered = sum(1 for g in self.groups
                              if self._state[g.name].last_step is None)
        rules_unrecovered_groups.set(unrecovered)
        if not unrecovered:
            return
        self._stalled_ticks += 1
        if self._stalled_ticks == 10 or self._stalled_ticks % 600 == 0:
            log.warning(
                "rules: no ingest horizon after %d ticks; %d group(s) "
                "unrecovered, cache floor pinned at sentinel until data "
                "flows", self._stalled_ticks, unrecovered)

    # ------------------------------------------------------------- loop

    def start(self, tick_s: float = 1.0) -> "RuleManager":
        if self._thread is not None or not self.groups:
            return self

        def loop():
            while not self._stop.wait(tick_s):
                try:
                    self.tick()
                except Exception:
                    log.warning("rules tick failed", exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="rule-manager")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._notifier is not None:
            self._notifier.close()

    def tick(self) -> int:
        """Evaluate every group over its newly-completed steps; returns
        the number of (rule, step) evaluations performed.

        Queries and sink writes run WITHOUT the state lock: ``_lock`` is
        taken only for the brief commit of each group's watermark and
        alert state, so floor reads and snapshots never wait out a slow
        evaluation. ``_eval_lock`` keeps ticks themselves serial."""
        with self._eval_lock:
            horizon = self.horizon_ms()
            if horizon is None:
                self._note_no_horizon_locked()
                return 0
            self._stalled_ticks = 0
            self._publish_floor(horizon)
            evaluated = 0
            for g in self.groups:
                st = self._state[g.name]
                with self._lock:
                    self._check_visibility(g, st)
                try:
                    evaluated += self._tick_group(g, st, horizon)
                except governor_mod.QueryRejected as e:
                    # shed under pressure: watermark unmoved, the same
                    # window is retried next tick — no skipped extent
                    rules_evals_shed.inc()
                    with self._lock:
                        st.last_error = f"shed: {e}"
                except Exception as e:
                    rules_eval_failures.inc()
                    with self._lock:
                        st.last_error = str(e)
                    log.warning("rule group %s eval failed", g.name,
                                exc_info=True)
            with self._lock:
                self._update_alert_gauges()
            self._publish_floor(horizon)
            return evaluated

    # ------------------------------------------------------ group eval

    def _tick_group(self, g: RuleGroup, st: _GroupState,
                    horizon: int) -> int:
        interval = g.interval_ms
        if horizon < 0:
            return 0
        last_complete = (horizon // interval) * interval
        last_step = st.last_step
        if last_step is None:
            last_step = self._recover(g, st, last_complete)
        if last_complete <= last_step:
            return 0
        first = last_step + interval
        nsteps = (last_complete - first) // interval + 1
        if nsteps > self.max_catchup_steps:
            skipped = nsteps - self.max_catchup_steps
            rules_steps_skipped.inc(skipped * max(1, len(g.rules)))
            log.warning("rule group %s: %d steps behind, skipping %d "
                        "(max_catchup_steps=%d)", g.name, nsteps, skipped,
                        self.max_catchup_steps)
            first = last_complete - (self.max_catchup_steps - 1) * interval
            nsteps = self.max_catchup_steps
        FaultInjector.fire("rules.eval", group=g.name, start=first,
                           end=last_complete)
        t0 = time.perf_counter()
        with traced_operation("rules", group=g.name, steps=nsteps):
            # evaluate ALL rules before writing anything is not possible
            # in bounded memory for wide outputs; instead write per rule
            # and rely on idempotent re-writes, but stage alert-state
            # commits so a mid-group failure retries from clean state
            staged_states: dict[str, tuple[dict, int, list]] = {}
            offsets: dict[int, int] = {}
            for rule in g.rules:
                res = self.svc.query_range(
                    rule.expr, first // 1000, interval // 1000,
                    last_complete // 1000, QueryContext(origin="rules"))
                if res.partial:
                    raise RuntimeError(
                        f"partial result for rule {rule.name}: "
                        f"{'; '.join(res.warnings) or 'unknown'}")
                if isinstance(rule, RecordingRule):
                    samples = self._recording_samples(rule, res)
                else:
                    samples, new_states, transitions, changes = \
                        self._alerting_samples(g, rule, res, first,
                                               interval, last_complete)
                    staged_states[rule.name] = (
                        new_states, transitions,
                        notify.events_from_transitions(
                            g.name, rule.annotations, changes))
                FaultInjector.fire("rules.write", group=g.name,
                                   rule=rule.name, count=len(samples))
                if samples:
                    n, offs = self.sink.write(self._container(samples))
                    rules_samples_written.inc(n)
                    for s, o in offs.items():
                        offsets[s] = max(offsets.get(s, -1), o)
            # commit record: one watermark sample at the window's last
            # step — written only after every rule's outputs
            _, offs = self.sink.write(self._container([(
                dict(self.default_labels,
                     _metric_=WATERMARK_METRIC, group=g.name),
                last_complete, last_complete / 1000.0)]))
            for s, o in offs.items():
                offsets[s] = max(offsets.get(s, -1), o)
        notify_events: list = []
        with self._lock:
            st.last_step = last_complete
            for name, (states, transitions, events) in \
                    staged_states.items():
                st.alert_states[name] = states
                if transitions:
                    # counted only here: a discarded stage (failed or
                    # shed group) re-evaluates the same window next tick
                    # and must not double-count its transitions or
                    # re-notify them
                    alerts_transitions.inc(transitions)
                    notify_events.extend(events)
            if offsets:
                if st.visible_step == _UNRECOVERED:
                    # fresh start over a WAL sink: nothing was ever
                    # written at or below the resume point, which
                    # bounds the floor until the offsets are consumed
                    st.visible_step = last_step
                st.pending_offsets = offsets
                st.pending_step = last_complete
                self._check_visibility(g, st)
            else:
                st.visible_step = last_complete
            st.last_error = ""
            st.last_eval_wall = time.time()
            st.last_eval_duration = time.perf_counter() - t0
        # notification hand-off OUTSIDE _lock: submit() is a bounded
        # put_nowait, and the webhook POST runs on the notifier's worker
        if self._notifier is not None and notify_events:
            self._notifier.submit(notify_events)
        rules_evals.inc()
        rules_steps_evaluated.inc(nsteps * len(g.rules))
        rules_eval_seconds.observe(st.last_eval_duration)
        get_gauge("filodb_rules_last_eval_ts",
                  {"group": g.name}).set(last_complete / 1000.0)
        return nsteps * len(g.rules)

    def _check_visibility(self, g: RuleGroup, st: _GroupState) -> None:
        """Advance the cache-floor watermark once WAL-appended outputs
        have been consumed by the shards (LogSink); MemstoreSink writes
        are visible immediately and never stage pending offsets."""
        if st.pending_step is None:
            return
        for shard_num, off in st.pending_offsets.items():
            try:
                shard = self.svc.memstore.get_shard(self.svc.dataset,
                                                    shard_num)
            except KeyError:
                # shard not local: the result cache bypasses entirely
                # when the shard set is incomplete, so the floor is moot
                continue
            if shard.latest_offset < off:
                return
        st.visible_step = st.pending_step
        st.pending_step = None
        st.pending_offsets = {}

    # -------------------------------------------------------- recovery

    def _recover(self, g: RuleGroup, st: _GroupState,
                 last_complete: int) -> int:
        """Resume the group from its durable commit record; returns the
        watermark step to resume after. A recovered marker is committed
        to group state immediately (outputs through it are durably
        written); a FRESH START is not — its resume point carries no
        recorded data, so it must not surface as a snapshot watermark
        until the first window's outputs commit.

        ``max_over_time(marker[interval])`` windows are (t−i, t] — each
        step sees exactly the marker sample written AT that step, so
        selector lookback (300s staleness) cannot overstate the
        watermark and cause skipped extents. The watermark is taken from
        the last non-NaN step's POSITION (int64 ms, exact), never from
        the sample value: query materialization is float32, which cannot
        represent epoch seconds exactly."""
        interval = g.interval_ms
        lookback = min(self.max_catchup_steps, 10_000)
        start = max(0, last_complete - (lookback - 1) * interval)
        wm = None
        if last_complete >= 0:
            q = (f'max_over_time({WATERMARK_METRIC}'
                 f'{{group={_q(g.name)}}}[{g.interval_s}s])')
            res = self.svc.query_range(q, start // 1000, interval // 1000,
                                       last_complete // 1000,
                                       QueryContext(origin="rules"))
            m = res.result
            if m.num_series:
                vals = np.asarray(m.values, dtype=float)
                # fmax ignores NaN without the all-NaN-slice warning
                best = np.fmax.reduce(vals, axis=0)
                idx = np.where(~np.isnan(best))[0]
                if idx.size:
                    wm = int(np.asarray(m.steps_ms)[idx[-1]])
        if wm is None:
            fresh = last_complete - interval
            log.info("rule group %s: fresh start at %d", g.name, fresh)
            return fresh
        recovered = {rule.name: self._recover_alert_states(g, rule, wm)
                     for rule in g.rules if isinstance(rule, AlertingRule)}
        with self._lock:
            st.last_step = wm
            st.visible_step = wm
            st.alert_states.update(recovered)
        log.info("rule group %s: recovered watermark %d", g.name, wm)
        return wm

    def _recover_alert_states(self, g: RuleGroup, rule: AlertingRule,
                              wm: int) -> dict:
        """``ALERTS_FOR_STATE`` values are SECONDS-ACTIVE at the sample's
        own step (not the activation timestamp, which float32 query
        materialization could not carry exactly); the activation time is
        reconstructed as ``wm − value``. The selector is scoped by the
        ``_group_`` stamp the evaluator puts on every for-state sample:
        an equally-named alert in another group (or a leftover series
        from a deleted rule elsewhere) must not resurrect here."""
        q = (f'max_over_time({ALERTS_FOR_STATE_METRIC}'
             f'{{alertname={_q(rule.name)},_group_={_q(g.name)}}}'
             f'[{g.interval_s}s])')
        res = self.svc.query_range(q, wm // 1000, g.interval_s, wm // 1000,
                                   QueryContext(origin="rules"))
        m = res.result
        states: dict = {}
        for i, key in enumerate(m.keys):
            v = float(np.asarray(m.values)[i, -1])
            if math.isnan(v):
                continue
            active_since = wm - int(round(v)) * 1000
            labels = tuple(sorted(
                (k, val) for k, val in key.labels
                if k not in ("_metric_", "_group_")))
            states[labels] = AlertState(
                active_since_ms=active_since,
                firing=(wm - active_since) >= rule.for_ms,
                value=float("nan"))
        return states

    # ------------------------------------------------------- rule eval

    def _output_labels(self, rule, series_labels) -> dict[str, str]:
        # _group_ is system-owned (the for-state recovery scope stamp)
        # and never flows from inputs to outputs
        out = {k: v for k, v in series_labels
               if k not in ("_metric_", "_group_")}
        out.update(rule.labels)
        for k, v in self.default_labels.items():
            out.setdefault(k, v)
        return out

    def _recording_samples(self, rule: RecordingRule, res) -> list:
        m = res.result
        if m.num_series == 0:
            return []
        vals = np.asarray(m.values, dtype=float)
        if vals.ndim != 2:
            raise ValueError(f"rule {rule.name}: histogram-shaped output "
                             f"cannot be recorded")
        steps = np.asarray(m.steps_ms)
        samples = []
        for i, key in enumerate(m.keys):
            labels = self._output_labels(rule, key.labels)
            labels["_metric_"] = rule.record
            row = vals[i]
            for j in np.where(~np.isnan(row))[0]:
                samples.append((labels, int(steps[j]), float(row[j])))
        return samples

    def _alerting_samples(self, g: RuleGroup, rule: AlertingRule, res,
                          first: int, interval: int, last: int):
        """Run the inactive→pending→firing state machine over the new
        steps; returns (samples, new_states, transitions, changes) with
        state — and the transition count plus the notification change
        list — committed by the caller only after the group's writes all
        succeed. ``changes`` entries are
        ``(labels_key, state, value, active_since_ms, ts_ms)``."""
        m = res.result
        vals = np.asarray(m.values, dtype=float) if m.num_series else None
        if vals is not None and vals.ndim != 2:
            raise ValueError(f"alert {rule.name}: histogram-shaped output "
                             f"is not a valid alert condition")
        keys = []
        if m.num_series:
            for key in m.keys:
                labels = self._output_labels(rule, key.labels)
                labels["alertname"] = rule.name
                keys.append(tuple(sorted(labels.items())))
        states = {k: replace(v) for k, v in
                  self._state[g.name].alert_states.get(rule.name,
                                                       {}).items()}
        steps = np.asarray(m.steps_ms) if m.num_series else np.arange(
            first, last + interval, interval, dtype=np.int64)
        samples = []
        transitions = 0
        changes: list = []
        for j, ts in enumerate(int(t) for t in steps):
            active: dict = {}
            if vals is not None:
                col = vals[:, j]
                for i, k in enumerate(keys):
                    if not math.isnan(col[i]):
                        active[k] = float(col[i])
            for k, v in active.items():
                stt = states.get(k)
                if stt is None:
                    states[k] = stt = AlertState(active_since_ms=ts,
                                                 firing=False, value=v)
                    transitions += 1  # inactive -> pending
                    changes.append((k, notify.PENDING, v, ts, ts))
                stt.value = v
                firing = (ts - stt.active_since_ms) >= rule.for_ms
                if firing and not stt.firing:
                    transitions += 1  # pending -> firing
                    changes.append((k, notify.FIRING, v,
                                    stt.active_since_ms, ts))
                stt.firing = firing
            for k in [k for k in states if k not in active]:
                prev = states.pop(k)
                transitions += 1  # -> inactive
                changes.append((k, notify.RESOLVED, prev.value,
                                prev.active_since_ms, ts))
            for k, stt in states.items():
                labels = dict(k)
                alert_labels = dict(labels)
                alert_labels["_metric_"] = ALERTS_METRIC
                alert_labels["alertstate"] = ("firing" if stt.firing
                                              else "pending")
                samples.append((alert_labels, ts, 1.0))
                for_labels = dict(labels)
                for_labels["_metric_"] = ALERTS_FOR_STATE_METRIC
                # recovery scope stamp: restart filters for-state by
                # {alertname, _group_} so same-named alerts in other
                # groups cannot cross-contaminate recovered state
                for_labels["_group_"] = g.name
                # seconds-active at this step: small enough to survive
                # float32 query materialization exactly (epoch seconds
                # would not); recovery computes wm − value
                samples.append((for_labels, ts,
                                (ts - stt.active_since_ms) / 1000.0))
        return samples, states, transitions, changes

    @staticmethod
    def _container(samples) -> RecordContainer:
        cont = RecordContainer()
        for labels, ts, v in samples:
            cont.add(IngestRecord(PartKey.create("gauge", labels), ts,
                                  (v,)))
        return cont

    def _update_alert_gauges(self) -> None:
        firing = pending = 0
        for g in self.groups:
            for states in self._state[g.name].alert_states.values():
                for stt in states.values():
                    if stt.firing:
                        firing += 1
                    else:
                        pending += 1
        alerts_firing.set(firing)
        alerts_pending.set(pending)

    # ------------------------------------------------------- snapshots

    def rules_snapshot(self) -> list[dict]:
        """Prom-compat ``/api/v1/rules`` group payloads."""
        out = []
        with self._lock:
            for g in self.groups:
                st = self._state[g.name]
                rules = []
                for rule in g.rules:
                    base = {
                        "name": rule.name,
                        "query": rule.expr,
                        "labels": dict(rule.labels),
                        "health": "err" if st.last_error else "ok",
                        "lastError": st.last_error,
                        "evaluationTime": st.last_eval_duration,
                        "lastEvaluation": st.last_eval_wall,
                    }
                    if isinstance(rule, RecordingRule):
                        base["type"] = "recording"
                    else:
                        base["type"] = "alerting"
                        base["duration"] = rule.for_ms / 1000.0
                        base["annotations"] = dict(rule.annotations)
                        base["alerts"] = self._alert_payloads(g, rule)
                    rules.append(base)
                out.append({
                    "name": g.name,
                    "interval": g.interval_s,
                    "dataset": g.dataset,
                    "watermark": st.last_step,
                    "rules": rules,
                })
        return out

    def alerts_snapshot(self) -> list[dict]:
        """Prom-compat ``/api/v1/alerts`` payloads (active only)."""
        out = []
        with self._lock:
            for g in self.groups:
                for rule in g.rules:
                    if isinstance(rule, AlertingRule):
                        out.extend(self._alert_payloads(g, rule))
        return out

    def _alert_payloads(self, g: RuleGroup, rule: AlertingRule) -> list:
        states = self._state[g.name].alert_states.get(rule.name, {})
        out = []
        for labels, stt in sorted(states.items()):
            out.append({
                "labels": dict(labels),
                "annotations": dict(rule.annotations),
                "state": "firing" if stt.firing else "pending",
                "activeAt": stt.active_since_ms / 1000.0,
                "value": (None if math.isnan(stt.value)
                          else str(stt.value)),
            })
        return out
