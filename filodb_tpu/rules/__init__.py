"""Standing queries: recording rules and alert evaluation on ingest."""

from filodb_tpu.rules.model import (
    AlertingRule,
    RecordingRule,
    RuleGroup,
    load_groups,
)
from filodb_tpu.rules.manager import LogSink, MemstoreSink, RuleManager

__all__ = [
    "AlertingRule",
    "RecordingRule",
    "RuleGroup",
    "RuleManager",
    "LogSink",
    "MemstoreSink",
    "load_groups",
]
