"""Standing queries: recording rules and alert evaluation on ingest."""

from filodb_tpu.rules.model import (
    AlertingRule,
    RecordingRule,
    RuleGroup,
    load_groups,
)
from filodb_tpu.rules.manager import LogSink, MemstoreSink, RuleManager
from filodb_tpu.rules.notify import AlertEvent, WebhookNotifier

__all__ = [
    "AlertEvent",
    "AlertingRule",
    "RecordingRule",
    "RuleGroup",
    "RuleManager",
    "LogSink",
    "MemstoreSink",
    "WebhookNotifier",
    "load_groups",
]
