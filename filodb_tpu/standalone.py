"""FiloServer: the standalone server process.

Counterpart of reference ``standalone/src/main/scala/filodb.standalone/
FiloServer.scala:38,86``: boots the stores, joins the cluster (seed
discovery), starts per-shard ingestion with recovery, and serves the
Prometheus HTTP API, the plan-executor port (remote dispatch) and optionally
the Influx gateway.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import sys

from filodb_tpu.config import ServerConfig
from filodb_tpu.coordinator.cluster import FilodbCluster, Node
from filodb_tpu.coordinator.remote import PlanExecutorServer
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.localstore import (
    LocalDiskColumnStore,
    LocalDiskMetaStore,
)
# imported unconditionally so the filodb_objectstore_* metric families are
# registered (and scrape-visible) regardless of the configured backend
from filodb_tpu.core.store.objectstore import open_object_store
# likewise the filodb_rules_*/filodb_alerts_* families render even with no
# rule groups configured
from filodb_tpu.rules import LogSink, RuleManager, load_groups
from filodb_tpu.gateway.server import ContainerSink, GatewayServer
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.kafka.log import SegmentedFileLog

log = logging.getLogger(__name__)


class FiloServer:
    def __init__(self, config: ServerConfig):
        self.config = config
        if config.resilience:
            from filodb_tpu.utils import resilience
            resilience.configure(**config.resilience)
        if config.governor:
            from filodb_tpu.utils import governor
            governor.configure(**config.governor)
        if config.tracing:
            from filodb_tpu.utils import tracing
            tracing.configure(**config.tracing)
        self.watchdog = None
        os.makedirs(config.data_dir, exist_ok=True)
        self.store_server = None
        if config.store_remote:
            # remote durability tier (reference: CassandraColumnStore role)
            from filodb_tpu.core.store.remotestore import (
                RemoteColumnStore,
                RemoteMetaStore,
            )
            host, port = config.store_remote.rsplit(":", 1)
            self.column_store = RemoteColumnStore(host, int(port))
            self.meta_store = RemoteMetaStore(host, int(port))
        else:
            if config.store.get("backend") == "object":
                # S3-compatible durable tier: write-behind segment upload
                # with CRC32C tripwires (core/store/objectstore.py)
                self.column_store, self.meta_store = open_object_store(
                    config.store, config.data_dir)
            else:
                self.column_store = LocalDiskColumnStore(
                    os.path.join(config.data_dir, "columnstore"))
                self.meta_store = LocalDiskMetaStore(
                    os.path.join(config.data_dir, "columnstore"))
            if config.store_server_port:
                from filodb_tpu.core.store.remotestore import (
                    ChunkStoreServer,
                )
                self.store_server = ChunkStoreServer(
                    host="0.0.0.0", port=config.store_server_port,
                    backing=self.column_store, meta=self.meta_store).start()
        self.memstore = TimeSeriesMemStore(self.column_store, self.meta_store)
        self.node = Node(config.node_name, self.memstore)
        self.cluster = FilodbCluster()
        self.logs: dict[tuple[str, int], SegmentedFileLog] = {}
        self.http: FiloHttpServer | None = None
        self.gateway: GatewayServer | None = None
        self.executor: PlanExecutorServer | None = None
        self.selfmon = None
        self.mesh_supervisor = None  # multi-process mesh worker processes
        self.mesh_runtime = None     # root-side descriptor router
        self._setup_meta_dataset()

    def _setup_meta_dataset(self) -> None:
        """Register the ``_meta`` self-monitoring dataset when selfmon is
        enabled. Appended AFTER the user datasets: the gateway and the
        rules default-dataset both bind to the FIRST configured dataset,
        and that must stay the user's."""
        sm_cfg = self.config.selfmon or {}
        if not sm_cfg.get("enabled") or "_meta" in self.config.datasets:
            return
        from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
        self.config.datasets["_meta"] = IngestionConfig(
            dataset="_meta",
            num_shards=int(sm_cfg.get("num_shards", 1)),
            min_num_nodes=1,
            store=StoreConfig(groups_per_shard=4))
        self.config.spreads["_meta"] = 0

    def _wal_path(self, dataset: str, shard: int) -> str:
        root = self.config.wal_dir or os.path.join(self.config.data_dir,
                                                   "wal")
        return os.path.join(root, dataset, f"shard-{shard}")

    def _shard_log(self, dataset: str, shard: int):
        key = (dataset, shard)
        if key not in self.logs:
            if self.config.wal_kafka:
                # external Kafka broker: topic per dataset, partition ==
                # shard (reference KafkaIngestionStream contract)
                from filodb_tpu.kafka.kafka_protocol import KafkaReplayLog
                host, port = self.config.wal_kafka.rsplit(":", 1)
                self.logs[key] = KafkaReplayLog(host, int(port), dataset,
                                                shard)
            elif self.config.wal_remote:
                # networked log (the Kafka contract): no shared FS needed
                from filodb_tpu.kafka.log_server import RemoteLog
                host, port = self.config.wal_remote.rsplit(":", 1)
                self.logs[key] = RemoteLog(host, int(port), dataset, shard)
            else:
                # members tail segments the gateway host appends to on the
                # shared wal_dir: their view must be read-only (an
                # append-mode open would run torn-tail recovery against a
                # live file)
                tailer = bool(self.config.seeds) \
                    and not self.config.gateway_port
                self.logs[key] = SegmentedFileLog(
                    self._wal_path(dataset, shard),
                    fsync=self.config.wal_fsync, read_only=tailer)
        return self.logs[key]

    def _start_mesh_workers(self, cfg, services: dict) -> None:
        """Boot the multi-process mesh runtime (coordinator role only):
        spawn N worker processes each owning a contiguous shard slice,
        then attach the descriptor router to the dataset's query service.
        Workers that never come up cost nothing at query time — the
        runtime's per-worker breakers route every query to the
        single-process engines until the slice answers."""
        mw = dict(cfg.mesh_workers or {})
        if not mw.get("enabled") or not services:
            return
        ds = mw.get("dataset") or next(iter(cfg.datasets))
        if ds not in services:
            log.warning("mesh_workers.dataset %r not served here; "
                        "multi-process mesh disabled", ds)
            return
        ing = cfg.datasets[ds]
        seed = mw.get("seed") or None
        config_path = None
        if not seed:
            # minimal worker config: shared WAL location + the dataset's
            # shard/store shape (workers recover-then-tail read-only)
            import dataclasses as _dc
            config_path = os.path.join(cfg.data_dir,
                                       "mesh_worker_config.json")
            os.makedirs(cfg.data_dir, exist_ok=True)
            with open(config_path, "w") as f:
                json.dump({"data_dir": cfg.data_dir,
                           "wal_dir": cfg.wal_dir,
                           "datasets": {ds: {
                               "num_shards": ing.num_shards,
                               "store": _dc.asdict(ing.store)}}}, f)
        from filodb_tpu.coordinator.mesh_cluster import MeshClusterRuntime
        from filodb_tpu.parallel.multiproc import MeshWorkerSupervisor
        sup = MeshWorkerSupervisor(
            dataset=ds, num_shards=ing.num_shards,
            workers=int(mw.get("workers", 2)),
            base_port=int(mw.get("base_port", 0)),
            config_path=config_path, seed=seed).spawn()
        try:
            sup.wait_ready(timeout_s=float(mw.get("ready_timeout_s",
                                                  120.0)))
        except (TimeoutError, RuntimeError) as e:
            # degraded boot: serve single-process until workers answer
            log.warning("mesh workers not ready (%s); serving via "
                        "single-process engines until they are", e)
        self.mesh_supervisor = sup
        self.mesh_runtime = MeshClusterRuntime(
            self.memstore, ds, ing.num_shards, sup.addresses(),
            timeout=float(mw.get("timeout_s", 30.0)))
        services[ds].mesh_cluster = self.mesh_runtime

    @staticmethod
    def _build_notifier(notify_cfg: dict):
        """Webhook egress for alert transitions; None when unconfigured
        (the common case — notifications stay opt-in per deployment)."""
        url = notify_cfg.get("webhook_url")
        if not url:
            return None
        from filodb_tpu.rules.notify import WebhookNotifier
        from filodb_tpu.utils.resilience import RetryPolicy
        return WebhookNotifier(
            url, timeout_s=float(notify_cfg.get("timeout_s", 5.0)),
            retry_policy=RetryPolicy(
                max_attempts=int(notify_cfg.get("max_attempts", 4)),
                base_backoff_s=0.1, max_backoff_s=2.0),
            queue_depth=int(notify_cfg.get("queue_depth", 256)))

    @staticmethod
    def _default_meta_alerts(sm_cfg: dict) -> dict:
        """The shipped self-monitoring alert group, evaluated over
        ``_meta`` like any user group: shard ingest lag and an open
        circuit breaker — the two signals that mean "this node is no
        longer keeping up / no longer talking to a peer"."""
        thr = float(sm_cfg.get("lag_alert_threshold_s", 60.0))
        return {
            "name": "selfmon_default",
            "dataset": "_meta",
            "interval": sm_cfg.get("alert_interval", "5s"),
            "rules": [
                {"alert": "FilodbIngestLagHigh",
                 "expr": f"max(filodb_ingest_lag_seconds) > {thr}",
                 "for": sm_cfg.get("lag_alert_for", "30s"),
                 "labels": {"severity": "warning"},
                 "annotations": {"summary":
                                 "shard ingest lag above threshold"}},
                {"alert": "FilodbBreakerOpen",
                 "expr": "max(filodb_breaker_state) >= 2",
                 "for": "0s",
                 "labels": {"severity": "warning"},
                 "annotations": {"summary":
                                 "a circuit breaker to a peer is open"}},
            ],
        }

    # -- control handlers (member side; reference NodeCoordinatorActor) --

    def _handle_start_shard(self, dataset: str, shard: int):
        cfg = self.config.datasets[dataset]
        self.node.start_shard(dataset, shard, cfg,
                              self._shard_log(dataset, shard))
        return True

    def _handle_stop_shard(self, dataset: str, shard: int):
        self.node.stop_shard(dataset, shard)
        return True

    def _handle_prepare_handoff(self, dataset: str, shard: int):
        """Migration source side: flush + drain the shard's durable state
        and return its replay offset (coordinator/migration.py SYNC)."""
        return self.node.prepare_handoff(dataset, shard)

    def _handle_shard_offset(self, dataset: str, shard: int):
        return self.node.shard_offset(dataset, shard)

    def _handle_migration_status(self, dataset: str):
        """Coordinator side: in-flight migrations for the CLI/shardmap."""
        return [mig.snapshot() for (d, _s), mig in
                self.cluster.migrations.items() if d == dataset]

    def _handle_shard_status(self, dataset: str):
        out = []
        for (d, s), w in self.node._workers.items():
            if d == dataset:
                out.append((s, "active" if w.caught_up.is_set()
                            else "recovery"))
        return out

    def _handle_shard_events(self, dataset: str, since_seq: int,
                             epoch: str | None = None):
        """Sequenced shard-event feed for member subscribers (reference
        StatusActor ack/resync): events after ``since_seq``, or a full
        snapshot when the follower fell behind the retained window or its
        epoch predates a coordinator restart."""
        sm = self.cluster.shard_managers.get(dataset)
        if sm is None:
            return ([], since_seq, False, epoch)
        events, seq, resynced, ep = sm.events_since(since_seq, epoch)
        # 6-tuples since replica sets: old 4-field readers were removed in
        # the same change (both ends of this wire ship together), and the
        # subscriber unpacks with *rest so further growth stays compatible
        return ([(e.shard, e.status.name, e.node, e.progress,
                  e.replica, e.watermark)
                 for e in events], seq, resynced, ep)

    def _handle_role(self):
        """(role, coord_host, coord_port) — consul bootstrap probes this
        to find an ESTABLISHED cluster before electing by address. A node
        still booting answers 'undecided'."""
        if getattr(self, "is_coordinator", False):
            return ("coordinator", None, None)
        ca = getattr(self, "_coord_addr", None)
        if ca is not None:
            return ("member", ca[0], ca[1])
        return ("undecided", None, None)

    def _handle_join(self, name: str, host: str, control_port: int):
        """Coordinator side: a remote member joined (reference
        NodeClusterActor member-up). Shard assignment (which calls back to
        the member) runs off the handler thread so the join reply isn't held
        hostage to the member's own startup."""
        import threading
        from filodb_tpu.coordinator.bootstrap import RemoteNodeHandle

        def do_join():
            try:
                self.cluster.join(RemoteNodeHandle(name, host, control_port))
            except Exception:
                log.exception("join of %s failed", name)

        threading.Thread(target=do_join, daemon=True).start()
        return True

    def start(self) -> "FiloServer":
        cfg = self.config
        if cfg.wal_server_port:
            # broker role: serve this node's WAL dir over TCP (reference
            # Kafka broker analog)
            from filodb_tpu.kafka.log_server import LogServer
            root = cfg.wal_dir or os.path.join(cfg.data_dir, "wal")
            self.log_server = LogServer(root,
                                        port=cfg.wal_server_port).start()
            if not cfg.wal_remote:
                # the broker's own shards go through the server too — one
                # owner per log file
                cfg.wal_remote = f"127.0.0.1:{self.log_server.port}"
        # control/executor port: plan shipping + shard lifecycle messages
        self.executor = PlanExecutorServer(
            self.memstore, port=cfg.executor_port,
            extra_handlers={
                "start_shard": self._handle_start_shard,
                "stop_shard": self._handle_stop_shard,
                "shard_status": self._handle_shard_status,
                "shard_events": self._handle_shard_events,
                "prepare_handoff": self._handle_prepare_handoff,
                "shard_offset": self._handle_shard_offset,
                "migration_status": self._handle_migration_status,
                "join": self._handle_join,
                "role": self._handle_role,
            }).start()
        self.node.executor_port = self.executor.port
        self._consul = None
        self._consul_registered = False
        if cfg.consul:
            # Consul-backed seed discovery (reference akka-bootstrapper
            # Consul strategy). Register FIRST, then decide the role:
            #  - any discovered node answering the "role" control query as
            #    coordinator (or a member pointing at one) is joined — an
            #    ESTABLISHED cluster always wins, regardless of boot order;
            #  - otherwise (everyone racing or unreachable), the lowest
            #    (host, port) forms the cluster and the rest join it — the
            #    reference's sorted head-seed election.
            from filodb_tpu.coordinator.bootstrap import ConsulDiscovery
            from filodb_tpu.coordinator.remote import RemotePlanDispatcher
            self._consul = ConsulDiscovery(
                host=cfg.consul.get("host", "127.0.0.1"),
                port=int(cfg.consul.get("port", 8500)),
                service_name=cfg.consul.get("service", "filodb"))
            adv = cfg.consul.get("advertise", "127.0.0.1")
            me = (adv, self.executor.port)
            try:
                self._consul.register(cfg.node_name, adv,
                                      self.executor.port)
                self._consul_registered = True
            except OSError as e:
                log.warning("consul register failed: %s", e)
            if not cfg.seeds:
                others = sorted(t for t in self._consul.discover()
                                if tuple(t) != me)
                coord_addr = None
                for h, p in others:
                    try:
                        role, ch, cp = RemotePlanDispatcher(h, p).call(
                            "role")
                    except (ConnectionError, OSError, RuntimeError):
                        continue
                    if role == "coordinator":
                        coord_addr = (h, p)
                        break
                    if role == "member" and ch:
                        coord_addr = (ch, cp)
                        break
                if coord_addr is not None:
                    cfg.seeds = [f"{coord_addr[0]}:{coord_addr[1]}"]
                elif others and min(others) < me:
                    cfg.seeds = [f"{h}:{p}" for h, p in others]
                # else: we sort lowest (or are alone) -> form the cluster
                log.info("consul discovery: role=%s seeds=%s",
                         "member" if cfg.seeds else "coordinator",
                         cfg.seeds)
        # role is decided once seeds are final; the "role" control query
        # (consul bootstrap of later nodes) depends on this being set for
        # every node, not just failover-enabled ones
        self.is_coordinator = not cfg.seeds
        services = {}
        self.rule_managers: dict[str, RuleManager] = {}
        if cfg.seeds:
            # member role: register with the coordinator; shard assignments
            # arrive as start_shard control messages
            from filodb_tpu.coordinator.remote import RemotePlanDispatcher
            joined = False
            for seed in cfg.seeds:
                host, port = seed.rsplit(":", 1)
                try:
                    RemotePlanDispatcher(host, int(port)).call(
                        "join", cfg.node_name, "127.0.0.1",
                        self.executor.port)
                    joined = True
                    self._coord_addr = (host, int(port))
                    break
                except (ConnectionError, OSError, RuntimeError) as e:
                    log.warning("seed %s unreachable: %s", seed, e)
            if not joined:
                raise RuntimeError("could not join any seed")
            # mirror the coordinator's shard map locally (reference
            # StatusActor subscription with ack/resync); members serve
            # cluster-status queries from this mirror
            from filodb_tpu.coordinator.bootstrap import (
                ShardUpdateSubscriber,
            )
            self.shard_subscribers = {}
            for name, ing_cfg in cfg.datasets.items():
                self.shard_subscribers[name] = ShardUpdateSubscriber(
                    name, ing_cfg.num_shards,
                    RemotePlanDispatcher(host, int(port)))
            import threading as _th
            self._sub_stop = _th.Event()

            def poll_loop():
                while not self._sub_stop.wait(1.0):
                    for sub in self.shard_subscribers.values():
                        try:
                            sub.poll()
                        except Exception:
                            log.debug("shard-update poll failed",
                                      exc_info=True)

            _th.Thread(target=poll_loop, daemon=True,
                       name="shard-updates").start()
        else:
            # coordinator role: own the cluster singleton
            mig_cfg = cfg.migration or {}
            self.cluster.auto_rebalance = bool(
                mig_cfg.get("auto_rebalance", False))
            self.cluster.migration_lag_threshold = int(
                mig_cfg.get("lag_threshold", 0))
            self.cluster.migration_catchup_timeout_s = float(
                mig_cfg.get("catchup_timeout_s", 30.0))
            rep_cfg = cfg.replication or {}
            self.cluster.replication = int(rep_cfg.get("n_replicas", 0))
            self.cluster.replica_in_sync_lag = int(
                rep_cfg.get("in_sync_lag", 0))
            self.cluster.replica_hedge_s = float(
                rep_cfg.get("hedge_s", 0.05))
            self.cluster.replica_durable_sync_s = float(
                rep_cfg.get("durable_sync_s", 5.0))
            self.cluster.join(self.node)
            from filodb_tpu.coordinator.bootstrap import poll_remote_statuses
            for name, ing_cfg in cfg.datasets.items():
                logs = {s: self._shard_log(name, s)
                        for s in range(ing_cfg.num_shards)}
                self.cluster.setup_dataset(ing_cfg, logs)
                services[name] = self.cluster.query_service(
                    name, cfg.spreads.get(name, 1),
                    engine=cfg.engines.get(name, "mesh"),
                    result_cache=cfg.result_cache)
                self.cluster.on_heartbeat.append(
                    lambda n=name: poll_remote_statuses(self.cluster, n))
            # adaptive planner: load persisted per-dataset cost estimates
            # and register the live retry-after provider before any query
            # admission happens — restarts keep learned routing
            from filodb_tpu.coordinator import adaptive_planner
            for name in cfg.datasets:
                adaptive_planner.install(name, self.meta_store,
                                         cfg.cost_model)
            self.cluster.start_failure_detector()
            self._start_mesh_workers(cfg, services)
            # standing queries: one RuleManager per dataset with groups,
            # writing outputs through the shard WAL (first-class series)
            rules_cfg = dict(cfg.rules or {})
            sm_cfg = cfg.selfmon or {}
            groups_cfg = list(rules_cfg.get("groups") or [])
            if sm_cfg.get("enabled") and sm_cfg.get("default_alerts", True):
                groups_cfg.append(self._default_meta_alerts(sm_cfg))
            rules_cfg["groups"] = groups_cfg
            if groups_cfg:
                first_ds = next(iter(cfg.datasets))
                by_ds: dict[str, list] = {}
                for grp in load_groups(rules_cfg, first_ds):
                    by_ds.setdefault(grp.dataset, []).append(grp)
                notify_cfg = rules_cfg.get("notify", {}) or {}
                for ds, grps in by_ds.items():
                    ing = cfg.datasets[ds]
                    sink = LogSink(
                        {s: self._shard_log(ds, s)
                         for s in range(ing.num_shards)},
                        ing.num_shards, cfg.spreads.get(ds, 1))
                    # _meta carries only selfmon samples stamped at tick
                    # time: the default 5-minute out-of-order allowance
                    # would hold alert evaluation that far behind the
                    # ingest clock for no reason
                    ooo = (int(sm_cfg.get("ooo_allowance_ms", 2_000))
                           if ds == "_meta" else None)
                    self.rule_managers[ds] = RuleManager(
                        services[ds], sink, grps,
                        ooo_allowance_ms=ooo,
                        max_catchup_steps=int(
                            rules_cfg.get("max_catchup_steps", 512)),
                        notifier=self._build_notifier(notify_cfg),
                    ).start(float(rules_cfg.get("tick_s", 1.0)))
            if sm_cfg.get("enabled"):
                from filodb_tpu.rules.manager import LogSink as _MetaSink
                from filodb_tpu.utils.selfmon import MetaMonitor
                ing = cfg.datasets["_meta"]
                meta_sink = _MetaSink(
                    {s: self._shard_log("_meta", s)
                     for s in range(ing.num_shards)},
                    ing.num_shards, cfg.spreads.get("_meta", 0))
                self.selfmon = MetaMonitor(
                    meta_sink,
                    interval_s=float(sm_cfg.get("interval_s", 15.0)),
                    node=cfg.node_name,
                    instance=f"{cfg.node_name}:{cfg.http_port}",
                    include_buckets=bool(sm_cfg.get("include_buckets",
                                                    False)))
                self.selfmon.start()
        shard_maps = {
            name: (lambda n=name: self.shard_subscribers[n].mapper)
            for name in getattr(self, "shard_subscribers", {})
        }
        if cfg.http_impl == "fast":
            from filodb_tpu.http.fastserver import FastHttpServer
            http_cls = FastHttpServer
        else:
            http_cls = FiloHttpServer
        self.http = http_cls(services, port=cfg.http_port,
                             cluster=self.cluster
                             if not cfg.seeds else None,
                             shard_maps=shard_maps,
                             reuse_port=cfg.http_reuse_port,
                             response_cache=cfg.http_response_cache,
                             rule_managers=self.rule_managers).start()
        if cfg.gateway_port:
            first = next(iter(cfg.datasets.values()))
            sink = ContainerSink(
                {s: self._shard_log(first.dataset, s)
                 for s in range(first.num_shards)},
                first.num_shards, cfg.spreads.get(first.dataset, 1),
                dataset=first.dataset)
            self.gateway = GatewayServer(sink, port=cfg.gateway_port).start()
        # memory-pressure watchdog: write-buffer-pool occupancy and result-
        # cache bytes drive the governor's ok → degraded → critical states;
        # degraded evicts the result caches and tightens admission,
        # critical sheds gateway ingest and new expensive queries
        import weakref
        from filodb_tpu.utils.governor import MemoryWatchdog
        self.watchdog = MemoryWatchdog()
        memstore = self.memstore
        datasets = list(cfg.datasets)

        def buffer_pool_utilization():
            worst = None
            for name in datasets:
                for shard in memstore.shards_for(name):
                    for pool in getattr(shard, "buffer_pools", {}).values():
                        frac = pool.in_use / max(1, pool.cap)
                        worst = frac if worst is None else max(worst, frac)
            return worst

        self.watchdog.add_source("write_buffer_pools",
                                 buffer_pool_utilization)
        for name, svc in services.items():
            rc = getattr(svc, "result_cache", None)
            if rc is None:
                continue
            rc_ref = weakref.ref(rc)

            def cache_fraction(rc_ref=rc_ref):
                rc = rc_ref()
                if rc is None:
                    return None
                return rc.nbytes / max(1, rc.config.max_bytes)

            self.watchdog.add_source(f"result_cache.{name}", cache_fraction)

        def evict_caches(_state):
            for svc in services.values():
                rc = getattr(svc, "result_cache", None)
                if rc is not None:
                    rc.clear()

        self.watchdog.on_degraded.append(evict_caches)
        if not cfg.seeds:
            # PR 4 watchdog → PR 6 rebalance: a node going CRITICAL sheds
            # whole shards to peers via live migration, not just caches.
            # Runs off the watchdog thread — migrations block through
            # catch-up and must not stall pressure sampling.
            import threading as _th2
            cluster, me = self.cluster, cfg.node_name

            def shed_on_pressure(state):
                if state != "critical" or len(cluster.nodes) < 2:
                    return
                _th2.Thread(target=lambda: cluster.shed_load(me),
                            daemon=True, name="shed-load").start()

            self.watchdog.on_degraded.append(shed_on_pressure)
        # per-tenant active-series gauges summed over this node's shards
        from filodb_tpu.utils.governor import register_tenant_series_gauges
        register_tenant_series_gauges(
            lambda: [sh for name in datasets
                     for sh in memstore.shards_for(name)])
        self.watchdog.start()
        if os.environ.get("FILODB_PROFILER"):
            # built-in sampling profiler (reference SimpleProfiler started
            # from FiloServer.start)
            from filodb_tpu.utils.profiler import SimpleProfiler
            self.profiler = SimpleProfiler().start()
        if cfg.enable_failover:
            self._setup_failover()
        if cfg.downsample and not cfg.seeds:
            self._setup_downsampling(services)
        if not cfg.seeds:
            # tier federation wraps whatever planner the dataset ended up
            # with (raw-only or raw+downsample) — must run AFTER the
            # downsample plane so it can absorb the ds planner as a tier
            self._setup_federation(services)
        log.info("FiloServer up: http=%d executor=%d role=%s", self.http.port,
                 self.executor.port, "member" if cfg.seeds else "coordinator")
        return self

    # -- downsampling plane (reference DownsamplerMain scheduled job +
    #    LongTimeRangePlanner query routing) -------------------------------

    def _setup_downsampling(self, services: dict):
        import threading
        import time as _time
        from filodb_tpu.coordinator.longtime_planner import (
            LongTimeRangePlanner,
        )
        from filodb_tpu.coordinator.planner import SingleClusterPlanner
        from filodb_tpu.core.downsample import (
            DownsampledTimeSeriesStore,
            DownsamplerJob,
        )
        cfg = self.config
        self._ds_threads = []
        for dataset, ds_cfg in cfg.downsample.items():
            ing = cfg.datasets[dataset]
            resolutions = tuple(ds_cfg.get("resolutions_ms",
                                           (300_000, 3_600_000)))
            schedule_s = ds_cfg.get("schedule_s", 6 * 3600)
            raw_retention = ds_cfg.get("raw_retention_ms",
                                       ing.store.retention_ms)
            job = DownsamplerJob(self.column_store, dataset,
                                 ing.num_shards, resolutions,
                                 meta_store=self.meta_store)

            def runner(job=job, schedule_s=schedule_s):
                while True:
                    now_ms = int(_time.time() * 1000)
                    try:
                        # checkpointed: a restart resumes from the last
                        # persisted watermark, re-covering any window lost
                        # to a crash between raw flush and ds run
                        job.catch_up(now_ms)
                    except Exception:
                        log.exception("downsampler job failed")
                    _time.sleep(schedule_s)

            t = threading.Thread(target=runner, daemon=True,
                                 name=f"downsampler-{dataset}")
            t.start()
            self._ds_threads.append(t)
            # queries split raw vs downsample at the raw-retention boundary
            svc = services.get(dataset)
            if svc is not None:
                from filodb_tpu.core.downsample.downsampler import (
                    ds_dataset_name,
                )
                raw_planner = svc.planner
                dispatcher = getattr(raw_planner, "dispatcher_for_shard",
                                     None)
                if ds_cfg.get("streaming"):
                    # streaming rollups live in co-sharded memstore datasets
                    ds_planner = SingleClusterPlanner(
                        dataset, ing.num_shards,
                        cfg.spreads.get(dataset, 1),
                        dispatcher_for_shard=dispatcher,
                        dataset_name_override=ds_dataset_name(
                            dataset, min(resolutions)))
                else:
                    ds_store = DownsampledTimeSeriesStore(
                        self.column_store, dataset, min(resolutions),
                        ing.num_shards)
                    ds_planner = SingleClusterPlanner(
                        dataset, ing.num_shards,
                        cfg.spreads.get(dataset, 1), store=ds_store)
                svc.planner = LongTimeRangePlanner(
                    raw_planner, ds_planner, raw_retention)

    # -- tier federation (query/federation.py): one query_range across
    #    memstore, the downsample tier and object-store history ------------

    def _setup_federation(self, services: dict):
        fed = dict(self.config.federation or {})
        # opt-in: routing the hot tier by configured memory retention is
        # only safe when the operator asserts data past that horizon is
        # durably uploaded; without an explicit horizon the memstore (or
        # the downsample wiring's LongTimeRangePlanner) serves everything
        if not fed.get("enabled", True) or not fed.get("mem_retention_ms"):
            return
        from filodb_tpu.coordinator.longtime_planner import (
            LongTimeRangePlanner,
        )
        from filodb_tpu.coordinator.tiered_planner import (
            build_tiered_planner,
        )
        cfg = self.config
        for dataset, svc in services.items():
            if dataset.startswith("_"):
                continue  # _meta self-monitoring stays memstore-only
            ing = cfg.datasets.get(dataset)
            if ing is None:
                continue
            mem_retention = fed["mem_retention_ms"]
            raw_planner, ds_planner, raw_retention = svc.planner, None, None
            if isinstance(svc.planner, LongTimeRangePlanner):
                raw_planner = svc.planner.raw_planner
                ds_planner = svc.planner.ds_planner
                raw_retention = svc.planner.raw_retention_ms
            svc.planner = build_tiered_planner(
                raw_planner, self.column_store, dataset, ing.num_shards,
                cfg.spreads.get(dataset, 1),
                mem_retention_ms=int(mem_retention),
                raw_retention_ms=raw_retention,
                ds_planner=ds_planner,
                odp_max_chunks=int(fed.get("odp_max_chunks", 10_000)),
                refresh_s=float(fed.get("refresh_s", 60.0)))
            log.info("federation: %s routed across memstore%s/objectstore "
                     "(mem floor %dms)", dataset,
                     "/downsample" if ds_planner is not None else "",
                     mem_retention)

    # -- singleton failover (reference ClusterSingletonFailoverSpec) --------

    def _registry(self):
        from filodb_tpu.coordinator.bootstrap import MemberRegistry
        root = self.config.wal_dir or os.path.join(self.config.data_dir,
                                                   "wal")
        return MemberRegistry(os.path.join(root, "members.txt"))

    def _setup_failover(self):
        import threading
        reg = self._registry()
        role = "member" if self.config.seeds else "coord"
        reg.register(role, self.config.node_name, "127.0.0.1",
                     self.executor.port)
        self.is_coordinator = role == "coord"
        if role == "member":
            self._failover_stop = threading.Event()
            self._failover_thread = threading.Thread(
                target=self._failover_watch, daemon=True)
            self._failover_thread.start()

    def _failover_watch(self, interval_s: float = 0.25):
        from filodb_tpu.coordinator.bootstrap import (
            alive_members,
            RemotePlanDispatcher,
        )
        reg = self._registry()
        misses = 0
        while not self._failover_stop.wait(interval_s):
            coord = reg.current_coordinator()
            if coord == self.config.node_name:
                return  # we promoted
            members = reg.members()
            entry = members.get(coord)
            if entry is not None and RemotePlanDispatcher(
                    entry[1], entry[2], timeout=1.0).ping():
                misses = 0
                continue
            misses += 1
            if misses < 3:
                continue
            alive = alive_members(reg)
            alive.pop(coord, None)
            if alive and min(alive) == self.config.node_name:
                log.warning("coordinator %s down; promoting self", coord)
                try:
                    self._promote(alive)
                except Exception:
                    log.exception("promotion failed")
                return
            misses = 0  # another member should promote; keep watching

    def _promote(self, alive: dict):
        """Become the cluster singleton: adopt running members' shards,
        reassign the dead coordinator's shards, serve queries."""
        from filodb_tpu.coordinator.bootstrap import (
            RemoteNodeHandle,
            poll_remote_statuses,
        )
        from filodb_tpu.coordinator.shard_manager import ShardManager
        from filodb_tpu.coordinator.shardmapper import ShardStatus
        cfg = self.config
        self.cluster = FilodbCluster()
        self.cluster.join(self.node)
        for name, (host, port) in alive.items():
            if name != cfg.node_name:
                self.cluster.nodes[name] = RemoteNodeHandle(name, host, port)
        for dataset, ing_cfg in cfg.datasets.items():
            logs = {s: self._shard_log(dataset, s)
                    for s in range(ing_cfg.num_shards)}
            for shard, l in logs.items():
                self.cluster.logs[(dataset, shard)] = l
            self.cluster.configs[dataset] = ing_cfg
            # degraded mode: a promoted singleton assigns to the survivors
            # even below min-num-nodes — availability over balance until
            # replacement members join
            sm = ShardManager(dataset, ing_cfg.num_shards,
                              min(ing_cfg.min_num_nodes,
                                  len(self.cluster.nodes)))
            self.cluster.shard_managers[dataset] = sm
            # adopt what's already running (incl. our own shards)
            for name, node in self.cluster.nodes.items():
                if name == cfg.node_name:
                    statuses = self._handle_shard_status(dataset)
                else:
                    try:
                        statuses = node.shard_status(dataset)
                    except (ConnectionError, OSError, RuntimeError):
                        statuses = []
                for shard, st in statuses:
                    sm.adopt(shard, name,
                             ShardStatus.ACTIVE if st == "active"
                             else ShardStatus.RECOVERY)
            # the dead coordinator's shards are unassigned: reassign
            for ev in sm.rebalance():
                self.cluster._on_event(dataset, ev)
            svc = self.cluster.query_service(
                dataset, cfg.spreads.get(dataset, 1),
                engine=cfg.engines.get(dataset, "mesh"),
                result_cache=cfg.result_cache)
            self.http.services[dataset] = svc
            self.cluster.on_heartbeat.append(
                lambda n=dataset: poll_remote_statuses(self.cluster, n))
        self.http.cluster = self.cluster
        self.cluster.start_failure_detector()
        self._registry().register("coord", cfg.node_name, "127.0.0.1",
                                  self.executor.port)
        self.is_coordinator = True

    def shutdown(self):
        if self.selfmon is not None:
            self.selfmon.stop()  # before the WALs close under its sink
        for mgr in getattr(self, "rule_managers", {}).values():
            mgr.stop()
        if getattr(self, "watchdog", None) is not None:
            self.watchdog.stop()  # also resets the governor state to OK
        if getattr(self, "_failover_stop", None) is not None:
            self._failover_stop.set()
        if getattr(self, "_sub_stop", None) is not None:
            self._sub_stop.set()  # stop the shard-update poll loop
        if self.http:
            self.http.stop()
        if self.gateway:
            self.gateway.stop()
        if self.executor:
            self.executor.stop()
        if getattr(self, "mesh_runtime", None) is not None:
            self.mesh_runtime.shutdown()
        if getattr(self, "mesh_supervisor", None) is not None:
            self.mesh_supervisor.stop()
        self.cluster.stop()
        for l in self.logs.values():
            l.close()
        if getattr(self, "log_server", None) is not None:
            self.log_server.stop()  # broker role: port, thread, open logs
        if getattr(self, "_consul", None) is not None:
            try:
                self._consul.deregister(self.config.node_name)
            except OSError:
                pass
        if self.store_server is not None:
            self.store_server.shutdown()
        self.column_store.close()
        if getattr(self, "is_coordinator", False):
            # learned cost estimates survive restarts via the metastore
            from filodb_tpu.coordinator import adaptive_planner
            for name in getattr(self.config, "datasets", {}) or {}:
                try:
                    adaptive_planner.persist(name, self.meta_store)
                except Exception:
                    log.debug("cost-model persist failed for %s", name,
                              exc_info=True)
        self.meta_store.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description="filodb_tpu standalone server")
    ap.add_argument("--config", help="server config JSON", default=None)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    # Honor JAX_PLATFORMS even when a sitecustomize has overridden
    # jax_platforms at interpreter boot (e.g. to a tunneled TPU backend):
    # the operator's env choice wins.
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        try:
            import jax
            jax.config.update("jax_platforms", plat)
        except Exception:  # pragma: no cover - jax always importable here
            log.warning("could not apply JAX_PLATFORMS=%s", plat)
    server = FiloServer(ServerConfig.load(args.config)).start()
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    import time
    while not stop:
        time.sleep(0.5)
    server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
