"""FiloServer: the standalone server process.

Counterpart of reference ``standalone/src/main/scala/filodb.standalone/
FiloServer.scala:38,86``: boots the stores, joins the cluster (seed
discovery), starts per-shard ingestion with recovery, and serves the
Prometheus HTTP API, the plan-executor port (remote dispatch) and optionally
the Influx gateway.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import sys

from filodb_tpu.config import ServerConfig
from filodb_tpu.coordinator.cluster import FilodbCluster, Node
from filodb_tpu.coordinator.remote import PlanExecutorServer
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.localstore import (
    LocalDiskColumnStore,
    LocalDiskMetaStore,
)
from filodb_tpu.gateway.server import ContainerSink, GatewayServer
from filodb_tpu.http.server import FiloHttpServer
from filodb_tpu.kafka.log import FileLog

log = logging.getLogger(__name__)


class FiloServer:
    def __init__(self, config: ServerConfig):
        self.config = config
        os.makedirs(config.data_dir, exist_ok=True)
        self.column_store = LocalDiskColumnStore(
            os.path.join(config.data_dir, "columnstore"))
        self.meta_store = LocalDiskMetaStore(
            os.path.join(config.data_dir, "columnstore"))
        self.memstore = TimeSeriesMemStore(self.column_store, self.meta_store)
        self.node = Node(config.node_name, self.memstore)
        self.cluster = FilodbCluster()
        self.logs: dict[tuple[str, int], FileLog] = {}
        self.http: FiloHttpServer | None = None
        self.gateway: GatewayServer | None = None
        self.executor: PlanExecutorServer | None = None

    def start(self) -> "FiloServer":
        cfg = self.config
        # plan-executor port (remote scatter-gather)
        self.executor = PlanExecutorServer(self.memstore,
                                           port=cfg.executor_port).start()
        self.node.executor_port = self.executor.port
        self.cluster.join(self.node)
        services = {}
        for name, ing_cfg in cfg.datasets.items():
            logs = {}
            for shard in range(ing_cfg.num_shards):
                p = os.path.join(cfg.data_dir, "wal", name,
                                 f"shard-{shard}.log")
                logs[shard] = FileLog(p)
                self.logs[(name, shard)] = logs[shard]
            self.cluster.setup_dataset(ing_cfg, logs)
            services[name] = self.cluster.query_service(
                name, cfg.spreads.get(name, 1))
        self.cluster.start_failure_detector()
        self.http = FiloHttpServer(services, port=cfg.http_port,
                                   cluster=self.cluster).start()
        if cfg.gateway_port:
            first = next(iter(cfg.datasets.values()))
            sink = ContainerSink(
                {s: self.logs[(first.dataset, s)]
                 for s in range(first.num_shards)},
                first.num_shards, cfg.spreads.get(first.dataset, 1))
            self.gateway = GatewayServer(sink, port=cfg.gateway_port).start()
        log.info("FiloServer up: http=%d executor=%d", self.http.port,
                 self.executor.port)
        return self

    def shutdown(self):
        if self.http:
            self.http.stop()
        if self.gateway:
            self.gateway.stop()
        if self.executor:
            self.executor.stop()
        self.cluster.stop()
        for l in self.logs.values():
            l.close()
        self.column_store.close()
        self.meta_store.close()


def main(argv=None):
    ap = argparse.ArgumentParser(description="filodb_tpu standalone server")
    ap.add_argument("--config", help="server config JSON", default=None)
    args = ap.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = FiloServer(ServerConfig.load(args.config)).start()
    stop = []
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    import time
    while not stop:
        time.sleep(0.5)
    server.shutdown()


if __name__ == "__main__":
    sys.exit(main())
