"""Deterministic seed store for the multi-process mesh harness.

Mesh worker processes started with ``--seed
filodb_tpu.testing.mesh_store:build_store`` rebuild EXACTLY this store:
every input is seeded and shard placement (``ingestion_shard``) hashes
record content, so N independent processes derive identical per-shard
data — which is what lets the N×1 CPU harness assert byte-identity
against a single-process engine over the same builder's output.
"""

from __future__ import annotations

DATASET = "timeseries"
NUM_SHARDS = 4
N_SERIES = 48
N_SAMPLES = 180
START_MS = 1_600_000_000_000
INTERVAL_MS = 10_000


def build_store():
    """A fully-ingested memstore: ``N_SERIES`` counters routed over
    ``NUM_SHARDS`` shards, with resets so rate correction is exercised
    across the process boundary."""
    from filodb_tpu.coordinator.ingestion import ingest_routed
    from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
    from filodb_tpu.core.store.config import StoreConfig
    from filodb_tpu.testing.data import counter_series, counter_stream

    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup(DATASET, s, StoreConfig(max_chunk_size=100,
                                         groups_per_shard=4))
    keys = counter_series(N_SERIES)
    stream = counter_stream(keys, N_SAMPLES, start_ms=START_MS,
                            interval_ms=INTERVAL_MS, seed=7,
                            reset_every=60)
    ingest_routed(ms, DATASET, stream, NUM_SHARDS)
    return ms
