"""In-process S3-subset fake for hermetic object-store tests.

Models the slice of the S3 API the object-store tier actually uses —
PUT / GET (with byte ranges) / LIST (prefix) / DELETE plus basic
multipart upload — with two extras real S3 lacks:

- **optional disk persistence** (``root=``): objects live as files under
  a directory, written with the write-temp-then-``os.replace`` pattern,
  so a *new* ``FakeS3`` instance over the same root sees everything a
  previous instance stored.  That is what lets durability tests model a
  process crash: drop every in-memory structure, rebuild from the
  "bucket", and the data had better still be there.
- **injectable faults and latency** (``inject``): arm the next N calls
  of an op to raise, so upload-retry paths can be exercised
  deterministically without a network.

Deliberately NOT a network server — calls are plain method calls, the
same interface ``HttpS3Client`` (objectstore.py) exposes for real
endpoints.
"""

from __future__ import annotations

import os
import threading
import time
import urllib.parse


class S3NotFound(KeyError):
    """GET/DELETE of a key that does not exist (HTTP 404 analog)."""


class S3TransientError(ConnectionError):
    """Injected/transient failure (HTTP 500/503 analog) — retryable."""


def _quote_key(key: str) -> str:
    # object keys contain "/" — keep them as directories on disk so LIST
    # stays cheap, but escape anything else that the filesystem dislikes
    return "/".join(urllib.parse.quote(part, safe="")
                    for part in key.split("/"))


class FakeS3:
    """Thread-safe in-memory (or dir-backed) S3 subset.

    Buckets are implicit: the store holds one flat key space; callers
    prepend ``bucket/`` themselves (the object-store tier does).
    """

    def __init__(self, root: str | None = None, latency_s: float = 0.0):
        self.root = root
        self.latency_s = latency_s
        self._objects: dict[str, bytes] = {}
        self._mpu: dict[str, dict[int, bytes]] = {}
        self._mpu_seq = 0
        self._lock = threading.Lock()
        # op -> list of [remaining_count, exc_factory]
        self._faults: dict[str, list[list]] = {}
        self.op_counts: dict[str, int] = {}
        if root:
            os.makedirs(root, exist_ok=True)

    # ------------------------------------------------------------- faults
    def inject(self, op: str, times: int = 1, exc=None) -> None:
        """Arm the next ``times`` calls of ``op`` (put/get/list/delete/
        multipart) to raise ``exc`` (default ``S3TransientError``)."""
        exc = exc or (lambda: S3TransientError(f"injected {op} fault"))
        if isinstance(exc, BaseException):
            e = exc
            exc = lambda: e  # noqa: E731
        elif isinstance(exc, type):
            cls = exc
            exc = lambda: cls(f"injected {op} fault")  # noqa: E731
        with self._lock:
            self._faults.setdefault(op, []).append([times, exc])

    def clear_faults(self) -> None:
        with self._lock:
            self._faults.clear()

    def _enter(self, op: str):
        if self.latency_s:
            time.sleep(self.latency_s)
        with self._lock:
            self.op_counts[op] = self.op_counts.get(op, 0) + 1
            for f in self._faults.get(op, ()):
                if f[0] > 0:
                    f[0] -= 1
                    raise f[1]()

    # ------------------------------------------------------------ objects
    def _path(self, key: str) -> str:
        return os.path.join(self.root, _quote_key(key))

    def put_object(self, key: str, data: bytes) -> None:
        self._enter("put")
        if self.root:
            path = self._path(key)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp-%d" % threading.get_ident()
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        else:
            with self._lock:
                self._objects[key] = bytes(data)

    def get_object(self, key: str, start: int | None = None,
                   length: int | None = None) -> bytes:
        """GET, optionally with a byte range (offset + length)."""
        self._enter("get")
        if self.root:
            path = self._path(key)
            try:
                with open(path, "rb") as f:
                    if start:
                        f.seek(start)
                    return f.read(length) if length is not None else f.read()
            except FileNotFoundError:
                raise S3NotFound(key) from None
        with self._lock:
            try:
                data = self._objects[key]
            except KeyError:
                raise S3NotFound(key) from None
        if start is None:
            return data
        end = len(data) if length is None else start + length
        return data[start:end]

    def list_objects(self, prefix: str = "") -> list[str]:
        """All keys with the given prefix, sorted."""
        self._enter("list")
        if self.root:
            out = []
            for dirpath, _dirs, files in os.walk(self.root):
                for fn in files:
                    if fn.endswith(".tmp") or ".tmp-" in fn:
                        continue
                    rel = os.path.relpath(os.path.join(dirpath, fn),
                                          self.root)
                    key = "/".join(urllib.parse.unquote(p)
                                   for p in rel.split(os.sep))
                    if key.startswith(prefix):
                        out.append(key)
            return sorted(out)
        with self._lock:
            return sorted(k for k in self._objects if k.startswith(prefix))

    def delete_object(self, key: str) -> None:
        """DELETE — idempotent, like S3 (deleting a missing key is OK)."""
        self._enter("delete")
        if self.root:
            try:
                os.remove(self._path(key))
            except FileNotFoundError:
                pass
            return
        with self._lock:
            self._objects.pop(key, None)

    # ---------------------------------------------------------- multipart
    def create_multipart(self, key: str) -> str:
        self._enter("multipart")
        with self._lock:
            self._mpu_seq += 1
            upload_id = f"mpu-{self._mpu_seq}"
            self._mpu[upload_id] = {}
        return upload_id

    def upload_part(self, key: str, upload_id: str, part_number: int,
                    data: bytes) -> None:
        self._enter("multipart")
        with self._lock:
            if upload_id not in self._mpu:
                raise S3NotFound(upload_id)
            self._mpu[upload_id][part_number] = bytes(data)

    def complete_multipart(self, key: str, upload_id: str) -> None:
        self._enter("multipart")
        with self._lock:
            parts = self._mpu.pop(upload_id, None)
        if parts is None:
            raise S3NotFound(upload_id)
        blob = b"".join(parts[n] for n in sorted(parts))
        # the final assembly is an ordinary PUT (counted as one)
        self.put_object(key, blob)

    def abort_multipart(self, key: str, upload_id: str) -> None:
        self._enter("multipart")
        with self._lock:
            self._mpu.pop(upload_id, None)

    # ------------------------------------------------------------ helpers
    def corrupt(self, key: str, offset: int = 0, xor: int = 0xFF) -> None:
        """Flip byte(s) in a stored object — the integrity-tripwire test
        hook.  XORs the byte at ``offset`` with ``xor``."""
        data = bytearray(self.get_object(key))
        data[offset] ^= xor
        if self.root:
            path = self._path(key)
            with open(path, "wb") as f:
                f.write(bytes(data))
        else:
            with self._lock:
                self._objects[key] = bytes(data)

    def total_bytes(self) -> int:
        if self.root:
            return sum(len(self.get_object(k)) for k in self.list_objects())
        with self._lock:
            return sum(len(v) for v in self._objects.values())
