"""Synthetic time-series generators for tests and benchmarks.

Counterpart of the reference's canonical fixtures
(``core/src/test/scala/filodb.core/TestData.scala`` — ``MachineMetricsData:217``,
``MetricsTestData:468``) and the gateway's ``TestTimeseriesProducer``
(``gateway/src/main/scala/filodb/timeseries/TestTimeseriesProducer.scala``):
multi-series gauge/counter/histogram streams with app/instance label sets.
"""

from __future__ import annotations

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer, SomeData


def machine_metrics_series(n_series: int = 10, metric: str = "heap_usage",
                           ws: str = "demo", ns: str = "App-0") -> list[PartKey]:
    keys = []
    for i in range(n_series):
        keys.append(PartKey.create("gauge", {
            "_metric_": metric, "_ws_": ws, "_ns_": ns,
            "instance": f"instance-{i}", "host": f"H{i % 4}",
        }))
    return keys


def counter_series(n_series: int = 10, metric: str = "http_requests_total",
                   ws: str = "demo", ns: str = "App-0") -> list[PartKey]:
    return [PartKey.create("prom-counter", {
        "_metric_": metric, "_ws_": ws, "_ns_": ns,
        "instance": f"instance-{i}", "job": f"job-{i % 3}",
    }) for i in range(n_series)]


def histogram_series(n_series: int = 4, metric: str = "http_req_latency",
                     ws: str = "demo", ns: str = "App-0") -> list[PartKey]:
    return [PartKey.create("prom-histogram", {
        "_metric_": metric, "_ws_": ws, "_ns_": ns, "instance": f"instance-{i}",
    }) for i in range(n_series)]


def gauge_stream(keys: list[PartKey], n_samples: int, start_ms: int = 0,
                 interval_ms: int = 10_000, batch: int = 100, seed: int = 0,
                 start_offset: int = 0):
    """Yield SomeData containers of gauge samples, round-robin across series."""
    rng = np.random.default_rng(seed)
    values = {k: 50.0 + 30.0 * rng.random() for k in keys}
    container = RecordContainer()
    offset = start_offset
    for s in range(n_samples):
        ts = start_ms + s * interval_ms
        for k in keys:
            values[k] += rng.normal(0, 1.0)
            container.add(IngestRecord(k, ts, (values[k],)))
            if len(container) >= batch:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)


def counter_stream(keys: list[PartKey], n_samples: int, start_ms: int = 0,
                   interval_ms: int = 10_000, batch: int = 100, seed: int = 0,
                   reset_every: int = 0, start_value: float = 0.0):
    """Counter samples with optional resets to exercise rate correction.
    ``start_value`` sets the initial counter magnitude (a long-lived busy
    counter sits well beyond 2^24 — the f32-precision regime)."""
    rng = np.random.default_rng(seed)
    values = dict.fromkeys(keys, start_value)
    container = RecordContainer()
    offset = 0
    for s in range(n_samples):
        ts = start_ms + s * interval_ms
        for k in keys:
            if reset_every and s > 0 and s % reset_every == 0:
                values[k] = 0.0
            values[k] += float(rng.integers(0, 20))
            container.add(IngestRecord(k, ts, (values[k],)))
            if len(container) >= batch:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)


DEFAULT_LES = np.array([0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                        np.inf])


def histogram_stream(keys, n_samples: int, start_ms: int = 0,
                     interval_ms: int = 10_000, batch: int = 100, seed: int = 0,
                     les: np.ndarray = DEFAULT_LES):
    """prom-histogram samples: (sum, count, (les, cumulative buckets))."""
    rng = np.random.default_rng(seed)
    nb = len(les)
    state = {k: np.zeros(nb, np.int64) for k in keys}
    sums = dict.fromkeys(keys, 0.0)
    container = RecordContainer()
    offset = 0
    for s in range(n_samples):
        ts = start_ms + s * interval_ms
        for k in keys:
            incr = rng.integers(0, 5, nb)
            cum = np.cumsum(incr)
            state[k] = state[k] + cum
            sums[k] += float(cum[-1]) * 0.2
            container.add(IngestRecord(
                k, ts, (sums[k], float(state[k][-1]), (les, state[k].copy()))))
            if len(container) >= batch:
                yield SomeData(container, offset)
                offset += 1
                container = RecordContainer()
    if len(container):
        yield SomeData(container, offset)
