"""Dataset schemas and column metadata.

Counterpart of the reference's schema system
(``core/src/main/scala/filodb.core/metadata/Schemas.scala:29,58,170,258``,
``Column.scala:94-103``) and its default schema config
(``core/src/main/resources/filodb-defaults.conf:23-110``): ``gauge``,
``untyped``, ``prom-counter``, ``prom-histogram`` and the downsample schemas.

Schemas carry a stable 16-bit schema id (hash of name + column types) used to
tag ingest records and chunks, mirroring ``RecordSchema.schemaID``.
"""

from __future__ import annotations

import enum
import zlib
from dataclasses import dataclass, field


class ColumnType(enum.Enum):
    TIMESTAMP = "ts"
    DOUBLE = "double"
    LONG = "long"
    INT = "int"
    HISTOGRAM = "hist"
    STRING = "string"
    MAP = "map"


@dataclass(frozen=True)
class Column:
    name: str
    ctype: ColumnType
    # detectDrops: counter columns get reset-correction in rate/increase
    is_counter: bool = False


@dataclass(frozen=True)
class DataSchema:
    """Column layout of a time series row. Column 0 is always the timestamp."""

    name: str
    columns: tuple[Column, ...]
    value_column: int  # index of the default value column for queries
    downsamplers: tuple[str, ...] = ()  # e.g. ("tTime(0)", "dMin(1)", ...)
    downsample_schema: str | None = None

    def __post_init__(self):
        assert self.columns[0].ctype == ColumnType.TIMESTAMP, "col 0 must be timestamp"

    @property
    def value_col_name(self) -> str:
        return self.columns[self.value_column].name


@dataclass(frozen=True)
class PartitionSchema:
    """Partition-key layout: which labels form the shard key.

    Reference: ``PartitionSchema`` with predefined keys and shard-key columns
    (``filodb-defaults.conf`` ``partition-schema`` + ``shard-key-columns``).
    """

    shard_key_labels: tuple[str, ...] = ("_ws_", "_ns_", "_metric_")
    predefined_labels: tuple[str, ...] = (
        "_ws_", "_ns_", "_metric_", "app", "instance", "host", "le", "job",
    )


@dataclass(frozen=True)
class Schema:
    data: DataSchema
    part: PartitionSchema = field(default_factory=PartitionSchema)

    @property
    def name(self) -> str:
        return self.data.name

    @property
    def schema_id(self) -> int:
        sig = self.data.name + "|" + ",".join(
            f"{c.name}:{c.ctype.value}" for c in self.data.columns
        )
        return zlib.crc32(sig.encode()) & 0xFFFF


def _mk(name, cols, value_column, downsamplers=(), ds_schema=None) -> Schema:
    return Schema(DataSchema(name, tuple(cols), value_column, tuple(downsamplers),
                             ds_schema))


GAUGE = _mk(
    "gauge",
    [Column("timestamp", ColumnType.TIMESTAMP), Column("value", ColumnType.DOUBLE)],
    value_column=1,
    downsamplers=["tTime(0)", "dMin(1)", "dMax(1)", "dSum(1)", "dCount(1)", "dAvg(1)"],
    ds_schema="ds-gauge",
)

UNTYPED = _mk(
    "untyped",
    [Column("timestamp", ColumnType.TIMESTAMP), Column("value", ColumnType.DOUBLE)],
    value_column=1,
)

PROM_COUNTER = _mk(
    "prom-counter",
    [Column("timestamp", ColumnType.TIMESTAMP),
     Column("value", ColumnType.DOUBLE, is_counter=True)],
    value_column=1,
    downsamplers=["tTime(0)", "dLast(1)"],
    ds_schema="prom-counter",
)

PROM_HISTOGRAM = _mk(
    "prom-histogram",
    [Column("timestamp", ColumnType.TIMESTAMP),
     Column("sum", ColumnType.DOUBLE, is_counter=True),
     Column("count", ColumnType.DOUBLE, is_counter=True),
     Column("h", ColumnType.HISTOGRAM, is_counter=True)],
    value_column=3,
    downsamplers=["tTime(0)", "dLast(1)", "dLast(2)", "hLast(3)"],
    ds_schema="prom-histogram",
)

DS_GAUGE = _mk(
    "ds-gauge",
    [Column("timestamp", ColumnType.TIMESTAMP),
     Column("min", ColumnType.DOUBLE),
     Column("max", ColumnType.DOUBLE),
     Column("sum", ColumnType.DOUBLE),
     Column("count", ColumnType.DOUBLE),
     Column("avg", ColumnType.DOUBLE)],
    value_column=5,
)


class Schemas:
    """Registry of schemas, lookup by name or id (reference ``Schemas.scala:258``)."""

    def __init__(self, schemas: list[Schema] | None = None):
        self._by_name: dict[str, Schema] = {}
        self._by_id: dict[int, Schema] = {}
        for s in schemas or [GAUGE, UNTYPED, PROM_COUNTER, PROM_HISTOGRAM, DS_GAUGE]:
            self.register(s)

    def register(self, s: Schema) -> None:
        if s.schema_id in self._by_id and self._by_id[s.schema_id].name != s.name:
            raise ValueError(f"schema id clash: {s.name} vs {self._by_id[s.schema_id].name}")
        self._by_name[s.name] = s
        self._by_id[s.schema_id] = s

    def __getitem__(self, name: str) -> Schema:
        return self._by_name[name]

    def by_id(self, sid: int) -> Schema:
        return self._by_id[sid]

    def __contains__(self, name: str) -> bool:
        return name in self._by_name

    @property
    def all(self) -> list[Schema]:
        return list(self._by_name.values())


DEFAULT_SCHEMAS = Schemas()
