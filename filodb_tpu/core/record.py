"""Ingestion records and containers.

Counterpart of the reference's BinaryRecord v2 ingestion records and
RecordContainers (``core/src/main/scala/filodb.core/binaryrecord2/
RecordBuilder.scala:34``, ``RecordContainer.scala:13-27``): the unit shipped
from gateways over the log into shards is a container of schema-tagged records,
each holding (partition key, timestamp, data values). Containers serialize to
bytes so they can ride a Kafka-compatible log and be replayed on recovery.
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import Schemas


def encode_labels(labels: tuple[tuple[str, str], ...]) -> bytes:
    """Label-section wire codec: u16 nlabels | (u16 klen|k|u16 vlen|v)*.
    Shared by container records and the native part-key blob — the native
    hash-map keys them byte-identically, so there is exactly one encoder."""
    out = [struct.pack("<H", len(labels))]
    for k, v in labels:
        kb, vb = k.encode(), v.encode()
        out.append(struct.pack("<H", len(kb)))
        out.append(kb)
        out.append(struct.pack("<H", len(vb)))
        out.append(vb)
    return b"".join(out)


def decode_labels(data: bytes, off: int) -> tuple[tuple, int]:
    (nlabels,) = struct.unpack_from("<H", data, off)
    off += 2
    labels = []
    for _ in range(nlabels):
        (kl,) = struct.unpack_from("<H", data, off)
        off += 2
        k = data[off : off + kl].decode()
        off += kl
        (vl,) = struct.unpack_from("<H", data, off)
        off += 2
        labels.append((k, data[off : off + vl].decode()))
        off += vl
    return tuple(labels), off


_SCHEMA_ID_CACHE: dict[str, int] = {}


def _schema_ids(name: str) -> int:
    sid = _SCHEMA_ID_CACHE.get(name)
    if sid is None:
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        sid = DEFAULT_SCHEMAS[name].schema_id
        _SCHEMA_ID_CACHE[name] = sid
    return sid


@dataclass(frozen=True)
class IngestRecord:
    """One sample for one series. ``values`` follows the schema's non-timestamp
    data columns in order; histogram values are (nb,) int64 cumulative buckets
    (with bucket bounds carried in the partition key label scheme or schema)."""

    part_key: PartKey
    timestamp: int  # epoch millis
    values: tuple

    def __post_init__(self):
        # normalize numpy arrays for hashability at container level
        pass


@dataclass
class RecordContainer:
    """A batch of records plus the log offset it came from."""

    records: list[IngestRecord] = field(default_factory=list)

    def add(self, rec: IngestRecord) -> None:
        self.records.append(rec)

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def serialize(self) -> bytes:
        """Versioned schema-tagged binary layout (v2) — the wire/WAL format.

        Mirrors the reference's RecordContainer contract
        (``RecordContainer.scala:13-27``, ``RecordBuilder.scala:34``): each
        record embeds the partition hash, timestamp, schema id, the full
        part key (sorted labels) and the column values. No pickle: the
        format is language-neutral and parsed directly by the C++ ingest
        runtime.

        Layout (little-endian)::

            u8 ver=2 | u32 n_records | records...
            record: u32 rec_len | u32 part_hash | i64 ts | u16 schema_id
                    | u16 nlabels | (u16 klen|k|u16 vlen|v)*  (sorted)
                    | u8 nvals | values*
            value:  u8 0 | f64                      (double column)
                    u8 1 | u16 nb | f64*nb | i64*nb (histogram les+counts)
        """
        out = [struct.pack("<BI", 2, len(self.records))]
        for r in self.records:
            body = [struct.pack("<IqH", r.part_key.part_hash, r.timestamp,
                                _schema_ids(r.part_key.schema)),
                    encode_labels(r.part_key.labels),
                    struct.pack("<B", len(r.values))]
            for v in r.values:
                if isinstance(v, tuple) or (
                        isinstance(v, np.ndarray) and v.ndim):
                    les, counts = v
                    les = np.ascontiguousarray(les, np.float64)
                    counts = np.ascontiguousarray(counts, np.int64)
                    body.append(struct.pack("<BH", 1, len(les)))
                    body.append(les.tobytes())
                    body.append(counts.tobytes())
                else:
                    body.append(struct.pack("<Bd", 0, float(v)))
            payload = b"".join(body)
            out.append(struct.pack("<I", len(payload)))
            out.append(payload)
        return b"".join(out)

    @staticmethod
    def deserialize(data: bytes, schemas: Schemas | None = None) -> "RecordContainer":
        ver = data[0]
        if ver == 1:
            return RecordContainer._deserialize_v1_pickle(data)
        assert ver == 2, f"unknown container version {ver}"
        (n,) = struct.unpack_from("<I", data, 1)
        off = 5
        c = RecordContainer()
        key_memo: dict = {}  # same series repeats within a batch
        from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
        reg = schemas or DEFAULT_SCHEMAS
        for _ in range(n):
            (rec_len,) = struct.unpack_from("<I", data, off)
            off += 4
            end = off + rec_len
            part_hash, ts, sid = struct.unpack_from("<IqH", data, off)
            off += 14
            labels_start = off
            labels, off = decode_labels(data, off)
            label_blob = data[labels_start:off]
            nvals = data[off]
            off += 1
            vals = []
            for _ in range(nvals):
                tag = data[off]
                off += 1
                if tag == 0:
                    (x,) = struct.unpack_from("<d", data, off)
                    off += 8
                    vals.append(x)
                else:
                    (nb,) = struct.unpack_from("<H", data, off)
                    off += 2
                    les = np.frombuffer(data, np.float64, nb, off).copy()
                    off += 8 * nb
                    counts = np.frombuffer(data, np.int64, nb, off).copy()
                    off += 8 * nb
                    vals.append((les, counts))
            assert off == end, "record length mismatch"
            memo_key = (sid, label_blob)
            pk = key_memo.get(memo_key)
            if pk is None:
                pk = PartKey(reg.by_id(sid).name, tuple(labels))
                pk.__dict__["part_hash"] = part_hash  # seed the cached hash
                key_memo[memo_key] = pk
            c.add(IngestRecord(pk, ts, tuple(vals)))
        return c

    @staticmethod
    def _deserialize_v1_pickle(data: bytes) -> "RecordContainer":
        """Legacy WAL segments written before the binary format. Pickle is
        code execution, so this path is OPT-IN (local replay of old files
        only) — container bytes now also arrive over the network
        (log_server), where a crafted v1 frame must never deserialize."""
        import os
        if not os.environ.get("FILODB_ALLOW_LEGACY_WAL"):
            raise ValueError(
                "legacy v1 (pickle) container rejected; set "
                "FILODB_ALLOW_LEGACY_WAL=1 only when replaying trusted "
                "pre-binary WAL files")
        ver, ln = struct.unpack_from("<BI", data, 0)
        raw = pickle.loads(data[5 : 5 + ln])
        c = RecordContainer()
        for schema, labels, ts, values in raw:
            vals = tuple(np.asarray(v, np.int64) if isinstance(v, list) else v
                         for v in values)
            c.add(IngestRecord(PartKey(schema, labels), ts, vals))
        return c


def container_max_ts(raw: bytes) -> int:
    """Max record timestamp in a serialized v2 container, or -1.

    A header-only scan (rec_len + the fixed-offset i64 ts per record): the
    native ingest lane never builds Python records, but the shard still
    needs its ingest high-water timestamp for the result cache's mutable
    horizon."""
    if not raw or raw[0] != 2:
        return -1
    (n,) = struct.unpack_from("<I", raw, 1)
    off = 5
    mx = -1
    for _ in range(n):
        (rec_len,) = struct.unpack_from("<I", raw, off)
        (ts,) = struct.unpack_from("<q", raw, off + 8)
        if ts > mx:
            mx = ts
        off += 4 + rec_len
    return mx


class BytesContainer:
    """A container backed by its serialized bytes, parsed lazily.

    WAL replay and network transports hand these to the shard: the native
    ingest lane consumes ``raw`` directly in C++ (no per-record Python
    objects); the host fallback iterates, triggering a one-time parse.
    """

    __slots__ = ("raw", "_parsed")

    def __init__(self, raw: bytes):
        self.raw = raw
        self._parsed = None

    @property
    def records(self) -> list[IngestRecord]:
        if self._parsed is None:
            self._parsed = RecordContainer.deserialize(self.raw).records
        return self._parsed

    def __len__(self) -> int:
        if self._parsed is not None:
            return len(self._parsed)
        if self.raw[0] == 2:
            (n,) = struct.unpack_from("<I", self.raw, 1)
            return n
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def serialize(self) -> bytes:
        return self.raw


@dataclass(frozen=True)
class SomeData:
    """A container together with its log offset (reference ``SomeData``)."""

    container: RecordContainer | BytesContainer
    offset: int
