"""Ingestion records and containers.

Counterpart of the reference's BinaryRecord v2 ingestion records and
RecordContainers (``core/src/main/scala/filodb.core/binaryrecord2/
RecordBuilder.scala:34``, ``RecordContainer.scala:13-27``): the unit shipped
from gateways over the log into shards is a container of schema-tagged records,
each holding (partition key, timestamp, data values). Containers serialize to
bytes so they can ride a Kafka-compatible log and be replayed on recovery.
"""

from __future__ import annotations

import pickle
import struct
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import Schemas


@dataclass(frozen=True)
class IngestRecord:
    """One sample for one series. ``values`` follows the schema's non-timestamp
    data columns in order; histogram values are (nb,) int64 cumulative buckets
    (with bucket bounds carried in the partition key label scheme or schema)."""

    part_key: PartKey
    timestamp: int  # epoch millis
    values: tuple

    def __post_init__(self):
        # normalize numpy arrays for hashability at container level
        pass


@dataclass
class RecordContainer:
    """A batch of records plus the log offset it came from."""

    records: list[IngestRecord] = field(default_factory=list)

    def add(self, rec: IngestRecord) -> None:
        self.records.append(rec)

    def __len__(self) -> int:
        return len(self.records)

    def __iter__(self):
        return iter(self.records)

    def serialize(self) -> bytes:
        # versioned, length-prefixed pickle: containers are internal transport,
        # produced and consumed only by our own gateway/shard runtimes.
        payload = pickle.dumps(
            [(r.part_key.schema, r.part_key.labels, r.timestamp,
              tuple(v.tolist() if isinstance(v, np.ndarray) else v for v in r.values))
             for r in self.records],
            protocol=pickle.HIGHEST_PROTOCOL,
        )
        return struct.pack("<BI", 1, len(payload)) + payload

    @staticmethod
    def deserialize(data: bytes, schemas: Schemas | None = None) -> "RecordContainer":
        ver, ln = struct.unpack_from("<BI", data, 0)
        assert ver == 1
        raw = pickle.loads(data[5 : 5 + ln])
        c = RecordContainer()
        for schema, labels, ts, values in raw:
            vals = tuple(np.asarray(v, np.int64) if isinstance(v, list) else v
                         for v in values)
            c.add(IngestRecord(PartKey(schema, labels), ts, vals))
        return c


@dataclass(frozen=True)
class SomeData:
    """A container together with its log offset (reference ``SomeData``)."""

    container: RecordContainer
    offset: int
