"""Chunk source/sink, column-store and meta-store interfaces plus in-memory
implementations.

Counterparts:
- ``ChunkSource``/``ChunkSink``/``ColumnStore`` —
  ``core/src/main/scala/filodb.core/store/ChunkSource.scala:66``,
  ``ChunkSink.scala:21``, ``ColumnStore.scala:59``
- ``MetaStore`` (checkpoints) — ``core/.../store/MetaStore.scala:14,48,67``
- ``NullColumnStore`` test fake — ``ChunkSink.scala:116``
- ``InMemoryMetaStore`` — ``core/.../store/InMemoryMetaStore.scala``
"""

from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.memory.chunk import Chunk


@dataclass(frozen=True)
class PartKeyRecord:
    part_key: PartKey
    start_time: int
    end_time: int


class ColumnStore:
    """Durable store of encoded chunks + part keys, per (dataset, shard)."""

    def initialize(self, dataset: str, num_shards: int) -> None:
        raise NotImplementedError

    def write_chunks(self, dataset: str, shard: int, part_key: PartKey,
                     chunks: list[Chunk], ingestion_time: int) -> None:
        raise NotImplementedError

    def read_chunks(self, dataset: str, shard: int, part_key: PartKey,
                    start_time: int, end_time: int) -> list[Chunk]:
        raise NotImplementedError

    def write_part_keys(self, dataset: str, shard: int,
                        records: list[PartKeyRecord]) -> None:
        raise NotImplementedError

    def scan_part_keys(self, dataset: str, shard: int) -> list[PartKeyRecord]:
        raise NotImplementedError

    def scan_part_keys_split(self, dataset: str, shard: int, split: int,
                             n_splits: int) -> list[PartKeyRecord]:
        """One token-range split of the part-key scan, for parallel readers
        (downsampler/repair jobs) — the reference's ``getScanSplits``
        (``CassandraColumnStore.scala:52``). Default: hash-filter over the
        full scan; remote impls filter server-side."""
        from filodb_tpu.core.store.remotestore import split_of
        from filodb_tpu.core.store.localstore import _pk_blob
        if n_splits <= 1:
            return self.scan_part_keys(dataset, shard)
        return [r for r in self.scan_part_keys(dataset, shard)
                if split_of(_pk_blob(r.part_key), n_splits) == split]

    def scan_chunks_by_ingestion_time(self, dataset: str, shard: int,
                                      start: int, end: int):
        """Yield (part_key, chunks) whose ingestion time falls in [start, end)
        — the downsampler's scan (reference ``IngestionTimeIndexTable``)."""
        raise NotImplementedError

    def scan_chunks_by_ingestion_time_split(self, dataset: str, shard: int,
                                            start: int, end: int, split: int,
                                            n_splits: int):
        """One token-range split of the ingestion-time scan — the fan-out
        unit for downsample/repair jobs.  Default: hash-filter over the
        full scan; the object store restricts to key-prefix buckets."""
        if n_splits <= 1:
            yield from self.scan_chunks_by_ingestion_time(dataset, shard,
                                                          start, end)
            return
        from filodb_tpu.core.store.remotestore import split_of
        from filodb_tpu.core.store.localstore import _pk_blob
        for pk, chunks in self.scan_chunks_by_ingestion_time(
                dataset, shard, start, end):
            if split_of(_pk_blob(pk), n_splits) == split:
                yield pk, chunks

    def truncate(self, dataset: str) -> None:
        raise NotImplementedError

    def delete_part_keys(self, dataset: str, shard: int,
                         part_keys: list[PartKey]) -> None:
        """Remove part keys + their chunks (cardinality buster)."""
        raise NotImplementedError

    def max_persisted_ts(self, dataset: str, shard: int
                         ) -> dict[PartKey, int]:
        """Max persisted chunk end_time per part key. Recovery seeds each
        partition's out-of-order floor from this so WAL replay of rows that
        were already flushed (ingested mid-flush, above the checkpoint) is
        deduplicated instead of double-written."""
        return {}

    # ---- index snapshots (reference: durable Lucene index dir) ----------

    def write_index_snapshot(self, dataset: str, shard: int,
                             data: bytes) -> None:
        """Persist an index snapshot (atomic replace)."""

    def read_index_snapshot(self, dataset: str, shard: int) -> bytes | None:
        return None

    def update_tokens(self, dataset: str, shard: int) -> tuple[int, int]:
        """(chunk_token, pk_token): monotonic write counters. A snapshot
        stores the tokens captured BEFORE serialization; restore replays
        only entries written after them (idempotent overlaps)."""
        return (-1, -1)

    # ---- migration manifests (coordinator/migration.py) -----------------
    # The shard-migration state machine persists its manifest NEXT TO the
    # shard's data so either side of a handoff can crash and resume from
    # durable state. Durable backends (object store, local disk) override
    # with real persistence; the in-process default keeps manifests in a
    # dict, which is exactly as durable as the rest of an in-memory store.

    def write_migration_manifest(self, dataset: str, shard: int,
                                 data: bytes) -> None:
        if not hasattr(self, "_migration_manifests"):
            self._migration_manifests = {}
        self._migration_manifests[(dataset, shard)] = data

    def read_migration_manifest(self, dataset: str,
                                shard: int) -> bytes | None:
        return getattr(self, "_migration_manifests", {}).get(
            (dataset, shard))

    def delete_migration_manifest(self, dataset: str, shard: int) -> None:
        getattr(self, "_migration_manifests", {}).pop((dataset, shard),
                                                      None)

    def max_persisted_ts_since(self, dataset: str, shard: int,
                               chunk_token: int) -> dict[PartKey, int]:
        """Delta of max_persisted_ts for chunks written after the token."""
        return self.max_persisted_ts(dataset, shard)

    def scan_part_keys_since(self, dataset: str, shard: int,
                             pk_token: int) -> list[PartKeyRecord]:
        """Part keys created/updated after the token."""
        return self.scan_part_keys(dataset, shard)


class MetaStore:
    """Cluster metadata + ingestion checkpoints."""

    def write_checkpoint(self, dataset: str, shard: int, group: int,
                         offset: int) -> None:
        raise NotImplementedError

    def read_checkpoints(self, dataset: str, shard: int) -> dict[int, int]:
        raise NotImplementedError

    def read_earliest_checkpoint(self, dataset: str, shard: int) -> int:
        cps = self.read_checkpoints(dataset, shard)
        return min(cps.values()) if cps else -1

    # ---- cost-model snapshots (query/cost_model.py) ----------------------
    # Learned per-(dataset, plan-signature) cost estimates persist next to
    # the ingestion checkpoints so restarts keep their calibration instead
    # of re-learning from cold. Same durability contract as migration
    # manifests: durable backends override with real persistence; the
    # in-process default keeps blobs in a dict.

    def write_cost_model(self, dataset: str, data: bytes) -> None:
        if not hasattr(self, "_cost_models"):
            self._cost_models = {}
        self._cost_models[dataset] = data

    def read_cost_model(self, dataset: str) -> bytes | None:
        return getattr(self, "_cost_models", {}).get(dataset)


class NullColumnStore(ColumnStore):
    """Discards chunks; for tests/benchmarks (reference ``NullColumnStore``)."""

    def initialize(self, dataset: str, num_shards: int) -> None:
        pass

    def write_chunks(self, dataset, shard, part_key, chunks, ingestion_time):
        pass

    def read_chunks(self, dataset, shard, part_key, start_time, end_time):
        return []

    def write_part_keys(self, dataset, shard, records):
        pass

    def scan_part_keys(self, dataset, shard):
        return []

    def scan_chunks_by_ingestion_time(self, dataset, shard, start, end):
        return iter(())

    def truncate(self, dataset):
        pass


class InMemoryColumnStore(ColumnStore):
    """Keeps everything in process memory; the recovery/ODP test double."""

    def __init__(self):
        # (dataset, shard) -> part_key -> list[(ingestion_time, Chunk)]
        self._chunks = defaultdict(lambda: defaultdict(list))
        self._part_keys: dict[tuple, dict[PartKey, PartKeyRecord]] = defaultdict(dict)

    def initialize(self, dataset: str, num_shards: int) -> None:
        pass

    def write_chunks(self, dataset, shard, part_key, chunks, ingestion_time):
        store = self._chunks[(dataset, shard)][part_key]
        existing = {c.id for _, c in store}
        for c in chunks:
            if c.id not in existing:
                store.append((ingestion_time, c))

    def read_chunks(self, dataset, shard, part_key, start_time, end_time):
        out = [c for _, c in self._chunks[(dataset, shard)].get(part_key, [])
               if c.end_time >= start_time and c.start_time <= end_time]
        return sorted(out, key=lambda c: c.id)

    def write_part_keys(self, dataset, shard, records):
        d = self._part_keys[(dataset, shard)]
        for r in records:
            prev = d.get(r.part_key)
            if prev is not None:
                r = PartKeyRecord(r.part_key, min(prev.start_time, r.start_time),
                                  r.end_time)
            d[r.part_key] = r

    def scan_part_keys(self, dataset, shard):
        return list(self._part_keys[(dataset, shard)].values())

    def scan_chunks_by_ingestion_time(self, dataset, shard, start, end):
        for pk, entries in self._chunks[(dataset, shard)].items():
            sel = [c for t, c in entries if start <= t < end]
            if sel:
                yield pk, sorted(sel, key=lambda c: c.id)

    def truncate(self, dataset):
        for key in [k for k in self._chunks if k[0] == dataset]:
            del self._chunks[key]
        for key in [k for k in self._part_keys if k[0] == dataset]:
            del self._part_keys[key]

    def delete_part_keys(self, dataset, shard, part_keys):
        d = self._part_keys[(dataset, shard)]
        c = self._chunks[(dataset, shard)]
        for pk in part_keys:
            d.pop(pk, None)
            c.pop(pk, None)

    def max_persisted_ts(self, dataset, shard):
        return {pk: max(c.end_time for _, c in entries)
                for pk, entries in self._chunks[(dataset, shard)].items()
                if entries}

    def write_index_snapshot(self, dataset, shard, data):
        if not hasattr(self, "_snapshots"):
            self._snapshots = {}
        self._snapshots[(dataset, shard)] = data

    def read_index_snapshot(self, dataset, shard):
        return getattr(self, "_snapshots", {}).get((dataset, shard))

    def update_tokens(self, dataset, shard):
        # in-memory double: counts stand in for write counters (chunk and
        # part-key writes are append-only here)
        nchunks = sum(len(v) for v in self._chunks[(dataset, shard)].values())
        return (nchunks, len(self._part_keys[(dataset, shard)]))


class InMemoryMetaStore(MetaStore):
    def __init__(self):
        self._checkpoints: dict[tuple, dict[int, int]] = defaultdict(dict)

    def write_checkpoint(self, dataset, shard, group, offset):
        self._checkpoints[(dataset, shard)][group] = offset

    def read_checkpoints(self, dataset, shard):
        return dict(self._checkpoints[(dataset, shard)])
