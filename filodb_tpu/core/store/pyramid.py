"""Aggregate pyramids: per-segment and per-bucket summary objects.

PR 15 put a 12-slot summary + log2 sketch on every sealed chunk (FSG2).
This module climbs the hierarchy (ROADMAP item 2, the Zarr "chunk-level
cumulative sums in reduced dimensions" design from PAPERS.md): at seal
and compaction the object store rolls those chunk summaries up into

    seg-XXXXXXXX.pyr   one merged row + sketch per (part key, column),
                       plus the per-chunk rows (cid-ordered) so a reader
                       can descend one level without touching payloads
    bkt-XXXXXXXX.pyr   one merged row per (part key, column) covering a
                       whole compacted bucket (``covers`` = the segment
                       seqs it summarizes)

plus per-object population sketches (top-k of per-series maxima and an
HLL of part keys — ``memory/sketches.py``) that make ``topk`` and
cardinality estimates summary-only scans under the approx lane.

Pyramid objects are DERIVED data: best-effort, separately fetchable,
never load-bearing for correctness.  A missing/corrupt/raced pyramid
demotes the reader one level (bucket → segment → chunk rows → payload
fallback) — the same exact/bypass algebra the sidecar lane uses.

Determinism contract (bitwise parity of mode "1" vs mode "decode"):
every merged row is ``merge_rows_seq`` — a strict left fold of the
scalar merge — over count>0 chunk rows sorted by chunk id.  Chunk
summaries are themselves bitwise-reproducible from lossless decode
(``memory/chunk.py``), so a reader that recomputes the fold from
decoded payloads reproduces the stored rows bit for bit.

This module must not import ``objectstore`` (the store imports us);
pyramid objects carry their own zlib CRC32 footer rather than reusing
the store's CRC32C helper.
"""

from __future__ import annotations

import struct
import time
import zlib

import numpy as np

from filodb_tpu.memory.chunk import (
    S_COUNT,
    S_FIRST_TS,
    S_FIRST_VAL,
    S_LAST_TS,
    S_LAST_VAL,
    S_MAX,
    SKETCH_BUCKETS,
    STATS_WIDTH,
    ensure_summary,
)
from filodb_tpu.memory.sketches import HLLSketch, TopKSketch, _hash64
from filodb_tpu.utils.metrics import Counter

# metric families asserted by tests/test_metrics_scrape.py and covered by
# filolint PR207 (every exposed filodb_pyramid_* family must be pinned)
PYR_WRITTEN_SEG = Counter("filodb_pyramid_objects_written",
                          {"level": "segment"},
                          help="segment pyramid objects written")
PYR_WRITTEN_BKT = Counter("filodb_pyramid_objects_written",
                          {"level": "bucket"},
                          help="bucket pyramid objects written")
PYR_BACKFILLED = Counter(
    "filodb_pyramid_backfilled",
    help="legacy segments that gained pyramid coverage via compaction")
PYR_SERVED = Counter(
    "filodb_pyramid_served",
    help="cold-tier leaf evaluations served from pyramid aggregates")
PYR_FALLBACK = Counter(
    "filodb_pyramid_fallback",
    help="pyramid reads demoted to chunk-payload fallback")
PYR_NODES_BUCKET = Counter("filodb_pyramid_nodes", {"level": "bucket"})
PYR_NODES_SEGMENT = Counter("filodb_pyramid_nodes", {"level": "segment"})
PYR_NODES_CHUNK = Counter("filodb_pyramid_nodes", {"level": "chunk"})
PYR_NODES_DECODE = Counter("filodb_pyramid_nodes", {"level": "decode"})
PYR_BYTES_DOWN = Counter(
    "filodb_pyramid_bytes_down",
    help="bytes of pyramid objects fetched from the object store")

_MAGIC_SEG = b"FPY1"
_MAGIC_BKT = b"FPB1"
_ENT_HDR = struct.Struct("<HBBI")  # pk_len, col, flags, n_chunk_rows
_F_SKETCH = 1


# ---------------------------------------------------------------------------
# merge algebra (scalar-row analog of sidecar_lane._merge_vec)

def _merge_row(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Merge two consecutive-in-time count>0 stats rows [STATS_WIDTH]
    with the kernels' counter-reset carry at the boundary."""
    from filodb_tpu.memory.chunk import (S_CHANGES, S_CORR, S_MIN, S_RESETS,
                                         S_SUM, S_SUMSQ)
    out = a.copy()
    out[S_COUNT] = a[S_COUNT] + b[S_COUNT]
    out[S_SUM] = a[S_SUM] + b[S_SUM]
    out[S_SUMSQ] = a[S_SUMSQ] + b[S_SUMSQ]
    out[S_MIN] = min(a[S_MIN], b[S_MIN])
    out[S_MAX] = max(a[S_MAX], b[S_MAX])
    out[S_LAST_TS] = b[S_LAST_TS]
    out[S_LAST_VAL] = b[S_LAST_VAL]
    bdrop = b[S_FIRST_VAL] < a[S_LAST_VAL]
    out[S_RESETS] = a[S_RESETS] + bdrop + b[S_RESETS]
    out[S_CORR] = (a[S_CORR] + (a[S_LAST_VAL] if bdrop else 0.0)) \
        + b[S_CORR]
    out[S_CHANGES] = a[S_CHANGES] \
        + (b[S_FIRST_VAL] != a[S_LAST_VAL]) + b[S_CHANGES]
    return out


def merge_rows_seq(rows) -> np.ndarray | None:
    """Strict left fold of ``_merge_row`` over count>0 rows (callers pass
    rows cid-sorted).  The SAME fold runs at write time and in decode
    mode, so stored parent rows are bitwise-reproducible.  Returns None
    when no row has samples."""
    acc = None
    for r in rows:
        if r[S_COUNT] <= 0:
            continue
        acc = r.copy() if acc is None else _merge_row(acc, r)
    return acc


def _rows_ordered(rows: np.ndarray) -> bool:
    """Exactness precondition for folding rows as consecutive segments:
    count>0 rows (already cid-sorted) must be time-ordered and
    non-overlapping by valid-sample span."""
    live = rows[rows[:, S_COUNT] > 0]
    if len(live) < 2:
        return True
    starts = live[:, S_FIRST_TS]
    ends = live[:, S_LAST_TS]
    return not (np.any(np.diff(starts) <= 0)
                or np.any(starts[1:] <= ends[:-1]))


# ---------------------------------------------------------------------------
# build (writer side: _seal and compaction hand us the sealed rows)

def _collect(pyr_rows, value_col: int = 1):
    """Group sealed ``(pk_blob, chunk)`` rows into per-(pk, col) chunk
    stats + sketches, cid-sorted.  Chunks without a usable summary for a
    column poison that (pk, col) entry — readers fall back to payloads
    there rather than trusting a partial roll-up."""
    groups: dict[tuple[bytes, int], dict] = {}
    n_chunks: dict[bytes, int] = {}
    for pk_blob, ch in pyr_rows:
        n_chunks[pk_blob] = n_chunks.get(pk_blob, 0) + 1
        summary = ensure_summary(ch)
        ncols = len(summary) if summary is not None else 0
        for col in range(1, ncols):
            cs = summary[col]
            if cs is None:
                continue
            g = groups.setdefault((pk_blob, col),
                                  {"cids": [], "rows": [], "sketches": []})
            g["cids"].append(ch.id)
            g["rows"].append(cs.stats)
            g["sketches"].append(cs.sketch)
    out = {}
    for (pk_blob, col), g in groups.items():
        if len(g["cids"]) != n_chunks[pk_blob]:
            continue  # partial summary coverage: demote to payloads
        order = np.argsort(np.asarray(g["cids"], np.int64), kind="stable")
        cids = np.asarray(g["cids"], np.int64)[order]
        rows = np.vstack([g["rows"][i] for i in order])
        sketches = [g["sketches"][i] for i in order]
        if not _rows_ordered(rows):
            continue  # out-of-order seals: reader uses payload fallback
        merged = merge_rows_seq(rows)
        if merged is None:
            continue
        sk = None
        if all(s is not None for s in sketches):
            sk = np.zeros(SKETCH_BUCKETS, np.int64)
            for s, row in zip(sketches, rows):
                if row[S_COUNT] > 0:
                    sk += s.astype(np.int64)
        out[(pk_blob, col)] = (cids, rows, merged, sk)
    return out


def _footer_sketches(entries, value_col: int = 1) -> tuple:
    """(TopKSketch over per-series maxima of the value column, HLL over
    part keys) for one pyramid object."""
    topk = TopKSketch(capacity=64)
    hll = HLLSketch()
    for (pk_blob, col), (_cids, _rows, merged, _sk) in entries.items():
        if col != value_col:
            continue
        hll.update_hashes(np.array([_hash64(pk_blob)], np.uint64))
        topk.update(pk_blob, float(merged[S_MAX]))
    return topk, hll


def _pack_entries(entries, with_chunk_rows: bool) -> list[bytes]:
    parts = [struct.pack("<I", len(entries))]
    for (pk_blob, col) in sorted(entries):
        cids, rows, merged, sk = entries[(pk_blob, col)]
        flags = _F_SKETCH if sk is not None else 0
        n = len(cids)
        parts.append(_ENT_HDR.pack(len(pk_blob), col, flags, n))
        parts.append(pk_blob)
        parts.append(cids.astype("<i8").tobytes())
        if with_chunk_rows:
            parts.append(rows.astype("<f8").tobytes())
        parts.append(merged.astype("<f8").tobytes())
        if sk is not None:
            parts.append(sk.astype("<i8").tobytes())
    return parts


def _pack_footer(topk: TopKSketch, hll: HLLSketch) -> list[bytes]:
    tb = topk.serialize()
    return [struct.pack("<I", len(tb)), tb, hll.serialize()]


def build_segment_pyramid(pyr_rows, value_col: int = 1) -> bytes | None:
    """Serialize one segment's pyramid object from its sealed
    ``(pk_blob, chunk)`` rows; None when nothing is summarizable."""
    entries = _collect(pyr_rows, value_col)
    if not entries:
        return None
    topk, hll = _footer_sketches(entries, value_col)
    body = b"".join([_MAGIC_SEG] + _pack_entries(entries, True)
                    + _pack_footer(topk, hll))
    PYR_WRITTEN_SEG.inc()
    return body + struct.pack("<I", zlib.crc32(body))


def build_bucket_pyramid(pyr_rows, covers, value_col: int = 1
                         ) -> bytes | None:
    """Serialize a bucket-level pyramid covering segment seqs ``covers``
    (compaction collapses a bucket to one segment, so the per-(pk, col)
    merged rows ARE the new segment's rows — stored without the chunk
    rows, one level terser)."""
    entries = _collect(pyr_rows, value_col)
    if not entries:
        return None
    topk, hll = _footer_sketches(entries, value_col)
    head = [_MAGIC_BKT, struct.pack("<I", len(covers))]
    head.append(np.asarray(sorted(covers), "<i8").tobytes())
    body = b"".join(head + _pack_entries(entries, False)
                    + _pack_footer(topk, hll))
    PYR_WRITTEN_BKT.inc()
    return body + struct.pack("<I", zlib.crc32(body))


# ---------------------------------------------------------------------------
# parse (reader side)

class PyramidParseError(Exception):
    """A pyramid object failed its CRC or structure checks — readers
    demote to the next level down, never error the query."""


def _parse_common(data: bytes, magic: bytes, key: str):
    if len(data) < len(magic) + 4 or data[:4] != magic:
        raise PyramidParseError(f"{key}: bad magic/size")
    (crc,) = struct.unpack_from("<I", data, len(data) - 4)
    body = data[:-4]
    if zlib.crc32(body) != crc:
        raise PyramidParseError(f"{key}: CRC32 mismatch")
    return body


def _unpack_entries(body: bytes, off: int, with_chunk_rows: bool):
    (n_entries,) = struct.unpack_from("<I", body, off)
    off += 4
    entries: dict[tuple[bytes, int], dict] = {}
    for _ in range(n_entries):
        pk_len, col, flags, n = _ENT_HDR.unpack_from(body, off)
        off += _ENT_HDR.size
        pk_blob = bytes(body[off:off + pk_len])
        off += pk_len
        cids = np.frombuffer(body, "<i8", n, off).copy()
        off += 8 * n
        rows = None
        if with_chunk_rows:
            rows = np.frombuffer(body, "<f8", n * STATS_WIDTH,
                                 off).reshape(n, STATS_WIDTH).copy()
            off += 8 * n * STATS_WIDTH
        merged = np.frombuffer(body, "<f8", STATS_WIDTH, off).copy()
        off += 8 * STATS_WIDTH
        sk = None
        if flags & _F_SKETCH:
            sk = np.frombuffer(body, "<i8", SKETCH_BUCKETS, off).copy()
            off += 8 * SKETCH_BUCKETS
        entries[(pk_blob, int(col))] = {
            "cids": cids, "rows": rows, "row": merged, "sketch": sk}
    return entries, off


def _unpack_footer(body: bytes, off: int):
    (tlen,) = struct.unpack_from("<I", body, off)
    off += 4
    topk, _ = TopKSketch.deserialize(body[off:off + tlen])
    off += tlen
    hll, _ = HLLSketch.deserialize(body, off)
    return topk, hll


def parse_segment_pyramid(data: bytes, key: str = "?") -> dict:
    """{"entries": {(pk_blob, col): {cids, rows, row, sketch}},
    "topk", "hll"}.  Raises :class:`PyramidParseError` on mismatch."""
    body = _parse_common(data, _MAGIC_SEG, key)
    try:
        entries, off = _unpack_entries(body, 4, True)
        topk, hll = _unpack_footer(body, off)
    except (struct.error, ValueError) as e:
        raise PyramidParseError(f"{key}: truncated: {e}") from None
    return {"entries": entries, "topk": topk, "hll": hll}


def parse_bucket_pyramid(data: bytes, key: str = "?") -> dict:
    """Like :func:`parse_segment_pyramid` plus ``covers`` (segment seqs
    the bucket row summarizes); entries carry no per-chunk rows."""
    body = _parse_common(data, _MAGIC_BKT, key)
    try:
        (n_cov,) = struct.unpack_from("<I", body, 4)
        off = 8
        covers = [int(c) for c in np.frombuffer(body, "<i8", n_cov, off)]
        off += 8 * n_cov
        entries, off = _unpack_entries(body, off, False)
        topk, hll = _unpack_footer(body, off)
    except (struct.error, ValueError) as e:
        raise PyramidParseError(f"{key}: truncated: {e}") from None
    return {"entries": entries, "topk": topk, "hll": hll,
            "covers": covers}


# ---------------------------------------------------------------------------
# per-shard read-through cache

_NEG_TTL_S = 5.0


class ShardPyramidCache:
    """Read-through cache over one shard's pyramid objects.  Parsed
    positives are immutable (pyramid keys are never rewritten in place)
    and cached forever; negatives (not-yet-uploaded, mid-backfill) age
    out after a short TTL so the read-race window self-heals."""

    def __init__(self, store, dataset: str, shard: int):
        self.store = store
        self.dataset = dataset
        self.shard = shard
        self._segs: dict[int, dict] = {}
        self._buckets: dict[int, dict] = {}
        self._neg: dict = {}
        # read-cache accounting: the pyramid lane folds deltas of these
        # into QueryStats.cache_hits/misses (the cold-tier analog of the
        # leaf batch cache)
        self.hits = 0
        self.misses = 0

    def _negative(self, key) -> bool:
        t = self._neg.get(key)
        return t is not None and time.monotonic() - t < _NEG_TTL_S

    def refs(self, part_key):
        return self.store.pyramid_refs(self.dataset, self.shard, part_key)

    def segment(self, seq: int) -> dict | None:
        p = self._segs.get(seq)
        if p is not None:
            self.hits += 1
            return p
        if self._negative(("s", seq)):
            return None
        self.misses += 1
        p = self.store.read_segment_pyramid(self.dataset, self.shard, seq)
        if p is None:
            self._neg[("s", seq)] = time.monotonic()
            return None
        self._segs[seq] = p
        return p

    def bucket(self, bkt: int, seq: int) -> dict | None:
        """``seq`` is the bucket pyramid's writing segment seq (from the
        shard's ``bucket_pyramids`` index) — compaction rewrites bucket
        objects under new seqs, so the cache keys on it."""
        p = self._buckets.get((bkt, seq))
        if p is not None:
            self.hits += 1
            return p
        if self._negative(("b", bkt, seq)):
            return None
        self.misses += 1
        p = self.store.read_bucket_pyramid(self.dataset, self.shard, bkt)
        if p is None:
            self._neg[("b", bkt, seq)] = time.monotonic()
            return None
        self._buckets[(bkt, seq)] = p
        return p

    def clear(self) -> None:
        self._segs.clear()
        self._buckets.clear()
        self._neg.clear()


def make_pyramid_cache(store, dataset: str, shard: int
                       ) -> ShardPyramidCache | None:
    """A pyramid cache for stores that publish the pyramid read API
    (``ObjectStoreColumnStore``); None for backends without one —
    callers then bypass to the payload path."""
    if not hasattr(store, "read_segment_pyramid"):
        return None
    return ShardPyramidCache(store, dataset, shard)
