"""Offline repair / migration jobs over column stores.

Counterpart of reference ``spark-jobs`` repair plane (without Spark — the
jobs walk the store's scan APIs directly):

- ``ChunkCopier``           (``repair/ChunkCopier.scala:1-210``): copy chunks
  between clusters/stores for a time window (disaster recovery, migration).
- ``PartitionKeysCopier``   (``repair/PartitionKeysCopier.scala:1-180``).
- ``CardinalityBuster``     (``cardbuster/PerShardCardinalityBuster.scala``):
  delete part keys (and optionally chunks) matching filters to claw back
  cardinality.
- ``DSIndexJob``            (``downsampler/index/DSIndexJob.scala``): migrate
  part-key updates from the raw to the downsample dataset.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

from filodb_tpu.core.filters import ColumnFilter
from filodb_tpu.core.store.api import ColumnStore, PartKeyRecord

log = logging.getLogger(__name__)


@dataclass
class ChunkCopier:
    source: ColumnStore
    target: ColumnStore
    dataset: str
    num_shards: int
    n_splits: int = 1   # fan the scan out over token-range splits

    def run(self, ingestion_start: int, ingestion_end: int) -> dict:
        stats = {"partitions": 0, "chunks": 0}
        for shard in range(self.num_shards):
            for split in range(max(1, self.n_splits)):
                self._copy_split(shard, split, ingestion_start,
                                 ingestion_end, stats)
        return stats

    def run_split(self, split: int, ingestion_start: int,
                  ingestion_end: int) -> dict:
        """One split's worth of work — the unit a parallel worker owns
        (reference: one Spark task per token-range split)."""
        stats = {"partitions": 0, "chunks": 0}
        for shard in range(self.num_shards):
            self._copy_split(shard, split, ingestion_start, ingestion_end,
                             stats)
        return stats

    def _copy_split(self, shard, split, t0, t1, stats):
        for part_key, chunks in \
                self.source.scan_chunks_by_ingestion_time_split(
                    self.dataset, shard, t0, t1, split,
                    max(1, self.n_splits)):
            self.target.write_chunks(self.dataset, shard, part_key,
                                     chunks, t1)
            stats["partitions"] += 1
            stats["chunks"] += len(chunks)
        return stats


@dataclass
class PartitionKeysCopier:
    source: ColumnStore
    target: ColumnStore
    dataset: str
    num_shards: int
    n_splits: int = 1   # fan the scan out over token-range splits

    def run(self) -> int:
        return sum(self.run_split(s) for s in range(max(1, self.n_splits)))

    def run_split(self, split: int) -> int:
        n = 0
        for shard in range(self.num_shards):
            recs = self.source.scan_part_keys_split(
                self.dataset, shard, split, max(1, self.n_splits))
            if recs:
                self.target.write_part_keys(self.dataset, shard, recs)
                n += len(recs)
        return n


@dataclass
class CardinalityBuster:
    """Delete part keys matching filters (reference PerShardCardinalityBuster).

    Requires the column store to support deletion; stores without it raise.
    """

    store: ColumnStore
    dataset: str
    num_shards: int

    def run(self, filters: list[ColumnFilter]) -> int:
        busted = 0
        for shard in range(self.num_shards):
            keep: list[PartKeyRecord] = []
            victims = []
            for rec in self.store.scan_part_keys(self.dataset, shard):
                lm = rec.part_key.label_map
                if all(f.filter.matches(lm.get(f.column, ""))
                       for f in filters):
                    victims.append(rec)
                else:
                    keep.append(rec)
            if victims:
                self._delete(shard, victims, keep)
                busted += len(victims)
        return busted

    def _delete(self, shard, victims, keep):
        delete = getattr(self.store, "delete_part_keys", None)
        if delete is None:
            raise NotImplementedError(
                f"{type(self.store).__name__} does not support deletion")
        delete(self.dataset, shard, [v.part_key for v in victims])


@dataclass
class DSIndexJob:
    """Copy raw part-key updates into the downsample dataset's key table."""

    store: ColumnStore
    dataset: str
    ds_dataset: str
    num_shards: int
    n_splits: int = 1   # fan the scan out over token-range splits

    def run(self) -> int:
        return sum(self.run_split(s) for s in range(max(1, self.n_splits)))

    def run_split(self, split: int) -> int:
        n = 0
        for shard in range(self.num_shards):
            recs = self.store.scan_part_keys_split(
                self.dataset, shard, split, max(1, self.n_splits))
            ds_recs = [PartKeyRecord(
                r.part_key.__class__(
                    _ds_schema_for(r.part_key.schema), r.part_key.labels),
                r.start_time, r.end_time) for r in recs]
            if ds_recs:
                self.store.write_part_keys(self.ds_dataset, shard, ds_recs)
                n += len(ds_recs)
        return n


def _ds_schema_for(schema: str) -> str:
    from filodb_tpu.core.schemas import DEFAULT_SCHEMAS
    if schema in DEFAULT_SCHEMAS:
        ds = DEFAULT_SCHEMAS[schema].data.downsample_schema
        if ds:
            return ds
    return schema
