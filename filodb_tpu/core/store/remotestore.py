"""Networked ColumnStore: chunk-server + remote client behind the same API.

The reference's durability tier is a remote database with token-range scan
splits (``cassandra/src/main/scala/filodb.cassandra/columnstore/
CassandraColumnStore.scala:52`` ``getScanSplits``, 4-table data model).
This module provides the same capability natively: ``ChunkStoreServer``
fronts any :class:`ColumnStore`/:class:`MetaStore` (by default the
local-disk sqlite store) over the framed, secret-authenticated transport
shared with plan shipping and the ingest log; ``RemoteColumnStore`` /
``RemoteMetaStore`` implement the store interfaces over that wire, so every
memstore/ODP/downsampler/repair path runs unchanged against a remote
durability tier.

Scan splits: part keys hash (crc32 of the canonical key blob) into
``n_splits`` token ranges; ``scan_part_keys_split`` filters SERVER-side so
parallel scan clients (downsampler, repair jobs) each pull only their
range — the ``getScanSplits`` analog.

Protocol messages (typed wire codec, one request per frame):
    ("write_chunks", ds, shard, pk_blob, [chunk_bytes], ingestion_time)
    ("read_chunks",  ds, shard, pk_blob, start, end) -> ("ok", [bytes])
    ("write_pks",    ds, shard, [(pk_blob, st, et)])
    ("scan_pks",     ds, shard, split, n_splits) -> ("ok", [(blob, st, et)])
    ("scan_pks_since", ds, shard, token)
    ("scan_ingest",  ds, shard, start, end) -> ("ok", [(blob, [bytes])])
    ("max_ts", ds, shard) / ("max_ts_since", ds, shard, token)
    ("tokens", ds, shard) -> ("ok", (chunk_token, pk_token))
    ("delete_pks", ds, shard, [blobs]) | ("truncate", ds)
    ("write_snap", ds, shard, bytes) | ("read_snap", ds, shard)
    ("write_cp", ds, shard, group, off) | ("read_cps", ds, shard)
    ("initialize", ds, num_shards) | ("ping",)
"""

from __future__ import annotations

import logging
import re
import socket
import socketserver
import threading
import zlib

from filodb_tpu.coordinator.remote import (
    TRANSPORT_ERRORS,
    _recv_msg,
    _send_msg,
    cluster_secret,
    make_authed_handler,
)
from filodb_tpu.core.store.api import ColumnStore, MetaStore, PartKeyRecord
from filodb_tpu.memory.chunk import Chunk
from filodb_tpu.utils.resilience import FaultInjector, breaker_for

log = logging.getLogger(__name__)

_SAFE_NAME = re.compile(r"[A-Za-z0-9_.-]{1,128}\Z")

# one scan reply is materialized in memory before send; scans beyond this
# must use split scans (which is what the parallel jobs do anyway)
MAX_SCAN_ROWS = 200_000


class StoreOpError(RuntimeError):
    """Deterministic server-side ('err', ...) reply — do not retry."""


def split_of(pk_blob: bytes, n_splits: int) -> int:
    """Token-range split of a part key (crc32 over the canonical blob)."""
    return zlib.crc32(pk_blob) % n_splits if n_splits > 1 else 0


def _validate_target(dataset, shard) -> str | None:
    if not isinstance(dataset, str) or not _SAFE_NAME.fullmatch(dataset) \
            or dataset in (".", ".."):
        return f"invalid dataset name {dataset!r}"
    if not isinstance(shard, int) or isinstance(shard, bool) or shard < 0 \
            or shard > 1_000_000:
        return f"invalid shard {shard!r}"
    return None


class ChunkStoreServer:
    """Serves a ColumnStore + MetaStore over TCP (the database-server role).

    ``backing``/``meta`` default to the local-disk sqlite store rooted at
    ``root`` — the same 4-table model, now reachable across hosts.
    """

    def __init__(self, root: str | None = None, host: str = "127.0.0.1",
                 port: int = 0, backing: ColumnStore | None = None,
                 meta: MetaStore | None = None, secret: str | None = None):
        if backing is None or meta is None:
            from filodb_tpu.core.store.localstore import (
                LocalDiskColumnStore,
                LocalDiskMetaStore,
            )
            assert root is not None, "root required without explicit stores"
            backing = backing or LocalDiskColumnStore(root)
            meta = meta or LocalDiskMetaStore(root)
        self.store = backing
        self.meta = meta
        self.secret = secret if secret is not None else cluster_secret()
        Handler = make_authed_handler(lambda: self.secret, self._handle,
                                      "chunk store")

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True

        self.server = Server((host, port), Handler)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        daemon=True)

    def start(self) -> "ChunkStoreServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self.server.shutdown()
        self.server.server_close()

    # -- request handling --------------------------------------------------

    def _handle(self, msg):  # noqa: C901
        from filodb_tpu.core.store.localstore import _pk_blob, _pk_from_blob
        kind = msg[0]
        try:
            if kind == "ping":
                return ("pong",)
            if kind == "initialize":
                _, ds, num_shards = msg
                if not isinstance(ds, str) or not _SAFE_NAME.fullmatch(ds):
                    return ("err", f"invalid dataset name {ds!r}")
                self.store.initialize(ds, int(num_shards))
                return ("ok", True)
            if kind == "truncate":
                _, ds = msg
                if not isinstance(ds, str) or not _SAFE_NAME.fullmatch(ds):
                    return ("err", f"invalid dataset name {ds!r}")
                self.store.truncate(ds)
                return ("ok", True)
            bad = _validate_target(msg[1], msg[2])
            if bad is not None:
                return ("err", bad)
            _, ds, shard = msg[:3]
            rest = msg[3:]
            if kind == "write_chunks":
                pk_blob, chunk_bytes, itime = rest
                self.store.write_chunks(
                    ds, shard, _pk_from_blob(pk_blob),
                    [Chunk.deserialize(b) for b in chunk_bytes], int(itime))
                return ("ok", True)
            if kind == "read_chunks":
                pk_blob, st, et = rest
                chunks = self.store.read_chunks(ds, shard,
                                                _pk_from_blob(pk_blob),
                                                int(st), int(et))
                return ("ok", [c.serialize() for c in chunks])
            if kind == "write_pks":
                (recs,) = rest
                self.store.write_part_keys(ds, shard, [
                    PartKeyRecord(_pk_from_blob(b), int(st), int(et))
                    for b, st, et in recs])
                return ("ok", True)
            if kind in ("scan_pks", "scan_pks_since"):
                if kind == "scan_pks":
                    split, n_splits = rest
                    recs = self.store.scan_part_keys(ds, shard)
                    if n_splits and n_splits > 1:
                        recs = [r for r in recs
                                if split_of(_pk_blob(r.part_key),
                                            n_splits) == split]
                else:
                    (token,) = rest
                    recs = self.store.scan_part_keys_since(ds, shard,
                                                           int(token))
                recs = recs[:MAX_SCAN_ROWS]
                return ("ok", [(_pk_blob(r.part_key), r.start_time,
                                r.end_time) for r in recs])
            if kind == "scan_ingest":
                start, end = rest
                out = []
                for pk, chunks in self.store.scan_chunks_by_ingestion_time(
                        ds, shard, int(start), int(end)):
                    out.append((_pk_blob(pk),
                                [c.serialize() for c in chunks]))
                    if len(out) >= MAX_SCAN_ROWS:
                        break
                return ("ok", out)
            if kind == "delete_pks":
                (blobs,) = rest
                self.store.delete_part_keys(
                    ds, shard, [_pk_from_blob(b) for b in blobs])
                return ("ok", True)
            if kind in ("max_ts", "max_ts_since"):
                if kind == "max_ts":
                    d = self.store.max_persisted_ts(ds, shard)
                else:
                    d = self.store.max_persisted_ts_since(ds, shard,
                                                          int(rest[0]))
                return ("ok", [(_pk_blob(pk), ts) for pk, ts in d.items()])
            if kind == "tokens":
                return ("ok", tuple(self.store.update_tokens(ds, shard)))
            if kind == "write_snap":
                (data,) = rest
                self.store.write_index_snapshot(ds, shard, data)
                return ("ok", True)
            if kind == "read_snap":
                return ("ok", self.store.read_index_snapshot(ds, shard))
            if kind == "write_cp":
                group, off = rest
                self.meta.write_checkpoint(ds, shard, int(group), int(off))
                return ("ok", True)
            if kind == "read_cps":
                return ("ok", list(self.meta.read_checkpoints(
                    ds, shard).items()))
            return ("err", f"unknown message {kind!r}")
        except StoreOpError as e:
            return ("err", str(e))
        except Exception as e:  # noqa: BLE001 — protocol boundary
            log.exception("chunk store op %s failed", kind)
            return ("err", f"{type(e).__name__}: {e}")


class _RemoteConn:
    """One pooled authed connection with reconnect-on-transport-error.

    A pooled socket may have gone stale since the previous op (server
    restart, idle timeout); the first transport failure on a pooled socket
    is therefore retried once on a fresh connection before surfacing. The
    peer's circuit breaker short-circuits calls while the store is down.
    """

    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.peer = f"{host}:{port}"
        self._lock = threading.Lock()
        self._sock: socket.socket | None = None

    def _conn_locked(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout)
            # the fd is owned-but-unpublished until self._sock = s; any
            # exception before that (setsockopt, auth) must close it
            try:
                s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                secret = cluster_secret()
                if secret is not None:
                    _send_msg(s, ("auth", secret))
                    if _recv_msg(s)[0] != "ok":
                        raise ConnectionError("chunk store auth rejected")
            except BaseException:
                try:
                    s.close()
                except OSError:
                    pass
                raise
            self._sock = s
        return self._sock

    def _drop_locked(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _roundtrip(self, msg):
        FaultInjector.fire("store.call", host=self.host, port=self.port,
                           op=msg[0])
        sock = self._conn_locked()
        _send_msg(sock, msg)
        return _recv_msg(sock)

    def call(self, *msg):
        breaker = breaker_for(self.peer)
        # same transport set as RemotePlanDispatcher (EOFError/ValueError
        # cover decode errors off a half-dead store); calling() guarantees
        # every admitted call — including a half-open probe — reports
        # exactly one breaker outcome even if an unexpected error escapes
        with breaker.calling(transport_errors=TRANSPORT_ERRORS):
            with self._lock:
                pooled = self._sock is not None
                try:
                    try:
                        resp = self._roundtrip(msg)
                    except TRANSPORT_ERRORS:
                        self._drop_locked()
                        if not pooled:
                            raise
                        # stale pooled socket: one retry on a fresh
                        # connection
                        resp = self._roundtrip(msg)
                except TRANSPORT_ERRORS:
                    self._drop_locked()
                    raise
        if resp[0] == "ok":
            return resp[1]
        if resp[0] == "pong":
            return True
        raise StoreOpError(f"chunk store op failed: {resp[1]}")


class RemoteColumnStore(ColumnStore):
    """ColumnStore client over a ``ChunkStoreServer`` — the Cassandra-
    ColumnStore analog: remote durability with server-side scan splits."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 pool: int = 4):
        self._conns = [_RemoteConn(host, port, timeout) for _ in range(pool)]
        self._rr = 0

    def _call(self, *msg):
        # round-robin over pooled connections: parallel split scans and
        # concurrent flush groups don't serialize on one socket
        self._rr = (self._rr + 1) % len(self._conns)
        return self._conns[self._rr].call(*msg)

    def initialize(self, dataset, num_shards):
        self._call("initialize", dataset, num_shards)

    def write_chunks(self, dataset, shard, part_key, chunks, ingestion_time):
        from filodb_tpu.core.store.localstore import _pk_blob
        self._call("write_chunks", dataset, shard, _pk_blob(part_key),
                   [c.serialize() for c in chunks], ingestion_time)

    def read_chunks(self, dataset, shard, part_key, start_time, end_time):
        from filodb_tpu.core.store.localstore import _pk_blob
        out = self._call("read_chunks", dataset, shard, _pk_blob(part_key),
                         start_time, end_time)
        return [Chunk.deserialize(b) for b in out]

    def write_part_keys(self, dataset, shard, records):
        from filodb_tpu.core.store.localstore import _pk_blob
        self._call("write_pks", dataset, shard,
                   [(_pk_blob(r.part_key), r.start_time, r.end_time)
                    for r in records])

    def _pks(self, rows):
        from filodb_tpu.core.store.localstore import _pk_from_blob
        return [PartKeyRecord(_pk_from_blob(b), st, et)
                for b, st, et in rows]

    def scan_part_keys(self, dataset, shard):
        return self._pks(self._call("scan_pks", dataset, shard, 0, 1))

    def scan_part_keys_split(self, dataset, shard, split, n_splits):
        """One token-range split, filtered server-side (``getScanSplits``)."""
        return self._pks(self._call("scan_pks", dataset, shard, split,
                                    n_splits))

    def scan_part_keys_since(self, dataset, shard, pk_token):
        return self._pks(self._call("scan_pks_since", dataset, shard,
                                    pk_token))

    def scan_chunks_by_ingestion_time(self, dataset, shard, start, end):
        from filodb_tpu.core.store.localstore import _pk_from_blob
        for blob, chunk_bytes in self._call("scan_ingest", dataset, shard,
                                            start, end):
            yield _pk_from_blob(blob), [Chunk.deserialize(b)
                                        for b in chunk_bytes]

    def truncate(self, dataset):
        self._call("truncate", dataset)

    def delete_part_keys(self, dataset, shard, part_keys):
        from filodb_tpu.core.store.localstore import _pk_blob
        self._call("delete_pks", dataset, shard,
                   [_pk_blob(pk) for pk in part_keys])

    def max_persisted_ts(self, dataset, shard):
        from filodb_tpu.core.store.localstore import _pk_from_blob
        return {_pk_from_blob(b): ts
                for b, ts in self._call("max_ts", dataset, shard)}

    def max_persisted_ts_since(self, dataset, shard, chunk_token):
        from filodb_tpu.core.store.localstore import _pk_from_blob
        return {_pk_from_blob(b): ts
                for b, ts in self._call("max_ts_since", dataset, shard,
                                        chunk_token)}

    def update_tokens(self, dataset, shard):
        return tuple(self._call("tokens", dataset, shard))

    def write_index_snapshot(self, dataset, shard, data):
        self._call("write_snap", dataset, shard, bytes(data))

    def read_index_snapshot(self, dataset, shard):
        return self._call("read_snap", dataset, shard)

    def close(self):
        for c in self._conns:
            with c._lock:
                if c._sock is not None:
                    try:
                        c._sock.close()
                    except OSError:
                        pass
                    c._sock = None


class RemoteMetaStore(MetaStore):
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._conn = _RemoteConn(host, port, timeout)

    def write_checkpoint(self, dataset, shard, group, offset):
        self._conn.call("write_cp", dataset, shard, group, offset)

    def read_checkpoints(self, dataset, shard):
        return dict(self._conn.call("read_cps", dataset, shard))

    def close(self):
        with self._conn._lock:
            if self._conn._sock is not None:
                try:
                    self._conn._sock.close()
                except OSError:
                    pass
                self._conn._sock = None
