"""Object-store (S3-compatible) durable tier: segment objects + manifest.

The reference's durability pillar is a real distributed store
(``CassandraColumnStore.scala:52``) with token-range split scans; this
module is that tier on S3-compatible object storage.  Everything the
4-table API stores is batched into immutable, append-only **segment
objects**:

    {prefix}/{dataset}/shard-{N}/b{BB}/seg-{SEQ:08d}.seg   data segments
    {prefix}/{dataset}/shard-{N}/manifest.json             live-segment list
    {prefix}/{dataset}/shard-{N}/checkpoints.json          meta checkpoints
    {prefix}/{dataset}/shard-{N}/index.snap                index snapshot

``BB`` is the part key's **bucket** — ``crc32(pk_blob) % bucket_count``,
the same hash family as ``split_of`` (remotestore.py), so bucket ``b``
serves token-range split ``b % n_splits`` whenever ``n_splits`` divides
``bucket_count``: split scans become key-prefix scans, the object-store
analog of Cassandra token ranges, and offline jobs (downsampler, repair)
can open a split-restricted view that never even GETs the other buckets.

Durability model — **write-behind with checkpoint ordering**:
``write_chunks``/``write_part_keys`` append to an in-memory open segment
per bucket (read-your-writes via the in-memory index); segments seal at
``segment_target_bytes`` or at a checkpoint barrier and are enqueued on
ONE bounded FIFO shared with the meta store.  ``write_checkpoint`` seals
the shard's open segments and enqueues the checkpoint object *behind*
them, so a checkpoint can never become visible remotely before the data
it covers: a crash mid-upload leaves the checkpoint missing and WAL
replay re-covers the gap — an acked flush is never lost.  The uploader
retries transient faults with ``RetryPolicy`` backoff forever (puts are
idempotent: segment keys are unique per seq) and uses multipart for
large segments.  A *fatal* (non-transient) failure — an S3 403/400, say
— poisons the shard instead: every task FIFO-queued behind it is parked
so the checkpoint can never overtake the data it covers, and the next
``flush()``/``close()`` raises :class:`ObjectStoreError` rather than
acking lost data.

Integrity tripwires: every segment carries a CRC32C (Castagnoli) footer
verified on full reads (recovery, compaction), and every chunk entry
carries its own CRC32C verified on ranged reads — a flipped byte raises
:class:`CorruptSegmentError` and bumps ``filodb_objectstore_corrupt_total``
instead of returning silent garbage.
"""

from __future__ import annotations

import collections
import io
import json
import queue
import struct
import threading
import time
import weakref

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.store.api import (ColumnStore, MetaStore, PartKeyRecord)
from filodb_tpu.core.store.localstore import _pk_blob, _pk_from_blob
from filodb_tpu.core.store.remotestore import split_of
# one-way import: pyramid never imports the object store (its objects
# carry their own CRC); importing it here also registers the
# filodb_pyramid_* metric families at store boot
from filodb_tpu.core.store import pyramid
from filodb_tpu.memory.chunk import Chunk, ensure_summary
from filodb_tpu.utils.metrics import Counter, Gauge, GaugeFn
from filodb_tpu.utils.resilience import FaultInjector, RetryPolicy
from filodb_tpu.utils.tracing import span

# --------------------------------------------------------------------------
# CRC32C (Castagnoli, poly 0x1EDC6F41 reflected = 0x82F63B78).  Not in the
# Python stdlib (zlib.crc32 is CRC32/IEEE); slice-by-8 table implementation.

_CRC32C_POLY = 0x82F63B78


def _make_tables():
    t0 = []
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _CRC32C_POLY if c & 1 else c >> 1
        t0.append(c)
    tables = [t0]
    for t in range(1, 8):
        prev = tables[t - 1]
        tables.append([(prev[i] >> 8) ^ t0[prev[i] & 0xFF]
                       for i in range(256)])
    return tables


_T = _make_tables()


def crc32c(data: bytes, crc: int = 0) -> int:
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    crc ^= 0xFFFFFFFF
    view = memoryview(data)
    n = len(view) - len(view) % 8
    i = 0
    while i < n:
        crc ^= view[i] | view[i + 1] << 8 | view[i + 2] << 16 \
            | view[i + 3] << 24
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[crc >> 24]
               ^ t3[view[i + 4]] ^ t2[view[i + 5]]
               ^ t1[view[i + 6]] ^ t0[view[i + 7]])
        i += 8
    for b in view[n:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# --------------------------------------------------------------------------
# errors + metrics

class CorruptSegmentError(Exception):
    """A segment (or chunk entry) failed its CRC32C check — the store
    refuses to return the bytes rather than serve silent garbage."""


class ObjectStoreError(Exception):
    """Non-transient object-store failure surfaced to the caller."""


PUTS = Counter("filodb_objectstore_puts")
GETS = Counter("filodb_objectstore_gets")
BYTES_UP = Counter("filodb_objectstore_bytes_up")
BYTES_DOWN = Counter("filodb_objectstore_bytes_down")
# chunk-payload share of BYTES_DOWN (ranged GETs only — excludes
# manifests, pyramids and bootstrap full-segment loads): the pyramid
# lane's zero-payload claim is asserted against this counter's delta
PAYLOAD_BYTES_DOWN = Counter(
    "filodb_objectstore_payload_bytes_down",
    help="bytes of chunk payload fetched via ranged GETs")
RETRIES = Counter("filodb_objectstore_retries")
COMPACTIONS = Counter("filodb_objectstore_compactions")
CORRUPT = Counter("filodb_objectstore_corrupt")
QUEUE_DEPTH = Gauge("filodb_objectstore_queue_depth")

# live stores the oldest-task-age gauge aggregates over; weak so a closed
# or collected store drops out without an unregister hook
_INSTANCES: "weakref.WeakSet" = weakref.WeakSet()


def _oldest_task_age() -> float:
    """Age of the oldest queued-or-in-flight write-behind task across live
    stores. Depth alone hides a wedged uploader (depth 1 forever looks
    healthy); age turns it into a ramp an alert can threshold."""
    oldest = None
    for store in list(_INSTANCES):
        dq = store._inflight_ts
        try:
            t0 = dq[0]
        except IndexError:
            continue
        if oldest is None or t0 < oldest:
            oldest = t0
    return 0.0 if oldest is None else max(0.0, time.time() - oldest)


OLDEST_TASK_AGE = GaugeFn(
    "filodb_objectstore_oldest_task_age_seconds", _oldest_task_age,
    help="age of the oldest queued-or-in-flight write-behind task")

# --------------------------------------------------------------------------
# segment binary format

# FSG2 chunk payloads carry the chunk aggregate sidecar trailer
# (memory/chunk.py); FSG1 segments (pre-sidecar) stay readable — their
# chunks deserialize without summaries and compaction backfills them
_MAGIC = b"FSG2"
_MAGIC_V1 = b"FSG1"
_FOOTER = struct.Struct("<BII")       # 0xFE, entry_count, crc32c(body)
_FOOTER_MARK = 0xFE
_E_CHUNK, _E_PARTKEY, _E_DELETE = 1, 2, 3
_CHUNK_HDR = struct.Struct("<qqqqqI")  # id, start, end, itime, upd, dlen
_PK_HDR = struct.Struct("<qqq")        # start, end, upd


class _ChunkRef:
    """In-memory index entry for one stored chunk payload."""
    __slots__ = ("chunk_id", "start_time", "end_time", "ingestion_time",
                 "upd", "seq", "offset", "length", "crc")

    def __init__(self, chunk_id, start_time, end_time, ingestion_time,
                 upd, seq, offset, length, crc):
        self.chunk_id = chunk_id
        self.start_time = start_time
        self.end_time = end_time
        self.ingestion_time = ingestion_time
        self.upd = upd
        self.seq = seq          # segment sequence number
        self.offset = offset    # byte offset of the chunk payload
        self.length = length    # payload length
        self.crc = crc          # crc32c of the payload


class _OpenSegment:
    """Append-only in-memory segment being built for one bucket."""

    def __init__(self, seq: int, bucket: int):
        self.seq = seq
        self.bucket = bucket
        self.buf = io.BytesIO()
        self.buf.write(_MAGIC)
        self.entries = 0
        self.max_upd = 0
        # sealed (pk_blob, chunk) rows for the pyramid roll-up at seal
        self.pyr_rows: list[tuple[bytes, Chunk]] = []

    def size(self) -> int:
        return self.buf.tell()

    def add_chunk(self, pk_blob: bytes, ch: Chunk, ingestion_time: int,
                  upd: int) -> tuple[int, int, int]:
        """Append a chunk entry; returns (payload_offset, length, crc)."""
        data = ch.serialize()
        crc = crc32c(data)
        b = self.buf
        b.write(struct.pack("<BI", _E_CHUNK, len(pk_blob)))
        b.write(pk_blob)
        b.write(_CHUNK_HDR.pack(ch.id, ch.start_time, ch.end_time,
                                ingestion_time, upd, len(data)))
        off = b.tell()
        b.write(data)
        b.write(struct.pack("<I", crc))
        self.entries += 1
        self.max_upd = max(self.max_upd, upd)
        self.pyr_rows.append((pk_blob, ch))
        return off, len(data), crc

    def add_part_key(self, pk_blob: bytes, start: int, end: int,
                     upd: int) -> None:
        b = self.buf
        b.write(struct.pack("<BI", _E_PARTKEY, len(pk_blob)))
        b.write(pk_blob)
        b.write(_PK_HDR.pack(start, end, upd))
        self.entries += 1
        self.max_upd = max(self.max_upd, upd)

    def add_delete(self, pk_blob: bytes) -> None:
        b = self.buf
        b.write(struct.pack("<BI", _E_DELETE, len(pk_blob)))
        b.write(pk_blob)
        self.entries += 1

    def finish(self) -> bytes:
        body = self.buf.getvalue()
        return body + _FOOTER.pack(_FOOTER_MARK, self.entries, crc32c(body))


def parse_segment(data: bytes, key: str = "?"):
    """Verify the footer CRC and yield entries:
    ``("chunk", pk_blob, id, start, end, itime, upd, payload_off, length,
    crc, payload)`` / ``("partkey", pk_blob, start, end, upd)`` /
    ``("delete", pk_blob)``.  Raises :class:`CorruptSegmentError` on any
    mismatch."""
    if len(data) < len(_MAGIC) + _FOOTER.size \
            or data[:4] not in (_MAGIC, _MAGIC_V1):
        CORRUPT.inc()
        raise CorruptSegmentError(f"{key}: bad magic/size")
    mark, count, crc = _FOOTER.unpack_from(data, len(data) - _FOOTER.size)
    body = data[:len(data) - _FOOTER.size]
    if mark != _FOOTER_MARK or crc32c(body) != crc:
        CORRUPT.inc()
        raise CorruptSegmentError(f"{key}: footer CRC32C mismatch")
    pos, seen = 4, 0
    out = []
    try:
        while pos < len(body):
            etype, pk_len = struct.unpack_from("<BI", body, pos)
            pos += 5
            pk_blob = bytes(body[pos:pos + pk_len])
            pos += pk_len
            if etype == _E_CHUNK:
                cid, st, et, itime, upd, dlen = _CHUNK_HDR.unpack_from(
                    body, pos)
                pos += _CHUNK_HDR.size
                payload = bytes(body[pos:pos + dlen])
                off = pos
                pos += dlen
                (ecrc,) = struct.unpack_from("<I", body, pos)
                pos += 4
                out.append(("chunk", pk_blob, cid, st, et, itime, upd,
                            off, dlen, ecrc, payload))
            elif etype == _E_PARTKEY:
                st, et, upd = _PK_HDR.unpack_from(body, pos)
                pos += _PK_HDR.size
                out.append(("partkey", pk_blob, st, et, upd))
            elif etype == _E_DELETE:
                out.append(("delete", pk_blob))
            else:
                raise CorruptSegmentError(f"{key}: unknown entry {etype}")
            seen += 1
    except (struct.error, CorruptSegmentError) as e:
        CORRUPT.inc()
        raise CorruptSegmentError(f"{key}: truncated entry stream: {e}") \
            from None
    if seen != count:
        CORRUPT.inc()
        raise CorruptSegmentError(f"{key}: entry count {seen} != {count}")
    return out


class _SegmentInfo:
    __slots__ = ("seq", "bucket", "key", "size", "crc", "entries", "max_upd",
                 "uploaded")

    def __init__(self, seq, bucket, key, size, crc, entries, max_upd,
                 uploaded):
        self.seq = seq
        self.bucket = bucket
        self.key = key
        self.size = size
        self.crc = crc
        self.entries = entries
        self.max_upd = max_upd
        self.uploaded = uploaded


class _ShardState:
    def __init__(self):
        self.parts: dict[PartKey, list] = {}      # pk -> [start, end, upd, bkt]
        self.chunks: dict[PartKey, dict[int, _ChunkRef]] = {}
        self.upd = 0
        self.next_seq = 1
        self.segments: dict[int, _SegmentInfo] = {}
        self.pending: dict[int, bytes] = {}       # seq -> sealed bytes
        self.open: dict[int, _OpenSegment] = {}   # bucket -> open segment
        self.checkpoints: dict[int, int] = {}
        # pyramid index: seg seqs with an UPLOADED seg-*.pyr beside them,
        # and per-bucket {"bucket","seq","key","covers"} roll-up records.
        # Both land in the manifest only after their object is durable —
        # a reader that races an upload just demotes to chunk fallback
        self.seg_pyramids: set[int] = set()
        self.bucket_pyramids: dict[int, dict] = {}


_STOP = object()


class ObjectStoreColumnStore(ColumnStore):
    """S3-compatible ColumnStore over immutable segment objects.

    ``client`` is anything with the :class:`~filodb_tpu.testing.fake_s3.
    FakeS3` surface (put_object/get_object/list_objects/delete_object +
    multipart).  ``split_filter=(split, n_splits)`` opens a
    split-restricted view: only buckets serving that split are loaded
    from the manifest (the key-prefix analog of a token-range scan)."""

    def __init__(self, client, bucket: str = "filodb", prefix: str = "",
                 segment_target_bytes: int = 1 << 20,
                 bucket_count: int = 8,
                 upload_queue_depth: int = 64,
                 compact_min_segments: int = 6,
                 multipart_threshold: int = 8 << 20,
                 auto_compact: bool = True,
                 retry_policy: RetryPolicy | None = None,
                 read_retry_policy: RetryPolicy | None = None):
        self.client = client
        self.bucket = bucket
        self.prefix = (prefix.strip("/") + "/") if prefix.strip("/") else ""
        self.segment_target_bytes = segment_target_bytes
        self.bucket_count = bucket_count
        self.compact_min_segments = compact_min_segments
        self.multipart_threshold = multipart_threshold
        self.auto_compact = auto_compact
        self.split_filter: tuple[int, int] | None = None
        # upload retries never give up on transient faults: an acked flush
        # must eventually land.  RetryPolicy paces one backoff "round";
        # the uploader loops rounds forever (see _uploader_put).
        self.retry_policy = retry_policy or RetryPolicy(
            max_attempts=5, base_backoff_s=0.05, max_backoff_s=2.0)
        self.read_retry_policy = read_retry_policy or RetryPolicy(
            max_attempts=3, base_backoff_s=0.02, max_backoff_s=0.5)
        self._lock = threading.RLock()
        self._states: dict[tuple[str, int], _ShardState] = {}
        self._queue: queue.Queue = queue.Queue(maxsize=upload_queue_depth)
        # tasks staged under _lock (fixing their global order), moved onto
        # the bounded queue OUTSIDE _lock — the uploader needs _lock to
        # mark completions, so blocking on a full queue while holding it
        # would deadlock
        self._staged: collections.deque = collections.deque()
        self._stage_lock = threading.Lock()
        self._closed = False
        self._upload_errors: list[str] = []
        # shards with a fatal (non-transient) upload failure: everything
        # queued behind the failed task is parked so a checkpoint can
        # never overtake the data it covers; flush() raises for them
        self._failed: set[tuple[str, int]] = set()
        # enqueue wall times of queued + in-flight tasks, FIFO-aligned with
        # _queue (single consumer): front = oldest, feeds the age gauge
        self._inflight_ts: collections.deque = collections.deque()
        _INSTANCES.add(self)
        self._uploader = threading.Thread(target=self._upload_loop,
                                          name="objstore-uploader",
                                          daemon=True)
        self._uploader.start()

    # ------------------------------------------------------------- keys
    def _shard_prefix(self, dataset: str, shard: int) -> str:
        return f"{self.bucket}/{self.prefix}{dataset}/shard-{shard}/"

    def _seg_key(self, dataset: str, shard: int, bucket: int,
                 seq: int) -> str:
        return (self._shard_prefix(dataset, shard)
                + f"b{bucket:02d}/seg-{seq:08d}.seg")

    def _bucket_of(self, pk_blob: bytes) -> int:
        return split_of(pk_blob, self.bucket_count)

    def _bucket_in_split(self, bkt: int) -> bool:
        if self.split_filter is None:
            return True
        s, n = self.split_filter
        return bkt % n == s if self.bucket_count % n == 0 \
            else True  # incompatible split count: load everything

    def restrict_to_split(self, split: int, n_splits: int
                          ) -> "ObjectStoreColumnStore":
        """Mark this (fresh) store as a split view BEFORE any state is
        loaded; manifest segments outside the split's buckets are
        skipped entirely — no GETs, no index memory.  The view is
        strictly read-only (every write entry point raises): a write
        would republish the manifest from the filtered segment set and
        drop the foreign buckets' segments."""
        with self._lock:
            if self._states:
                raise ObjectStoreError(
                    "restrict_to_split must run before first access")
            self.split_filter = (split, n_splits)
        return self

    def _require_writable(self, op: str) -> None:
        if self.split_filter is not None:
            raise ObjectStoreError(
                f"{op}: this store is a read-only split view — a write "
                "would republish the shard manifest from the filtered "
                "segment set and drop every foreign-bucket segment")

    # ------------------------------------------------------------ client io
    def _transient(self) -> tuple:
        return (ConnectionError, TimeoutError, OSError)

    def _put_raw(self, key: str, data: bytes) -> None:
        FaultInjector.fire("objectstore.put", key=key)
        if len(data) >= self.multipart_threshold and hasattr(
                self.client, "create_multipart"):
            upload_id = self.client.create_multipart(key)
            try:
                part, n = self.multipart_threshold, 1
                for off in range(0, len(data), part):
                    self.client.upload_part(key, upload_id, n,
                                            data[off:off + part])
                    n += 1
                self.client.complete_multipart(key, upload_id)
            except BaseException:
                try:
                    self.client.abort_multipart(key, upload_id)
                except Exception:
                    pass
                raise
        else:
            self.client.put_object(key, data)
        PUTS.inc()
        BYTES_UP.inc(len(data))

    def _get_raw(self, key: str, start=None, length=None) -> bytes:
        data = self.client.get_object(key, start, length)
        GETS.inc()
        BYTES_DOWN.inc(len(data))
        return data

    def _get(self, key: str, start=None, length=None) -> bytes:
        """GET with bounded retry on transient faults (read path)."""
        return self.read_retry_policy.call(
            lambda: self._get_raw(key, start, length),
            retry_on=self._transient(),
            on_retry=lambda *a, **k: RETRIES.inc(),
            site="objectstore.get")

    # ------------------------------------------------------------ uploader
    def _submit(self, task) -> None:
        """Stage a task in global order (caller MUST hold ``_lock``)."""
        self._staged.append(task)

    def _flush_staged(self) -> None:
        """Move staged tasks onto the bounded queue in order (caller must
        NOT hold ``_lock`` — the put blocks for backpressure)."""
        with self._stage_lock:
            while True:
                try:
                    task = self._staged.popleft()
                except IndexError:
                    return
                self._inflight_ts.append(time.time())
                self._queue.put(task)      # bounded: blocks = backpressure
                QUEUE_DEPTH.set(self._queue.qsize())

    def _upload_loop(self) -> None:
        while True:
            task = self._queue.get()
            QUEUE_DEPTH.set(self._queue.qsize())
            try:
                if task is _STOP:
                    return
                kind, dataset, shard = task[0], task[1], task[2]
                if kind == "compact":
                    # compaction failure never loses durable data (the
                    # old segments stay live in the manifest): log it
                    # without poisoning the shard
                    try:
                        self._compact_bucket(dataset, shard, task[3])
                    except Exception as e:
                        self._upload_errors.append(f"compact: {e!r}")
                    continue
                if (dataset, shard) in self._failed:
                    # a task for this shard failed fatally earlier: park
                    # everything FIFO-ordered behind it, most critically
                    # checkpoints — a checkpoint landing without the data
                    # it covers would make WAL replay skip the lost flush
                    self._upload_errors.append(
                        f"{kind} parked behind failed upload "
                        f"({dataset}/shard-{shard})")
                    continue
                if kind == "pyramid":
                    # derived data: a failed pyramid upload never poisons
                    # the shard (readers just keep chunk-level fallback);
                    # the seq registers only after the PUT lands, closing
                    # the read-race window by construction
                    seq, key, data = task[3], task[4], task[5]
                    try:
                        self._uploader_put(key, data)
                        with self._lock:
                            st = self._states.get((dataset, shard))
                            if st is not None and seq in st.segments:
                                st.seg_pyramids.add(seq)
                        self._put_manifest(dataset, shard)
                    except Exception as e:
                        self._upload_errors.append(f"pyramid: {e!r}")
                    continue
                if kind == "segment":
                    seq, key, data = task[3], task[4], task[5]
                    # slow uploads land in the ingest-side flight recorder
                    # ring (tracing.slow_ingest), not the query ring
                    from filodb_tpu.utils.tracing import traced_operation
                    with traced_operation("objectstore", op="upload",
                                          shard=shard, nbytes=len(data)):
                        self._uploader_put(key, data)
                    with self._lock:
                        st = self._states.get((dataset, shard))
                        if st is not None:
                            seg = st.segments.get(seq)
                            if seg is not None:
                                seg.uploaded = True
                            st.pending.pop(seq, None)
                    self._put_manifest(dataset, shard)
                    if self.auto_compact:
                        try:
                            self._maybe_compact(dataset, shard)
                        except Exception as e:
                            self._upload_errors.append(f"compact: {e!r}")
                elif kind == "checkpoint":
                    key = self._shard_prefix(dataset, shard) \
                        + "checkpoints.json"
                    self._uploader_put(
                        key, json.dumps(task[3]).encode())
            except Exception as e:   # never kill the drain loop
                # fatal (non-transient) failure: nothing landed remotely;
                # poison the shard so later tasks cannot overtake this one
                self._upload_errors.append(f"{task[0]}: {e!r}")
                self._failed.add((task[1], task[2]))
            finally:
                if task is not _STOP:
                    # _STOP is enqueued directly (close() bypasses the
                    # staging deque), so it carries no timestamp
                    try:
                        self._inflight_ts.popleft()
                    except IndexError:
                        pass
                self._queue.task_done()

    def _uploader_put(self, key: str, data: bytes) -> None:
        """Retry forever with backoff: write-behind durability means an
        acked flush MUST eventually land (puts are idempotent — segment
        keys are never reused)."""
        while True:
            try:
                self.retry_policy.call(
                    lambda: self._put_raw(key, data),
                    retry_on=self._transient(),
                    on_retry=lambda *a, **k: RETRIES.inc(),
                    site="objectstore.put")
                return
            except self._transient():
                if self._closed:
                    raise
                RETRIES.inc()
                self.retry_policy.sleep(self.retry_policy.max_backoff_s)

    def _put_manifest(self, dataset: str, shard: int) -> None:
        self._require_writable("_put_manifest")
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is None:
                return
            doc = {
                "version": 1,
                "next_seq": st.next_seq,
                "upd": st.upd,
                "segments": [
                    {"seq": s.seq, "bucket": s.bucket, "key": s.key,
                     "size": s.size, "crc": s.crc, "entries": s.entries,
                     "max_upd": s.max_upd}
                    for s in sorted(st.segments.values(),
                                    key=lambda s: s.seq)
                    if s.uploaded],
                "pyramids": sorted(
                    q for q in st.seg_pyramids
                    if q in st.segments and st.segments[q].uploaded),
                "bucket_pyramids": [st.bucket_pyramids[b]
                                    for b in sorted(st.bucket_pyramids)],
            }
        key = self._shard_prefix(dataset, shard) + "manifest.json"
        self._uploader_put(key, json.dumps(doc).encode())

    # ------------------------------------------------------------ state
    def refresh_shard(self, dataset: str, shard: int) -> None:
        """Drop the cached in-memory state for a shard so the next access
        re-reads the remote manifest. A migration destination may have
        touched the shard's (then-empty) state before the source uploaded;
        without a refresh it would cold-recover from that stale cache.
        Only safe — and only done — when nothing local is un-uploaded."""
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is not None and not st.pending and not st.open:
                del self._states[(dataset, shard)]

    def sync_shard(self, dataset: str, shard: int) -> int:
        """Follower tail over the durable tier: re-read the remote
        manifest and apply only UNSEEN sealed segments to the cached
        state — GETs are per new segment, never a full reload. A replica
        syncer (coordinator/replication.py) calls this periodically so a
        read-only follower's view — including ``next_seq``, so a
        post-promotion flush can never collide with leader-written
        segment keys — tracks the leader's uploads. Only safe on a
        read-only view: a shard with open or pending local segments is
        the WRITER and is skipped (returns 0). Returns the number of new
        segments applied."""
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is not None and (st.pending or st.open):
                return 0
        if st is None:
            # first touch: the cold load IS the sync
            self._state(dataset, shard)
            return 0
        base = self._shard_prefix(dataset, shard)
        try:
            doc = json.loads(self._get(base + "manifest.json"))
        except KeyError:
            return 0
        with self._lock:
            if st.pending or st.open:
                return 0  # became a writer since the first check
            known = set(st.segments)
            st.next_seq = max(st.next_seq, int(doc.get("next_seq", 1)))
            st.upd = max(st.upd, int(doc.get("upd", 0)))
            st.seg_pyramids = {int(q) for q in doc.get("pyramids", ())}
            st.bucket_pyramids = {
                int(d["bucket"]): d
                for d in doc.get("bucket_pyramids", ())}
        applied = 0
        for s in sorted(doc.get("segments", ()),
                        key=lambda s: int(s["seq"])):
            if int(s["seq"]) in known:
                continue
            info = _SegmentInfo(
                int(s["seq"]), int(s["bucket"]), s["key"], int(s["size"]),
                int(s["crc"]), int(s["entries"]), int(s["max_upd"]), True)
            if not self._bucket_in_split(info.bucket):
                continue
            data = self._get(info.key)
            if crc32c(data[:-_FOOTER.size]) != info.crc:
                CORRUPT.inc()
                raise CorruptSegmentError(
                    f"{info.key}: manifest CRC mismatch")
            entries = parse_segment(data, info.key)
            # the GET ran outside the lock (same reasoning as _load_state:
            # a retried network read must not stall every other shard);
            # two racing syncs may both apply a segment — _apply_entries
            # upserts by key, so the second apply is a no-op
            with self._lock:
                self._apply_entries(st, info.seq, entries)
                st.segments[info.seq] = info
            applied += 1
        return applied

    def _state(self, dataset: str, shard: int) -> _ShardState:
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is not None:
                return st
        # Cold load runs OUTSIDE _lock: recovery does retried network
        # GETs per live segment, and holding the store lock across them
        # would stall every other shard's reads and the uploader's
        # completion marking for the whole recovery. Two racing loaders
        # both pay the read; setdefault keeps the first committed state
        # so any mutations applied to it are never discarded.
        st = self._load_state(dataset, shard)
        with self._lock:
            return self._states.setdefault((dataset, shard), st)

    def _load_state(self, dataset: str, shard: int) -> _ShardState:
        """Cold-start recovery: manifest → full-GET each live segment
        (CRC32C-verified) → rebuild the in-memory index in seq order."""
        st = _ShardState()
        base = self._shard_prefix(dataset, shard)
        with span("objectstore", op="load", dataset=dataset, shard=shard):
            try:
                doc = json.loads(self._get(base + "manifest.json"))
            except KeyError:
                doc = None
            except self._transient():
                raise
            if doc:
                st.next_seq = int(doc.get("next_seq", 1))
                st.upd = int(doc.get("upd", 0))
                st.seg_pyramids = {int(q)
                                   for q in doc.get("pyramids", ())}
                st.bucket_pyramids = {
                    int(d["bucket"]): d
                    for d in doc.get("bucket_pyramids", ())}
                for s in doc.get("segments", ()):
                    info = _SegmentInfo(
                        int(s["seq"]), int(s["bucket"]), s["key"],
                        int(s["size"]), int(s["crc"]), int(s["entries"]),
                        int(s["max_upd"]), True)
                    st.segments[info.seq] = info
                for info in sorted(st.segments.values(),
                                   key=lambda s: s.seq):
                    if not self._bucket_in_split(info.bucket):
                        continue
                    data = self._get(info.key)
                    if crc32c(data[:-_FOOTER.size]) != info.crc:
                        CORRUPT.inc()
                        raise CorruptSegmentError(
                            f"{info.key}: manifest CRC mismatch")
                    self._apply_entries(st, info.seq,
                                        parse_segment(data, info.key))
                if self.split_filter is not None:
                    st.segments = {
                        q: s for q, s in st.segments.items()
                        if self._bucket_in_split(s.bucket)}
            try:
                st.checkpoints = {
                    int(g): int(o) for g, o in json.loads(
                        self._get(base + "checkpoints.json")).items()}
            except KeyError:
                pass
        return st

    def _apply_entries(self, st: _ShardState, seq: int, entries) -> None:
        for e in entries:
            if e[0] == "chunk":
                _, pk_blob, cid, t0, t1, itime, upd, off, dlen, crc, _ = e
                pk = _pk_from_blob(pk_blob)
                st.chunks.setdefault(pk, {})[cid] = _ChunkRef(
                    cid, t0, t1, itime, upd, seq, off, dlen, crc)
            elif e[0] == "partkey":
                _, pk_blob, t0, t1, upd = e
                pk = _pk_from_blob(pk_blob)
                prev = st.parts.get(pk)
                if prev is not None:
                    t0 = min(prev[0], t0)
                st.parts[pk] = [t0, t1, upd, self._bucket_of(pk_blob)]
            else:  # delete
                pk = _pk_from_blob(e[1])
                st.parts.pop(pk, None)
                st.chunks.pop(pk, None)

    # -------------------------------------------------------- segment build
    def _open_for(self, st, dataset, shard, bkt) -> _OpenSegment:
        seg = st.open.get(bkt)
        if seg is None:
            seg = _OpenSegment(st.next_seq, bkt)
            st.next_seq += 1
            st.open[bkt] = seg
        return seg

    def _seal(self, st, dataset, shard, bkt) -> None:
        """Seal one open segment and hand it to the uploader (caller
        holds the lock)."""
        seg = st.open.pop(bkt, None)
        if seg is None or seg.entries == 0:
            return
        data = seg.finish()
        key = self._seg_key(dataset, shard, bkt, seg.seq)
        st.segments[seg.seq] = _SegmentInfo(
            seg.seq, bkt, key, len(data), crc32c(data[:-_FOOTER.size]),
            seg.entries, seg.max_upd, False)
        st.pending[seg.seq] = data
        self._submit(("segment", dataset, shard, seg.seq, key, data))
        # pyramid roll-up rides FIFO behind its segment, so the manifest
        # can never advertise a pyramid whose segment isn't durable yet.
        # FSG1-mode writers (legacy compat tests patch _MAGIC) emit no
        # pyramids — compaction backfills them on rewrite
        if _MAGIC == b"FSG2":
            pdata = pyramid.build_segment_pyramid(seg.pyr_rows)
            if pdata is not None:
                self._submit(("pyramid", dataset, shard, seg.seq,
                              key[:-4] + ".pyr", pdata))

    def _seal_all(self, st, dataset, shard) -> None:
        for bkt in list(st.open):
            self._seal(st, dataset, shard, bkt)

    # ------------------------------------------------------------- writes
    def initialize(self, dataset: str, num_shards: int) -> None:
        for s in range(num_shards):
            self._state(dataset, s)

    def write_chunks(self, dataset, shard, part_key, chunks,
                     ingestion_time):
        self._require_writable("write_chunks")
        blob = _pk_blob(part_key)
        bkt = self._bucket_of(blob)
        with span("objectstore", op="write_chunks", shard=shard):
            with self._lock:
                st = self._state(dataset, shard)
                st.upd += 1
                upd = st.upd
                refs = st.chunks.setdefault(part_key, {})
                seg = self._open_for(st, dataset, shard, bkt)
                for ch in chunks:
                    if ch.id in refs:   # idempotent re-flush (dedup by id)
                        continue
                    off, dlen, crc = seg.add_chunk(blob, ch,
                                                   ingestion_time, upd)
                    refs[ch.id] = _ChunkRef(
                        ch.id, ch.start_time, ch.end_time, ingestion_time,
                        upd, seg.seq, off, dlen, crc)
                if seg.size() >= self.segment_target_bytes:
                    self._seal(st, dataset, shard, bkt)
            self._flush_staged()

    def write_part_keys(self, dataset, shard, records):
        self._require_writable("write_part_keys")
        with span("objectstore", op="write_part_keys", shard=shard):
            with self._lock:
                st = self._state(dataset, shard)
                st.upd += 1
                upd = st.upd
                for r in records:
                    blob = _pk_blob(r.part_key)
                    bkt = self._bucket_of(blob)
                    start = r.start_time
                    prev = st.parts.get(r.part_key)
                    if prev is not None:
                        start = min(prev[0], start)
                    st.parts[r.part_key] = [start, r.end_time, upd, bkt]
                    seg = self._open_for(st, dataset, shard, bkt)
                    seg.add_part_key(blob, start, r.end_time, upd)
                    if seg.size() >= self.segment_target_bytes:
                        self._seal(st, dataset, shard, bkt)
            self._flush_staged()

    def delete_part_keys(self, dataset, shard, part_keys):
        self._require_writable("delete_part_keys")
        with self._lock:
            st = self._state(dataset, shard)
            for pk in part_keys:
                blob = _pk_blob(pk)
                st.parts.pop(pk, None)
                st.chunks.pop(pk, None)
                # durable tombstone so recovery replays the delete
                seg = self._open_for(st, dataset, shard,
                                     self._bucket_of(blob))
                seg.add_delete(blob)
        self._flush_staged()

    def truncate(self, dataset):
        self._require_writable("truncate")
        self.flush()
        with self._lock:
            for key in [k for k in self._states if k[0] == dataset]:
                del self._states[key]
        for key in self.client.list_objects(
                f"{self.bucket}/{self.prefix}{dataset}/"):
            self.client.delete_object(key)

    # -------------------------------------------------------------- reads
    def _fetch_refs(self, dataset, shard, st, part_key,
                    refs) -> dict[int, bytes]:
        """Fetch payload bytes for one part key's refs → {chunk_id:
        bytes}.  Pending/open segments are served from memory
        (read-your-writes); uploaded segments via ranged GETs, coalescing
        per-segment runs into one request when the covering range is not
        too sparse.  Every payload is CRC32C-verified against its ref."""
        out: dict[int, bytes] = {}
        groups = self._resolve_refs(st, part_key, refs, out)
        for key, key_refs in groups.items():
            try:
                self._ranged_get(key, key_refs, out)
            except KeyError:
                # the object itself 404'd: compaction deleted it between
                # the index snapshot and the GET — re-resolve via the
                # fresh index once and retry
                for k, rs in self._resolve_refs(st, part_key, key_refs,
                                                out).items():
                    self._ranged_get(k, rs, out)
        for ref in refs:
            data = out.get(ref.chunk_id)
            if data is None or len(data) != ref.length \
                    or crc32c(data) != ref.crc:
                CORRUPT.inc()
                raise CorruptSegmentError(
                    f"chunk {ref.chunk_id} in seg {ref.seq} "
                    f"({dataset}/shard-{shard}): payload CRC32C mismatch")
        return out

    def _resolve_refs(self, st, part_key, refs, out) -> dict:
        """Under the lock: serve refs living in pending/open segments
        straight from memory into ``out``; group the rest by live object
        key for ranged GETs.  A ref whose segment is no longer in the
        index (compaction swapped it out after the caller snapshotted
        the refs) is re-resolved against the fresh chunk index instead
        of being indexed blindly."""
        groups: dict[str, list[_ChunkRef]] = {}
        with self._lock:
            open_by_seq = {o.seq: o for o in st.open.values()}
            live = st.chunks.get(part_key, {})
            for ref in refs:
                if ref.chunk_id in out:
                    continue
                if ref.seq not in st.segments \
                        and ref.seq not in open_by_seq:
                    ref = live.get(ref.chunk_id) or ref
                data = st.pending.get(ref.seq)
                if data is None:
                    o = open_by_seq.get(ref.seq)
                    if o is not None:
                        data = o.buf.getvalue()
                if data is not None:
                    out[ref.chunk_id] = data[ref.offset:ref.offset
                                             + ref.length]
                elif ref.seq in st.segments:
                    groups.setdefault(st.segments[ref.seq].key,
                                      []).append(ref)
                # else: the chunk vanished entirely (concurrent delete) —
                # the CRC verification in _fetch_refs reports it
        return groups

    def _ranged_get(self, key: str, seq_refs: list[_ChunkRef],
                    out: dict[int, bytes]) -> None:
        seq_refs = sorted(seq_refs, key=lambda r: r.offset)
        lo = seq_refs[0].offset
        hi = max(r.offset + r.length for r in seq_refs)
        dense = sum(r.length for r in seq_refs)
        if hi - lo <= dense + 4096 * len(seq_refs):
            blob = self._get(key, lo, hi - lo)
            PAYLOAD_BYTES_DOWN.inc(hi - lo)
            for r in seq_refs:
                out[r.chunk_id] = blob[r.offset - lo:
                                       r.offset - lo + r.length]
        else:
            for r in seq_refs:
                out[r.chunk_id] = self._get(key, r.offset, r.length)
                PAYLOAD_BYTES_DOWN.inc(r.length)

    def read_chunks(self, dataset, shard, part_key, start_time, end_time):
        with span("objectstore", op="read_chunks", shard=shard):
            with self._lock:
                st = self._state(dataset, shard)
                refs = sorted(
                    (r for r in st.chunks.get(part_key, {}).values()
                     if r.end_time >= start_time
                     and r.start_time <= end_time),
                    key=lambda r: r.chunk_id)
            if not refs:
                return []
            payloads = self._fetch_refs(dataset, shard, st, part_key, refs)
            return [Chunk.deserialize(payloads[r.chunk_id]) for r in refs]

    # ------------------------------------------------------ pyramid reads
    def pyramid_refs(self, dataset, shard, part_key):
        """Pyramid-lane index snapshot for one part key: (chunk refs
        sorted by id, frozenset of seg seqs with a durable segment
        pyramid, this key's bucket roll-up record or None)."""
        # _state() outside the lock: a cold load does retried network
        # GETs and must not stall other shards (same as read_chunks' seam)
        st = self._state(dataset, shard)
        with self._lock:
            refs = sorted(st.chunks.get(part_key, {}).values(),
                          key=lambda r: r.chunk_id)
            part = st.parts.get(part_key)
            bkt = part[3] if part is not None \
                else self._bucket_of(_pk_blob(part_key))
            return refs, frozenset(st.seg_pyramids), \
                st.bucket_pyramids.get(bkt)

    def _read_pyramid_object(self, key: str, parse) -> dict | None:
        try:
            data = self._get(key)
        except KeyError:
            return None   # raced a compaction delete: demote a level
        pyramid.PYR_BYTES_DOWN.inc(len(data))
        try:
            return parse(data, key)
        except pyramid.PyramidParseError:
            CORRUPT.inc()
            return None   # derived data: corrupt pyramid only demotes

    def read_segment_pyramid(self, dataset, shard, seq) -> dict | None:
        st = self._state(dataset, shard)
        with self._lock:
            info = st.segments.get(seq)
            if seq not in st.seg_pyramids or info is None:
                return None
            key = info.key[:-4] + ".pyr"
        return self._read_pyramid_object(key,
                                         pyramid.parse_segment_pyramid)

    def read_bucket_pyramid(self, dataset, shard, bkt) -> dict | None:
        st = self._state(dataset, shard)
        with self._lock:
            bp = st.bucket_pyramids.get(bkt)
            if bp is None:
                return None
            key = bp["key"]
        return self._read_pyramid_object(key,
                                         pyramid.parse_bucket_pyramid)

    def pyramid_index(self, dataset, shard) -> tuple[list[int], dict]:
        """Enumeration for summary-only scans (approx topk/cardinality):
        (sorted seg seqs with a pyramid, {bucket: roll-up record})."""
        st = self._state(dataset, shard)
        with self._lock:
            return (sorted(q for q in st.seg_pyramids
                           if q in st.segments),
                    dict(st.bucket_pyramids))

    def scan_part_keys(self, dataset, shard):
        with self._lock:
            st = self._state(dataset, shard)
            return [PartKeyRecord(pk, v[0], v[1])
                    for pk, v in st.parts.items()]

    def scan_part_keys_split(self, dataset, shard, split, n_splits):
        if n_splits <= 1:
            return self.scan_part_keys(dataset, shard)
        with self._lock:
            st = self._state(dataset, shard)
            if self.bucket_count % n_splits == 0:
                # bucket ≡ crc32 (mod bucket_count) ⇒ bucket % n_splits
                # == split_of(blob, n_splits): the key-prefix split
                return [PartKeyRecord(pk, v[0], v[1])
                        for pk, v in st.parts.items()
                        if v[3] % n_splits == split]
            return [PartKeyRecord(pk, v[0], v[1])
                    for pk, v in st.parts.items()
                    if split_of(_pk_blob(pk), n_splits) == split]

    def scan_part_keys_since(self, dataset, shard, pk_token):
        with self._lock:
            st = self._state(dataset, shard)
            return [PartKeyRecord(pk, v[0], v[1])
                    for pk, v in st.parts.items() if v[2] > pk_token]

    def dataset_stats(self, dataset):
        """{series, bytes, segments} across this dataset's loaded shards —
        the tier-size introspection behind ``/api/v1/status/tiers``.
        Counts uploaded segment objects plus sealed-but-pending bytes
        (write-behind), so the number tracks what a cold read could
        touch."""
        series = bytes_ = segments = 0
        with self._lock:
            for (ds, _shard), st in self._states.items():
                if ds != dataset:
                    continue
                series += len(st.parts)
                for seg in st.segments.values():
                    bytes_ += seg.size
                    segments += 1
        return {"series": series, "bytes": bytes_, "segments": segments}

    def scan_chunks_by_ingestion_time(self, dataset, shard, start, end):
        yield from self.scan_chunks_by_ingestion_time_split(
            dataset, shard, start, end, 0, 1)

    def scan_chunks_by_ingestion_time_split(self, dataset, shard, start,
                                            end, split, n_splits):
        """Ingestion-time scan restricted to one token-range split — the
        fan-out unit for downsample/repair jobs."""
        with self._lock:
            st = self._state(dataset, shard)
            work = []
            for pk, refs in st.chunks.items():
                if n_splits > 1:
                    part = st.parts.get(pk)
                    bkt = part[3] if part is not None \
                        else self._bucket_of(_pk_blob(pk))
                    if self.bucket_count % n_splits == 0:
                        if bkt % n_splits != split:
                            continue
                    elif split_of(_pk_blob(pk), n_splits) != split:
                        continue
                sel = sorted((r for r in refs.values()
                              if start <= r.ingestion_time < end),
                             key=lambda r: r.chunk_id)
                if sel:
                    work.append((pk, sel))
        for pk, sel in work:
            payloads = self._fetch_refs(dataset, shard, st, pk, sel)
            yield pk, [Chunk.deserialize(payloads[r.chunk_id])
                       for r in sel]

    def max_persisted_ts(self, dataset, shard):
        with self._lock:
            st = self._state(dataset, shard)
            return {pk: max(r.end_time for r in refs.values())
                    for pk, refs in st.chunks.items() if refs}

    def max_persisted_ts_since(self, dataset, shard, chunk_token):
        with self._lock:
            st = self._state(dataset, shard)
            out = {}
            for pk, refs in st.chunks.items():
                sel = [r.end_time for r in refs.values()
                       if r.upd > chunk_token]
                if sel:
                    out[pk] = max(sel)
            return out

    def update_tokens(self, dataset, shard):
        with self._lock:
            st = self._state(dataset, shard)
            return (st.upd, st.upd)

    # ----------------------------------------------------- index snapshots
    def write_index_snapshot(self, dataset, shard, data):
        self._require_writable("write_index_snapshot")
        key = self._shard_prefix(dataset, shard) + "index.snap"
        with span("objectstore", op="write_snapshot", shard=shard):
            # synchronous (not write-behind): the caller treats a returned
            # snapshot write as replay-barrier state
            self.retry_policy.call(
                lambda: self._put_raw(key, data),
                retry_on=self._transient(),
                on_retry=lambda *a, **k: RETRIES.inc(),
                site="objectstore.put")

    def read_index_snapshot(self, dataset, shard):
        key = self._shard_prefix(dataset, shard) + "index.snap"
        try:
            return self._get(key)
        except KeyError:
            return None

    # ------------------------------------------------- migration manifests
    # Synchronous (not write-behind): the migration state machine treats a
    # returned write as the crash-resume barrier for its current phase, so
    # it must be durable before the phase's work starts.

    def write_migration_manifest(self, dataset, shard, data):
        self._require_writable("write_migration_manifest")
        key = self._shard_prefix(dataset, shard) + "migration.json"
        with span("objectstore", op="write_migration", shard=shard):
            self.retry_policy.call(
                lambda: self._put_raw(key, data),
                retry_on=self._transient(),
                on_retry=lambda *a, **k: RETRIES.inc(),
                site="objectstore.put")

    def read_migration_manifest(self, dataset, shard):
        key = self._shard_prefix(dataset, shard) + "migration.json"
        try:
            return self._get(key)
        except KeyError:
            return None

    def delete_migration_manifest(self, dataset, shard):
        self._require_writable("delete_migration_manifest")
        key = self._shard_prefix(dataset, shard) + "migration.json"
        try:
            self.client.delete_object(key)
        except KeyError:
            pass

    # ---------------------------------------------------------- compaction
    def _maybe_compact(self, dataset: str, shard: int) -> None:
        """Queue compaction for buckets with many small uploaded
        segments (runs on the uploader thread → naturally serialized
        with uploads)."""
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is None:
                return
            small: dict[int, int] = {}
            for s in st.segments.values():
                if s.uploaded and s.size < self.segment_target_bytes // 2:
                    small[s.bucket] = small.get(s.bucket, 0) + 1
            due = [b for b, n in small.items()
                   if n >= self.compact_min_segments]
        for b in due:
            self._compact_bucket(dataset, shard, b)

    def compact(self, dataset: str, shard: int) -> int:
        """Compact every bucket of the shard now (test/operator hook).
        Returns the number of segments removed."""
        self._require_writable("compact")
        with self._lock:
            st = self._state(dataset, shard)
            buckets = {s.bucket for s in st.segments.values() if s.uploaded}
            before = len(st.segments)
        for b in sorted(buckets):
            self._compact_bucket(dataset, shard, b)
        with self._lock:
            return before - len(self._state(dataset, shard).segments)

    def _compact_bucket(self, dataset: str, shard: int, bkt: int) -> None:
        """Merge all uploaded segments of one bucket into a single new
        segment: read + verify olds, re-emit only live entries (latest
        part-key state, chunks still in the index), swap the manifest,
        delete the olds."""
        with self._lock:
            st = self._states.get((dataset, shard))
            if st is None:
                return
            olds = sorted((s for s in st.segments.values()
                           if s.bucket == bkt and s.uploaded),
                          key=lambda s: s.seq)
            if len(olds) < 2:
                return
        with span("objectstore", op="compact", shard=shard, bucket=bkt):
            parsed = [(s, parse_segment(self._get(s.key), s.key))
                      for s in olds]
            with self._lock:
                st = self._states.get((dataset, shard))
                if st is None:
                    return
                # a segment may have been compacted away meanwhile
                if any(s.seq not in st.segments for s, _ in parsed):
                    return
                # legacy (FSG1 / pre-pyramid FSG2) inputs gaining pyramid
                # coverage through this rewrite
                backfilled = sum(
                    1 for s, _ in parsed if s.seq not in st.seg_pyramids)
                new = _OpenSegment(st.next_seq, bkt)
                st.next_seq += 1
                moved: list[tuple[PartKey, _ChunkRef]] = []
                emitted_pks: set[PartKey] = set()
                for s, entries in parsed:
                    for e in entries:
                        if e[0] == "chunk":
                            _, blob, cid, *_rest = e
                            pk = _pk_from_blob(blob)
                            ref = st.chunks.get(pk, {}).get(cid)
                            if ref is None or ref.seq != s.seq:
                                continue   # deleted or superseded
                            ch = Chunk.deserialize(e[10])
                            # FSG1 → FSG2 backfill: chunks from pre-sidecar
                            # segments gain summaries on rewrite
                            ensure_summary(ch, backfill=True)
                            off, dlen, crc = new.add_chunk(
                                blob, ch, ref.ingestion_time, ref.upd)
                            moved.append((pk, _ChunkRef(
                                cid, ref.start_time, ref.end_time,
                                ref.ingestion_time, ref.upd, new.seq,
                                off, dlen, crc)))
                        elif e[0] == "partkey":
                            pk = _pk_from_blob(e[1])
                            cur = st.parts.get(pk)
                            if cur is None or pk in emitted_pks:
                                continue   # deleted or already emitted
                            emitted_pks.add(pk)
                            new.add_part_key(e[1], cur[0], cur[1], cur[2])
                        # deletes need no re-emit: their effect is already
                        # folded into the surviving entries
                data = new.finish()
                key = self._seg_key(dataset, shard, bkt, new.seq)
                info = _SegmentInfo(
                    new.seq, bkt, key, len(data),
                    crc32c(data[:-_FOOTER.size]), new.entries,
                    new.max_upd, False)
            # pyramid roll-ups over the rewritten rows: the segment level
            # plus the bucket level (the compacted bucket IS one segment,
            # so the bucket rows equal the new segment's rows — covers
            # records that). ensure_summary above backfilled legacy chunks
            spyr = pyramid.build_segment_pyramid(new.pyr_rows)
            bpyr = pyramid.build_bucket_pyramid(new.pyr_rows, [new.seq])
            pkey = key[:-4] + ".pyr"
            bkey = self._shard_prefix(dataset, shard) \
                + f"b{bkt:02d}/bkt-{new.seq:08d}.pyr"
            # upload the replacement BEFORE swapping the index/manifest
            self._uploader_put(key, data)
            info.uploaded = True
            # pyramids too land BEFORE the swap (a manifest must never
            # advertise an absent pyramid); their failure only demotes
            # readers to chunk fallback, never aborts the compaction
            spyr_ok = bpyr_ok = False
            try:
                if spyr is not None:
                    self._uploader_put(pkey, spyr)
                    spyr_ok = True
                if bpyr is not None:
                    self._uploader_put(bkey, bpyr)
                    bpyr_ok = True
            except Exception as e:
                self._upload_errors.append(f"pyramid: {e!r}")
            with self._lock:
                st.segments[info.seq] = info
                for pk, ref in moved:
                    live = st.chunks.get(pk, {})
                    if live.get(ref.chunk_id) is not None:
                        live[ref.chunk_id] = ref
                for s, _ in parsed:
                    st.segments.pop(s.seq, None)
                    st.seg_pyramids.discard(s.seq)
                if spyr_ok:
                    st.seg_pyramids.add(new.seq)
                old_bp = st.bucket_pyramids.pop(bkt, None)
                if bpyr_ok:
                    st.bucket_pyramids[bkt] = {
                        "bucket": bkt, "seq": new.seq, "key": bkey,
                        "covers": [new.seq]}
            self._put_manifest(dataset, shard)
            for s, _ in parsed:
                for k in (s.key, s.key[:-4] + ".pyr"):
                    try:
                        self.client.delete_object(k)
                    except Exception:
                        pass   # orphan object; harmless (not in manifest)
            if old_bp is not None and old_bp.get("key") != bkey:
                try:
                    self.client.delete_object(old_bp["key"])
                except Exception:
                    pass
            if spyr_ok and backfilled:
                pyramid.PYR_BACKFILLED.inc(backfilled)
            COMPACTIONS.inc()

    # ------------------------------------------------------------ lifecycle
    def flush(self) -> None:
        """Seal all open segments and drain the upload queue (blocks
        until everything staged so far is durably uploaded).  Raises
        :class:`ObjectStoreError` if any upload failed fatally — a
        returned flush() is the durability ack, so it must never report
        success over lost data."""
        with self._lock:
            for (dataset, shard), st in self._states.items():
                self._seal_all(st, dataset, shard)
        self._flush_staged()
        self._queue.join()
        if self._failed:
            shards = ", ".join(f"{d}/shard-{s}"
                               for d, s in sorted(self._failed))
            raise ObjectStoreError(
                f"write-behind upload failed fatally for {shards}; "
                "flushed data is NOT durable: "
                + "; ".join(self._upload_errors[-3:]))

    def upload_errors(self) -> list[str]:
        return list(self._upload_errors)

    def close(self) -> None:
        if self._closed:
            return
        try:
            self.flush()
        finally:
            # the uploader stops even when flush() raises (parked shards,
            # upload errors): _closed makes _uploader_put's retry-forever
            # loop re-raise instead of backing off, so the drain to _STOP
            # cannot wedge the join behind a dead endpoint
            self._closed = True
            self._queue.put(_STOP)
            self._uploader.join(timeout=30)


class HttpS3Client:
    """Minimal path-style S3 REST client (stdlib-only) with optional
    AWS SigV4 signing — enough for minio/S3-compatible endpoints:
    PUT / GET (+Range) / DELETE / ListObjectsV2.  Multipart is not
    offered (no ``create_multipart`` attr), so the uploader falls back
    to single PUTs; S3 single-PUT tops out at 5 GiB, far above any
    segment this tier produces."""

    def __init__(self, endpoint: str, access_key: str | None = None,
                 secret_key: str | None = None, region: str = "us-east-1",
                 timeout_s: float = 30.0):
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.timeout_s = timeout_s

    # -- SigV4 ------------------------------------------------------------
    def _sign(self, method: str, path: str, query: str, headers: dict,
              payload: bytes) -> dict:
        """``query`` must already be in canonical form (see
        :func:`_canon_query`) — the same string goes into the signed
        canonical request and the request URL, so they cannot
        disagree."""
        import datetime
        import hashlib
        import hmac
        import urllib.parse as up
        if not self.access_key:
            return headers
        now = datetime.datetime.now(datetime.timezone.utc)
        amzdate = now.strftime("%Y%m%dT%H%M%SZ")
        datestamp = now.strftime("%Y%m%d")
        host = up.urlparse(self.endpoint).netloc
        payload_hash = hashlib.sha256(payload).hexdigest()
        headers = dict(headers)
        headers["host"] = host
        headers["x-amz-date"] = amzdate
        headers["x-amz-content-sha256"] = payload_hash
        signed = sorted(k.lower() for k in headers)
        canonical_headers = "".join(
            f"{k}:{str(headers[_orig(headers, k)]).strip()}\n"
            for k in signed)
        canonical = "\n".join([
            method, up.quote(path), query, canonical_headers,
            ";".join(signed), payload_hash])
        scope = f"{datestamp}/{self.region}/s3/aws4_request"
        to_sign = "\n".join([
            "AWS4-HMAC-SHA256", amzdate, scope,
            hashlib.sha256(canonical.encode()).hexdigest()])

        def _hmac(key, msg):
            return hmac.new(key, msg.encode(), hashlib.sha256).digest()

        k = _hmac(("AWS4" + self.secret_key).encode(), datestamp)
        k = _hmac(k, self.region)
        k = _hmac(k, "s3")
        k = _hmac(k, "aws4_request")
        sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
        headers["Authorization"] = (
            f"AWS4-HMAC-SHA256 Credential={self.access_key}/{scope}, "
            f"SignedHeaders={';'.join(signed)}, Signature={sig}")
        return headers

    def _request(self, method: str, key: str, params: dict | None = None,
                 data: bytes = b"", headers: dict | None = None) -> bytes:
        import urllib.error
        import urllib.request
        path = "/" + key
        query = _canon_query(params) if params else ""
        headers = self._sign(method, path, query, headers or {}, data)
        url = self.endpoint + path + ("?" + query if query else "")
        req = urllib.request.Request(url, data=data or None, method=method,
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise KeyError(key) from None
            if e.code in (500, 502, 503, 504, 429):
                raise ConnectionError(f"s3 {method} {key}: {e.code}") \
                    from None
            raise ObjectStoreError(
                f"s3 {method} {key}: {e.code} {e.reason}") from None
        except urllib.error.URLError as e:
            raise ConnectionError(f"s3 {method} {key}: {e.reason}") \
                from None

    def put_object(self, key: str, data: bytes) -> None:
        self._request("PUT", key, data=data)

    def get_object(self, key: str, start: int | None = None,
                   length: int | None = None) -> bytes:
        headers = {}
        if start is not None:
            end = "" if length is None else start + length - 1
            headers["Range"] = f"bytes={start}-{end}"
        return self._request("GET", key, headers=headers)

    def delete_object(self, key: str) -> None:
        try:
            self._request("DELETE", key)
        except KeyError:
            pass

    def list_objects(self, prefix: str = "") -> list[str]:
        import xml.etree.ElementTree as ET
        bucket, _, rest = prefix.partition("/")
        out: list[str] = []
        token = None
        while True:
            params = {"list-type": "2", "prefix": rest}
            if token:
                params["continuation-token"] = token
            xml = self._request("GET", bucket, params=params)
            root = ET.fromstring(xml)
            ns = root.tag.partition("}")[0] + "}" if "}" in root.tag else ""
            for c in root.iter(f"{ns}Key"):
                out.append(f"{bucket}/{c.text}")
            trunc = root.findtext(f"{ns}IsTruncated") == "true"
            token = root.findtext(f"{ns}NextContinuationToken")
            if not trunc or not token:
                return out


def _canon_query(params: dict | None) -> str:
    """SigV4 canonical query string: keys and values percent-encoded
    with the RFC 3986 unreserved set only (``/`` becomes ``%2F``),
    pairs sorted by encoded key.  Valid as-is in the request URL."""
    import urllib.parse as up
    if not params:
        return ""
    pairs = sorted((up.quote(str(k), safe=""), up.quote(str(v), safe=""))
                   for k, v in params.items())
    return "&".join(f"{k}={v}" for k, v in pairs)


def _orig(headers: dict, lower: str) -> str:
    for k in headers:
        if k.lower() == lower:
            return k
    return lower


def open_object_store(store_cfg: dict, data_dir: str
                      ) -> tuple[ObjectStoreColumnStore,
                                 "ObjectStoreMetaStore"]:
    """Build the object-store tier from a ``config.store`` block.  No
    endpoint (or a plain path) → directory-backed in-process fake under
    ``data_dir`` (hermetic dev/test); ``http(s)://…`` → real
    S3-compatible service."""
    import os
    endpoint = store_cfg.get("endpoint")
    if endpoint and str(endpoint).startswith(("http://", "https://")):
        client = HttpS3Client(
            endpoint,
            access_key=store_cfg.get("access_key"),
            secret_key=store_cfg.get("secret_key"),
            region=store_cfg.get("region", "us-east-1"))
    else:
        from filodb_tpu.testing.fake_s3 import FakeS3
        root = endpoint or os.path.join(data_dir, "objectstore")
        client = FakeS3(root=root)
    cs = ObjectStoreColumnStore(
        client,
        bucket=store_cfg.get("bucket", "filodb"),
        prefix=store_cfg.get("prefix", ""),
        segment_target_bytes=int(
            store_cfg.get("segment_target_bytes", 1 << 20)),
        bucket_count=int(store_cfg.get("bucket_count", 8)),
        upload_queue_depth=int(store_cfg.get("upload_queue_depth", 64)))
    return cs, ObjectStoreMetaStore(cs)


class ObjectStoreMetaStore(MetaStore):
    """Checkpoints on the same bucket, ordered behind the data they cover.

    Shares the column store's single FIFO uploader: ``write_checkpoint``
    first seals the shard's open segments into the queue, then enqueues
    the checkpoint object — so remotely the checkpoint only ever appears
    *after* the flushed data it acknowledges."""

    def __init__(self, column_store: ObjectStoreColumnStore):
        self.cs = column_store

    def write_checkpoint(self, dataset, shard, group, offset):
        cs = self.cs
        cs._require_writable("write_checkpoint")
        with span("objectstore", op="write_checkpoint", shard=shard):
            with cs._lock:
                st = cs._state(dataset, shard)
                cs._seal_all(st, dataset, shard)
                st.checkpoints[group] = offset
                # staged AFTER the seals, under the same lock: FIFO order
                # guarantees the checkpoint object lands last
                cs._submit(("checkpoint", dataset, shard,
                            dict(st.checkpoints)))
            cs._flush_staged()

    def read_checkpoints(self, dataset, shard):
        with self.cs._lock:
            return dict(self.cs._state(dataset, shard).checkpoints)

    def close(self) -> None:
        pass   # lifecycle owned by the column store
