"""Store and ingestion configuration.

Counterpart of reference ``StoreConfig``/``IngestionConfig``
(``core/src/main/scala/filodb.core/store/IngestionConfig.scala:1-211``) and the
per-dataset source config (``conf/timeseries-dev-source.conf:1-111``).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass(frozen=True)
class StoreConfig:
    flush_interval_ms: int = 3_600_000          # flush-interval = 1h
    max_chunk_size: int = 400                   # max-chunks-size: samples/chunk
    groups_per_shard: int = 20                  # flush groups (reference: 20 dev)
    shard_mem_mb: int = 256                     # shard-mem-size
    disk_ttl_ms: int = 3 * 24 * 3_600_000       # disk-time-to-live
    retention_ms: int = 3 * 24 * 3_600_000      # in-memory retention before purge
    flush_task_parallelism: int = 2
    demand_paging_enabled: bool = True
    max_query_matches: int = 250_000
    # evicted part-key bloom/tracking capacity
    evicted_pk_bloom_filter_capacity: int = 50_000
    # debug: part keys whose str() contains any of these substrings get a
    # TracingTimeSeriesPartition (reference trace-filters config)
    trace_part_key_substrings: tuple[str, ...] = ()
    # single-writer discipline check (reference FiloSchedulers.assertThreadName)
    assert_single_writer: bool = False
    # encode device pages at ingest and run the decode-on-device query path
    device_pages: bool = False
    # route binary containers through the C++ ingest core when possible
    # (scalar-column schemas; falls back per-container otherwise)
    native_ingest: bool = True
    # persist the part-key index snapshot this often (0 = only on demand);
    # restart loads the snapshot + delta instead of a full part-key scan
    index_snapshot_interval_ms: int = 600_000


@dataclass(frozen=True)
class IngestionConfig:
    dataset: str
    num_shards: int = 4
    min_num_nodes: int = 1
    source_factory: str = "in-proc"             # reference sourcefactory class
    source_config: dict = field(default_factory=dict)
    store: StoreConfig = field(default_factory=StoreConfig)
    # downsampling plane config: {"resolutions_ms": [...], "streaming": bool,
    # "schedule_s": N, "raw_retention_ms": M} (reference downsample config)
    downsample: dict | None = None
