"""Store APIs: chunk sources/sinks, column store, meta store, configs.

Counterpart of reference ``core/src/main/scala/filodb.core/store/``.
"""
