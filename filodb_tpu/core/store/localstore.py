"""Durable local column store + meta store (sqlite-backed).

Counterpart of the reference's Cassandra plugin (``cassandra/`` module) with
the same four-table data model:

- ``chunks``      — (partition, chunkid) → encoded chunkset
  (reference ``TimeSeriesChunksTable.scala:34``)
- ``ingestion_time_index`` — (partition, ingestion_time, chunkid) for
  downsampler/ODP scans by ingestion window
  (reference ``IngestionTimeIndexTable.scala:31``)
- ``partkeys``    — partKey → (startTime, endTime) per shard
  (reference ``PartitionKeysTable.scala:26``)
- ``checkpoints`` — (shard, group) → offset
  (reference ``metastore/CheckpointTable.scala:24``)

sqlite (stdlib) provides the durable KV substrate the way Cassandra does for
the reference; the store interface (``ColumnStore``/``MetaStore``) is the
pluggable seam for object-store/Cassandra backends later.
"""

from __future__ import annotations

import os
import sqlite3
import threading

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.store.api import ColumnStore, MetaStore, PartKeyRecord
from filodb_tpu.memory.chunk import Chunk


def _pk_blob(pk: PartKey) -> bytes:
    return pk.serialized


def _pk_from_blob(blob: bytes) -> PartKey:
    parts = blob.split(b"\x00")
    schema = parts[0].decode()
    labels = []
    for p in parts[1:]:
        k, v = p.split(b"\x01", 1)
        labels.append((k.decode(), v.decode()))
    return PartKey(schema, tuple(labels))


class _Db:
    """One sqlite database per (dataset, shard), lazily opened."""

    def __init__(self, root: str):
        self.root = root
        self._conns: dict[tuple[str, int], sqlite3.Connection] = {}
        self._lock = threading.Lock()

    def conn(self, dataset: str, shard: int) -> sqlite3.Connection:
        key = (dataset, shard)
        with self._lock:
            c = self._conns.get(key)
            if c is None:
                d = os.path.join(self.root, dataset)
                os.makedirs(d, exist_ok=True)
                c = sqlite3.connect(os.path.join(d, f"shard-{shard}.db"),
                                    check_same_thread=False)
                # the meta store and the column store hold SEPARATE
                # connections to one shard file; concurrent group flushes
                # interleave chunk and checkpoint writes, so lock waits
                # must block-and-retry instead of raising immediately
                c.execute("PRAGMA busy_timeout=10000")
                c.execute("PRAGMA journal_mode=WAL")
                c.execute("PRAGMA synchronous=NORMAL")
                c.execute("""CREATE TABLE IF NOT EXISTS chunks (
                    partition BLOB, chunkid INTEGER, start_time INTEGER,
                    end_time INTEGER, data BLOB,
                    PRIMARY KEY (partition, chunkid))""")
                c.execute("""CREATE TABLE IF NOT EXISTS ingestion_time_index (
                    partition BLOB, ingestion_time INTEGER, chunkid INTEGER,
                    PRIMARY KEY (partition, ingestion_time, chunkid))""")
                c.execute("""CREATE TABLE IF NOT EXISTS partkeys (
                    partition BLOB PRIMARY KEY, start_time INTEGER,
                    end_time INTEGER)""")
                c.execute("""CREATE TABLE IF NOT EXISTS checkpoints (
                    grp INTEGER PRIMARY KEY, offset INTEGER)""")
                # monotonic write counters for snapshot delta-replay
                for tbl in ("chunks", "partkeys"):
                    try:
                        c.execute(f"ALTER TABLE {tbl} ADD COLUMN upd "
                                  "INTEGER DEFAULT 0")
                    except sqlite3.OperationalError:
                        pass  # column already present
                self._conns[key] = c
            return c

    def close(self):
        with self._lock:
            for c in self._conns.values():
                c.close()
            self._conns.clear()


class LocalDiskColumnStore(ColumnStore):
    def __init__(self, root: str):
        self.root = root
        self._db = _Db(root)
        self._wlock = threading.Lock()
        self._upd: dict[tuple[str, int], int] = {}

    def initialize(self, dataset: str, num_shards: int) -> None:
        for s in range(num_shards):
            self._db.conn(dataset, s)

    def _upd_peek(self, c, dataset, shard) -> int:
        """Current write counter, initializing from the db once (caller
        holds _wlock)."""
        key = (dataset, shard)
        cur = self._upd.get(key)
        if cur is None:
            cur = c.execute(
                "SELECT MAX(m) FROM (SELECT COALESCE(MAX(upd),0) m FROM "
                "chunks UNION ALL SELECT COALESCE(MAX(upd),0) FROM partkeys)"
            ).fetchone()[0] or 0
            self._upd[key] = cur
        return cur

    def _next_upd(self, c, dataset, shard) -> int:
        cur = self._upd_peek(c, dataset, shard) + 1
        self._upd[(dataset, shard)] = cur
        return cur

    def write_chunks(self, dataset, shard, part_key, chunks, ingestion_time):
        c = self._db.conn(dataset, shard)
        blob = _pk_blob(part_key)
        with self._wlock:
            upd = self._next_upd(c, dataset, shard)
            c.executemany(
                "INSERT OR IGNORE INTO chunks(partition, chunkid, "
                "start_time, end_time, data, upd) VALUES (?,?,?,?,?,?)",
                [(blob, ch.id, ch.start_time, ch.end_time, ch.serialize(),
                  upd) for ch in chunks])
            c.executemany(
                "INSERT OR IGNORE INTO ingestion_time_index VALUES (?,?,?)",
                [(blob, ingestion_time, ch.id) for ch in chunks])
            c.commit()

    def read_chunks(self, dataset, shard, part_key, start_time, end_time):
        c = self._db.conn(dataset, shard)
        rows = c.execute(
            "SELECT data FROM chunks WHERE partition=? AND end_time>=? AND "
            "start_time<=? ORDER BY chunkid", (_pk_blob(part_key), start_time,
                                               end_time)).fetchall()
        return [Chunk.deserialize(r[0]) for r in rows]

    def write_part_keys(self, dataset, shard, records):
        c = self._db.conn(dataset, shard)
        with self._wlock:
            upd = self._next_upd(c, dataset, shard)
            for r in records:
                c.execute(
                    "INSERT INTO partkeys(partition, start_time, end_time, "
                    "upd) VALUES (?,?,?,?) ON CONFLICT(partition)"
                    " DO UPDATE SET start_time=MIN(start_time, excluded."
                    "start_time), end_time=excluded.end_time, "
                    "upd=excluded.upd",
                    (_pk_blob(r.part_key), r.start_time, r.end_time, upd))
            c.commit()

    def scan_part_keys(self, dataset, shard):
        c = self._db.conn(dataset, shard)
        rows = c.execute(
            "SELECT partition, start_time, end_time FROM partkeys").fetchall()
        return [PartKeyRecord(_pk_from_blob(b), st, et) for b, st, et in rows]

    def scan_chunks_by_ingestion_time(self, dataset, shard, start, end):
        c = self._db.conn(dataset, shard)
        parts = c.execute(
            "SELECT DISTINCT partition FROM ingestion_time_index WHERE "
            "ingestion_time>=? AND ingestion_time<?", (start, end)).fetchall()
        for (blob,) in parts:
            ids = [r[0] for r in c.execute(
                "SELECT chunkid FROM ingestion_time_index WHERE partition=? "
                "AND ingestion_time>=? AND ingestion_time<?",
                (blob, start, end))]
            if not ids:
                continue
            q = ",".join("?" * len(ids))
            rows = c.execute(
                f"SELECT data FROM chunks WHERE partition=? AND chunkid IN "
                f"({q}) ORDER BY chunkid", (blob, *ids)).fetchall()
            yield _pk_from_blob(blob), [Chunk.deserialize(r[0]) for r in rows]

    def truncate(self, dataset):
        import glob
        import os as _os
        self._db.close()
        for f in glob.glob(os.path.join(self.root, dataset, "shard-*.db*")):
            _os.remove(f)

    def delete_part_keys(self, dataset, shard, part_keys):
        c = self._db.conn(dataset, shard)
        with self._wlock:
            for pk in part_keys:
                blob = _pk_blob(pk)
                c.execute("DELETE FROM partkeys WHERE partition=?", (blob,))
                c.execute("DELETE FROM chunks WHERE partition=?", (blob,))
                c.execute("DELETE FROM ingestion_time_index WHERE "
                          "partition=?", (blob,))
            c.commit()

    def max_persisted_ts(self, dataset, shard):
        c = self._db.conn(dataset, shard)
        rows = c.execute(
            "SELECT partition, MAX(end_time) FROM chunks GROUP BY partition"
        ).fetchall()
        return {_pk_from_blob(b): int(mx) for b, mx in rows}

    def max_persisted_ts_since(self, dataset, shard, chunk_token):
        c = self._db.conn(dataset, shard)
        rows = c.execute(
            "SELECT partition, MAX(end_time) FROM chunks WHERE upd > ? "
            "GROUP BY partition", (chunk_token,)).fetchall()
        return {_pk_from_blob(b): int(mx) for b, mx in rows}

    def scan_part_keys_since(self, dataset, shard, pk_token):
        c = self._db.conn(dataset, shard)
        rows = c.execute(
            "SELECT partition, start_time, end_time FROM partkeys "
            "WHERE upd > ?", (pk_token,)).fetchall()
        return [PartKeyRecord(_pk_from_blob(b), st, et) for b, st, et in rows]

    def update_tokens(self, dataset, shard):
        c = self._db.conn(dataset, shard)
        with self._wlock:
            cur = self._upd_peek(c, dataset, shard)
        return (cur, cur)

    def write_index_snapshot(self, dataset, shard, data):
        d = os.path.join(self.root, dataset)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"index-shard-{shard}.snap")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)  # atomic: readers never see a partial file

    def read_index_snapshot(self, dataset, shard):
        path = os.path.join(self.root, dataset, f"index-shard-{shard}.snap")
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    # migration manifests: atomic-replace files beside the shard db, so a
    # crashed handoff resumes from durable phase state after restart
    def write_migration_manifest(self, dataset, shard, data):
        d = os.path.join(self.root, dataset)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"migration-shard-{shard}.json")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_migration_manifest(self, dataset, shard):
        path = os.path.join(self.root, dataset,
                            f"migration-shard-{shard}.json")
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete_migration_manifest(self, dataset, shard):
        path = os.path.join(self.root, dataset,
                            f"migration-shard-{shard}.json")
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def close(self):
        self._db.close()


class LocalDiskMetaStore(MetaStore):
    def __init__(self, root: str):
        self._db = _Db(root)
        self._wlock = threading.Lock()

    def write_checkpoint(self, dataset, shard, group, offset):
        c = self._db.conn(dataset, shard)
        with self._wlock:
            c.execute("INSERT INTO checkpoints VALUES (?,?) ON CONFLICT(grp) "
                      "DO UPDATE SET offset=excluded.offset", (group, offset))
            c.commit()

    def read_checkpoints(self, dataset, shard):
        c = self._db.conn(dataset, shard)
        return dict(c.execute("SELECT grp, offset FROM checkpoints"))

    # cost-model snapshots: atomic-replace file beside the dataset's shard
    # dbs, so learned estimates survive a restart (query/cost_model.py)
    def write_cost_model(self, dataset, data):
        d = os.path.join(self._db.root, dataset)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "costmodel.json")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    def read_cost_model(self, dataset):
        path = os.path.join(self._db.root, dataset, "costmodel.json")
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def close(self):
        self._db.close()
