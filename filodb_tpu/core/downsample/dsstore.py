"""Downsample read store: serves queries directly from the column store.

Counterpart of reference ``DownsampledTimeSeriesStore.scala:22`` /
``DownsampledTimeSeriesShard.scala:48``: no write buffers — the in-memory
state is just the part-key index (bootstrapped from the persisted part keys);
chunk data is read from the column store per query (and flows through the
same SeriesBatch → kernel path as raw data).
"""

from __future__ import annotations

import logging

from filodb_tpu.core.downsample.downsampler import ds_dataset_name
from filodb_tpu.core.memstore.index import PartKeyIndex
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, Schemas
from filodb_tpu.core.store.api import ColumnStore
from filodb_tpu.core.store.config import StoreConfig

log = logging.getLogger(__name__)


class PagedReadablePartition:
    """Read-only partition view over persisted chunks (reference
    ``PagedReadablePartition``). Duck-types TimeSeriesPartition's read API."""

    def __init__(self, part_id, part_key, schema, column_store, dataset,
                 shard):
        self.part_id = part_id
        self.part_key = part_key
        self.schema = schema
        self._cs = column_store
        self._dataset = dataset
        self._shard = shard
        # chunk accounting for QueryStats: duck-typed partitions have no
        # chunks_in_range, so leaf scans fold this count in after decode
        self.chunks_read = 0

    def read_samples(self, start, end, col=None, extra_chunks=None):
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        chunks = self._cs.read_chunks(self._dataset, self._shard,
                                      self.part_key, start, end)
        self.chunks_read = len(chunks)
        tmp = TimeSeriesPartition(self.part_id, self.part_key, self.schema)
        tmp.chunks = chunks
        return tmp.read_samples(start, end, col)


class DownsampledTimeSeriesShard:
    def __init__(self, dataset: str, ds_dataset: str, shard: int,
                 column_store: ColumnStore, schemas: Schemas):
        self.dataset = dataset
        self.ds_dataset = ds_dataset
        self.shard_num = shard
        self.column_store = column_store
        self.schemas = schemas
        self.index = PartKeyIndex()
        self.config = StoreConfig(demand_paging_enabled=False)
        self._refreshed = False
        self._known: dict = {}
        self._parts: dict = {}
        # leaf-exec batch cache protocol (see TimeSeriesShard.batch_cache);
        # ds data only changes when the downsampler job republishes
        self.batch_cache: dict = {}
        self.batch_cache_cap = 64

    @property
    def data_version(self) -> int:
        return len(self._known)

    def refresh_index(self) -> int:
        """Bootstrap/refresh the index from persisted ds part keys
        (reference index bootstrap + periodic refresh thread)."""
        n = 0
        for rec in self.column_store.scan_part_keys(self.ds_dataset,
                                                    self.shard_num):
            if rec.part_key in self._known:
                pid = self._known[rec.part_key]
                self.index.update_end_time(pid, rec.end_time)
                continue
            pid = len(self._known)
            self._known[rec.part_key] = pid
            self.index.add_part_key(pid, rec.part_key, rec.start_time,
                                    rec.end_time)
            self._parts[pid] = PagedReadablePartition(
                pid, rec.part_key, self.schemas[rec.part_key.schema],
                self.column_store, self.ds_dataset, self.shard_num)
            n += 1
        self._refreshed = True
        return n

    def lookup_partitions(self, filters, start, end):
        if not self._refreshed:
            self.refresh_index()
        return self.index.part_ids_from_filters(filters, start, end)

    def partition(self, pid):
        return self._parts.get(pid)

    def label_values(self, label, filters=None, start=0, end=2**62):
        if not self._refreshed:
            self.refresh_index()
        return self.index.label_values(label, filters, start, end)

    def label_names(self):
        if not self._refreshed:
            self.refresh_index()
        return self.index.label_names()

    @property
    def num_partitions(self):
        return len(self._known)


class DownsampledTimeSeriesStore:
    """Memstore-shaped facade over downsampled data for the exec layer."""

    def __init__(self, column_store: ColumnStore, dataset: str,
                 resolution_ms: int, num_shards: int,
                 schemas: Schemas | None = None):
        self.column_store = column_store
        self.dataset = dataset
        self.resolution_ms = resolution_ms
        self.ds_dataset = ds_dataset_name(dataset, resolution_ms)
        self.schemas = schemas or DEFAULT_SCHEMAS
        self._shards = {
            s: DownsampledTimeSeriesShard(dataset, self.ds_dataset, s,
                                          column_store, self.schemas)
            for s in range(num_shards)}

    def get_shard(self, dataset: str, shard: int):
        return self._shards[shard]

    def shards_for(self, dataset: str):
        return [self._shards[s] for s in sorted(self._shards)]

    def label_values(self, dataset, label, filters=None, start=0, end=2**62):
        out = set()
        for s in self.shards_for(dataset):
            out.update(s.label_values(label, filters, start, end))
        return sorted(out)

    def label_names(self, dataset):
        out = set()
        for s in self.shards_for(dataset):
            out.update(s.label_names())
        return sorted(out)
