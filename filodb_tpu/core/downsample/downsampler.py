"""Chunk downsamplers + streaming/batch downsampling.

Counterpart of reference ``ChunkDownsampler.scala:16-31`` (dMin/dMax/dSum/
dCount/dAvg/tTime/dLast), ``DownsamplePeriodMarker.scala`` (time-based period
boundaries), ``ShardDownsampler.scala:1-103`` (emit downsample records at
flush) and ``BatchDownsampler.scala:37`` (offline job over the ingestion-time
index).

Gauge rows downsample into the ``ds-gauge`` schema (ts,min,max,sum,count,avg);
counters keep last-sample semantics (``dLast``); period timestamps are the
last raw sample time in the period (``tTime`` semantics).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import IngestRecord, RecordContainer
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, Schemas
from filodb_tpu.core.store.api import ColumnStore, MetaStore, PartKeyRecord

log = logging.getLogger(__name__)


def downsample_samples(ts: np.ndarray, vals: np.ndarray, resolution_ms: int):
    """Aggregate (ts, vals) into time buckets of ``resolution_ms``.

    Returns (bucket_last_ts, min, max, sum, count, avg, last) arrays — the
    full downsampler family evaluated in one segmented pass (numpy reduceat;
    bulk batches go through the same prefix-sum kernels as queries).
    """
    if len(ts) == 0:
        z = np.array([], np.float64)
        return np.array([], np.int64), z, z, z, z, z, z
    bucket = ts // resolution_ms
    # segment boundaries (ts sorted)
    starts = np.flatnonzero(np.concatenate([[True], bucket[1:] != bucket[:-1]]))
    ends = np.concatenate([starts[1:], [len(ts)]])
    t_last = ts[ends - 1]
    mins = np.minimum.reduceat(vals, starts)
    maxs = np.maximum.reduceat(vals, starts)
    sums = np.add.reduceat(vals, starts)
    counts = (ends - starts).astype(np.float64)
    avgs = sums / counts
    lasts = vals[ends - 1]
    return t_last, mins, maxs, sums, counts, avgs, lasts


def downsample_partition(part: TimeSeriesPartition, resolution_ms: int,
                         start: int, end: int) -> list[IngestRecord]:
    """Downsample one partition's raw samples into ds records."""
    schema = part.schema
    ts, vals = part.read_samples(start, end)
    if len(ts) == 0 or not np.ndim(vals):
        return []
    is_counter = schema.data.columns[schema.data.value_column].is_counter
    ds_key = PartKey(schema.data.downsample_schema or "ds-gauge",
                     part.part_key.labels)
    t_last, mins, maxs, sums, counts, avgs, lasts = downsample_samples(
        np.asarray(ts), np.asarray(vals, np.float64), resolution_ms)
    out = []
    for i in range(len(t_last)):
        if is_counter:
            # prom-counter ds schema: (ts, value=dLast)
            out.append(IngestRecord(
                PartKey("prom-counter", part.part_key.labels),
                int(t_last[i]), (float(lasts[i]),)))
        else:
            out.append(IngestRecord(ds_key, int(t_last[i]),
                                    (float(mins[i]), float(maxs[i]),
                                     float(sums[i]), float(counts[i]),
                                     float(avgs[i]))))
    return out


@dataclass
class ShardDownsampler:
    """Streaming downsampler: emits downsample records at flush time
    (reference ``ShardDownsampler`` publishing to the downsample dataset)."""

    resolutions_ms: tuple[int, ...] = (300_000, 3_600_000)
    publish: "callable | None" = None  # fn(resolution, RecordContainer)

    def on_flush(self, part: TimeSeriesPartition, flushed_chunks) -> None:
        if self.publish is None or not flushed_chunks:
            return
        start = min(c.start_time for c in flushed_chunks)
        end = max(c.end_time for c in flushed_chunks)
        for res in self.resolutions_ms:
            recs = downsample_partition(part, res, start, end)
            if recs:
                c = RecordContainer()
                for r in recs:
                    c.add(r)
                self.records_created = getattr(
                    self, "records_created", 0) + len(recs)
                self.publish(res, c)


def ds_dataset_name(dataset: str, resolution_ms: int) -> str:
    return f"{dataset}_ds_{resolution_ms // 60000}m"


@dataclass
class DownsamplerJob:
    """Batch downsampler (reference ``DownsamplerMain``/``BatchDownsampler``):
    scans raw chunks by ingestion-time window, replays them through the
    downsamplers, writes ds chunks + part keys to the column store under the
    downsample dataset."""

    column_store: ColumnStore
    dataset: str
    num_shards: int
    resolutions_ms: tuple[int, ...] = (300_000, 3_600_000)
    schemas: Schemas = field(default_factory=lambda: DEFAULT_SCHEMAS)
    max_chunk_size: int = 400
    # when set, catch_up() persists per-shard progress checkpoints so a
    # crashed/restarted job rescans exactly the unprocessed ingestion-time
    # window instead of everything (or, worse, nothing)
    meta_store: MetaStore | None = None
    n_splits: int = 1   # fan the ingestion-time scan out over store splits

    def run(self, ingestion_start: int, ingestion_end: int,
            user_start: int = 0, user_end: int = 2**62) -> dict:
        stats = {"partitions": 0, "ds_chunks": 0, "ds_samples": 0}
        for shard in range(self.num_shards):
            for res in self.resolutions_ms:
                self._downsample_shard(shard, res, ingestion_start,
                                       ingestion_end, user_start, user_end,
                                       stats)
        return stats

    # -- checkpointed catch-up (reference: DownsamplerMain watermarks) -----

    def _ckpt_dataset(self) -> str:
        return f"{self.dataset}__dsckpt"

    def last_checkpoint(self, shard: int) -> int:
        """Ingestion-time watermark this shard is downsampled up to."""
        if self.meta_store is None:
            return 0
        return self.meta_store.read_checkpoints(
            self._ckpt_dataset(), shard).get(0, 0)

    def catch_up(self, now_ms: int, user_start: int = 0,
                 user_end: int = 2**62) -> dict:
        """Downsample every shard from its persisted checkpoint up to
        ``now_ms`` and advance the checkpoint.  After a crash between a
        raw flush and the next scheduled downsample run, the lost window
        is re-scanned via ``scan_chunks_by_ingestion_time`` from the last
        checkpoint — nothing is silently skipped.  Re-downsampling an
        overlapping window is idempotent: ds chunk ids are deterministic
        and the store dedups by chunk id."""
        stats = {"partitions": 0, "ds_chunks": 0, "ds_samples": 0,
                 "scanned_from": {}}
        for shard in range(self.num_shards):
            start = self.last_checkpoint(shard)
            stats["scanned_from"][shard] = start
            for res in self.resolutions_ms:
                self._downsample_shard(shard, res, start, now_ms,
                                       user_start, user_end, stats)
            if self.meta_store is not None:
                self.meta_store.write_checkpoint(
                    self._ckpt_dataset(), shard, 0, now_ms)
        return stats

    def _iter_raw(self, shard, t0, t1):
        if self.n_splits <= 1:
            yield from self.column_store.scan_chunks_by_ingestion_time(
                self.dataset, shard, t0, t1)
            return
        for split in range(self.n_splits):
            yield from self.column_store.scan_chunks_by_ingestion_time_split(
                self.dataset, shard, t0, t1, split, self.n_splits)

    def _downsample_shard(self, shard, res, t0, t1, us, ue, stats):
        ds_name = ds_dataset_name(self.dataset, res)
        pkrecs = []
        for part_key, chunks in self._iter_raw(shard, t0, t1):
            schema = self.schemas[part_key.schema]
            if schema.data.downsample_schema is None:
                continue
            # rebuild a transient partition from the persisted chunks
            part = TimeSeriesPartition(0, part_key, schema,
                                       self.max_chunk_size)
            part.chunks = sorted(chunks, key=lambda c: c.id)
            recs = downsample_partition(part, res, us, ue)
            if not recs:
                continue
            stats["partitions"] += 1
            stats["ds_samples"] += len(recs)
            ds_schema = self.schemas[recs[0].part_key.schema]
            ds_part = TimeSeriesPartition(0, recs[0].part_key, ds_schema,
                                          self.max_chunk_size)
            for r in recs:
                ds_part.ingest(r.timestamp, r.values)
            out_chunks = ds_part.make_flush_chunks()
            self.column_store.write_chunks(ds_name, shard, recs[0].part_key,
                                           out_chunks, ingestion_time=t1)
            stats["ds_chunks"] += len(out_chunks)
            pkrecs.append(PartKeyRecord(recs[0].part_key,
                                        recs[0].timestamp,
                                        recs[-1].timestamp))
        if pkrecs:
            self.column_store.write_part_keys(ds_name, shard, pkrecs)
