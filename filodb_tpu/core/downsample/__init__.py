"""Downsampling: raw chunks → lower-resolution rollups for long retention.

Counterpart of reference ``core/src/main/scala/filodb.core/downsample/``
(ChunkDownsampler hierarchy, DownsamplePeriodMarker, ShardDownsampler,
DownsampledTimeSeriesStore) and the Spark batch job
(``spark-jobs/.../downsampler/chunk/DownsamplerMain.scala``) — without Spark:
the batch job walks the column store's ingestion-time index directly.
"""

from filodb_tpu.core.downsample.downsampler import (  # noqa: F401
    DownsamplerJob,
    ShardDownsampler,
    downsample_partition,
)
from filodb_tpu.core.downsample.dsstore import (  # noqa: F401
    DownsampledTimeSeriesStore,
)
