"""Storage engine: schemas, partition keys, memstore, store APIs, downsampling.

Counterpart of the reference's ``core/`` module
(``core/src/main/scala/filodb.core/``).
"""
