"""Column filters for partition-key lookup.

Counterpart of reference ``core/src/main/scala/filodb.core/query/KeyFilter.scala``
(``ColumnFilter`` / ``Filter`` with Equals/In/EqualsRegex/NotEqualsRegex...).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class Filter:
    def matches(self, value: str) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class Equals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value == self.value


@dataclass(frozen=True)
class NotEquals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value != self.value


@dataclass(frozen=True)
class In(Filter):
    values: frozenset[str]

    def matches(self, value: str) -> bool:
        return value in self.values


def _compile_anchored(pattern: str) -> re.Pattern:
    # PromQL regexes are fully anchored (RE2 ^(?:pattern)$ semantics)
    return re.compile(f"^(?:{pattern})$")


_RE_META = set(".^$*+?{}[]|()\\")


def _split_top_level_alts(pattern: str) -> list[str]:
    """Split on top-level ``|`` (escapes consumed, group nesting tracked,
    character classes scanned opaquely — ``(``/``|``/``[`` inside ``[...]``
    are literals and must not desync the depth counter). An escaped
    sequence stays in its part verbatim, so parts containing ``\\`` still
    read as non-literal downstream."""
    parts, cur, depth = [], [], 0
    in_class = False
    class_start = -1
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\":
            cur.append(ch)
            i += 1
            if i < len(pattern):
                cur.append(pattern[i])
                i += 1
            continue
        if in_class:
            # ']' is literal as the first class char ("[]]") or right
            # after a negation ("[^]]")
            first = i == class_start + 1 or (
                i == class_start + 2 and pattern[class_start + 1] == "^")
            if ch == "]" and not first:
                in_class = False
            cur.append(ch)
            i += 1
            continue
        if ch == "[":
            in_class = True
            class_start = i
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
        if ch == "|" and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        i += 1
    parts.append("".join(cur))
    return parts


from functools import lru_cache


@lru_cache(maxsize=1024)
def regex_plan(pattern: str) -> tuple[str, object]:
    """Pre-analyze an anchored regex the way Prometheus'
    FastRegexMatcher / Lucene's automata rewriting do
    (reference ``PartKeyLuceneIndex.scala:455`` leans on Lucene's
    ``RegexpQuery`` automaton; this is the index-side equivalent):

    - ``("literal", s)``  — no metacharacters: an Equals lookup
    - ``("alts", [s..])`` — top-level alternation of literals: an In lookup
    - ``("prefix", p)``   — literal prefix: narrow the value scan to the
      sorted value table's prefix range before running the regex
    - ``("scan", None)``  — fall back to the full value-table scan
    """
    if not any(ch in _RE_META for ch in pattern):
        return ("literal", pattern)
    parts = _split_top_level_alts(pattern)
    if len(parts) > 1:
        if all(p and not any(ch in _RE_META for ch in p) for p in parts):
            return ("alts", parts)
        # top-level alternation with non-literal branches: the pattern
        # head is NOT a mandatory prefix of every match
        return ("scan", None)
    prefix = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch in _RE_META:
            break
        if i + 1 < len(pattern) and pattern[i + 1] in "*+?{":
            break  # quantifier makes this char optional/repeated
        prefix.append(ch)
        i += 1
    if prefix:
        return ("prefix", "".join(prefix))
    return ("scan", None)


class _CompiledRegexMixin:
    """Per-instance compiled-pattern memo: ``matches`` runs once per value
    in index value-table scans — recompiling (even via the re module's
    bounded cache) dominates the scan."""

    def _rx(self) -> re.Pattern:
        rx = self.__dict__.get("_rx_c")
        if rx is None:
            rx = _compile_anchored(self.pattern)
            object.__setattr__(self, "_rx_c", rx)
        return rx


@dataclass(frozen=True)
class EqualsRegex(Filter, _CompiledRegexMixin):
    pattern: str

    def matches(self, value: str) -> bool:
        return self._rx().match(value) is not None


@dataclass(frozen=True)
class NotEqualsRegex(Filter, _CompiledRegexMixin):
    pattern: str

    def matches(self, value: str) -> bool:
        return self._rx().match(value) is None


@dataclass(frozen=True)
class ColumnFilter:
    column: str
    filter: Filter

    def __str__(self) -> str:
        f = self.filter
        if isinstance(f, Equals):
            return f'{self.column}="{f.value}"'
        if isinstance(f, NotEquals):
            return f'{self.column}!="{f.value}"'
        if isinstance(f, EqualsRegex):
            return f'{self.column}=~"{f.pattern}"'
        if isinstance(f, NotEqualsRegex):
            return f'{self.column}!~"{f.pattern}"'
        if isinstance(f, In):
            return f'{self.column} in {sorted(f.values)}'
        return f"{self.column}?{f}"
