"""Column filters for partition-key lookup.

Counterpart of reference ``core/src/main/scala/filodb.core/query/KeyFilter.scala``
(``ColumnFilter`` / ``Filter`` with Equals/In/EqualsRegex/NotEqualsRegex...).
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class Filter:
    def matches(self, value: str) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError


@dataclass(frozen=True)
class Equals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value == self.value


@dataclass(frozen=True)
class NotEquals(Filter):
    value: str

    def matches(self, value: str) -> bool:
        return value != self.value


@dataclass(frozen=True)
class In(Filter):
    values: frozenset[str]

    def matches(self, value: str) -> bool:
        return value in self.values


def _compile_anchored(pattern: str) -> re.Pattern:
    # PromQL regexes are fully anchored (RE2 ^(?:pattern)$ semantics)
    return re.compile(f"^(?:{pattern})$")


class _CompiledRegexMixin:
    """Per-instance compiled-pattern memo: ``matches`` runs once per value
    in index value-table scans — recompiling (even via the re module's
    bounded cache) dominates the scan."""

    def _rx(self) -> re.Pattern:
        rx = self.__dict__.get("_rx_c")
        if rx is None:
            rx = _compile_anchored(self.pattern)
            object.__setattr__(self, "_rx_c", rx)
        return rx


@dataclass(frozen=True)
class EqualsRegex(Filter, _CompiledRegexMixin):
    pattern: str

    def matches(self, value: str) -> bool:
        return self._rx().match(value) is not None


@dataclass(frozen=True)
class NotEqualsRegex(Filter, _CompiledRegexMixin):
    pattern: str

    def matches(self, value: str) -> bool:
        return self._rx().match(value) is None


@dataclass(frozen=True)
class ColumnFilter:
    column: str
    filter: Filter

    def __str__(self) -> str:
        f = self.filter
        if isinstance(f, Equals):
            return f'{self.column}="{f.value}"'
        if isinstance(f, NotEquals):
            return f'{self.column}!="{f.value}"'
        if isinstance(f, EqualsRegex):
            return f'{self.column}=~"{f.pattern}"'
        if isinstance(f, NotEqualsRegex):
            return f'{self.column}!~"{f.pattern}"'
        if isinstance(f, In):
            return f'{self.column} in {sorted(f.values)}'
        return f"{self.column}?{f}"
