"""Partition keys, hashing and shard routing.

Counterpart of the reference's BinaryRecord v2 partition keys and ShardMapper
routing (``core/src/main/scala/filodb.core/binaryrecord2/RecordSchema.scala:112``,
``coordinator/src/main/scala/filodb.coordinator/ShardMapper.scala:26-49``,
``doc/sharding.md:23-56``).

Semantics preserved:
- A partition key is (schema, sorted label map). The metric name is the label
  ``_metric_``; shard-key labels (default ``_ws_``, ``_ns_``, ``_metric_``)
  determine the *shard-key hash*.
- shard = upper bits from shardKeyHash | lower ``spread`` bits from the full
  partition hash — so all series of one (workspace, namespace, metric) land in
  a bounded group of 2^spread shards, enabling bounded query fan-out.

Hash is murmur3-32 over the canonical serialized key, stable across processes
(used by gateways to route without coordination, like the reference's gateway).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

METRIC_LABEL = "_metric_"


def murmur3_32(data: bytes, seed: int = 0) -> int:
    """Stable 32-bit murmur3 (x86 variant); C++ fast path when available
    (bit-exact with the python fallback below)."""
    from filodb_tpu.memory import native

    h = native.murmur3_32_native(data, seed)
    if h is not None:
        return h
    return _murmur3_32_py(data, seed)


def _murmur3_32_py(data: bytes, seed: int = 0) -> int:
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed
    n = len(data)
    rounded = n - (n & 3)
    for i in range(0, rounded, 4):
        k = int.from_bytes(data[i : i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[rounded:]
    if len(tail) >= 3:
        k ^= tail[2] << 16
    if len(tail) >= 2:
        k ^= tail[1] << 8
    if len(tail) >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


@dataclass(frozen=True)
class PartKey:
    """An immutable partition key: schema name + label map (incl. _metric_)."""

    schema: str
    labels: tuple[tuple[str, str], ...]  # sorted (name, value) pairs

    @staticmethod
    def create(schema: str, labels: dict[str, str]) -> "PartKey":
        return PartKey(schema, tuple(sorted(labels.items())))

    @cached_property
    def label_map(self) -> dict[str, str]:
        return dict(self.labels)

    @cached_property
    def range_vector_key(self):
        """Series-identity key for query results, built once per partition:
        ``labels`` is already sorted, so this skips the dict+sort round trip
        of ``RangeVectorKey.of`` — which costs ~40us x every series on every
        batch rebuild."""
        from filodb_tpu.query.model import RangeVectorKey
        return RangeVectorKey(self.labels)

    @property
    def metric(self) -> str:
        return self.label_map.get(METRIC_LABEL, "")

    @cached_property
    def serialized(self) -> bytes:
        parts = [self.schema.encode()]
        for k, v in self.labels:
            parts.append(k.encode() + b"\x01" + v.encode())
        return b"\x00".join(parts)

    @cached_property
    def part_hash(self) -> int:
        return murmur3_32(self.serialized)

    def shard_key_hash(self, shard_key_labels: tuple[str, ...]) -> int:
        return shard_key_hash(
            {k: self.label_map.get(k, "") for k in shard_key_labels}
        )

    def __str__(self) -> str:
        inner = ",".join(f"{k}={v}" for k, v in self.labels if k != METRIC_LABEL)
        return f"{self.metric}{{{inner}}}"


def shard_key_hash(shard_key_values: dict[str, str]) -> int:
    """Hash of the shard-key labels only (reference ``RecordBuilder.shardKeyHash``)."""
    data = b"\x00".join(
        k.encode() + b"\x01" + v.encode() for k, v in sorted(shard_key_values.items())
    )
    return murmur3_32(data, seed=0x5EED)


def ingestion_shard(shard_key_h: int, part_h: int, num_shards: int, spread: int) -> int:
    """Compute the owning shard (reference ``ShardMapper.ingestionShard:37-49``).

    Upper bits of the shard come from the shard-key hash; the low ``spread``
    bits come from the whole-key hash, so one shard key spans 2^spread shards.
    """
    assert num_shards & (num_shards - 1) == 0, "num_shards must be a power of 2"
    mask = (1 << spread) - 1
    return (shard_key_h & ~mask | part_h & mask) & (num_shards - 1)


def shards_for_shard_key(shard_key_h: int, num_shards: int, spread: int) -> list[int]:
    """All shards a shard key maps to at a given spread — the query fan-out set
    (reference ``ShardMapper.queryShards``)."""
    mask = (1 << spread) - 1
    base = shard_key_h & ~mask & (num_shards - 1)
    return [(base | i) & (num_shards - 1) for i in range(1 << spread)]
