"""TimeSeriesMemStore: multi-shard in-memory store with ingest/recover streams.

Counterpart of reference ``MemStore``/``TimeSeriesMemStore``
(``core/src/main/scala/filodb.core/memstore/MemStore.scala:49``,
``TimeSeriesMemStore.scala:23,60,114,147``): ``setup(shard)`` creates shard
state, ``ingest_stream`` consumes an iterator of containers interleaving
time-staggered group flushes, ``recover_stream`` replays a log range honoring
per-group watermarks. Reactive monix Observables become plain Python
iterators/generators — the concurrency model is single-writer-per-shard with
queries reading immutable chunk snapshots.
"""

from __future__ import annotations

import logging
import time
from collections.abc import Iterable, Iterator

from filodb_tpu.core.memstore.shard import TimeSeriesShard
from filodb_tpu.core.record import SomeData
from filodb_tpu.core.schemas import DEFAULT_SCHEMAS, Schemas
from filodb_tpu.core.store.api import (
    ColumnStore,
    InMemoryMetaStore,
    MetaStore,
    NullColumnStore,
)
from filodb_tpu.core.store.config import StoreConfig

log = logging.getLogger(__name__)


class TimeSeriesMemStore:
    def __init__(self, column_store: ColumnStore | None = None,
                 meta_store: MetaStore | None = None,
                 schemas: Schemas | None = None):
        self.column_store = column_store or NullColumnStore()
        self.meta_store = meta_store or InMemoryMetaStore()
        self.schemas = schemas or DEFAULT_SCHEMAS
        self._shards: dict[tuple[str, int], TimeSeriesShard] = {}

    # ---- lifecycle -------------------------------------------------------

    def setup(self, dataset: str, shard: int,
              store_config: StoreConfig | None = None) -> TimeSeriesShard:
        key = (dataset, shard)
        if key in self._shards:
            raise ValueError(f"shard already setup: {key}")
        s = TimeSeriesShard(dataset, shard, self.schemas,
                            store_config or StoreConfig(),
                            self.column_store, self.meta_store)
        self._shards[key] = s
        return s

    def get_shard(self, dataset: str, shard: int) -> TimeSeriesShard:
        return self._shards[(dataset, shard)]

    def shards_for(self, dataset: str) -> list[TimeSeriesShard]:
        return [s for (ds, _), s in sorted(self._shards.items()) if ds == dataset]

    def teardown(self, dataset: str, shard: int) -> None:
        self._shards.pop((dataset, shard), None)

    # ---- ingestion -------------------------------------------------------

    def ingest(self, dataset: str, shard: int, data: SomeData) -> int:
        return self._shards[(dataset, shard)].ingest(data)

    def ingest_stream(self, dataset: str, shard: int,
                      stream: Iterable[SomeData],
                      flush_stagger: int | None = None,
                      cancel=lambda: False) -> int:
        """Consume a container stream, interleaving round-robin group flushes
        every ``flush_stagger`` containers (the reference staggers flush tasks
        across the flush interval; here the cadence is container-count-based
        for determinism in tests, wall-clock in the server runtime)."""
        s = self._shards[(dataset, shard)]
        total = 0
        since_flush = 0
        for data in stream:
            if cancel():
                break
            total += s.ingest(data)
            since_flush += 1
            if flush_stagger and since_flush >= flush_stagger:
                s.flush_group(s.next_flush_group())
                since_flush = 0
        return total

    def recover_stream(self, dataset: str, shard: int,
                       stream: Iterable[SomeData],
                       checkpoint_interval: int = 0) -> Iterator[int]:
        """Replay a log stream from a recovery start offset, yielding progress
        offsets (reference ``recoverStream`` yields checkpoints back to the
        ingestion actor)."""
        s = self._shards[(dataset, shard)]
        n = 0
        for data in stream:
            s.ingest(data)
            n += 1
            if checkpoint_interval and n % checkpoint_interval == 0:
                yield data.offset
        yield s.latest_offset

    # ---- recovery --------------------------------------------------------

    def recover_index(self, dataset: str, shard: int) -> int:
        return self._shards[(dataset, shard)].recover_index()

    def recovery_start_offset(self, dataset: str, shard: int) -> int:
        return self._shards[(dataset, shard)].setup_watermarks_for_recovery()

    # ---- query surface ---------------------------------------------------

    def lookup_partitions(self, dataset: str, shard: int, filters,
                          start: int, end: int) -> list[int]:
        return self._shards[(dataset, shard)].lookup_partitions(filters, start, end)

    def label_values(self, dataset: str, label: str, filters=None,
                     start: int = 0, end: int | None = None) -> list[str]:
        out: set[str] = set()
        for s in self.shards_for(dataset):
            out.update(s.label_values(
                label, filters, start,
                end if end is not None else 9_223_372_036_854_775_807))
        return sorted(out)

    def label_names(self, dataset: str) -> list[str]:
        out: set[str] = set()
        for s in self.shards_for(dataset):
            out.update(s.label_names())
        return sorted(out)

    def flush_all(self, dataset: str) -> int:
        now = int(time.time() * 1000)
        return sum(s.flush_all(now) for s in self.shards_for(dataset))
