"""Per-series partition state: write buffers and the encoded chunk list.

Counterpart of the reference's ``TimeSeriesPartition``
(``core/src/main/scala/filodb.core/memstore/TimeSeriesPartition.scala:64,137,
233,252,303``): appending write buffers receive samples; when full (or at
flush), ``switch_buffers`` encodes them into an immutable compressed chunk
(``encodeOneChunkset``); ``make_flush_chunks`` hands not-yet-persisted chunks
to the column store. Out-of-order/duplicate timestamps within a partition are
dropped, as in the reference ingest path.

TPU-first redesign notes: buffers are preallocated numpy arrays (the analog of
the reference's off-heap ``WriteBufferPool`` appenders); the query path reads
whole chunks as dense arrays — there is no per-row reader abstraction because
the query engine consumes columns, not rows.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import ColumnType, Schema
from filodb_tpu.memory.chunk import Chunk, encode_chunk
from filodb_tpu.memory.codecs import HistogramColumn
from filodb_tpu.utils.metrics import Counter

# process-wide (reference keeps an untagged chunks-queried counter beside the
# per-shard one, ``TimeSeriesShard.scala:48``)
chunks_queried = Counter("memstore_chunks_queried")


@dataclass
class _Buffers:
    ts: np.ndarray
    cols: list  # ndarray per non-ts column; hist cols start as None (lazy nb)
    n: int = 0


def _measure_unreferenced_buf_rc() -> int:
    """Refcount an otherwise-unreferenced free-list buffer shows from
    inside ``_reusable``, measured on a sentinel through the identical
    call shape (list slot → obtain-local → method argument → getrefcount
    argument). Measured rather than hard-coded: CPython 3.11 moved call
    arguments into the callee frame while 3.10 copies them with an extra
    incref per Python-level call, so the constant is version-dependent."""
    import sys

    free = [object()]

    def probe(obj):
        return sys.getrefcount(obj)

    def obtain_shape():
        buf = free[0]
        return probe(buf)

    return obtain_shape()


_UNREFERENCED_BUF_RC = _measure_unreferenced_buf_rc()


class WriteBufferPool:
    """Recycles appender sets across partitions of one schema — the analog
    of reference ``WriteBufferPool.scala:1-92`` (pre-allocated reusable
    appenders), sized for series churn: at 1M-series scale with turnover,
    allocating fresh numpy buffers per created partition is measurable
    allocator pressure.

    Read-vs-reclaim safety: queries read partitions lock-free, so a buffer
    released at eviction could still be referenced by an in-flight reader.
    Reclamation is DETERMINISTIC (the role the reference's EvictionLock
    latch plays, ``doc/memory_safety.md``): a released buffer is re-issued
    only once nothing outside the pool references the buffer object or any
    array that reuse would mutate in place. CPython refcounts are exact and
    a numpy view pins its base array, so a reader stalled for any length of
    time (GC pause, ODP page-in, device compile) keeps the buffer out of
    circulation simply by still holding it — no wall-clock assumption."""

    # how many free-list entries obtain() probes per call: a buffer pinned
    # by a long reader must not wedge the whole pool behind it
    _PROBE = 8

    def __init__(self, schema: Schema, max_chunk_size: int, cap: int = 2048):
        self.schema = schema
        self.max_chunk_size = max_chunk_size
        self.cap = cap
        self._free: list[_Buffers] = []
        self.obtained = 0
        self.reused = 0
        self.blocked = 0  # probes skipped because a reader still held a ref
        self.released = 0  # buffers handed back (parked or not)

    def _reusable(self, buf: _Buffers) -> bool:
        """True when no reader can still observe a mutation of ``buf``.

        Expected refcounts when unreferenced: the buffer object is held by
        the free list, obtain()'s local, this call's argument passing, and
        getrefcount's argument — exactly ``_UNREFERENCED_BUF_RC``, measured
        at import because the per-call-level cost differs across CPython
        versions (3.11 moved arguments into the callee frame; 3.10 copies
        them, adding one count per Python-level call). Each
        in-place-mutated array is held only by its _Buffers field plus
        getrefcount's argument (= 2, +1 for the loop variable) — those are
        borrowed straight off the value stack, version-stable.
        Histogram/string columns are REPLACED (not mutated) at re-issue, so
        stale references to those can never observe new data and are not
        checked."""
        import sys
        if sys.getrefcount(buf) > _UNREFERENCED_BUF_RC:
            return False
        if sys.getrefcount(buf.ts) > 2:
            return False
        cols = self.schema.data.columns[1:]
        for ci in range(len(cols)):
            # index access, not zip: zip's yielded tuple would itself hold
            # a reference to the array for the duration of the loop body
            if cols[ci].ctype in (ColumnType.HISTOGRAM, ColumnType.STRING,
                                  ColumnType.MAP):
                continue
            data = buf.cols[ci]
            if data is not None and sys.getrefcount(data) > 3:
                return False
        return True

    def obtain(self, factory) -> _Buffers:
        self.obtained += 1
        for i in range(min(len(self._free), self._PROBE)):
            buf = self._free[i]
            if not self._reusable(buf):
                self.blocked += 1
                continue
            self._free.pop(i)
            self.reused += 1
            # ALL resets happen at re-issue, once provably unreferenced: a
            # released buffer stays bit-identical while any in-flight
            # reader still holds it
            buf.n = 0
            for ci, col in enumerate(self.schema.data.columns[1:]):
                if col.ctype == ColumnType.HISTOGRAM:
                    buf.cols[ci] = None  # bucket schemes vary per series
                elif col.ctype in (ColumnType.STRING, ColumnType.MAP):
                    buf.cols[ci] = [None] * self.max_chunk_size
            return buf
        return factory()

    @property
    def in_use(self) -> int:
        """Buffers currently held by live partitions — the memory
        watchdog's write-path pressure signal (``in_use / cap``)."""
        return max(0, self.obtained - self.released)

    def release(self, buf: _Buffers | None) -> None:
        """Park a buffer for later reuse. Deliberately does NOT touch the
        buffer's contents — see obtain()."""
        if buf is None:
            return
        self.released += 1
        if len(self._free) >= self.cap \
                or len(buf.ts) != self.max_chunk_size:
            return
        self._free.append(buf)


class TimeSeriesPartition:
    """One time series: label key + chunks + active write buffer."""

    __slots__ = ("part_id", "part_key", "schema", "max_chunk_size", "chunks",
                 "_buf", "_chunk_seq", "_flushed_id", "bucket_les", "shard",
                 "device_pages", "_dedup_floor", "buffer_pool", "_sc_cache")

    def __init__(self, part_id: int, part_key: PartKey, schema: Schema,
                 max_chunk_size: int = 400, shard: int = 0,
                 device_pages: bool = False,
                 buffer_pool: "WriteBufferPool | None" = None):
        self.part_id = part_id
        self.part_key = part_key
        self.schema = schema
        self.shard = shard
        self.max_chunk_size = max_chunk_size
        self.chunks: list[Chunk] = []  # sorted by start time
        self.buffer_pool = buffer_pool
        self._buf = buffer_pool.obtain(self._new_buffers) if buffer_pool \
            else self._new_buffers()
        self._chunk_seq = 0
        self._flushed_id = -1  # highest chunk id already persisted
        self.bucket_les: np.ndarray | None = None
        # encode device pages at chunk-seal time (decode-on-TPU query path)
        self.device_pages = device_pages
        # out-of-order floor seeded at recovery with the max persisted chunk
        # timestamp, so WAL replay of rows already flushed before a crash is
        # dropped instead of double-written (evicted chunks keep protecting
        # against re-ingest the same way)
        self._dedup_floor = -1

    def _new_buffers(self) -> _Buffers:
        cols = []
        for c in self.schema.data.columns[1:]:
            if c.ctype == ColumnType.DOUBLE:
                cols.append(np.empty(self.max_chunk_size, np.float64))
            elif c.ctype in (ColumnType.LONG, ColumnType.INT, ColumnType.TIMESTAMP):
                cols.append(np.empty(self.max_chunk_size, np.int64))
            elif c.ctype == ColumnType.HISTOGRAM:
                cols.append(None)  # allocated on first sample (bucket count)
            elif c.ctype in (ColumnType.STRING, ColumnType.MAP):
                cols.append([None] * self.max_chunk_size)
            else:
                raise ValueError(f"unsupported {c.ctype}")
        return _Buffers(np.empty(self.max_chunk_size, np.int64), cols)

    # ---- ingest ----------------------------------------------------------

    @property
    def latest_ts(self) -> int:
        if self._buf.n:
            return max(int(self._buf.ts[self._buf.n - 1]), self._dedup_floor)
        if self.chunks:
            return max(self.chunks[-1].end_time, self._dedup_floor)
        return self._dedup_floor

    def seed_dedup_floor(self, ts: int) -> None:
        """Raise the out-of-order floor (recovery: max persisted ts)."""
        if ts > self._dedup_floor:
            self._dedup_floor = ts

    @property
    def earliest_ts(self) -> int:
        if self.chunks:
            return self.chunks[0].start_time
        if self._buf.n:
            return int(self._buf.ts[0])
        return -1

    @property
    def num_samples(self) -> int:
        return sum(c.num_rows for c in self.chunks) + self._buf.n

    def ingest(self, ts: int, values: tuple) -> bool:
        """Add one sample. Returns False for dropped (out-of-order) samples."""
        if ts <= self.latest_ts:
            return False  # drop out-of-order / duplicate (reference semantics)
        b = self._buf
        i = b.n
        b.ts[i] = ts
        for ci, (col, v) in enumerate(zip(self.schema.data.columns[1:], values)):
            if col.ctype == ColumnType.HISTOGRAM:
                les, buckets = v  # (les float64[nb], cumulative counts int64[nb])
                buckets = np.asarray(buckets, np.int64)
                if b.cols[ci] is None or (
                        self.bucket_les is not None
                        and len(buckets) != b.cols[ci].shape[1]):
                    # bucket-scheme change forces a chunk switch
                    if b.cols[ci] is not None and b.n > 0:
                        self.switch_buffers()
                        b = self._buf
                        i = 0
                        b.ts[i] = ts
                    b.cols[ci] = np.zeros(
                        (self.max_chunk_size, len(buckets)), np.int64)
                self.bucket_les = np.asarray(les, np.float64)
                b.cols[ci][i] = buckets
            elif col.ctype in (ColumnType.STRING, ColumnType.MAP):
                b.cols[ci][i] = v
            else:
                b.cols[ci][i] = v
        b.n = i + 1
        if b.n >= self.max_chunk_size:
            self.switch_buffers()
        return True

    def switch_buffers(self) -> Chunk | None:
        """Encode the active buffer into an immutable chunk
        (reference ``switchBuffers`` → ``encodeOneChunkset``)."""
        b = self._buf
        if b.n == 0:
            return None
        cols = []
        for col, data in zip(self.schema.data.columns[1:], b.cols):
            if col.ctype == ColumnType.HISTOGRAM:
                rows = data[: b.n] if data is not None else np.zeros((b.n, 0), np.int64)
                cols.append(HistogramColumn(
                    self.bucket_les if self.bucket_les is not None
                    else np.zeros(rows.shape[1]), rows))
            elif col.ctype in (ColumnType.STRING, ColumnType.MAP):
                cols.append(data[: b.n])
            else:
                cols.append(data[: b.n])
        chunk = encode_chunk(self.schema, b.ts[: b.n], cols, self._chunk_seq)
        if self.device_pages:
            # ingest-time device-page encoding (no decode round trip)
            from filodb_tpu.query.engine.device_batch import attach_pages
            page_cols: dict = {}
            for ci, col in enumerate(self.schema.data.columns[1:]):
                if col.ctype == ColumnType.DOUBLE:
                    page_cols[ci + 1] = np.asarray(b.cols[ci][: b.n],
                                                   np.float64)
                elif col.ctype == ColumnType.HISTOGRAM \
                        and b.cols[ci] is not None:
                    les = (self.bucket_les if self.bucket_les is not None
                           else np.zeros(b.cols[ci].shape[1]))
                    page_cols[ci + 1] = (les, b.cols[ci][: b.n])
            attach_pages(chunk, b.ts[: b.n].copy(), page_cols)
        self._chunk_seq = (self._chunk_seq + 1) & 0xFFF
        # swap the buffer BEFORE publishing the chunk: a concurrent reader
        # (reads chunks first, then the buffer) can momentarily miss the
        # sealed samples but can never double-count them. The sealed buffer
        # is NOT returned to the pool — a lock-free reader may still hold
        # it; it is garbage-collected once unreferenced. Pool recycling
        # happens only at partition eviction/purge (quarantined).
        self._buf = self._new_buffers()
        self.chunks.append(chunk)
        return chunk

    def release_buffers(self) -> None:
        """Return the write buffer to the pool (eviction/purge path — the
        partition must never ingest again afterwards)."""
        if self.buffer_pool is not None:
            self.buffer_pool.release(self._buf)
            self._buf = _Buffers(np.empty(0, np.int64),
                                 [None] * len(self._buf.cols))

    # ---- flush -----------------------------------------------------------

    def make_flush_chunks(self, flush_buffer: bool = True) -> list[Chunk]:
        """Chunks not yet persisted; optionally seals the active buffer first
        (reference ``makeFlushChunks``)."""
        if flush_buffer:
            self.switch_buffers()
        return [c for c in self.chunks if c.id > self._flushed_id]

    def mark_flushed(self, up_to_id: int) -> None:
        self._flushed_id = max(self._flushed_id, up_to_id)

    @property
    def unflushed_count(self) -> int:
        return sum(1 for c in self.chunks if c.id > self._flushed_id) + (
            1 if self._buf.n else 0)

    # ---- read ------------------------------------------------------------

    def chunks_in_range(self, start: int, end: int,
                        include_buffer: bool = True) -> list[Chunk]:
        out = [c for c in self.chunks if c.end_time >= start and c.start_time <= end]
        if include_buffer and self._buf.n:
            b = self._buf
            bstart, bend = int(b.ts[0]), int(b.ts[b.n - 1])
            if bend >= start and bstart <= end:
                # materialize a transient chunk view of the write buffer
                out.append(self._buffer_chunk())
        return out

    def _buffer_chunk(self) -> Chunk:
        b = self._buf
        cols = []
        bles = self.bucket_les
        for col, data in zip(self.schema.data.columns[1:], b.cols):
            if col.ctype == ColumnType.HISTOGRAM:
                rows = data[: b.n] if data is not None else np.zeros((b.n, 0), np.int64)
                cols.append(HistogramColumn(
                    bles if bles is not None
                    else np.zeros(rows.shape[1]), rows))
            else:
                cols.append(data[: b.n])
        return encode_chunk(self.schema, b.ts[: b.n], cols, 0xFFF,
                            with_summary=False)

    def has_unpersisted_data(self) -> bool:
        """True while buffer samples or un-flushed chunks remain — such a
        partition must not be fully evicted (call after
        ``evict_flushed_chunks``, which leaves only un-flushed chunks)."""
        return self._buf.n > 0 or bool(self.chunks)

    def evict_flushed_chunks(self) -> int:
        """Drop already-persisted chunks from memory (they remain readable via
        on-demand paging). Reference: block reclaim / partition eviction."""
        before = len(self.chunks)
        evicted = [c for c in self.chunks if c.id <= self._flushed_id]
        if evicted:
            # keep rejecting re-ingest of timestamps the evicted chunks held
            self.seed_dedup_floor(max(c.end_time for c in evicted))
        self.chunks = [c for c in self.chunks if c.id > self._flushed_id]
        return before - len(self.chunks)

    def read_samples(self, start: int, end: int, col: int = None,
                     extra_chunks: list | None = None):  # noqa: C901
        """Decode all samples with start <= ts <= end for one value column.

        Returns (ts int64[n], values) where values is float64[n] or
        HistogramColumn. ``extra_chunks`` holds ODP-paged chunks merged in
        (deduped by chunk id).
        """
        if col is None:
            col = self.schema.data.value_column
        chunks = self.chunks_in_range(start, end, include_buffer=False)
        if extra_chunks:
            have = {c.id for c in chunks}
            for c in extra_chunks:
                if (c.id not in have and c.end_time >= start
                        and c.start_time <= end):
                    chunks.append(c)
            chunks.sort(key=lambda c: c.id)
        ts_parts, val_parts = [], []
        les = None
        chunks_queried.inc(len(chunks))
        for c in chunks:
            ts = c.decode_column(0)
            vals = c.decode_column(col)
            mask = (ts >= start) & (ts <= end)
            ts_parts.append(ts[mask])
            if isinstance(vals, HistogramColumn):
                les = vals.les
                val_parts.append(vals.rows[mask])
            else:
                val_parts.append(np.asarray(vals)[mask])
        # append the active write buffer directly (no encode round-trip);
        # snapshot the fill count ONCE — a concurrent ingester may append
        # while we read (readers see a consistent prefix)
        b = self._buf
        n = b.n
        if n:
            bts = b.ts[:n]
            mask = (bts >= start) & (bts <= end)
            if mask.any():
                ts_parts.append(bts[mask].copy())
                data = b.cols[col - 1]
                colspec = self.schema.data.columns[col]
                if colspec.ctype == ColumnType.HISTOGRAM:
                    bles = self.bucket_les
                    les = bles if bles is not None else les
                    rows = (data[:n] if data is not None
                            else np.zeros((n, 0), np.int64))
                    val_parts.append(rows[mask].copy())
                else:
                    val_parts.append(np.asarray(data[:n])[mask].copy())
        if not ts_parts:
            empty = np.array([], np.int64)
            return empty, (HistogramColumn(np.array([]), np.zeros((0, 0), np.int64))
                           if les is not None else np.array([], np.float64))
        ts = np.concatenate(ts_parts)
        order = np.argsort(ts, kind="stable")
        if les is not None:
            return ts[order], HistogramColumn(les, np.concatenate(val_parts)[order])
        return ts[order], np.concatenate(val_parts)[order]


class TracingTimeSeriesPartition(TimeSeriesPartition):
    """Debug partition that logs every ingest/encode event for targeted
    part keys (reference ``TracingTimeSeriesPartition``,
    ``TimeSeriesPartition.scala:494``; enabled per part-key via
    ``StoreConfig.trace_part_key_substrings``)."""

    __slots__ = ()

    def ingest(self, ts: int, values: tuple) -> bool:
        ok = super().ingest(ts, values)
        logging.getLogger("filodb_tpu.trace").info(
            "TRACE %s shard=%d ingest ts=%d values=%s accepted=%s",
            self.part_key, self.shard, ts, values, ok)
        return ok

    def switch_buffers(self):
        chunk = super().switch_buffers()
        if chunk is not None:
            logging.getLogger("filodb_tpu.trace").info(
                "TRACE %s shard=%d encoded chunk id=%d rows=%d bytes=%d",
                self.part_key, self.shard, chunk.id, chunk.num_rows,
                chunk.nbytes)
        return chunk
