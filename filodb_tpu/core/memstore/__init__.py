"""In-memory columnar memstore: shards, partitions, index, flush lifecycle.

Counterpart of reference ``core/src/main/scala/filodb.core/memstore/``.
"""
