"""Persistent part-key index snapshots: fast restart at high cardinality.

Counterpart of the reference's durable Lucene index
(``core/src/main/scala/filodb.core/memstore/PartKeyLuceneIndex.scala:38-42``
mmap directory + ``IndexBootstrapper``): instead of rebuilding 1M-series
postings by scanning part keys on every restart (~minutes), the shard
periodically serializes its index and restores it in one pass: the C++ core
exports/bootstraps the partition registry as one byte section, and postings
load as flat numpy arrays straight into the index's frozen tier (sorted
value tables + pid arrays — no per-value Python objects). PartKey objects
materialize lazily on first access.

Format (little-endian)::

    magic "FIDX4" | u32 n_pids | i64 snapshot_ms | i64 chunk_token
    | i64 pk_token
    u32 core_len | core section (shard_core_bootstrap layout:
        u32 klen | key | u32 hash | i64 floor | u8 alive | u8 ncols)*
    i32* key_len [n_pids]  (vectorized offset computation at load)
    u32 n_host | i32* host-backed pids (python partitions, e.g. histograms)
    i64* starts [n_pids] | i64* ends [n_pids]
    u32 n_labels | per label:
        u16 name_len | name | u32 nv
        u32 voff[nv+1] | value blob
        i64 poff[nv+1] | i32 pids[poff[nv]]
    u32 card_len | cardinality tracker state (json tree,
        O(shard-key prefixes))
"""

from __future__ import annotations

import struct

import numpy as np

from filodb_tpu.core.memstore.index import FrozenLabel

MAGIC = b"FIDX4"

_UNSET = object()


class LazyList:
    """List-alike materializing entries on first access — restart stays
    O(bytes) instead of O(series) Python objects; the first full iteration
    (flush/purge tick) amortizes materialization."""

    __slots__ = ("_items", "_make")

    def __init__(self, n: int, make):
        self._items = [_UNSET] * n
        self._make = make

    def __len__(self) -> int:
        return len(self._items)

    def __getitem__(self, i):
        v = self._items[i]
        if v is _UNSET:
            v = self._items[i] = self._make(i)
        return v

    def __setitem__(self, i, v):
        self._items[i] = v

    def append(self, v) -> None:
        self._items.append(v)

    def __iter__(self):
        for i in range(len(self._items)):
            yield self[i]


def save_snapshot(shard, chunk_token: int = -1, pk_token: int = -1,
                  snapshot_ms: int = 0) -> bytes:
    """Serialize a shard's index + partition registry. Tokens are the
    column store's update counters at capture time: restore replays only
    chunk-floor/part-key changes AFTER them."""
    from filodb_tpu.core.memstore.native_shard import (
        NativeBackedPartition,
        part_key_blob,
    )

    n = len(shard.partitions)
    out = bytearray()
    out += MAGIC
    out += struct.pack("<Iqqq", n, snapshot_ms, chunk_token, pk_token)

    if shard._native_core is not None:
        # the shard tracks host-backed pids; scanning partitions would
        # materialize every lazy wrapper under the write lock
        host_pids = sorted(shard._host_pids)
    else:
        host_pids = [pid for pid, p in enumerate(shard.partitions)
                     if p is not None
                     and not isinstance(p, NativeBackedPartition)]
    if shard._native_core is not None:
        core_sec, key_off, key_len = shard._native_core.export_entries(n)
        core_sec = bytearray(core_sec)
        # host-backed partitions keep their dedup floor on the Python side;
        # patch it over the (stale) native slot value
        for pid in host_pids:
            floor = getattr(shard.partitions[pid], "_dedup_floor", -1)
            struct.pack_into("<q", core_sec,
                             int(key_off[pid]) + int(key_len[pid]) + 4,
                             floor)
        key_len = np.ascontiguousarray(key_len, np.int32)
    else:
        core_sec = bytearray()
        key_len = np.zeros(n, np.int32)
        for pid in range(n):
            part = shard.partitions[pid]
            if part is None:
                core_sec += struct.pack("<IIqBB", 0, 0, -1, 0, 0)
                continue
            blob = part_key_blob(part.part_key)
            key_len[pid] = len(blob)
            core_sec += struct.pack("<I", len(blob))
            core_sec += blob
            core_sec += struct.pack("<IqBB", part.part_key.part_hash,
                                    getattr(part, "_dedup_floor", -1), 1,
                                    len(part.schema.data.columns) - 1)
    out += struct.pack("<I", len(core_sec))
    out += core_sec
    out += key_len.tobytes()
    out += struct.pack("<I", len(host_pids))
    out += np.asarray(host_pids, np.int32).tobytes()

    idx = shard.index
    out += np.ascontiguousarray(idx._start[:n], np.int64).tobytes()
    out += np.ascontiguousarray(idx._end[:n], np.int64).tobytes()

    labels = list(idx.frozen_labels())
    out += struct.pack("<I", len(labels))
    for name, fl in labels:
        nb = name.encode()
        out += struct.pack("<H", len(nb))
        out += nb
        out += struct.pack("<I", fl.nv)
        out += np.ascontiguousarray(fl.voff, np.uint32).tobytes()
        out += fl.vblob
        out += np.ascontiguousarray(fl.poff, np.int64).tobytes()
        out += np.ascontiguousarray(fl.pids, np.int32).tobytes()

    import json
    card = json.dumps(shard.cardinality.to_state()).encode()
    out += struct.pack("<I", len(card))
    out += card
    # evicted-partkey bloom (appended section; absent in older snapshots)
    bloom = json.dumps(shard.evicted_keys.state()).encode()
    out += struct.pack("<I", len(bloom))
    out += bloom
    return bytes(out)


def load_snapshot(shard, data: bytes) -> dict:
    """Restore a shard's index, partitions and native core from snapshot
    bytes. Returns {"pids", "snapshot_ms", "chunk_token", "pk_token"}.
    Requires an empty shard (fresh start)."""
    from filodb_tpu.core.memstore.native_shard import (
        NativeBackedPartition,
        part_key_from_blob,
    )

    assert data[:5] == MAGIC, "bad index snapshot"
    n, snapshot_ms, chunk_token, pk_token = struct.unpack_from("<Iqqq",
                                                               data, 5)
    off = 5 + struct.calcsize("<Iqqq")
    (core_len,) = struct.unpack_from("<I", data, off)
    off += 4
    core_sec = data[off : off + core_len]
    off += core_len
    key_len = np.frombuffer(data, np.int32, n, off)
    off += 4 * n
    (n_host,) = struct.unpack_from("<I", data, off)
    off += 4
    host_pids = set(np.frombuffer(data, np.int32, n_host, off).tolist())
    off += 4 * n_host
    starts = np.frombuffer(data, np.int64, n, off)
    off += 8 * n
    ends = np.frombuffer(data, np.int64, n, off)
    off += 8 * n

    # native core: one bulk call over the raw entry section
    if shard._native_core is not None:
        got = shard._native_core.bootstrap(core_sec)
        assert got == n, (got, n)

    # partition wrappers; PartKeys stay lazy (blob slices). Entry offsets
    # come from the stored key-length array (vectorized, no header parse).
    schemas = shard.schemas
    max_chunk = shard.config.max_chunk_size
    shard_num = shard.shard_num
    core = shard._native_core
    entry_sizes = key_len.astype(np.int64) + 18  # u32 + key + 14 tail bytes
    blob_starts = np.concatenate(([0], np.cumsum(entry_sizes)))[:-1] + 4
    kl_list = key_len.tolist()
    bs_list = blob_starts.tolist()

    def make_blob(i: int):
        ln = kl_list[i]
        return core_sec[bs_list[i] : bs_list[i] + ln] if ln else None

    blobs = LazyList(n, make_blob)
    if core is not None:
        def make_part(i: int):
            b = blobs[i]
            if b is None:
                return None
            return NativeBackedPartition(core, i, max_chunk_size=max_chunk,
                                         shard=shard_num, key_blob=b,
                                         schemas=schemas)

        parts = LazyList(n, make_part)
    else:
        parts = LazyList(n, lambda i: None)
    # host-backed partitions (histograms) and the no-native fallback get
    # eager python partitions
    from filodb_tpu.core.memstore.partition import TimeSeriesPartition
    host_iter = host_pids if core is not None else \
        [pid for pid in range(n) if kl_list[pid]]
    for pid in host_iter:
        blob = blobs[pid]
        if blob is None:
            continue
        key = part_key_from_blob(blob, schemas)
        p = TimeSeriesPartition(pid, key, schemas[key.schema], max_chunk,
                                shard_num,
                                device_pages=shard.config.device_pages)
        (floor,) = struct.unpack_from("<q", core_sec,
                                      bs_list[pid] + kl_list[pid] + 4)
        if floor > -1:
            p.seed_dedup_floor(floor)
        shard._by_key[key] = pid
        shard._host_pids.add(pid)
        parts[pid] = p
    shard.partitions = parts

    # index: bounds arrays + lazy blobs + frozen postings (numpy slices)
    idx = shard.index
    idx._schemas = schemas
    idx._part_keys = LazyList(n, make_blob)
    cap = max(len(idx._start), n, 1)
    idx._start = np.full(cap, np.iinfo(np.int64).max, np.int64)
    idx._end = np.full(cap, np.iinfo(np.int64).max, np.int64)
    idx._start[:n] = starts
    idx._end[:n] = ends
    # live count from the bounds array (tombstones carry INGESTING starts)
    idx._count = int(np.count_nonzero(starts != np.iinfo(np.int64).max))

    (n_labels,) = struct.unpack_from("<I", data, off)
    off += 4
    for _ in range(n_labels):
        (nl,) = struct.unpack_from("<H", data, off)
        off += 2
        name = data[off : off + nl].decode()
        off += nl
        (nv,) = struct.unpack_from("<I", data, off)
        off += 4
        voff = np.frombuffer(data, np.uint32, nv + 1, off)
        off += 4 * (nv + 1)
        vblob = data[off : off + int(voff[-1])]
        off += int(voff[-1])
        poff = np.frombuffer(data, np.int64, nv + 1, off)
        off += 8 * (nv + 1)
        npids = int(poff[-1])
        pids = np.frombuffer(data, np.int32, npids, off)
        off += 4 * npids
        idx.load_frozen(name, FrozenLabel(voff, vblob, poff, pids))

    import json
    (card_len,) = struct.unpack_from("<I", data, off)
    off += 4
    shard.cardinality.load_state(
        json.loads(data[off : off + card_len].decode()))
    off += card_len
    if off + 4 <= len(data):  # evicted-partkey bloom (newer snapshots)
        from filodb_tpu.utils.bloom import BloomFilter
        (bl,) = struct.unpack_from("<I", data, off)
        off += 4
        shard.evicted_keys = BloomFilter.from_state(
            json.loads(data[off : off + bl].decode()))
        off += bl
    return {"pids": n, "snapshot_ms": snapshot_ms,
            "chunk_token": chunk_token, "pk_token": pk_token}
