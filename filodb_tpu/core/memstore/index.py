"""Part-key tag inverted index.

Counterpart of the reference's ``PartKeyLuceneIndex``
(``core/src/main/scala/filodb.core/memstore/PartKeyLuceneIndex.scala:38-42,71``):
per shard, maps label=value postings to partition ids, tracks per-partition
[startTime, endTime] for time-bounded lookups, supports Equals / NotEquals /
regex / In filters (``leafFilter:455``, ``partIdsFromFilters:494``) and label
introspection (labelValues / indexNames).

Rebuilt TPU-first as a pure in-process structure: postings are Python sets
over int part-ids (dense, starting at 0), time bounds are parallel numpy
arrays — no Lucene, no mmap. Regex filters scan the per-label value
dictionary, which is tiny relative to the postings.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from filodb_tpu.core.filters import ColumnFilter, Equals, In
from filodb_tpu.core.partkey import PartKey

_INIT_CAP = 1024
# endTime for a still-ingesting partition (reference Long.MaxValue semantics)
INGESTING = np.iinfo(np.int64).max


class PartKeyIndex:
    """Tag index for one shard."""

    def __init__(self):
        # label -> value -> set of partIds
        self._postings: dict[str, dict[str, set[int]]] = defaultdict(
            lambda: defaultdict(set)
        )
        self._part_keys: list[PartKey | None] = []
        self._start: np.ndarray = np.full(_INIT_CAP, np.iinfo(np.int64).max, np.int64)
        self._end: np.ndarray = np.full(_INIT_CAP, np.iinfo(np.int64).max, np.int64)
        self._count = 0

    def __len__(self) -> int:
        return self._count

    def _ensure(self, part_id: int) -> None:
        while part_id >= len(self._start):
            self._start = np.concatenate([self._start,
                                          np.full(len(self._start), INGESTING)])
            self._end = np.concatenate([self._end,
                                        np.full(len(self._end), INGESTING)])
        while part_id >= len(self._part_keys):
            self._part_keys.append(None)

    def add_part_key(self, part_id: int, key: PartKey, start_time: int,
                     end_time: int = INGESTING) -> None:
        self._ensure(part_id)
        if self._part_keys[part_id] is None:
            self._count += 1
        self._part_keys[part_id] = key
        self._start[part_id] = start_time
        self._end[part_id] = end_time
        for name, value in key.labels:
            self._postings[name][value].add(part_id)

    def remove_part_key(self, part_id: int) -> None:
        key = self._part_keys[part_id]
        if key is None:
            return
        for name, value in key.labels:
            s = self._postings[name].get(value)
            if s is not None:
                s.discard(part_id)
                if not s:
                    del self._postings[name][value]
        self._part_keys[part_id] = None
        self._start[part_id] = INGESTING
        self._end[part_id] = INGESTING
        self._count -= 1

    def update_end_time(self, part_id: int, end_time: int) -> None:
        self._end[part_id] = end_time

    def start_time(self, part_id: int) -> int:
        return int(self._start[part_id])

    def end_time(self, part_id: int) -> int:
        return int(self._end[part_id])

    def part_key(self, part_id: int) -> PartKey | None:
        return self._part_keys[part_id] if part_id < len(self._part_keys) else None

    def _ids_for_filter(self, f: ColumnFilter) -> set[int] | None:
        """Postings for one filter; None means 'all' (negative filters)."""
        by_value = self._postings.get(f.column)
        flt = f.filter
        if isinstance(flt, Equals):
            if by_value is None:
                return set()
            return set(by_value.get(flt.value, ()))
        if isinstance(flt, In):
            if by_value is None:
                return set()
            out: set[int] = set()
            for v in flt.values:
                out |= by_value.get(v, set())
            return out
        # regex / not-equals: scan the value dictionary for this label
        if by_value is None:
            return None  # label absent everywhere: negative filters pass all
        out = set()
        for value, ids in by_value.items():
            if flt.matches(value):
                out |= ids
        return out

    def part_ids_from_filters(
        self, filters: list[ColumnFilter], start_time: int, end_time: int
    ) -> list[int]:
        """Intersect filter postings, then apply the time overlap predicate
        (reference ``partIdsFromFilters:494``)."""
        result: set[int] | None = None
        negatives: list[ColumnFilter] = []
        for f in filters:
            flt = f.filter
            if isinstance(flt, (Equals, In)):
                ids = self._ids_for_filter(f)
                result = ids if result is None else result & ids
                if not result:
                    return []
            else:
                negatives.append(f)
        if result is None:
            result = {i for i, k in enumerate(self._part_keys) if k is not None}
        for f in negatives:
            # match semantics: absent label == "" for negative/regex filters
            keep = set()
            for pid in result:
                key = self._part_keys[pid]
                if key is not None and f.filter.matches(key.label_map.get(f.column, "")):
                    keep.add(pid)
            result = keep
        if not result:
            return []
        ids = np.fromiter(result, dtype=np.int64)
        ok = (self._start[ids] <= end_time) & (self._end[ids] >= start_time)
        return sorted(int(i) for i in ids[ok])

    def label_names(self) -> list[str]:
        return sorted(k for k, v in self._postings.items() if v)

    def label_values(self, label: str,
                     filters: list[ColumnFilter] | None = None,
                     start_time: int = 0, end_time: int = INGESTING) -> list[str]:
        by_value = self._postings.get(label)
        if not by_value:
            return []
        if not filters:
            return sorted(by_value.keys())
        ids = set(self.part_ids_from_filters(filters, start_time, end_time))
        return sorted(v for v, pids in by_value.items() if pids & ids)
