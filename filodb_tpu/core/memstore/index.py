"""Part-key tag inverted index.

Counterpart of the reference's ``PartKeyLuceneIndex``
(``core/src/main/scala/filodb.core/memstore/PartKeyLuceneIndex.scala:38-42,71``):
per shard, maps label=value postings to partition ids, tracks per-partition
[startTime, endTime] for time-bounded lookups, supports Equals / NotEquals /
regex / In filters (``leafFilter:455``, ``partIdsFromFilters:494``) and label
introspection (labelValues / indexNames).

Rebuilt TPU-first as a two-tier structure (no Lucene, no mmap):

- **frozen tier**: per label, a sorted value table (offset-indexed bytes) and
  flat sorted pid arrays — loaded as zero-copy numpy slices from an index
  snapshot; lookups are a binary search + array slice, and filter
  intersections are ``np.intersect1d`` over sorted arrays (the
  roaring-bitmap analog, vectorized instead of pointer-chasing sets).
- **tail tier**: plain ``dict → set`` postings for keys added since the last
  freeze/restore; merged into query results and folded into the next
  snapshot.

Regex/negative filters scan the per-label value table, which is tiny
relative to the postings; non-empty-matching regexes use the value scan as a
positive filter (Lucene's regexp query analog).
"""

from __future__ import annotations

from bisect import bisect_left
from collections import defaultdict

import numpy as np

from filodb_tpu.core.filters import ColumnFilter, Equals, EqualsRegex, In
from filodb_tpu.core.partkey import PartKey

_INIT_CAP = 1024
# endTime for a still-ingesting partition (reference Long.MaxValue semantics)
INGESTING = np.iinfo(np.int64).max
_EMPTY = np.array([], np.int64)


class FrozenLabel:
    """One label's frozen postings: sorted value table + flat pid arrays."""

    __slots__ = ("voff", "vblob", "poff", "pids")

    def __init__(self, voff: np.ndarray, vblob: bytes, poff: np.ndarray,
                 pids: np.ndarray):
        self.voff = voff    # u32 [nv+1] offsets into vblob
        self.vblob = vblob  # concatenated value bytes, sorted
        self.poff = poff    # i64 [nv+1] offsets into pids
        self.pids = pids    # i32, sorted within each value's slice

    @property
    def nv(self) -> int:
        return len(self.voff) - 1

    def value(self, vi: int) -> bytes:
        return self.vblob[self.voff[vi] : self.voff[vi + 1]]

    def find(self, value: bytes) -> int:
        """Binary search the sorted value table; -1 when absent."""
        lo, hi = 0, self.nv
        while lo < hi:
            mid = (lo + hi) // 2
            if self.value(mid) < value:
                lo = mid + 1
            else:
                hi = mid
        if lo < self.nv and self.value(lo) == value:
            return lo
        return -1

    def pid_slice(self, vi: int) -> np.ndarray:
        return self.pids[self.poff[vi] : self.poff[vi + 1]]

    def prefix_range(self, prefix: bytes) -> tuple[int, int]:
        """[lo, hi) of sorted value-table indexes starting with ``prefix``
        — binary search against the prefix and its byte-successor."""
        def bisect(target: bytes) -> int:
            lo, hi = 0, self.nv
            while lo < hi:
                mid = (lo + hi) // 2
                if self.value(mid) < target:
                    lo = mid + 1
                else:
                    hi = mid
            return lo

        start = bisect(prefix)
        succ = bytearray(prefix)
        while succ and succ[-1] == 0xFF:
            succ.pop()
        if not succ:
            return start, self.nv
        succ[-1] += 1
        return start, bisect(bytes(succ))

    def values(self):
        for vi in range(self.nv):
            yield self.value(vi), vi

    @staticmethod
    def build(pairs: list) -> "FrozenLabel":
        """From (value_bytes, sorted pid sequence) pairs (any order).
        Sequences may be arrays or lists; the flat pid array is built with
        one fromiter pass (1M tiny per-value concatenations would dominate
        snapshot writes at high cardinality)."""
        from itertools import chain
        pairs.sort(key=lambda t: t[0])
        nv = len(pairs)
        vlens = np.fromiter((len(vb) for vb, _ in pairs), np.uint32, nv)
        plens = np.fromiter((len(a) for _, a in pairs), np.int64, nv)
        voff = np.zeros(nv + 1, np.uint32)
        np.cumsum(vlens, out=voff[1:])
        poff = np.zeros(nv + 1, np.int64)
        np.cumsum(plens, out=poff[1:])
        vblob = b"".join(vb for vb, _ in pairs)
        total = int(poff[-1])
        pids = np.fromiter(chain.from_iterable(a for _, a in pairs),
                           np.int32, total)
        return FrozenLabel(voff, vblob, poff, pids)


def _filter_cache_key(flt):
    """Stable per-predicate memo key for value-table scans (None = no memo)."""
    from filodb_tpu.core.filters import NotEquals, NotEqualsRegex
    if isinstance(flt, EqualsRegex):
        return ("re", flt.pattern)
    if isinstance(flt, NotEqualsRegex):
        return ("nre", flt.pattern)
    if isinstance(flt, NotEquals):
        return ("ne", flt.value)
    return None


def _intersect_sorted(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Intersection of two SORTED-unique id arrays via binary search —
    ``np.intersect1d`` re-sorts its inputs every call, which dominated
    regex-filter queries (all postings here are already sorted)."""
    if not len(a) or not len(b):
        return a[:0]
    if len(a) > len(b):
        a, b = b, a
    pos = np.searchsorted(b, a)
    pos[pos == len(b)] = len(b) - 1
    return a[b[pos] == a]


def _from_set(s: set[int]) -> np.ndarray:
    a = np.fromiter(s, np.int64, len(s))
    a.sort()
    return a


class PartKeyIndex:
    """Tag index for one shard."""

    def __init__(self, schemas=None):
        import os

        # schema registry for lazy blob -> PartKey materialization
        self._schemas = schemas
        # tail tier: label -> value -> set of partIds (new since freeze)
        self._tail: dict[str, dict[str, set[int]]] = defaultdict(
            lambda: defaultdict(set)
        )
        # frozen tier from a snapshot restore: label -> FrozenLabel
        self._frozen: dict[str, FrozenLabel] = {}
        # pids removed since freeze (may still appear in frozen arrays)
        self._deleted: set[int] = set()
        # entries are PartKey objects, or raw key blobs (bytes) after a
        # snapshot restore — materialized lazily via part_key()
        self._part_keys: list[PartKey | bytes | None] = []
        self._start: np.ndarray = np.full(_INIT_CAP, INGESTING, np.int64)
        self._end: np.ndarray = np.full(_INIT_CAP, INGESTING, np.int64)
        self._count = 0
        # native postings store (C++ TagIndex): owns label→value→pid postings
        # for series-create/equals/intersect hot paths; Python keeps times,
        # tombstones and key blobs. Falls back to the pure-Python tiers when
        # the toolchain is absent (or FILODB_NO_NATIVE_INDEX is set).
        self._nt = None
        if not os.environ.get("FILODB_NO_NATIVE_INDEX"):
            try:
                from filodb_tpu.memory.native import TagIndexNative
                self._nt = TagIndexNative()
            except Exception:
                self._nt = None
        # (label, predicate-key) -> (generation, ids): regex/value-scan memo
        self._vscan_cache: dict = {}
        # filters tuple -> (blob, blob addr, npairs): equals-query memo
        self._pairs_cache: dict = {}
        # (starts ref, ends ref, starts addr, ends addr, len) memo
        self._bounds_addr: tuple | None = None

    def __len__(self) -> int:
        return self._count

    @property
    def ram_bytes(self) -> int:
        """Approximate resident bytes of the index tiers (reference
        ``indexRamBytes`` gauge): time arrays + tail postings + native
        postings store."""
        n = self._start.nbytes + self._end.nbytes
        for vals in self._tail.values():
            for pids in vals.values():
                n += 64 + 8 * len(pids)
        for fl in self._frozen.values():
            n += len(fl.vblob) + fl.voff.nbytes + fl.poff.nbytes \
                + fl.pids.nbytes
        if self._nt is not None:
            try:
                n += int(self._nt.ram_bytes())
            except Exception:
                pass
        return n

    def _ensure(self, part_id: int) -> None:
        while part_id >= len(self._start):
            self._start = np.concatenate([self._start,
                                          np.full(len(self._start), INGESTING)])
            self._end = np.concatenate([self._end,
                                        np.full(len(self._end), INGESTING)])
        while part_id >= len(self._part_keys):
            self._part_keys.append(None)

    def add_part_key(self, part_id: int, key: PartKey, start_time: int,
                     end_time: int = INGESTING) -> None:
        self._ensure(part_id)
        if self._part_keys[part_id] is None:
            self._count += 1
        self._part_keys[part_id] = key
        self._start[part_id] = start_time
        self._end[part_id] = end_time
        if self._nt is not None:
            if part_id in self._deleted:
                # pid re-created after a remove: stale postings for the old
                # key would resurrect under a new key — purge them first
                self._nt.purge_pid(part_id)
            self._deleted.discard(part_id)
            from filodb_tpu.core.memstore.native_shard import part_key_blob
            self._nt.add(part_id, part_key_blob(key))
            return
        self._deleted.discard(part_id)
        for name, value in key.labels:
            self._tail[name][value].add(part_id)

    def add_part_key_blob(self, part_id: int, key: PartKey, blob: bytes,
                          start_time: int,
                          end_time: int = INGESTING) -> None:
        """Register postings from ``key`` but keep only the canonical blob
        in the key table (materialized lazily on demand): at high
        cardinality per-series PartKey objects dominate resident memory."""
        if self._nt is not None:
            self._ensure(part_id)
            if self._part_keys[part_id] is None:
                self._count += 1
            self._start[part_id] = start_time
            self._end[part_id] = end_time
            if part_id in self._deleted:
                self._nt.purge_pid(part_id)
                self._deleted.discard(part_id)
            self._nt.add(part_id, blob)
            self._part_keys[part_id] = blob
            return
        self.add_part_key(part_id, key, start_time, end_time)
        self._part_keys[part_id] = blob

    def remove_part_key(self, part_id: int) -> None:
        if self._nt is not None:
            if part_id >= len(self._part_keys) \
                    or self._part_keys[part_id] is None:
                return
            self._deleted.add(part_id)  # postings masked on query
            self._part_keys[part_id] = None
            self._start[part_id] = INGESTING
            self._end[part_id] = INGESTING
            self._count -= 1
            return
        key = self.part_key(part_id)
        if key is None:
            return
        for name, value in key.labels:
            by_value = self._tail.get(name)
            if by_value is not None:
                s = by_value.get(value)
                if s is not None:
                    s.discard(part_id)
                    if not s:
                        del by_value[value]
        self._deleted.add(part_id)  # masks any frozen postings
        self._part_keys[part_id] = None
        self._start[part_id] = INGESTING
        self._end[part_id] = INGESTING
        self._count -= 1

    def update_end_time(self, part_id: int, end_time: int) -> None:
        self._end[part_id] = end_time

    def set_start_time(self, part_id: int, start_time: int) -> None:
        self._start[part_id] = start_time

    def pid_for_exact_key(self, key: PartKey, blob: bytes,
                          exclude: int = -1) -> int | None:
        """Find a live pid whose part key is byte-identical to ``blob``
        (evicted-series identity restore). Label-equals intersection
        narrows candidates; blob equality rejects superset-label matches."""
        from filodb_tpu.core.filters import Equals
        filters = [ColumnFilter(k, Equals(v)) for k, v in key.labels]
        for pid in self.part_ids_from_filters(filters, 0, INGESTING):
            if pid == exclude:
                continue
            stored = self._part_keys[pid] \
                if pid < len(self._part_keys) else None
            if stored is None:
                continue
            if isinstance(stored, bytes):
                if stored == blob:
                    return pid
            else:
                from filodb_tpu.core.memstore.native_shard import (
                    part_key_blob,
                )
                if part_key_blob(stored) == blob:
                    return pid
        return None

    def start_time(self, part_id: int) -> int:
        return int(self._start[part_id])

    def end_time(self, part_id: int) -> int:
        return int(self._end[part_id])

    def part_key(self, part_id: int) -> PartKey | None:
        if part_id >= len(self._part_keys):
            return None
        k = self._part_keys[part_id]
        if isinstance(k, bytes):  # lazy blob from a snapshot restore
            from filodb_tpu.core.memstore.native_shard import (
                part_key_from_blob,
            )
            k = part_key_from_blob(k, self._schemas)
            self._part_keys[part_id] = k
        return k

    # ---- filter evaluation ----------------------------------------------

    def _equals_ids(self, col: str, value: str) -> np.ndarray:
        if self._nt is not None:
            return self._nt.equals(col, value).astype(np.int64)
        parts = []
        fr = self._frozen.get(col)
        if fr is not None:
            vi = fr.find(value.encode())
            if vi >= 0:
                parts.append(fr.pid_slice(vi).astype(np.int64))
        tail = self._tail.get(col)
        if tail is not None:
            s = tail.get(value)
            if s:
                parts.append(_from_set(s))
        if not parts:
            return _EMPTY
        if len(parts) == 1:
            return parts[0]
        return np.unique(np.concatenate(parts))

    def _value_scan_ids(self, col: str, match,
                        cache_key=None, prefix: str | None = None
                        ) -> np.ndarray:
        """Union postings of every value matching the predicate. Native
        path memoizes per (label, predicate) keyed on the postings
        generation — dashboards repeat the same regex scans. ``prefix``
        (a literal regex prefix, see ``filters.regex_plan``) narrows the
        candidate set before the regex runs: binary-searched range on the
        sorted frozen table, cheap ``startswith`` pre-filter elsewhere."""
        if self._nt is not None:
            gen = self._nt.generation
            ck = (col, cache_key) if cache_key is not None else None
            if ck is not None:
                hit = self._vscan_cache.get(ck)
                if hit is not None and hit[0] == gen:
                    return hit[1]
            values = self._nt.values(col)
            if prefix:
                cand = ((i, v) for i, v in enumerate(values)
                        if v.startswith(prefix))
            else:
                cand = enumerate(values)
            vids = np.fromiter((i for i, v in cand if match(v)), np.int32)
            ids = self._nt.union_values(col, vids).astype(np.int64) \
                if len(vids) else _EMPTY
            if ck is not None:
                if len(self._vscan_cache) >= 128:
                    self._vscan_cache.pop(next(iter(self._vscan_cache)))
                self._vscan_cache[ck] = (gen, ids)
            return ids
        parts = []
        fr = self._frozen.get(col)
        if fr is not None:
            if prefix:
                lo, hi = fr.prefix_range(prefix.encode())
                vrange = ((fr.value(vi), vi) for vi in range(lo, hi))
            else:
                vrange = fr.values()
            for vb, vi in vrange:
                if match(vb.decode()):
                    parts.append(fr.pid_slice(vi).astype(np.int64))
        tail = self._tail.get(col)
        if tail is not None:
            for value, s in tail.items():
                if prefix and not value.startswith(prefix):
                    continue
                if s and match(value):
                    parts.append(_from_set(s))
        if not parts:
            return _EMPTY
        return np.unique(np.concatenate(parts))

    def _ids_for_filter(self, f: ColumnFilter) -> np.ndarray:
        flt = f.filter
        if isinstance(flt, Equals):
            return self._equals_ids(f.column, flt.value)
        if isinstance(flt, In):
            parts = [self._equals_ids(f.column, v) for v in flt.values]
            parts = [p for p in parts if len(p)]
            if not parts:
                return _EMPTY
            return np.unique(np.concatenate(parts))
        if isinstance(flt, EqualsRegex):
            # FastRegexMatcher-style rewriting: literals and literal
            # alternations become postings lookups; a literal prefix
            # narrows the value scan (reference leans on Lucene regex
            # automata, PartKeyLuceneIndex.scala:455)
            from filodb_tpu.core.filters import regex_plan
            kind, arg = regex_plan(flt.pattern)
            if kind == "literal":
                return self._equals_ids(f.column, arg)
            if kind == "alts":
                parts = [self._equals_ids(f.column, v) for v in arg]
                parts = [p for p in parts if len(p)]
                if not parts:
                    return _EMPTY
                return np.unique(np.concatenate(parts))
            return self._value_scan_ids(f.column, flt.matches,
                                        cache_key=_filter_cache_key(flt),
                                        prefix=arg if kind == "prefix"
                                        else None)
        # NotEqualsRegex/NotEquals that can't match an absent label ("":
        # doesn't match): the per-label value scan is a sound positive filter
        return self._value_scan_ids(f.column, flt.matches,
                                    cache_key=_filter_cache_key(flt))

    def _label_all_ids(self, col: str) -> np.ndarray:
        """Every pid that has ANY value for this label."""
        if self._nt is not None:
            return self._nt.label_all(col).astype(np.int64)
        parts = []
        fr = self._frozen.get(col)
        if fr is not None and len(fr.pids):
            parts.append(np.unique(fr.pids).astype(np.int64))
        tail = self._tail.get(col)
        if tail is not None:
            for s in tail.values():
                if s:
                    parts.append(_from_set(s))
        if not parts:
            return _EMPTY
        return np.unique(np.concatenate(parts)) if len(parts) > 1 \
            else parts[0]

    def _all_live_ids(self) -> np.ndarray:
        # live entries have real start bounds (tombstones carry INGESTING) —
        # no key materialization needed
        n = len(self._part_keys)
        return np.flatnonzero(self._start[:n] != INGESTING).astype(np.int64)

    def _ids_for_filter_set(self, f: ColumnFilter) -> set[int]:
        """Tail-only postings as a set (fast path: nothing frozen)."""
        by_value = self._tail.get(f.column)
        flt = f.filter
        if by_value is None:
            return set()
        if isinstance(flt, Equals):
            return by_value.get(flt.value) or set()
        if isinstance(flt, In):
            out: set[int] = set()
            for v in flt.values:
                out |= by_value.get(v, set())
            return out
        out = set()
        for value, ids in by_value.items():
            if flt.matches(value):
                out |= ids
        return out

    def _native_query_prep(self, key: tuple):
        """(pairs_entry, bounds_snapshot) for the native query fast paths
        — memoized encoded pair buffers + raw bounds addresses."""
        ent = self._pairs_cache.get(key)
        if ent is None:
            from filodb_tpu.memory.native import TagIndexNative
            blob = TagIndexNative.encode_pairs(list(key))
            ent = (blob, TagIndexNative.addr_of(blob), len(key))
            if len(self._pairs_cache) >= 256:
                self._pairs_cache.pop(next(iter(self._pairs_cache)))
            self._pairs_cache[key] = ent
        ba = self._bounds_addr
        if ba is None or ba[0] is not self._start:
            ba = self._bounds_addr = (
                self._start, self._end, self._start.ctypes.data,
                self._end.ctypes.data, len(self._start))
        return ent, ba

    def part_ids_from_filters(
        self, filters: list[ColumnFilter], start_time: int, end_time: int
    ) -> list[int]:
        """Intersect filter postings, then apply the time overlap predicate
        (reference ``partIdsFromFilters:494``). Set ops while everything is
        in the mutable tail; sorted-array ops once a frozen tier exists;
        pure-Equals batches intersect natively (galloping, one C++ call)."""
        if self._nt is not None and not self._deleted and filters \
                and all(type(f.filter) is Equals for f in filters):
            # all-Equals fast path: intersection + time predicate in one
            # native call (the dominant query shape — shard-key lookups);
            # encoded pair buffers and raw bounds addresses are cached
            key = tuple((f.column, f.filter.value) for f in filters)
            ent, ba = self._native_query_prep(key)
            return self._nt.query_equals(ent[1], ent[2], ba[2], ba[3],
                                         ba[4], start_time, end_time)
        if self._nt is not None and not self._deleted and filters:
            # equals + positive-regex fast path: cached regex postings ride
            # into the native call as a sorted allow-list; intersection AND
            # the time predicate run in one C++ pass
            eqs = [f for f in filters if type(f.filter) is Equals]
            regs = [f for f in filters if isinstance(f.filter, EqualsRegex)
                    and not f.filter.matches("")]
            if regs and len(eqs) + len(regs) == len(filters):
                allow = None
                for f in regs:
                    ids = self._ids_for_filter(f)
                    allow = ids if allow is None \
                        else _intersect_sorted(allow, ids)
                    if not len(allow):
                        return []
                key = tuple((f.column, f.filter.value) for f in eqs)
                ent, ba = self._native_query_prep(key)
                return self._nt.query_equals_allow(
                    ent[1], ent[2], allow, ba[2], ba[3], ba[4],
                    start_time, end_time)
        if self._nt is None and not self._frozen:
            return self._part_ids_set_path(filters, start_time, end_time)
        result: np.ndarray | None = None
        negatives: list[ColumnFilter] = []
        eq_pairs: list[tuple[str, str]] = []
        others: list[ColumnFilter] = []
        for f in filters:
            flt = f.filter
            if self._nt is not None and isinstance(flt, Equals):
                eq_pairs.append((f.column, flt.value))
            elif isinstance(flt, (Equals, In)) or (
                    isinstance(flt, EqualsRegex) and not flt.matches("")):
                others.append(f)
            else:
                negatives.append(f)
        if eq_pairs:
            result = self._nt.intersect_equals(eq_pairs).astype(np.int64)
            if not len(result):
                return []
        for f in others:
            ids = self._ids_for_filter(f)
            result = ids if result is None \
                else _intersect_sorted(result, ids)
            if not len(result):
                return []
        if result is None:
            result = self._all_live_ids()
        if self._deleted and len(result):
            dead = _from_set(self._deleted)
            result = result[~np.isin(result, dead, assume_unique=True)]
        for f in negatives:
            # match semantics: absent label == "" for negative/regex
            # filters. Evaluated against the label's VALUE TABLE (frozen +
            # tail) — never by materializing per-series keys: keep pids
            # whose value matches, plus pids lacking the label entirely
            # when the filter matches "".
            if not len(result):
                break
            matched = self._value_scan_ids(
                f.column, f.filter.matches,
                cache_key=_filter_cache_key(f.filter))
            keep = result[np.isin(result, matched)] if len(matched) \
                else result[:0]
            if f.filter.matches(""):
                has_label = self._label_all_ids(f.column)
                absent = result[~np.isin(result, has_label)] \
                    if len(has_label) else result
                keep = np.union1d(keep, absent)
            result = keep
        if not len(result):
            return []
        ok = (self._start[result] <= end_time) & (self._end[result] >= start_time)
        return result[ok].tolist()

    def _part_ids_set_path(self, filters, start_time, end_time) -> list[int]:
        result: set[int] | None = None
        negatives: list[ColumnFilter] = []
        for f in filters:
            flt = f.filter
            if isinstance(flt, (Equals, In)):
                ids = self._ids_for_filter_set(f)
                result = set(ids) if result is None else result & ids
                if not result:
                    return []
            elif isinstance(flt, EqualsRegex) and not flt.matches(""):
                ids = self._ids_for_filter_set(f)
                result = ids if result is None else result & ids
                if not result:
                    return []
            else:
                negatives.append(f)
        if result is None:
            result = set(self._all_live_ids().tolist())
        for f in negatives:
            keep = set()
            for pid in result:
                key = self.part_key(pid)
                if key is not None and f.filter.matches(
                        key.label_map.get(f.column, "")):
                    keep.add(pid)
            result = keep
        if not result:
            return []
        ids = np.fromiter(result, dtype=np.int64)
        ok = (self._start[ids] <= end_time) & (self._end[ids] >= start_time)
        return sorted(int(i) for i in ids[ok])

    # ---- label introspection --------------------------------------------

    def label_names(self) -> list[str]:
        if self._nt is not None:
            return sorted(set(self._nt.labels()))
        names = {k for k, v in self._tail.items() if any(v.values())}
        names |= set(self._frozen.keys())
        return sorted(names)

    def label_values(self, label: str,
                     filters: list[ColumnFilter] | None = None,
                     start_time: int = 0, end_time: int = INGESTING) -> list[str]:
        if self._nt is not None:
            return self._label_values_native(label, filters, start_time,
                                             end_time)
        fr = self._frozen.get(label)
        tail = self._tail.get(label)
        if fr is None and not tail:
            return []
        if not filters:
            out = {v for v, s in (tail or {}).items() if s}
            if fr is not None:
                if self._deleted:
                    dead = _from_set(self._deleted)
                    for vb, vi in fr.values():
                        sl = fr.pid_slice(vi)
                        if len(sl) and not np.isin(
                                sl, dead, assume_unique=True).all():
                            out.add(vb.decode())
                else:
                    out |= {vb.decode() for vb, _ in fr.values()}
            return sorted(out)
        ids = np.asarray(
            self.part_ids_from_filters(filters, start_time, end_time),
            np.int64)
        out = set()
        if len(ids):
            if fr is not None:
                for vb, vi in fr.values():
                    if np.isin(fr.pid_slice(vi), ids).any():
                        out.add(vb.decode())
            for value, s in (tail or {}).items():
                if s and not s.isdisjoint(ids.tolist()):
                    out.add(value)
        return sorted(out)

    def _label_values_native(self, label, filters, start_time,
                             end_time) -> list[str]:
        values = self._nt.values(label)
        if not values:
            return []
        if not filters:
            if not self._deleted:
                return sorted(set(values))
            dead = _from_set(self._deleted)
            out = set()
            for v in values:
                sl = self._nt.equals(label, v).astype(np.int64)
                if len(sl) and not np.isin(sl, dead,
                                           assume_unique=True).all():
                    out.add(v)
            return sorted(out)
        ids = np.asarray(
            self.part_ids_from_filters(filters, start_time, end_time),
            np.int64)
        if not len(ids):
            return []
        out = set()
        for v in values:
            sl = self._nt.equals(label, v).astype(np.int64)
            if len(sl) and np.isin(sl, ids).any():
                out.add(v)
        return sorted(out)

    # ---- snapshot support -----------------------------------------------

    def frozen_labels(self):
        """Yield (label, FrozenLabel) merging the frozen and tail tiers with
        deletions applied — the snapshot writer's view. A frozen label with
        no tail additions and no deletions is yielded as-is (re-serialized
        wholesale, no per-value work)."""
        if self._nt is not None:
            dead = np.asarray(sorted(self._deleted), np.int32) \
                if self._deleted else np.empty(0, np.int32)
            for name in sorted(set(self._nt.labels())):
                voff, vblob, poff, pids = self._nt.export_label(name, dead)
                if len(voff) > 1:
                    yield name, FrozenLabel(voff, vblob, poff, pids)
            return
        dead = _from_set(self._deleted) if self._deleted else None
        labels = set(self._tail.keys()) | set(self._frozen.keys())
        for name in sorted(labels):
            fr = self._frozen.get(name)
            tail = {v: s for v, s in (self._tail.get(name) or {}).items()
                    if s}
            if fr is not None and not tail and dead is None:
                yield name, fr
                continue
            merged: dict[bytes, list] = {}
            if fr is not None:
                for vb, vi in fr.values():
                    sl = fr.pid_slice(vi)
                    if dead is not None and len(sl):
                        sl = sl[~np.isin(sl, dead, assume_unique=True)]
                    if len(sl):
                        merged[vb] = [sl]
            for value, s in tail.items():
                merged.setdefault(value.encode(), []).append(sorted(s))
            pairs = []
            for vb, seqs in merged.items():
                seq = seqs[0] if len(seqs) == 1 \
                    else np.unique(np.concatenate(
                        [np.asarray(a, np.int64) for a in seqs]))
                pairs.append((vb, seq))
            if pairs:
                yield name, FrozenLabel.build(pairs)

    def load_frozen(self, label: str, frozen: FrozenLabel) -> None:
        if self._nt is not None:
            self._nt.load_label(label, frozen.voff,
                                bytes(frozen.vblob), frozen.poff,
                                frozen.pids)
            return
        self._frozen[label] = frozen
