"""Native-backed shard ingest: Python wrappers over the C++ shard core.

The reference's ingest hot loop is native-tier code: per-shard single-writer
appenders over off-heap write buffers with O(1) part-key lookup
(``core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala:570``,
``TimeSeriesPartition.scala:137``, ``PartitionSet.scala``). Here the hot loop
lives in ``native/filodb_native.cpp`` (``shard_core_ingest``): binary
RecordContainer bytes are parsed, routed, appended and sealed into encoded
chunks entirely in C++ — Python sees only whole sealed chunks, partition
-creation events, and counters.

``NativeBackedPartition`` presents the ``TimeSeriesPartition`` protocol over
a native partition so the entire query/flush/eviction path works unchanged.
"""

from __future__ import annotations

import ctypes
import struct

import numpy as np

from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.schemas import Schema
from filodb_tpu.memory import native
from filodb_tpu.memory.chunk import Chunk
from filodb_tpu.memory.codecs import CODEC_XOR_DOUBLE


def native_available() -> bool:
    return native.get_lib() is not None


def part_key_blob(key: PartKey) -> bytes:
    """Canonical key bytes — byte-identical to the container v2 record's
    schema-id + label section (the native map key); one shared codec."""
    from filodb_tpu.core.record import _schema_ids, encode_labels
    return struct.pack("<H", _schema_ids(key.schema)) \
        + encode_labels(key.labels)


def part_key_from_blob(blob: bytes, schemas) -> PartKey:
    from filodb_tpu.core.record import decode_labels
    (sid,) = struct.unpack_from("<H", blob, 0)
    labels, _ = decode_labels(blob, 2)
    return PartKey(schemas.by_id(sid).name, labels)


class NativeShardCore:
    """Handle on one shard's C++ ingest core.

    ``lock`` serializes every C++ call that can touch a partition's vectors:
    the host query path reads lock-free under the GIL, but ctypes releases
    the GIL, so a reader copying a buffer while the ingest thread reallocs
    it would be a use-after-free. This is the native analog of the
    reference's ChunkMap read/write latch (``ChunkMap.scala:15-44``).
    """

    def __init__(self, max_chunk_size: int, groups: int):
        import threading
        self._lib = native.get_lib()
        if self._lib is None:
            raise RuntimeError("native library unavailable")
        self._core = ctypes.c_void_p(
            self._lib.shard_core_create(max_chunk_size, groups))
        self.lock = threading.RLock()

    def __del__(self):
        core, self._core = getattr(self, "_core", None), None
        if core:
            self._lib.shard_core_destroy(core)

    # -- ingest --

    def ingest(self, raw: bytes, offset: int) -> int:
        """Returns rows ingested, or -1 when the container holds value
        shapes the native lane doesn't cover (caller falls back)."""
        with self.lock:
            # bytes are immutable and the C side takes const — zero-copy
            return int(self._lib.shard_core_ingest(self._core, raw,
                                                   len(raw), offset))

    def set_watermark(self, group: int, offset: int) -> None:
        self._lib.shard_core_set_watermark(self._core, group, offset)

    def stat(self, which: int) -> int:
        return int(self._lib.shard_core_stat(self._core, which))

    def drain_new_parts(self) -> list[int]:
        with self.lock:
            n = self.stat(4)
            if not n:
                return []
            out = (ctypes.c_int32 * n)()
            got = self._lib.shard_core_drain_new(self._core, out, n)
            return list(out[:got])

    def key_blob(self, pid: int) -> bytes:
        with self.lock:
            n = self._lib.shard_core_key_len(self._core, pid)
            out = (ctypes.c_uint8 * max(n, 1))()
            self._lib.shard_core_key_copy(self._core, pid, out)
            return bytes(out[:n])

    def create_part(self, key: PartKey, ncols: int) -> int:
        blob = part_key_blob(key)
        buf = (ctypes.c_uint8 * len(blob)).from_buffer_copy(blob)
        with self.lock:
            return int(self._lib.shard_core_create_part(
                self._core, buf, len(blob), key.part_hash, ncols))

    def part_hash(self, pid: int) -> int:
        return int(self._lib.shard_core_part_hash(self._core, pid))

    def buf_fold(self, pids, t0s, t1s, col: int):
        """Batched sequential window fold over write buffers (the sidecar
        query lane's buffer tail): one C call for all partitions instead of
        a ctypes buffer copy per partition. Returns (stats [P, W, 12] f64,
        flags [P] i32) — see ``shard_buf_fold`` in filodb_native.cpp — or
        None when the loaded .so predates the entry point."""
        if not hasattr(self._lib, "shard_buf_fold"):
            return None
        pids = np.ascontiguousarray(pids, np.int32)
        t0s = np.ascontiguousarray(t0s, np.int64)
        t1s = np.ascontiguousarray(t1s, np.int64)
        P, W = len(pids), len(t0s)
        out = np.empty((P, W, 12), np.float64)
        flags = np.empty(P, np.int32)
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        with self.lock:
            self._lib.shard_buf_fold(
                self._core, pids.ctypes.data_as(i32p), P,
                t0s.ctypes.data_as(i64p), t1s.ctypes.data_as(i64p), W, col,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                flags.ctypes.data_as(i32p))
        return out, flags

    def lookup(self, key_blob: bytes) -> int:
        """pid for canonical key bytes, or -1 — the authoritative key map
        for restored shards (no host-language dictionary needed)."""
        buf = (ctypes.c_uint8 * len(key_blob)).from_buffer_copy(key_blob)
        with self.lock:
            return int(self._lib.shard_core_lookup(self._core, buf,
                                                   len(key_blob)))

    def bootstrap(self, buf: bytes) -> int:
        """Bulk-create partitions from snapshot entries (one C call)."""
        with self.lock:
            n = int(self._lib.shard_core_bootstrap(self._core, buf,
                                                   len(buf)))
        if n < 0:
            raise ValueError("malformed bootstrap buffer or non-empty core")
        return n

    def seed_floors(self, pids: np.ndarray, floors: np.ndarray) -> None:
        pids = np.ascontiguousarray(pids, np.int32)
        floors = np.ascontiguousarray(floors, np.int64)
        with self.lock:
            self._lib.shard_core_seed_floors(
                self._core,
                pids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                floors.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                len(pids))

    def part_floor(self, pid: int) -> int:
        return int(self._lib.part_floor(self._core, pid))

    def export_entries(self, n: int) -> tuple[bytes, np.ndarray, np.ndarray]:
        """(core_section, key_off i64[n], key_len i32[n]) — the snapshot's
        partition registry section, built in one C++ pass."""
        with self.lock:
            size = int(self._lib.shard_core_export_size(self._core))
            buf = (ctypes.c_uint8 * size)()
            key_off = np.empty(max(n, 1), np.int64)
            key_len = np.empty(max(n, 1), np.int32)
            self._lib.shard_core_export(
                self._core, buf,
                key_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                key_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        return bytes(buf), key_off[:n], key_len[:n]

    def floors(self, n: int) -> np.ndarray:
        out = np.empty(max(n, 1), np.int64)
        with self.lock:
            self._lib.shard_core_floors(
                self._core,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), n)
        return out[:n]


class NativeBackedPartition:
    """``TimeSeriesPartition``-protocol view over a native partition.

    Sealed chunks materialize lazily as ``Chunk`` objects (cached per native
    version); the active buffer materializes as a ``_Buffers`` snapshot on
    access. All mutation goes through the core.
    """

    __slots__ = ("part_id", "max_chunk_size", "shard",
                 "device_pages", "_core", "_lib", "_chunks_cache",
                 "_chunks_ver", "_part_key", "_schema", "_key_blob",
                 "_schemas", "_sc_cache")

    def __init__(self, core: NativeShardCore, part_id: int,
                 part_key: PartKey | None = None,
                 schema: Schema | None = None, max_chunk_size: int = 400,
                 shard: int = 0, key_blob: bytes | None = None,
                 schemas=None):
        """Either (part_key, schema) or (key_blob, schemas): snapshot
        restore passes blobs so a million keys don't materialize at boot —
        ``part_key``/``schema`` parse lazily on first access."""
        self._core = core
        self._lib = core._lib
        self.part_id = part_id
        self._part_key = part_key
        self._schema = schema
        self._key_blob = key_blob
        self._schemas = schemas
        self.max_chunk_size = max_chunk_size
        self.shard = shard
        self.device_pages = False
        # lazily allocated on first chunk read: an empty list per series
        # is ~56B x 1M series of dead weight at scale
        self._chunks_cache: list[Chunk] | None = None
        self._chunks_ver = -1

    @property
    def bucket_les(self) -> np.ndarray | None:
        """Current bucket bounds for the native hist column (None for
        all-scalar partitions) — the host partition's ``bucket_les``."""
        with self._core.lock:
            nb = int(self._lib.part_hist_nb(self._core._core, self.part_id))
            if nb <= 0:
                return None
            out = np.empty(nb, np.float64)
            self._lib.part_hist_les(
                self._core._core, self.part_id,
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            return out

    @property
    def part_key(self) -> PartKey:
        if self._part_key is None:
            self._part_key = part_key_from_blob(self._key_blob, self._schemas)
            self._part_key.__dict__["part_hash"] = \
                self._core.part_hash(self.part_id)
        return self._part_key

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            (sid,) = struct.unpack_from("<H", self._key_blob, 0)
            self._schema = self._schemas.by_id(sid)
        return self._schema

    # -- ingest (rare path: replay of object containers, tests) --

    def ingest(self, ts: int, values: tuple) -> bool:
        hist_at = next((i for i, v in enumerate(values)
                        if isinstance(v, tuple)
                        or (isinstance(v, np.ndarray) and v.ndim)), -1)
        if hist_at >= 0:
            les, counts = values[hist_at]
            les = np.ascontiguousarray(les, np.float64)
            counts = np.ascontiguousarray(counts, np.int64)
            dvals = np.array([float(v) if i != hist_at else np.nan
                              for i, v in enumerate(values)], np.float64)
            with self._core.lock:
                return bool(self._lib.part_append_hist(
                    self._core._core, self.part_id, ts,
                    dvals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    len(dvals),
                    les.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                    counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    len(les), hist_at))
        vals = np.asarray(values, np.float64)
        with self._core.lock:
            return bool(self._lib.part_append(
                self._core._core, self.part_id, ts,
                vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
                len(vals)))

    # -- state --

    @property
    def latest_ts(self) -> int:
        return int(self._lib.part_latest_ts(self._core._core, self.part_id))

    @property
    def earliest_ts(self) -> int:
        return int(self._lib.part_earliest_ts(self._core._core, self.part_id))

    @property
    def num_samples(self) -> int:
        return int(self._lib.part_num_samples(self._core._core, self.part_id))

    @property
    def first_ts(self) -> int:
        return int(self._lib.part_first_ts(self._core._core, self.part_id))

    def seed_dedup_floor(self, ts: int) -> None:
        self._lib.part_seed_floor(self._core._core, self.part_id, ts)

    @property
    def _flushed_id(self) -> int:
        return int(self._lib.part_flushed_id(self._core._core, self.part_id))

    # -- chunks --

    @property
    def chunks(self) -> list[Chunk]:
        core, pid = self._core._core, self.part_id
        with self._core.lock:
            ver = int(self._lib.part_version(core, pid))
            if ver == self._chunks_ver and self._chunks_cache is not None:
                return self._chunks_cache
            n = self._lib.part_num_sealed(core, pid)
            ncols = self._lib.part_ncols(core, pid)
            out: list[Chunk] = []
            meta = (ctypes.c_int64 * 4)()
            for i in range(n):
                self._lib.part_sealed_meta(core, pid, i, meta)
                vectors = []
                for col in range(ncols + 1):
                    ln = self._lib.part_sealed_veclen(core, pid, i, col)
                    buf = (ctypes.c_uint8 * ln)()
                    self._lib.part_sealed_veccopy(core, pid, i, col, buf)
                    vectors.append(bytes(buf))
                out.append(Chunk(int(meta[0]), int(meta[3]), int(meta[1]),
                                 int(meta[2]), tuple(vectors)))
            self._chunks_cache = out
            self._chunks_ver = ver
            return out

    @property
    def _buf(self):
        from filodb_tpu.core.memstore.partition import _Buffers
        core, pid = self._core._core, self.part_id
        with self._core.lock:
            n = self._lib.part_buf_count(core, pid)
            ncols = self._lib.part_ncols(core, pid)
            ts = np.empty(max(n, 1), np.int64)
            cols = np.empty((ncols, max(n, 1)), np.float64)
            if n:
                n = self._lib.part_buf_copy(
                    core, pid, n,
                    ts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    cols.ctypes.data_as(ctypes.POINTER(ctypes.c_double)))
            out_cols = [cols[i] for i in range(ncols)]
            hist_col = int(self._lib.part_hist_col(core, pid))
            if hist_col >= 0 and n:
                nb = int(self._lib.part_hist_nb(core, pid))
                rows = np.zeros((n, max(nb, 1)), np.int64)
                got = self._lib.part_buf_hist_copy(
                    core, pid, n,
                    rows.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
                out_cols[hist_col] = rows[:got] if got == n else \
                    np.vstack([rows[:got],
                               np.zeros((n - got, max(nb, 1)), np.int64)])
        return _Buffers(ts, out_cols, n)

    def switch_buffers(self) -> None:
        with self._core.lock:
            self._lib.part_seal_buffer(self._core._core, self.part_id)

    def make_flush_chunks(self, flush_buffer: bool = True) -> list[Chunk]:
        from filodb_tpu.memory.chunk import ensure_summary
        with self._core.lock:
            if flush_buffer:
                self._lib.part_seal_buffer(self._core._core, self.part_id)
            flushed = self._flushed_id
            out = [c for c in self.chunks if c.id > flushed]
        # natively-sealed chunks carry no summary yet: attach before the
        # chunks leave for the column store (decode memoizes on the Chunk,
        # and the version-keyed chunks cache keeps the attachment)
        for c in out:
            ensure_summary(c)
        return out

    def mark_flushed(self, up_to_id: int) -> None:
        self._lib.part_mark_flushed(self._core._core, self.part_id, up_to_id)

    def evict_flushed_chunks(self) -> int:
        with self._core.lock:
            return int(self._lib.part_evict_flushed(self._core._core,
                                                    self.part_id))

    def has_unpersisted_data(self) -> bool:
        """True while buffer samples or un-flushed sealed chunks remain
        (call after ``evict_flushed_chunks``, which drops flushed ones)."""
        with self._core.lock:
            core, pid = self._core._core, self.part_id
            return bool(self._lib.part_buf_count(core, pid)) \
                or bool(self._lib.part_num_sealed(core, pid))

    @property
    def chunk_nbytes(self) -> int:
        """Encoded chunk bytes without materializing Chunk objects."""
        with self._core.lock:
            return int(self._lib.part_chunk_bytes(self._core._core,
                                                  self.part_id))

    @property
    def unflushed_count(self) -> int:
        with self._core.lock:
            flushed = self._flushed_id
            n = sum(1 for c in self.chunks if c.id > flushed)
            if self._lib.part_buf_count(self._core._core, self.part_id):
                n += 1
            return n

    def free(self) -> None:
        with self._core.lock:
            self._lib.part_free(self._core._core, self.part_id)

    # -- reads: borrow the host partition's implementations (they only use
    #    the protocol surface: chunks / _buf / schema / bucket_les) --

    def chunks_in_range(self, start: int, end: int,
                        include_buffer: bool = True) -> list[Chunk]:
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        return TimeSeriesPartition.chunks_in_range(self, start, end,
                                                   include_buffer)

    def _buffer_chunk(self) -> Chunk:
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        return TimeSeriesPartition._buffer_chunk(self)

    def read_samples(self, start: int, end: int, col: int = None,
                     extra_chunks: list | None = None):
        from filodb_tpu.core.memstore.partition import TimeSeriesPartition
        return TimeSeriesPartition.read_samples(self, start, end, col,
                                                extra_chunks)


# sanity: the native value codec id must match what decode_any dispatches on
assert CODEC_XOR_DOUBLE == 3
