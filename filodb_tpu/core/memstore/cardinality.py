"""Cardinality tracking and quota enforcement.

Counterpart of reference ``core/src/main/scala/filodb.core/memstore/ratelimit/``
(``CardinalityTracker.scala:1-191``, ``QuotaSource``,
``RocksDbCardinalityStore``): per shard, a tree over the shard-key prefix
(workspace → namespace → metric) counting active/total time series, with
per-prefix quotas; creation of series beyond quota is rejected at ingest.
The store here is an in-process dict tree (the reference needs RocksDB
because JVM heap can't hold high-card trees; our counts are plain ints —
a few MB even at 1M series).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Cardinality:
    """Counts at one tree node (reference ``Cardinality``)."""

    name: str
    active_ts: int = 0
    total_ts: int = 0
    children: int = 0
    quota: int = 2**62


class QuotaExceededError(Exception):
    def __init__(self, prefix, quota):
        super().__init__(f"cardinality quota exceeded at {prefix}: {quota}")
        self.prefix = prefix
        self.quota = quota


@dataclass
class _Node:
    card: Cardinality
    children: dict[str, "_Node"] = field(default_factory=dict)


class CardinalityTracker:
    """Tracks series cardinality along the shard-key path."""

    def __init__(self, shard: int, shard_key_labels=("_ws_", "_ns_",
                                                     "_metric_"),
                 default_quotas: tuple[int, ...] | None = None):
        self.shard = shard
        self.shard_key_labels = shard_key_labels
        self._root = _Node(Cardinality("__root__"))
        # quota per depth: (root, ws, ns, metric)
        self._default_quotas = default_quotas or (2**62,) * (
            len(shard_key_labels) + 1)
        self._root.card.quota = self._default_quotas[0]
        self._has_quotas = any(q < 2**62 for q in self._default_quotas)

    def _path(self, labels: dict[str, str]) -> list[str]:
        return [labels.get(k, "") for k in self.shard_key_labels]

    def _walk(self, path: list[str], create: bool = False) -> list[_Node]:
        nodes = [self._root]
        cur = self._root
        for depth, part in enumerate(path):
            nxt = cur.children.get(part)
            if nxt is None:
                if not create:
                    return nodes
                nxt = _Node(Cardinality(part,
                                        quota=self._default_quotas[
                                            min(depth + 1,
                                                len(self._default_quotas) - 1)]))
                cur.children[part] = nxt
                cur.card.children += 1
            nodes.append(nxt)
            cur = nxt
        return nodes

    def to_state(self) -> list:
        """Serializable tree state (O(distinct shard-key prefixes), not
        O(series)) — rides in the index snapshot so restored shards keep
        their cardinality counts and quotas."""
        def walk(node):
            c = node.card
            return [c.name, c.active_ts, c.total_ts, c.children, c.quota,
                    [walk(ch) for ch in node.children.values()]]
        return walk(self._root)

    def load_state(self, state: list) -> None:
        def build(entry) -> _Node:
            name, active, total, children, quota, kids = entry
            n = _Node(Cardinality(name, active, total, children, quota))
            for kid in kids:
                n.children[kid[0]] = build(kid)
            return n
        self._root = build(state)
        self._has_quotas = self._has_quotas or self._any_finite(self._root)

    @staticmethod
    def _any_finite(node) -> bool:
        if node.card.quota < 2**62:
            return True
        return any(CardinalityTracker._any_finite(ch)
                   for ch in node.children.values())

    @property
    def has_quotas(self) -> bool:
        """True once any finite quota is configured (the native ingest lane
        defers to the host path so rejection happens before buffering)."""
        return getattr(self, "_has_quotas", False)

    def set_quota(self, prefix: list[str], quota: int) -> None:
        nodes = self._walk(prefix, create=True)
        nodes[-1].card.quota = quota
        if quota < 2**62:
            self._has_quotas = True

    def series_created(self, labels: dict[str, str]) -> None:
        """Increment counts; raises QuotaExceededError when a prefix is at
        quota (reference ``CardinalityTracker.incrementCount``)."""
        path = self._path(labels)
        nodes = self._walk(path, create=True)
        for i, n in enumerate(nodes):
            if n.card.active_ts + 1 > n.card.quota:
                raise QuotaExceededError(path[:i], n.card.quota)
        for n in nodes:
            n.card.active_ts += 1
            n.card.total_ts += 1

    def series_stopped(self, labels: dict[str, str]) -> None:
        for n in self._walk(self._path(labels)):
            n.card.active_ts = max(n.card.active_ts - 1, 0)

    def cardinality(self, prefix: list[str]) -> Cardinality:
        nodes = self._walk(prefix)
        if len(nodes) <= len(prefix):
            return Cardinality("/".join(prefix) or "__root__")
        return nodes[-1].card

    def top_k(self, prefix: list[str], k: int = 10) -> list[Cardinality]:
        """Highest-cardinality children under a prefix (CLI ``topkcard``)."""
        nodes = self._walk(prefix)
        if len(nodes) <= len(prefix):
            return []
        children = nodes[-1].children.values()
        return sorted((c.card for c in children),
                      key=lambda c: -c.active_ts)[:k]
