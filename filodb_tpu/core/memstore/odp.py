"""On-demand paging: pull cold chunks from the column store at query time.

Counterpart of reference ``OnDemandPagingShard.scala:27`` +
``DemandPagedChunkStore.scala:1-125``: when a query needs data older than
what's resident in memory (flushed-then-evicted chunks, or partitions
restored index-only after recovery), the missing chunk range is read from the
column store and attached to the partition as transient paged chunks (bounded
LRU per shard).
"""

from __future__ import annotations

import logging
from collections import OrderedDict

from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.memstore.shard import TimeSeriesShard
from filodb_tpu.utils.metrics import Counter

log = logging.getLogger(__name__)

odp_chunks_paged = Counter("odp_chunks_paged")
odp_requests = Counter("odp_requests")


class DemandPagedChunkCache:
    """Bounded per-shard cache of paged-in chunks, keyed (part_id, chunk_id)."""

    def __init__(self, max_chunks: int = 10_000):
        self.max_chunks = max_chunks
        self._lru: OrderedDict[tuple[int, int], object] = OrderedDict()

    def clear(self) -> None:
        """Drop all cached chunks (benchmarks use this to force cold reads)."""
        self._lru.clear()

    def get_or_load(self, shard: TimeSeriesShard, part: TimeSeriesPartition,
                    start: int, end: int) -> list:
        """Chunks from the column store overlapping [start, end] that are not
        resident in memory."""
        odp_requests.inc()
        resident = {c.id for c in part.chunks}
        disk_chunks = shard.column_store.read_chunks(
            shard.dataset, shard.shard_num, part.part_key, start, end)
        out = []
        for ch in disk_chunks:
            if ch.id in resident:
                continue
            key = (part.part_id, ch.id)
            cached = self._lru.get(key)
            if cached is None:
                self._lru[key] = ch
                odp_chunks_paged.inc()
                shard.stats.chunks_paged_in.inc()
                cached = ch
            else:
                self._lru.move_to_end(key)
            out.append(cached)
        while len(self._lru) > self.max_chunks:
            self._lru.popitem(last=False)
        return out


def needs_paging(part: TimeSeriesPartition, index_start: int,
                 query_start: int) -> bool:
    """True when the partition's in-memory data doesn't reach back to the
    query start but the index says data exists there."""
    earliest_mem = part.earliest_ts
    if earliest_mem == -1:
        return index_start < 2**62  # nothing in memory; anything on disk?
    return query_start < earliest_mem and index_start < earliest_mem


def page_partitions(shard: TimeSeriesShard, parts: list[TimeSeriesPartition],
                    start: int, end: int,
                    cache: DemandPagedChunkCache) -> dict[int, list]:
    """Return {part_id: odp_chunks} for partitions needing older data."""
    from filodb_tpu.utils.tracing import span, tag
    out = {}
    with span("odp-page", shard=shard.shard_num):  # ref: startODPSpan
        for p in parts:
            idx_start = shard.index.start_time(p.part_id)
            if needs_paging(p, idx_start, start):
                chunks = cache.get_or_load(shard, p, start, end)
                if chunks:
                    out[p.part_id] = chunks
                    shard.stats.partitions_paged_in.inc()
        tag("partitions_paged", len(out))
    return out
