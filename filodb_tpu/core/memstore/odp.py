"""On-demand paging: pull cold chunks from the column store at query time.

Counterpart of reference ``OnDemandPagingShard.scala:27`` +
``DemandPagedChunkStore.scala:1-125``: when a query needs data older than
what's resident in memory (flushed-then-evicted chunks, or partitions
restored index-only after recovery), the missing chunk range is read from the
column store and attached to the partition as transient paged chunks (bounded
LRU per shard).

The cold federation tier (``query/federation.py``) routes every read of
object-store-resident history through this cache, so it additionally keeps a
per-partition *range coverage* memo: once ``[start, end]`` was fully paged
for a partition with nothing resident in memory, a repeat request inside
that range serves straight from the LRU — no column-store read, and on an
object-store backend no ranged GET — until any of the partition's chunks is
evicted. Cache hits (both paths) refresh the LRU position.
"""

from __future__ import annotations

import logging
import weakref
from collections import OrderedDict

from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.memstore.shard import TimeSeriesShard
from filodb_tpu.utils.metrics import Counter, GaugeFn

log = logging.getLogger(__name__)

odp_chunks_paged = Counter("odp_chunks_paged")
odp_requests = Counter("odp_requests")
odp_range_hits = Counter("odp_range_hits")

# chunks currently held across every live ODP cache (all shards, raw and
# cold-tier); scrape-time callback so no update path is needed
_CACHES: "weakref.WeakSet[DemandPagedChunkCache]" = weakref.WeakSet()
odp_cache_chunks = GaugeFn("filodb_odp_cache_chunks",
                           lambda: sum(len(c) for c in _CACHES))


class DemandPagedChunkCache:
    """Bounded per-shard cache of paged-in chunks, keyed (part_id, chunk_id)."""

    def __init__(self, max_chunks: int = 10_000):
        self.max_chunks = max_chunks
        self._lru: OrderedDict[tuple[int, int], object] = OrderedDict()
        # coverage memo: part_id -> [(start, end), ...] ranges known to be
        # fully cached, and part_id -> cached chunk ids. Coverage is only
        # recorded for partitions with NO resident chunks (cold-tier
        # partitions): a resident set can shrink later, which would make
        # a remembered range silently incomplete.
        self._covered: dict[int, list[tuple[int, int]]] = {}
        self._part_chunks: dict[int, set[int]] = {}
        _CACHES.add(self)

    def __len__(self) -> int:
        return len(self._lru)

    def clear(self) -> None:
        """Drop all cached chunks (benchmarks use this to force cold reads)."""
        self._lru.clear()
        self._covered.clear()
        self._part_chunks.clear()

    def _covers(self, part_id: int, start: int, end: int) -> bool:
        return any(cs <= start and end <= ce
                   for cs, ce in self._covered.get(part_id, ()))

    def _evict_one(self) -> None:
        (pid, cid), _ = self._lru.popitem(last=False)
        ids = self._part_chunks.get(pid)
        if ids is not None:
            ids.discard(cid)
            if not ids:
                del self._part_chunks[pid]
        # any remembered range for this partition may now be incomplete
        self._covered.pop(pid, None)

    def get_or_load(self, shard: TimeSeriesShard, part: TimeSeriesPartition,
                    start: int, end: int) -> list:
        """Chunks from the column store overlapping [start, end] that are not
        resident in memory."""
        odp_requests.inc()
        pid = part.part_id
        if self._covers(pid, start, end):
            # covered repeat: serve from the LRU without touching the
            # store; hits refresh LRU position so hot cold-tier chunks
            # survive eviction pressure. Chunks outside [start, end] are
            # harmless — partition reads slice by timestamp anyway.
            odp_range_hits.inc()
            out = []
            for cid in list(self._part_chunks.get(pid, ())):
                key = (pid, cid)
                ch = self._lru.get(key)
                if ch is not None:
                    self._lru.move_to_end(key)
                    out.append(ch)
            return out
        resident = {c.id for c in part.chunks}
        disk_chunks = shard.column_store.read_chunks(
            shard.dataset, shard.shard_num, part.part_key, start, end)
        out = []
        for ch in disk_chunks:
            if ch.id in resident:
                continue
            key = (pid, ch.id)
            cached = self._lru.get(key)
            if cached is None:
                self._lru[key] = ch
                odp_chunks_paged.inc()
                shard.stats.chunks_paged_in.inc()
                cached = ch
            else:
                self._lru.move_to_end(key)
            self._part_chunks.setdefault(pid, set()).add(ch.id)
            out.append(cached)
        if not resident:
            ranges = self._covered.setdefault(pid, [])
            ranges.append((start, end))
            if len(ranges) > 16:
                del ranges[0]
        while len(self._lru) > self.max_chunks:
            self._evict_one()
        return out


def needs_paging(part: TimeSeriesPartition, index_start: int,
                 query_start: int) -> bool:
    """True when the partition's in-memory data doesn't reach back to the
    query start but the index says data exists there."""
    earliest_mem = part.earliest_ts
    if earliest_mem == -1:
        return index_start < 2**62  # nothing in memory; anything on disk?
    return query_start < earliest_mem and index_start < earliest_mem


def page_partitions(shard: TimeSeriesShard, parts: list[TimeSeriesPartition],
                    start: int, end: int,
                    cache: DemandPagedChunkCache) -> dict[int, list]:
    """Return {part_id: odp_chunks} for partitions needing older data."""
    from filodb_tpu.utils.tracing import span, tag
    out = {}
    with span("odp-page", shard=shard.shard_num):  # ref: startODPSpan
        for p in parts:
            idx_start = shard.index.start_time(p.part_id)
            if needs_paging(p, idx_start, start):
                chunks = cache.get_or_load(shard, p, start, end)
                if chunks:
                    out[p.part_id] = chunks
                    shard.stats.partitions_paged_in.inc()
        tag("partitions_paged", len(out))
    return out
