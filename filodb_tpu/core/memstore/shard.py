"""TimeSeriesShard: the heart of the memstore.

Counterpart of the reference's ``TimeSeriesShard``
(``core/src/main/scala/filodb.core/memstore/TimeSeriesShard.scala``):

- partition map + O(1) part-key lookup set (``:273,375``) — here a dict keyed
  by ``PartKey`` (hashable, precomputed hash) plus a dense partition list;
- tag index per shard (``:285``) — ``PartKeyIndex``;
- ``ingest(container, offset)`` entry (``:570``) with per-group recovery
  watermarks (``:525-561``): during replay, records whose group is already
  checkpointed past the offset are skipped;
- flush groups: partitions hash into ``groups_per_shard`` groups; flushes are
  time-staggered per group (``createFlushTasks:889``, ``doFlushSteps:969``):
  encode dirty buffers → write chunks to the column store → upsert dirty part
  keys → write the group checkpoint;
- partition purge for TTL-expired series (``:838``) and eviction under memory
  pressure (``:1301,1611``).

Single-writer discipline: one shard is ingested by one thread (the reference
pins an ingest scheduler per shard, ``:364``); queries take immutable
snapshots (encoded chunks are immutable; the write buffer is copied on read).
"""

from __future__ import annotations

import logging
import time as _time
from dataclasses import dataclass, field

from filodb_tpu.core.memstore.index import INGESTING, PartKeyIndex
from filodb_tpu.core.memstore.partition import TimeSeriesPartition
from filodb_tpu.core.partkey import PartKey
from filodb_tpu.core.record import SomeData
from filodb_tpu.core.schemas import Schemas
from filodb_tpu.core.store.api import ColumnStore, MetaStore, PartKeyRecord
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.utils.metrics import Counter, Gauge, GaugeFn, Histogram
from filodb_tpu.utils.resilience import FaultInjector
from filodb_tpu.utils.tracing import traced_operation

log = logging.getLogger(__name__)


class ShardStats:
    """The reference's full named shard metric set, tagged {dataset, shard}
    (``TimeSeriesShardStats``, ``TimeSeriesShard.scala:41-133``). Metric
    names keep the reference's Kamon names, Prometheus-sanitized. Gauges
    over live shard state (index sizes, pool sizes, chunk bytes) register
    as scrape-time callbacks via ``register_state_gauges``."""

    def __init__(self, dataset: str = "", shard: int = 0):
        tags = {"dataset": dataset, "shard": str(shard)}
        self.tags = tags

        def C(name):
            return Counter(name, tags)

        def G(name):
            return Gauge(name, tags)

        def H(name):
            return Histogram(name, tags)

        # ingest
        self.rows_ingested = C("memstore_rows_ingested")
        self.rows_skipped = C("recovery_row_skipped")
        self.quota_dropped = C("memstore_data_dropped")
        self.unknown_schema_dropped = C("memstore_unknown_schema_dropped")
        self.incompatible_containers = C("memstore_incompatible_containers")
        self.offsets_not_recovered = C("memstore_offsets_not_recovered")
        self.out_of_order_dropped = C("memstore_out_of_order_samples")
        self.ingestion_clock_delay = G("ingestion_clock_delay_ms")
        self.ingestion_pipeline_latency = H("ingestion_pipeline_latency_seconds")
        # partition lifecycle
        self.partitions_created = C("memstore_partitions_created")
        self.partitions_purged = C("memstore_partitions_purged")
        self.partitions_purged_index = C("memstore_partitions_purged_index")
        self.purge_time_ms = C("memstore_partitions_purge_time_ms")
        self.partitions_evicted = C("memstore_partitions_evicted")
        self.chunkids_evicted = C("memstore_chunkids_evicted")
        self.partitions_restored = C("memstore_partitions_paged_restored")
        self.eviction_stall_ns = C("memstore_eviction_stall_ns")
        self.num_partitions = G("num_partitions")
        # encode / flush
        self.samples_encoded = C("memstore_samples_encoded")
        self.encoded_bytes = C("memstore_encoded_bytes_allocated")
        self.encoded_hist_bytes = C("memstore_hist_encoded_bytes")
        self.chunks_flushed = C("memstore_flushes_chunks_written")
        self.flushes_done = C("memstore_flushes_success")
        self.flushes_failed = C("memstore_flushes_failed")
        self.dirty_keys_flushed = C("memstore_index_num_dirty_keys_flushed")
        self.flush_latency = H("chunk_flush_task_latency_seconds")
        self.downsample_records_created = C("memstore_downsample_records_created")
        # offsets (lag construction: kafka_latest - latest_inmemory, etc.)
        self.offset_latest_in_mem = G("shard_offset_latest_inmemory")
        self.offset_flushed_latest = G("shard_offset_flushed_latest")
        self.offset_flushed_earliest = G("shard_offset_flushed_earliest")
        # recovery
        self.recovery_time_ms = G("memstore_total_shard_recovery_time_ms")
        self.index_recovery_partkeys = C(
            "memstore_index_recovery_partkeys_processed")
        # query
        self.partitions_queried = C("memstore_partitions_queried")
        self.query_time_range_minutes = Histogram(
            "query_time_range_minutes", tags,
            bounds=(5.0, 15.0, 60.0, 180.0, 360.0, 720.0, 1440.0,
                    4320.0, 10080.0, 43200.0, 129600.0, 525600.0))
        # on-demand paging
        self.chunks_paged_in = C("chunks_paged_in")
        self.partitions_paged_in = C("memstore_partitions_paged_in")
        # evicted-part-key bloom
        self.bloom_queries = C("evicted_pk_bloom_filter_queries")
        self.bloom_fp = C("evicted_pk_bloom_filter_fp")

    def register_state_gauges(self, shard: "TimeSeriesShard") -> None:
        """Scrape-time gauges over live shard state (reference gauges that
        Kamon samples: index entries/bytes, buffer pool size, bloom size,
        chunk memory)."""
        import weakref
        ref = weakref.ref(shard)  # don't let the registry pin a dead shard

        def fn(get):
            def call():
                s = ref()
                # None drops the series from /metrics once the shard dies
                return get(s) if s is not None else None
            return call

        GaugeFn("memstore_index_entries", fn(lambda s: len(s.index)),
                self.tags)
        GaugeFn("memstore_timeseries_count", fn(lambda s: len(s.index)),
                self.tags)
        GaugeFn("memstore_index_ram_bytes",
                fn(lambda s: s.index.ram_bytes), self.tags)
        GaugeFn("memstore_writebuffer_pool_size",
                fn(lambda s: sum(len(p._free)
                                 for p in s.buffer_pools.values())),
                self.tags)
        GaugeFn("evicted_pk_bloom_filter_approx_size",
                fn(lambda s: s.evicted_keys.count), self.tags)
        GaugeFn("memstore_chunk_ram_bytes", fn(lambda s: s.chunk_bytes()),
                self.tags)
        GaugeFn("num_ingesting_partitions",
                fn(lambda s: sum(1 for p in s.partitions
                                 if p is not None and p.unflushed_count)),
                self.tags)
        # freshness: wall clock minus the shard's ingest high-water record
        # timestamp. None (series dropped) until the first ingest — a huge
        # bogus lag on an idle shard would page someone for nothing.
        GaugeFn("filodb_ingest_lag_seconds",
                fn(lambda s: None if s.max_ingested_ts < 0
                   else max(0.0, _time.time()
                            - s.max_ingested_ts / 1000.0)),
                self.tags)


class TimeSeriesShard:
    def __init__(self, dataset: str, shard_num: int, schemas: Schemas,
                 store_config: StoreConfig, column_store: ColumnStore,
                 meta_store: MetaStore):
        self.dataset = dataset
        self.shard_num = shard_num
        self.schemas = schemas
        self.config = store_config
        self.column_store = column_store
        self.meta_store = meta_store
        self.stats = ShardStats(dataset, shard_num)

        self.partitions: list[TimeSeriesPartition | None] = []
        self._by_key: dict[PartKey, int] = {}
        self.index = PartKeyIndex(schemas)
        # per-group recovery watermarks: ingest offsets <= watermark are skipped
        self.group_watermarks: list[int] = [-1] * store_config.groups_per_shard
        self._dirty_part_keys: set[int] = set()
        self._last_flushed_group = -1
        self._ingested_offset = -1
        # serializes buffer mutation between the ingest thread and the flush
        # scheduler (the reference runs buffer switching ON the ingest
        # scheduler; here a lock keeps flush callable from any thread)
        import threading as _threading
        self.write_lock = _threading.Lock()
        # cardinality metering + quotas (reference ratelimit/); configured
        # per-tenant quotas (governor `tenants` block) apply to every shard
        from filodb_tpu.core.memstore.cardinality import CardinalityTracker
        from filodb_tpu.utils.governor import apply_tenant_quotas
        self.cardinality = CardinalityTracker(shard_num)
        apply_tenant_quotas(self.cardinality)
        # optional streaming downsampler invoked at flush (reference
        # ShardDownsampler publishing to the downsample dataset)
        self.downsampler = None
        # on-demand paging cache (reference OnDemandPagingShard)
        from filodb_tpu.core.memstore.odp import DemandPagedChunkCache
        self.odp_cache = DemandPagedChunkCache()
        # write-buffer pools per schema (reference WriteBufferPool.scala):
        # appender sets recycled across series churn, re-issued only once
        # provably unreferenced by in-flight lock-free readers
        self.buffer_pools: dict[str, object] = {}
        # query-batch cache: repeated scans of unchanged data reuse the
        # decoded/padded SeriesBatch (keyed by ingest version; the analog of
        # the reference keeping chunks hot in block memory across queries)
        self.batch_cache: dict = {}
        self.batch_cache_cap = 64
        # max persisted chunk ts per part key, loaded at recovery; every
        # partition created afterwards (index scan OR replay — a crash can
        # land between write_chunks and write_part_keys, so replay may be
        # what re-creates the partition) seeds its dedup floor from here
        self._persisted_floors: dict[PartKey, int] = {}
        # C++ ingest core: binary containers bypass the Python record loop
        # entirely (reference native-tier ingest, TimeSeriesShard.scala:570)
        self._native_core = None
        self._nat_skipped_seen = 0
        self._nat_ooo_seen = 0
        self._nat_incompat_seen = 0
        # pids of host-backed (non-native) partitions, e.g. histograms —
        # lets shard-wide accounting avoid walking every lazy partition
        self._host_pids: set[int] = set()
        # evicted-part-key bloom (reference TimeSeriesShard.scala:457): a
        # positive answer at series-create time means the key MAY have been
        # evicted — restore its identity instead of minting a fresh one
        from filodb_tpu.utils.bloom import BloomFilter
        self.evicted_keys = BloomFilter(
            store_config.evicted_pk_bloom_filter_capacity)
        # ingest high-water timestamp (max record ts applied this process
        # lifetime); the result cache derives its mutable horizon from it.
        # -1 until the first ingest: a shard that hasn't ingested yet could
        # legitimately receive rows at ANY timestamp, so nothing is
        # provably immutable.
        self._max_ingested_ts = -1
        if store_config.native_ingest \
                and not store_config.trace_part_key_substrings \
                and not store_config.device_pages:
            from filodb_tpu.core.memstore.native_shard import (
                NativeShardCore,
                native_available,
            )
            if native_available():
                self._native_core = NativeShardCore(
                    store_config.max_chunk_size,
                    store_config.groups_per_shard)
        self.stats.register_state_gauges(self)

    @property
    def data_version(self) -> int:
        """Monotonic version bumped by every ingested row; query caches key
        on it."""
        return self.stats.rows_ingested.value + self.stats.partitions_purged.value

    @property
    def max_ingested_ts(self) -> int:
        """Max record timestamp this shard has seen (both ingest lanes);
        -1 before any ingest."""
        return self._max_ingested_ts

    # ---- partition lifecycle --------------------------------------------

    def group_of(self, key: PartKey) -> int:
        return key.part_hash % self.config.groups_per_shard

    def get_or_create_partition(self, key: PartKey, first_ts: int
                                ) -> TimeSeriesPartition:
        pid = self._pid_for_key(key)  # dict, or the C++ key map (restored)
        if pid is not None:
            part = self.partitions[pid]
            if part is not None:
                return part
            # a concurrent purge raced this lookup; fall through to recreate
            self._by_key.pop(key, None)
        self.cardinality.series_created(key.label_map)  # may raise quota
        schema = self.schemas[key.schema]
        pid = len(self.partitions)
        native_backed = False
        if self._native_core is not None:
            # every partition gets a native slot so pid numbering stays
            # aligned across both sides; only all-double schemas are
            # native-backed (records of other schemas can never reach the
            # native lane — their containers fail the scalar pre-scan)
            ncols = len(schema.data.columns) - 1
            nat_pid = self._native_core.create_part(key, ncols)
            assert nat_pid == pid, (nat_pid, pid)
            native_backed = self._native_eligible(schema)
        if native_backed:
            from filodb_tpu.core.memstore.native_shard import (
                NativeBackedPartition,
            )
            part = NativeBackedPartition(self._native_core, pid, key, schema,
                                         self.config.max_chunk_size,
                                         self.shard_num)
        else:
            cls = TimeSeriesPartition
            if self.config.trace_part_key_substrings:
                from filodb_tpu.core.memstore.partition import (
                    TracingTimeSeriesPartition,
                )
                kstr = str(key)
                if any(s in kstr
                       for s in self.config.trace_part_key_substrings):
                    cls = TracingTimeSeriesPartition
            part = cls(pid, key, schema, self.config.max_chunk_size,
                       self.shard_num, device_pages=self.config.device_pages,
                       buffer_pool=self._pool_for(schema))
        floor = self._persisted_floors.get(key)
        if floor is not None:
            part.seed_dedup_floor(floor)
        self.partitions.append(part)
        if self._native_core is not None and not native_backed:
            # AFTER the append: a concurrent chunk_bytes() snapshot of
            # _host_pids must never index past the partitions list
            self._host_pids.add(pid)
        self._by_key[key] = pid
        self.index.add_part_key(pid, key, first_ts)
        if self.evicted_keys.count:
            from filodb_tpu.core.memstore.native_shard import part_key_blob
            self._maybe_restore_evicted(pid, key, part_key_blob(key), part)
        self._dirty_part_keys.add(pid)
        self.stats.partitions_created.inc()
        self.stats.num_partitions.set(len(self.index))
        return part

    def _pool_for(self, schema):
        from filodb_tpu.core.memstore.partition import WriteBufferPool
        pool = self.buffer_pools.get(schema.name)
        if pool is None:
            pool = self.buffer_pools[schema.name] = WriteBufferPool(
                schema, self.config.max_chunk_size)
        return pool

    def _maybe_restore_evicted(self, pid: int, key: PartKey, blob: bytes,
                               part) -> None:
        """A series whose key hits the evicted-partkey bloom may be a
        previously-evicted series coming back: transfer the original
        startTime onto the new pid, retire the old index entry, and seed
        the dedup floor from the old endTime so replayed history can't
        double-ingest (reference TimeSeriesShard.scala:457 bloom +
        partkey restore)."""
        self.stats.bloom_queries.inc()
        if blob not in self.evicted_keys:
            return
        old = self.index.pid_for_exact_key(key, blob, exclude=pid)
        if old is None:
            self.stats.bloom_fp.inc()
            return  # bloom false positive
        old_start = self.index.start_time(old)
        old_end = self.index.end_time(old)
        if old_start < self.index.start_time(pid):
            self.index.set_start_time(pid, old_start)
        self.index.remove_part_key(old)
        if old < len(self.partitions):
            self.partitions[old] = None
        if old_end < 2**62:
            part.seed_dedup_floor(old_end)
        self._dirty_part_keys.add(pid)
        self.stats.partitions_restored.inc()

    def partition(self, part_id: int) -> TimeSeriesPartition | None:
        if part_id >= len(self.partitions):
            return None
        p = self.partitions[part_id]
        if p is None and self.index.part_key(part_id) is not None:
            # evicted partition, still indexed: materialize an empty shell —
            # reads page chunks back from the column store via ODP
            # (reference PagedReadablePartition over an evicted partId)
            return self._paged_shell(part_id)
        return p

    def _paged_shell(self, part_id: int) -> TimeSeriesPartition | None:
        key = self.index.part_key(part_id)
        if key is None:
            return None
        schema = self.schemas[key.schema]
        shell = TimeSeriesPartition(part_id, key, schema,
                                    self.config.max_chunk_size,
                                    self.shard_num,
                                    device_pages=self.config.device_pages)
        self.partitions[part_id] = shell  # cache; last-wins under races
        if self._native_core is not None:
            self._host_pids.add(part_id)
        return shell

    @property
    def num_partitions(self) -> int:
        # the index counts live keys; _by_key is empty for snapshot-restored
        # native shards (the C++ key map is authoritative there)
        return len(self.index)

    # ---- ingest ----------------------------------------------------------

    def ingest(self, data: SomeData) -> int:
        # stall/error injection point for freshness-alert chaos tests
        FaultInjector.fire("shard.ingest", dataset=self.dataset,
                           shard=self.shard_num, offset=data.offset)
        with traced_operation("ingest", dataset=self.dataset,
                              shard=self.shard_num):
            with self.stats.ingestion_pipeline_latency.time():
                return self._ingest_timed(data)

    def _ingest_timed(self, data: SomeData) -> int:
        """Ingest one container at an offset. Returns rows ingested."""
        if self.config.assert_single_writer:
            # single-writer-per-shard discipline tripwire (reference
            # FiloSchedulers.assertThreadName, TimeSeriesShard.scala:571)
            import threading
            tid = threading.get_ident()
            owner = getattr(self, "_writer_thread", None)
            if owner is None:
                self._writer_thread = tid
            elif owner != tid:
                raise AssertionError(
                    f"shard {self.shard_num} ingested from thread {tid}, "
                    f"owner is {owner}")
        with self.write_lock:
            return self._ingest_locked(data, data.offset)

    def _native_eligible(self, schema) -> bool:
        from filodb_tpu.core.schemas import ColumnType
        n_hist = 0
        for c in schema.data.columns[1:]:
            if c.ctype == ColumnType.HISTOGRAM:
                n_hist += 1
            elif c.ctype != ColumnType.DOUBLE:
                return False
        return n_hist <= 1  # native lane covers doubles + one hist column

    def _drain_native_parts(self) -> None:
        """Register partitions the C++ core created during ingest: index,
        cardinality metering, dirty part keys."""
        from filodb_tpu.core.memstore.native_shard import (
            NativeBackedPartition,
            part_key_from_blob,
        )
        core = self._native_core
        for pid in core.drain_new_parts():
            blob = core.key_blob(pid)
            key = part_key_from_blob(blob, self.schemas)
            # seed the hash from the container record: group_of/flush would
            # otherwise recompute it via the serialized blob
            key.__dict__["part_hash"] = core.part_hash(pid)
            # the wrapper stays blob-backed: the transient PartKey above is
            # only needed for registration and is dropped afterwards — at
            # 1M series, per-key PartKey objects (labels tuple + __dict__
            # caches) dominate resident memory; the C++ key map is the
            # authoritative lookup
            part = NativeBackedPartition(core, pid,
                                         max_chunk_size=self.config
                                         .max_chunk_size,
                                         shard=self.shard_num,
                                         key_blob=blob,
                                         schemas=self.schemas)
            assert pid == len(self.partitions), (pid, len(self.partitions))
            floor = self._persisted_floors.get(key)
            if floor is not None:
                part.seed_dedup_floor(floor)
            self.partitions.append(part)
            self.cardinality.series_created(key.label_map)
            self.index.add_part_key_blob(pid, key, blob, part.first_ts)
            if self.evicted_keys.count:
                self._maybe_restore_evicted(pid, key, blob, part)
            self._dirty_part_keys.add(pid)
            self.stats.partitions_created.inc()
        self.stats.num_partitions.set(len(self.index))

    def _ingest_native_locked(self, raw: bytes, offset: int) -> int:
        """Fast lane: container bytes parsed + appended + sealed in C++.
        Returns rows ingested, or -1 → caller takes the host loop."""
        core = self._native_core
        n = core.ingest(raw, offset)
        if n < 0:
            return -1
        from filodb_tpu.core.record import container_max_ts
        mx = container_max_ts(raw)
        if mx > self._max_ingested_ts:
            self._max_ingested_ts = mx
        if core.stat(4):
            self._drain_native_parts()
        skipped, ooo = core.stat(1), core.stat(2)
        if skipped != self._nat_skipped_seen:
            self.stats.rows_skipped.inc(skipped - self._nat_skipped_seen)
            self._nat_skipped_seen = skipped
        if ooo != self._nat_ooo_seen:
            self.stats.out_of_order_dropped.inc(ooo - self._nat_ooo_seen)
            self._nat_ooo_seen = ooo
        incompat = core.stat(5)
        if incompat != self._nat_incompat_seen:
            self.stats.incompatible_containers.inc(
                incompat - self._nat_incompat_seen)
            self._nat_incompat_seen = incompat
        self._ingested_offset = max(self._ingested_offset, offset)
        self.stats.rows_ingested.inc(n)
        return n

    def _ingest_locked(self, data: SomeData, offset: int) -> int:
        from filodb_tpu.core.memstore.cardinality import QuotaExceededError
        if self._native_core is not None \
                and not self.cardinality.has_quotas:
            raw = getattr(data.container, "raw", None)
            if raw is not None:
                n = self._ingest_native_locked(raw, offset)
                if n >= 0:
                    return n
        n = 0
        last_ts = -1
        for rec in data.container:
            group = self.group_of(rec.part_key)
            if offset <= self.group_watermarks[group]:
                self.stats.rows_skipped.inc()  # recovery replay below watermark
                continue
            try:
                part = self.get_or_create_partition(rec.part_key,
                                                    rec.timestamp)
            except QuotaExceededError:
                self.stats.quota_dropped.inc()
                from filodb_tpu.utils.governor import record_tenant_drop
                record_tenant_drop(rec.part_key.label_map)
                continue
            except KeyError:
                self.stats.unknown_schema_dropped.inc()
                continue
            if part.ingest(rec.timestamp, rec.values):
                n += 1
                last_ts = rec.timestamp
                if rec.timestamp > self._max_ingested_ts:
                    self._max_ingested_ts = rec.timestamp
            else:
                self.stats.out_of_order_dropped.inc()
        self._ingested_offset = max(self._ingested_offset, offset)
        self.stats.rows_ingested.inc(n)
        if last_ts > 0:
            self.stats.ingestion_clock_delay.set(
                int(_time.time() * 1000) - last_ts)
        return n

    @property
    def latest_offset(self) -> int:
        return self._ingested_offset

    # ---- flush -----------------------------------------------------------

    def flush_group(self, group: int, ingestion_time: int | None = None) -> int:
        """Flush all dirty partitions in a group (reference ``doFlushSteps``).
        Returns number of chunks written. Slow flushes land in the
        ingest-side flight recorder (``tracing.slow_ingest``)."""
        with traced_operation("flush", dataset=self.dataset,
                              shard=self.shard_num, group=group):
            return self._flush_group(group, ingestion_time)

    def _flush_group(self, group: int, ingestion_time: int | None) -> int:
        if ingestion_time is None:
            ingestion_time = int(_time.time() * 1000)
        written = 0
        t_flush0 = _time.perf_counter()
        dirty_pks: list[PartKeyRecord] = []
        # Capture the checkpoint offset BEFORE snapshotting any buffers:
        # rows at or below this offset are guaranteed to be in the buffers
        # we are about to seal. Rows ingested mid-flush (offset > captured)
        # may or may not make this flush; they stay above the watermark and
        # are replayed on recovery (idempotent: duplicate timestamps are
        # dropped as out-of-order). The reference captures the flush
        # watermark at buffer-switch time for the same reason.
        with self.write_lock:
            checkpoint_offset = self._ingested_offset
        for part in self.partitions:
            if part is None or self.group_of(part.part_key) != group:
                continue
            with self.write_lock:
                chunks = part.make_flush_chunks()
            if chunks:
                try:
                    self.column_store.write_chunks(
                        self.dataset, self.shard_num, part.part_key, chunks,
                        ingestion_time)
                except Exception:
                    self.stats.flushes_failed.inc()
                    raise
                part.mark_flushed(max(c.id for c in chunks))
                written += len(chunks)
                st = self.stats
                st.samples_encoded.inc(sum(c.num_rows for c in chunks))
                st.encoded_bytes.inc(sum(c.nbytes for c in chunks))
                from filodb_tpu.memory.codecs import CODEC_HIST_2D_DELTA
                st.encoded_hist_bytes.inc(sum(
                    len(v) for c in chunks for v in c.vectors
                    if v and v[0] == CODEC_HIST_2D_DELTA))
                if self.downsampler is not None:
                    before = getattr(self.downsampler, "records_created", 0)
                    self.downsampler.on_flush(part, chunks)
                    after = getattr(self.downsampler, "records_created", 0)
                    st.downsample_records_created.inc(after - before)
            if part.part_id in self._dirty_part_keys:
                dirty_pks.append(PartKeyRecord(
                    part.part_key, self.index.start_time(part.part_id),
                    self.index.end_time(part.part_id)))
                self._dirty_part_keys.discard(part.part_id)
        if dirty_pks:
            self.column_store.write_part_keys(self.dataset, self.shard_num,
                                              dirty_pks)
            self.stats.dirty_keys_flushed.inc(len(dirty_pks))
        # checkpoint: everything at or below this offset for this group is safe
        self.meta_store.write_checkpoint(self.dataset, self.shard_num, group,
                                         checkpoint_offset)
        self.group_watermarks[group] = max(self.group_watermarks[group],
                                           checkpoint_offset)
        if self._native_core is not None:
            self._native_core.set_watermark(group,
                                            self.group_watermarks[group])
        self.stats.chunks_flushed.inc(written)
        self.stats.flushes_done.inc()
        self.stats.flush_latency.observe(_time.perf_counter() - t_flush0)
        self.stats.offset_latest_in_mem.set(self._ingested_offset)
        self.stats.offset_flushed_latest.set(max(self.group_watermarks))
        self.stats.offset_flushed_earliest.set(min(self.group_watermarks))
        return written

    def flush_all(self, ingestion_time: int | None = None) -> int:
        """Flush every group; groups run concurrently up to
        ``flush_task_parallelism`` (reference ``flush-task-parallelism``,
        ``TimeSeriesMemStore.scala:130-135``). Group flushes touch disjoint
        partitions, so they parallelize safely."""
        par = max(self.config.flush_task_parallelism, 1)
        groups = range(self.config.groups_per_shard)
        if par == 1:
            return sum(self.flush_group(g, ingestion_time) for g in groups)
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=par) as ex:
            return sum(ex.map(
                lambda g: self.flush_group(g, ingestion_time), groups))

    def next_flush_group(self) -> int:
        """Round-robin group scheduling (the reference staggers groups across
        the flush interval, ``createFlushTasks:889``)."""
        self._last_flushed_group = (self._last_flushed_group + 1) \
            % self.config.groups_per_shard
        return self._last_flushed_group

    # ---- recovery --------------------------------------------------------

    def setup_watermarks_for_recovery(self) -> int:
        """Load per-group checkpoints; returns the replay start offset
        (min over groups, reference ``recoverStream`` contract)."""
        cps = self.meta_store.read_checkpoints(self.dataset, self.shard_num)
        for g, off in cps.items():
            if g < len(self.group_watermarks):
                self.group_watermarks[g] = off
                if self._native_core is not None:
                    self._native_core.set_watermark(g, off)
        missing = self.config.groups_per_shard - len(
            [g for g in cps if g < len(self.group_watermarks)])
        if cps and missing > 0:
            self.stats.offsets_not_recovered.inc(missing)
        return min(cps.values()) if cps else -1

    def recover_index(self) -> int:
        """Restore the tag index (reference ``IndexBootstrapper``). Returns
        #keys restored.

        Fast path: load the persisted index snapshot (postings + key blobs
        + floors in one pass; the C++ core bulk-bootstraps its key map) and
        delta-replay only part keys / chunk floors written after the
        snapshot's tokens. Fallback: full part-key scan.

        Each recovered partition's out-of-order floor is seeded with the max
        persisted chunk timestamp so WAL replay of rows that were flushed
        just before the crash (ingested mid-flush, above the checkpoint) is
        deduplicated instead of double-written."""
        t0 = _time.perf_counter()
        try:
            return self._recover_index_inner()
        finally:
            self.stats.recovery_time_ms.set(
                (_time.perf_counter() - t0) * 1000.0)

    def _recover_index_inner(self) -> int:
        if not self.partitions:
            snap = self.column_store.read_index_snapshot(self.dataset,
                                                         self.shard_num)
            if snap:
                try:
                    return self._recover_from_snapshot(snap)
                except Exception:
                    log.exception("index snapshot restore failed for "
                                  "%s/%d; falling back to full rebuild",
                                  self.dataset, self.shard_num)
                    self._reset_registry()
        self._persisted_floors = self.column_store.max_persisted_ts(
            self.dataset, self.shard_num)
        n = 0
        for rec in self.column_store.scan_part_keys(self.dataset, self.shard_num):
            if rec.part_key in self._by_key:
                continue
            # get_or_create_partition seeds the dedup floor
            part = self.get_or_create_partition(rec.part_key, rec.start_time)
            self.index.update_end_time(part.part_id, rec.end_time)
            self._dirty_part_keys.discard(part.part_id)
            n += 1
        self.stats.index_recovery_partkeys.inc(n)
        return n

    def _reset_registry(self) -> None:
        """Clear partition/index/native/cardinality state after a failed
        restore (a partially-loaded tracker would double-count during the
        full-rebuild fallback)."""
        from filodb_tpu.core.memstore.cardinality import CardinalityTracker
        from filodb_tpu.utils.governor import apply_tenant_quotas
        self.partitions = []
        self._by_key = {}
        self._host_pids = set()
        self.index = PartKeyIndex(self.schemas)
        self.cardinality = CardinalityTracker(self.shard_num)
        apply_tenant_quotas(self.cardinality)
        if self._native_core is not None:
            from filodb_tpu.core.memstore.native_shard import NativeShardCore
            self._native_core = NativeShardCore(self.config.max_chunk_size,
                                                self.config.groups_per_shard)

    def _recover_from_snapshot(self, snap: bytes) -> int:
        from filodb_tpu.core.memstore.index_snapshot import load_snapshot
        from filodb_tpu.core.memstore.native_shard import part_key_blob
        info = load_snapshot(self, snap)
        # delta: part keys created/updated after the snapshot's token
        for rec in self.column_store.scan_part_keys_since(
                self.dataset, self.shard_num, info["pk_token"]):
            pid = self._pid_for_key(rec.part_key)
            if pid is None:
                part = self.get_or_create_partition(rec.part_key,
                                                    rec.start_time)
                pid = part.part_id
                self._dirty_part_keys.discard(pid)
            self.index.update_end_time(pid, rec.end_time)
        # delta: chunk floors written after the snapshot's token
        delta_floors = self.column_store.max_persisted_ts_since(
            self.dataset, self.shard_num, info["chunk_token"])
        self._persisted_floors = delta_floors  # replay-created partitions
        for key, mx in delta_floors.items():
            pid = self._pid_for_key(key)
            if pid is not None and self.partitions[pid] is not None:
                self.partitions[pid].seed_dedup_floor(mx)
        self.stats.num_partitions.set(len(self.index))
        return len(self.index)

    def _pid_for_key(self, key: PartKey) -> int | None:
        pid = self._by_key.get(key)
        if pid is not None:
            return pid
        if self._native_core is not None:
            from filodb_tpu.core.memstore.native_shard import part_key_blob
            nat = self._native_core.lookup(part_key_blob(key))
            if nat >= 0:
                return nat
        return None

    def snapshot_index(self) -> int:
        """Serialize + persist the index snapshot (reference: the Lucene
        index directory surviving restarts). Returns snapshot bytes."""
        from filodb_tpu.core.memstore.index_snapshot import save_snapshot
        chunk_token, pk_token = self.column_store.update_tokens(
            self.dataset, self.shard_num)
        with self.write_lock:
            data = save_snapshot(self, chunk_token=chunk_token,
                                 pk_token=pk_token,
                                 snapshot_ms=int(_time.time() * 1000))
        self.column_store.write_index_snapshot(self.dataset, self.shard_num,
                                               data)
        return len(data)

    # ---- retention -------------------------------------------------------

    def purge_expired(self, now_ms: int) -> int:
        """Drop partitions whose data is entirely past retention
        (reference TTL purge ``TimeSeriesShard.scala:838``)."""
        cutoff = now_ms - self.config.retention_ms
        purged = 0
        t0 = _time.perf_counter()
        with self.write_lock:
            for pid, part in enumerate(self.partitions):
                if part is None:
                    continue
                latest = part.latest_ts
                if latest != -1 and latest < cutoff:
                    self.index.remove_part_key(pid)
                    self._by_key.pop(part.part_key, None)
                    self._host_pids.discard(pid)
                    if hasattr(part, "release_buffers"):
                        part.release_buffers()
                    self.partitions[pid] = None
                    if self._native_core is not None:
                        # EVERY partition has a native slot (pid alignment),
                        # not just native-backed ones — free it or the C++
                        # by_key entry survives and the next re-creation of
                        # this series trips the pid-alignment assert
                        with self._native_core.lock:
                            self._native_core._lib.part_free(
                                self._native_core._core, pid)
                    self.cardinality.series_stopped(part.part_key.label_map)
                    purged += 1
        if purged:
            self.stats.partitions_purged.inc(purged)
            self.stats.partitions_purged_index.inc(purged)
            self.stats.purge_time_ms.inc(
                int((_time.perf_counter() - t0) * 1000))
            self.stats.num_partitions.set(len(self.index))
        return purged

    def evict_partition_chunks(self, part_id: int) -> int:
        """Memory-pressure eviction: drop persisted chunks, keep the
        partition + index entry; reads fall back to ODP (reference
        ``TimeSeriesShard`` eviction ``:1611``)."""
        part = self.partitions[part_id]
        n = part.evict_flushed_chunks() if part else 0
        self.stats.chunkids_evicted.inc(n)
        return n

    def evict_partition(self, part_id: int) -> bool:
        """Fully evict one partition under memory pressure (reference
        ``TimeSeriesShard.scala:1611`` evictForHeadroom): only when every
        sample is persisted; frees the partition object and its native slot
        while KEEPING the index entry (endTime set) so queries can still
        reach the series via a paged shell + ODP; records the key in the
        evicted-partkey bloom so a later re-ingest restores the series
        identity. Caller holds ``write_lock``."""
        from filodb_tpu.core.memstore.native_shard import part_key_blob

        part = self.partitions[part_id]
        if part is None:
            return False
        self.stats.chunkids_evicted.inc(part.evict_flushed_chunks())
        if part.has_unpersisted_data():
            return False  # unpersisted data remains; not evictable
        key = part.part_key
        latest = self.index.end_time(part_id)
        idx_end = latest if latest < 2**62 else part.latest_ts
        if idx_end != -1 and idx_end < 2**62:
            self.index.update_end_time(part_id, idx_end)
        self.evicted_keys.add(part_key_blob(key))
        self._by_key.pop(key, None)
        self._host_pids.discard(part_id)
        if hasattr(part, "release_buffers"):
            part.release_buffers()
        self.partitions[part_id] = None
        if self._native_core is not None:
            with self._native_core.lock:
                self._native_core._lib.part_free(
                    self._native_core._core, part_id)
        self.cardinality.series_stopped(key.label_map)
        self.stats.partitions_evicted.inc()
        return True

    def evict_cold_partitions(self, max_evict: int,
                              now_ms: int | None = None,
                              min_idle_ms: int = 0) -> int:
        """Evict up to ``max_evict`` fully-persisted partitions, coldest
        (oldest latest-sample) first — the reference's time-ordered
        reclaim (``BlockManager.scala:124`` time-ordered block lists)."""
        cands = []
        for pid, p in enumerate(self.partitions):
            if p is None:
                continue
            latest = p.latest_ts
            if now_ms is not None and min_idle_ms \
                    and latest != -1 and latest > now_ms - min_idle_ms:
                continue
            cands.append((latest if latest != -1 else 0, pid))
        cands.sort()
        evicted = 0
        with self.write_lock:
            for _, pid in cands:
                if evicted >= max_evict:
                    break
                if self.evict_partition(pid):
                    evicted += 1
        return evicted

    def chunk_bytes(self) -> int:
        total = 0
        if self._native_core is not None:
            # one C++ pass over every native slot (the flush scheduler
            # calls this each tick; per-partition FFI or a walk of the
            # lazy partition list would be O(series))
            with self._native_core.lock:
                total += int(self._native_core._lib.shard_core_chunk_bytes(
                    self._native_core._core))
            # snapshot: the writer thread mutates the set under write_lock,
            # which this (flush-scheduler) path does not hold
            for pid in list(self._host_pids):
                p = self.partitions[pid]
                if p is not None:
                    total += sum(c.nbytes for c in p.chunks)
            return total
        for p in self.partitions:
            if p is None:
                continue
            nb = getattr(p, "chunk_nbytes", None)
            total += nb if nb is not None \
                else sum(c.nbytes for c in p.chunks)
        return total

    def enforce_memory(self, budget_bytes: int | None = None) -> int:
        """Evict persisted chunks, oldest-data partitions first, until chunk
        memory fits the shard budget (reference eviction under memory
        pressure with time-ordered reclaim, ``BlockManager`` "time-ordered"
        lists). Returns chunks evicted."""
        budget = budget_bytes if budget_bytes is not None \
            else self.config.shard_mem_mb * 1024 * 1024
        used = self.chunk_bytes()
        if used <= budget:
            return 0
        t0 = _time.perf_counter()
        evicted = 0
        parts = sorted((p for p in self.partitions if p is not None),
                       key=lambda p: p.latest_ts)
        for p in parts:
            if used <= budget:
                break
            before = sum(c.nbytes for c in p.chunks)
            n = p.evict_flushed_chunks()
            if n:
                used -= before - sum(c.nbytes for c in p.chunks)
                evicted += n
        if used > budget:
            # chunk eviction alone didn't reach the budget: fall back to
            # whole-partition eviction of the coldest fully-persisted series
            # (frees write buffers + native slots; queries keep working via
            # paged shells + ODP)
            headroom = max(len(self.index) // 20, 64)
            self.evict_cold_partitions(headroom)
        self.stats.eviction_stall_ns.inc(
            int((_time.perf_counter() - t0) * 1e9))
        return evicted

    def mark_part_ended(self, part_id: int, end_time: int) -> None:
        self.index.update_end_time(part_id, end_time)
        self._dirty_part_keys.add(part_id)

    # ---- query support ---------------------------------------------------

    def lookup_partitions(self, filters, start: int, end: int) -> list[int]:
        ids = self.index.part_ids_from_filters(filters, start, end)
        self.stats.partitions_queried.inc(len(ids))
        if end > start and end < INGESTING:
            self.stats.query_time_range_minutes.observe(
                (end - start) / 60_000.0)
        return ids

    def label_values(self, label: str, filters=None,
                     start: int = 0, end: int = INGESTING) -> list[str]:
        return self.index.label_values(label, filters, start, end)

    def label_names(self) -> list[str]:
        return self.index.label_names()
