"""Server/dataset configuration.

Counterpart of the reference's layered HOCON config system
(``filodb-defaults.conf`` ← server conf ← per-dataset source conf, parsed
into ``FilodbSettings``/``StoreConfig``/``IngestionConfig``). The format here
is JSON (stdlib; HOCON adds no capability), with the same layering: defaults
← server file ← per-dataset blocks.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from filodb_tpu.core.store.config import IngestionConfig, StoreConfig

DEFAULTS = {
    "node_name": "node-0",
    "data_dir": "./filodb-data",
    "wal_dir": None,
    "wal_fsync": False,           # fsync every WAL append (power-failure safe)
    "wal_server_port": 0,         # serve this node's WAL over TCP (broker)
    "wal_remote": None,           # "host:port" — use a remote log server
    "wal_kafka": None,            # "host:port" — external Kafka broker WAL
    "consul": None,               # {"host","port","service"} seed discovery
    "store_server_port": 0,       # serve this node's column store over TCP
    "store_remote": None,         # "host:port" — use a remote chunk store
    "http_port": 8080,
    "gateway_port": 0,            # 0 = disabled
    "executor_port": 0,           # plan-shipping server; 0 = ephemeral
    "seeds": [],                  # bootstrap seed addresses
    "enable_failover": False,     # singleton failover via member registry
    # fault-tolerance knobs (filodb_tpu.utils.resilience.ResilienceConfig);
    # keys here override that dataclass's defaults at boot
    "resilience": {
        "query_timeout_s": 30.0,      # per-query deadline
        "retry_max_attempts": 2,      # remote dispatch attempts
        "breaker_failure_threshold": 5,
        "breaker_reset_s": 10.0,
        "allow_partial": True,        # degrade instead of fail
        "partial_max_fraction": 0.5,  # max lost children per gather
    },
    # extent result cache (filodb_tpu.query.result_cache.ResultCacheConfig):
    # range queries split at step-aligned extent boundaries; extents ending
    # before the mutable horizon cache without a version stamp, so live
    # ingest only recomputes the head
    "result_cache": {
        "enabled": True,
        "extent_steps": 32,           # extent length in steps
        "max_bytes": 256 * 1024 * 1024,
        "ooo_allowance_ms": 300_000,  # out-of-order arrival allowance
    },
    # overload protection (filodb_tpu.utils.governor.GovernorConfig): query
    # admission control, scan-time cost budgets (0 = unlimited), and the
    # memory-pressure watchdog thresholds. Keys here override that
    # dataclass's defaults at boot.
    "governor": {
        "admission_capacity": 32,     # concurrent queries when healthy
        "admission_queue_limit": 128,
        "max_queue_wait_s": 5.0,
        "retry_after_s": 1.0,
        "degraded_capacity_factor": 0.5,
        "degraded_threshold": 0.75,
        "critical_threshold": 0.92,
        "watchdog_interval_s": 0.5,
        "max_samples_scanned": 0,     # per-query budget; 0 = unlimited
        "max_result_bytes": 0,
        "max_group_cardinality": 0,
        "budget_degrade": "partial",  # "partial" | "error"
        # concurrent standing-query (rule) evaluations; their own lowest-
        # priority admission class (never queued, shed outside OK)
        "rules_max_inflight": 2,
        # per-tenant admission classes + cardinality quotas keyed on the
        # _ws_ or _ws_/_ns_ shard-key prefix, e.g.
        #   "tenants": {"demo/App-0": {"max_inflight": 8,
        #                              "max_series": 100000}}
        # a flooding tenant sheds ONLY itself (reject reason "tenant" /
        # quota-dropped ingest), never its neighbors
        "tenants": {},
    },
    # trace-driven adaptive planner (filodb_tpu.query.cost_model.CostModel):
    # online per-(dataset, plan-signature) cost estimates routing the
    # either/or planning decisions (sidecar vs decode, pyramid fallback,
    # pushdown, lane, paging, admission class, cache admission). Below
    # min_samples every site reproduces the static heuristic exactly;
    # FILODB_ADAPTIVE=0 disables routing entirely (observation continues).
    "cost_model": {
        "min_samples": 8,             # arm warm-up before routing departs
        "max_signatures": 4096,       # LRU bound on (site, signature) keys
        "reservoir": 64,              # percentile reservoir per arm
        "cheap_threshold_s": 0.05,    # admit-class CHEAP/EXPENSIVE split
    },
    # distributed query tracing + slow-query flight recorder
    # (filodb_tpu.utils.tracing.TracingConfig): head-sampling rate for
    # full span trees (deterministic on query_id), tail capture of any
    # query/operation over the slow threshold into a bounded ring served
    # at /api/v1/debug/slow_queries and `filo-cli slowlog`
    "tracing": {
        "sample_rate": 0.0,           # 0..1 fraction of queries traced
        "slow_query_threshold_ms": 500.0,  # tail capture; 0 disables
        "slowlog_capacity": 128,      # flight-recorder ring size
        # ingest-side ring: slow gateway drains / shard ingests / flushes /
        # object-store uploads, served at /api/v1/status/ingest
        "slow_ingest_threshold_ms": 250.0,
        "ingest_slowlog_capacity": 128,
    },
    # self-monitoring (filodb_tpu/utils/selfmon.py): sample the in-process
    # metric registry every interval_s and ingest the families as series
    # into the dedicated "_meta" dataset through the normal ingest path —
    # PromQL, the result cache and standing rules/alerts all work over the
    # node's own telemetry. default_alerts ships an ingest-lag +
    # breaker-open alert group evaluated over _meta.
    "selfmon": {
        "enabled": False,
        "interval_s": 15.0,
        "num_shards": 1,
        "include_buckets": False,     # also ingest per-le bucket series
        "ooo_allowance_ms": 2_000,    # _meta rules horizon allowance
        "default_alerts": True,
        "lag_alert_threshold_s": 60.0,
        "lag_alert_for": "30s",
        "alert_interval": "5s",       # default alert group eval interval
    },
    # live shard migration / rebalancing (coordinator/migration.py)
    "migration": {
        "auto_rebalance": False,      # migrate shards off joining-node
                                      # imbalance and watchdog pressure
        "lag_threshold": 0,           # max replay-offset lag at flip
        "catchup_timeout_s": 30.0,    # abort CATCHUP after this long
    },
    # multi-process mesh runtime (parallel/multiproc.py +
    # coordinator/mesh_cluster.py): N worker processes each own a
    # contiguous slice of one dataset's shard space and execute lowered
    # mesh descriptors over per-process 1-device mesh slices; the
    # coordinator reduces at window boundaries and falls back to the
    # single-process engines when a slice is unavailable.
    "mesh_workers": {
        "enabled": False,
        "workers": 2,                 # processes to spawn (N×1 harness)
        "base_port": 0,               # 0 = ephemeral per worker
        "dataset": None,              # None = first configured dataset
        "timeout_s": 30.0,            # per-worker dispatch timeout cap
        "ready_timeout_s": 120.0,     # boot wait before serving degraded
        "seed": None,                 # module:callable harness data source
    },
    # continuous shard replication / HA serving
    # (coordinator/replication.py)
    "replication": {
        "n_replicas": 0,              # warm followers per shard (0 = off)
        "in_sync_lag": 0,             # max WAL-offset lag to count IN_SYNC
        "hedge_s": 0.05,              # hedged-read timer for replica reads
        "durable_sync_s": 5.0,        # follower sealed-segment sync cadence
    },
    # standing queries (filodb_tpu/rules): recording + alerting rule
    # groups evaluated incrementally on ingest progress. Each group:
    #   {"name": ..., "interval": "60s", "dataset": <defaults to first>,
    #    "rules": [{"record": "job:heap:avg", "expr": "...",
    #               "labels": {...}},
    #              {"alert": "HighHeap", "expr": "... > 0.9",
    #               "for": "5m", "labels": {...},
    #               "annotations": {...}}]}
    # intervals must be whole seconds; durations accept Prometheus
    # strings ("5m") or bare numbers meaning seconds.
    "rules": {
        "tick_s": 1.0,                # evaluation-loop poll interval
        "max_catchup_steps": 512,     # cap on steps replayed per tick
        "groups": [],
        # alert notification egress (rules/notify.py): Alertmanager-style
        # webhook POSTed on alert state transitions. webhook_url=None
        # disables egress entirely. Delivery is at-most-once off a
        # bounded queue; the POST never runs under the manager's locks.
        "notify": {
            "webhook_url": None,
            "timeout_s": 5.0,         # per-POST socket timeout
            "max_attempts": 4,        # RetryPolicy attempts per batch
            "queue_depth": 256,       # pending batches before dropping
        },
    },
    # tiered query federation (query/federation.py + coordinator/
    # tiered_planner.py): one query_range transparently spans the raw
    # memstore, the downsample tier and object-store history. Sub-ranges
    # older than memstore retention page chunks from the column store via
    # per-shard ODP caches and are stitched with the hot result.
    "federation": {
        "enabled": True,
        # memstore data floor; None = derive from the dataset's
        # store.retention_ms at boot
        "mem_retention_ms": None,
        "odp_max_chunks": 10_000,     # per cold shard ODP cache capacity
        "refresh_s": 60.0,            # cold part-key index staleness bound
    },
    # durable-store backend selection. "local" = sqlite-per-shard on
    # data_dir (default); "object" = S3-compatible object-store tier
    # (core/store/objectstore.py): write-behind segment upload, CRC32C
    # tripwires, key-prefix split scans. With backend="object" and no
    # endpoint, a directory-backed in-process fake under data_dir is used
    # (hermetic dev/test); "http(s)://host:port" targets a real
    # S3-compatible service (minio etc.).
    "store": {
        "backend": "local",
        "endpoint": None,
        "bucket": "filodb",
        "prefix": "",
        "access_key": None,
        "secret_key": None,
        "region": "us-east-1",
        "upload_queue_depth": 64,        # bounded write-behind queue
        "segment_target_bytes": 1 << 20,  # seal open segments at this size
        "bucket_count": 8,               # key-prefix split-scan fan-out
    },
    "datasets": {
        "timeseries": {
            "num_shards": 4,
            "min_num_nodes": 1,
            "spread": 1,
            # "engine": "mesh" lowers supported aggregations onto the
            # (shard × time) device mesh on single-node deployments
            "engine": "mesh",
            "store": {
                "flush_interval_ms": 3_600_000,
                "max_chunk_size": 400,
                "groups_per_shard": 20,
                "retention_ms": 3 * 24 * 3_600_000,
            },
            # optional downsampling plane:
            # "downsample": {"resolutions_ms": [300000, 3600000],
            #                "schedule_s": 21600,
            #                "raw_retention_ms": 259200000}
        }
    },
}


@dataclass
class ServerConfig:
    node_name: str = "node-0"
    data_dir: str = "./filodb-data"
    wal_dir: str | None = None  # shared log dir (the "Kafka"); default in data_dir
    wal_fsync: bool = False     # fsync every WAL append (power-failure safe)
    wal_server_port: int = 0    # serve this node's WAL over TCP (broker)
    wal_remote: str | None = None  # "host:port" — use a remote log server
    wal_kafka: str | None = None  # "host:port" — external Kafka broker
    consul: dict | None = None    # Consul seed discovery settings
    store_server_port: int = 0    # serve the column store over TCP
    store_remote: str | None = None  # "host:port" — remote chunk store
    http_port: int = 8080
    http_reuse_port: bool = False  # SO_REUSEPORT multi-process serving
    http_impl: str = "fast"  # "fast" event loop | "threaded" stdlib server
    http_response_cache: bool = True  # data_version-keyed rendered-JSON cache
    gateway_port: int = 0
    executor_port: int = 0
    seeds: list[str] = field(default_factory=list)
    enable_failover: bool = False
    datasets: dict[str, IngestionConfig] = field(default_factory=dict)
    spreads: dict[str, int] = field(default_factory=dict)
    downsample: dict[str, dict] = field(default_factory=dict)
    engines: dict[str, str] = field(default_factory=dict)  # dataset → engine
    resilience: dict = field(default_factory=dict)  # ResilienceConfig overrides
    result_cache: dict = field(default_factory=dict)  # ResultCacheConfig block
    governor: dict = field(default_factory=dict)  # GovernorConfig overrides
    cost_model: dict = field(default_factory=dict)  # adaptive planner config
    store: dict = field(default_factory=dict)  # durable-store backend block
    migration: dict = field(default_factory=dict)  # live-migration knobs
    mesh_workers: dict = field(default_factory=dict)  # multi-process mesh
    replication: dict = field(default_factory=dict)  # shard-replica knobs
    rules: dict = field(default_factory=dict)  # standing-query rule groups
    tracing: dict = field(default_factory=dict)  # TracingConfig overrides
    selfmon: dict = field(default_factory=dict)  # _meta self-monitoring
    federation: dict = field(default_factory=dict)  # tiered-query routing

    @staticmethod
    def load(path: str | None = None) -> "ServerConfig":
        cfg = json.loads(json.dumps(DEFAULTS))  # deep copy
        if path:
            with open(path) as f:
                user = json.load(f)
            _deep_merge(cfg, user)
        datasets = {}
        spreads = {}
        downsample = {}
        engines = {}
        for name, d in cfg["datasets"].items():
            if d.get("downsample"):
                downsample[name] = d["downsample"]
            store = StoreConfig(**{k: v for k, v in d.get("store", {}).items()
                                   if k in StoreConfig.__dataclass_fields__})
            datasets[name] = IngestionConfig(
                dataset=name, num_shards=d.get("num_shards", 4),
                min_num_nodes=d.get("min_num_nodes", 1), store=store,
                downsample=d.get("downsample"))
            spreads[name] = d.get("spread", 1)
            engines[name] = d.get("engine", "mesh")
        return ServerConfig(
            node_name=cfg["node_name"], data_dir=cfg["data_dir"],
            wal_dir=cfg.get("wal_dir"),
            wal_fsync=cfg.get("wal_fsync", False),
            wal_server_port=cfg.get("wal_server_port", 0),
            wal_remote=cfg.get("wal_remote"),
            wal_kafka=cfg.get("wal_kafka"),
            consul=cfg.get("consul"),
            store_server_port=cfg.get("store_server_port", 0),
            store_remote=cfg.get("store_remote"),
            http_port=cfg["http_port"],
            http_reuse_port=cfg.get("http_reuse_port", False),
            http_impl=cfg.get("http_impl", "fast"),
            http_response_cache=cfg.get("http_response_cache", True),
            gateway_port=cfg["gateway_port"],
            executor_port=cfg["executor_port"], seeds=cfg["seeds"],
            enable_failover=cfg.get("enable_failover", False),
            datasets=datasets, spreads=spreads, downsample=downsample,
            engines=engines, resilience=cfg.get("resilience", {}),
            result_cache=cfg.get("result_cache", {}),
            governor=cfg.get("governor", {}),
            cost_model=cfg.get("cost_model", {}),
            store=cfg.get("store", {}),
            migration=cfg.get("migration", {}),
            mesh_workers=cfg.get("mesh_workers", {}),
            replication=cfg.get("replication", {}),
            rules=cfg.get("rules", {}),
            tracing=cfg.get("tracing", {}),
            selfmon=cfg.get("selfmon", {}),
            federation=cfg.get("federation", {}))


def _deep_merge(base: dict, over: dict) -> None:
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(base.get(k), dict):
            _deep_merge(base[k], v)
        else:
            base[k] = v
