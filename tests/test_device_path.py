"""Decode-on-device query path tests: with ``device_pages`` enabled, queries
run over bit-packed pages decoded on-device (masked kernels) and must match
the host-decoded path to f32 precision.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)

START = 1_600_000_000


def _pair_of_services(streams, keys_schema="gauge"):
    """Same data ingested twice: host path vs device-pages path."""
    out = []
    for device in (False, True):
        ms = TimeSeriesMemStore()
        for s in range(2):
            ms.setup("timeseries", s,
                     StoreConfig(max_chunk_size=100, device_pages=device))
        for stream in streams():
            ingest_routed(ms, "timeseries", stream, 2, spread=1)
        out.append(QueryService(ms, "timeseries", 2, spread=1))
    return out


QUERIES = [
    'sum_over_time(heap_usage[5m])',
    'avg_over_time(heap_usage[7m])',
    'max_over_time(heap_usage[10m])',
    'min_over_time(heap_usage[10m])',
    'count_over_time(heap_usage[5m])',
    'heap_usage',                       # instant last-sample
    'sum(heap_usage)',
    'changes(heap_usage[10m])',
    'deriv(heap_usage[10m])',
]


class TestDevicePathGauges:
    @pytest.fixture(scope="class")
    def svcs(self):
        keys = machine_metrics_series(6)
        return _pair_of_services(
            lambda: [gauge_stream(keys, 500, start_ms=START * 1000, seed=4)])

    @pytest.mark.parametrize("query", QUERIES)
    def test_matches_host_path(self, svcs, query):
        host, dev = svcs
        r_h = host.query_range(query, START + 1800, 120, START + 4500).result
        r_d = dev.query_range(query, START + 1800, 120, START + 4500).result
        assert r_h.num_series == r_d.num_series
        # f32 value quantization on the device path
        np.testing.assert_allclose(r_d.values, r_h.values, rtol=2e-6,
                                   atol=1e-5, equal_nan=True)


class TestDevicePathCounters:
    def test_rate_with_resets(self):
        keys = counter_series(4)
        host, dev = _pair_of_services(
            lambda: [counter_stream(keys, 500, start_ms=START * 1000,
                                    seed=2, reset_every=130)])
        q = 'sum(rate(http_requests_total[5m]))'
        r_h = host.query_range(q, START + 1800, 60, START + 4500).result
        r_d = dev.query_range(q, START + 1800, 60, START + 4500).result
        np.testing.assert_allclose(r_d.values, r_h.values, rtol=5e-5,
                                   atol=1e-4, equal_nan=True)

    def test_quantile_and_holt_winters_on_device(self):
        keys = machine_metrics_series(3)
        host, dev = _pair_of_services(
            lambda: [gauge_stream(keys, 200, start_ms=START * 1000)])
        for q in ('quantile_over_time(0.9, heap_usage[5m])',
                  'holt_winters(heap_usage[10m], 0.5, 0.1)'):
            r_h = host.query_range(q, START + 900, 300, START + 1800).result
            r_d = dev.query_range(q, START + 900, 300, START + 1800).result
            np.testing.assert_allclose(r_d.values, r_h.values, rtol=2e-5,
                                       atol=1e-4, equal_nan=True, err_msg=q)

    def test_write_buffer_included(self):
        # unsealed buffer samples must appear in device-path results
        keys = machine_metrics_series(2)
        host, dev = _pair_of_services(
            lambda: [gauge_stream(keys, 130, start_ms=START * 1000)])
        q = 'count_over_time(heap_usage[30m])'
        r_h = host.query_range(q, START + 1295, 60, START + 1295).result
        r_d = dev.query_range(q, START + 1295, 60, START + 1295).result
        np.testing.assert_array_equal(r_d.values, r_h.values)
        assert r_d.values[0, 0] == 130.0


class TestDevicePathHistograms:
    def test_histogram_quantile_matches_host(self):
        from filodb_tpu.testing.data import histogram_series, histogram_stream

        keys = histogram_series(3)
        host, dev = _pair_of_services(
            lambda: [histogram_stream(keys, 300, start_ms=START * 1000,
                                      seed=9)])
        for q in ('histogram_quantile(0.9, rate(http_req_latency[5m]))',
                  'histogram_quantile(0.5, sum(rate(http_req_latency[5m])))'):
            r_h = host.query_range(q, START + 1200, 120, START + 2700).result
            r_d = dev.query_range(q, START + 1200, 120, START + 2700).result
            assert r_h.num_series == r_d.num_series
            np.testing.assert_allclose(r_d.values, r_h.values, rtol=5e-5,
                                       atol=1e-4, equal_nan=True, err_msg=q)

    def test_hist_buffer_included(self):
        from filodb_tpu.testing.data import histogram_series, histogram_stream

        keys = histogram_series(2)
        host, dev = _pair_of_services(
            lambda: [histogram_stream(keys, 130, start_ms=START * 1000)])
        q = 'histogram_quantile(0.99, rate(http_req_latency[10m]))'
        r_h = host.query_range(q, START + 1295, 60, START + 1295).result
        r_d = dev.query_range(q, START + 1295, 60, START + 1295).result
        np.testing.assert_allclose(r_d.values, r_h.values, rtol=5e-5,
                                   atol=1e-4, equal_nan=True)
