"""Gateway tests: Influx line protocol parsing + TCP ingestion path.

Mirrors reference ``gateway/src/test/scala/filodb/gateway`` specs
(InfluxProtocolParser histogram-aware conversion, GatewaySerer routing).
"""

import socket
import time

import numpy as np
import pytest

from filodb_tpu.gateway.influx import InfluxParseError, parse_influx_line
from filodb_tpu.gateway.server import ContainerSink, GatewayServer
from filodb_tpu.kafka.log import InMemoryLog


class TestInfluxParser:
    def test_simple_gauge(self):
        recs = parse_influx_line(
            "cpu_usage,host=h1,app=api value=42.5 1600000000000000000")
        assert len(recs) == 1
        r = recs[0]
        assert r.part_key.schema == "gauge"
        assert r.part_key.metric == "cpu_usage"
        assert r.part_key.label_map["host"] == "h1"
        assert r.timestamp == 1_600_000_000_000
        assert r.values == (42.5,)

    def test_counter(self):
        recs = parse_influx_line("reqs,host=h counter=100i 1600000000000000000")
        assert recs[0].part_key.schema == "prom-counter"
        assert recs[0].values == (100.0,)

    def test_multi_field_fanout(self):
        recs = parse_influx_line(
            "disk,host=h used=10,free=90 1600000000000000000")
        metrics = sorted(r.part_key.metric for r in recs)
        assert metrics == ["disk_free", "disk_used"]

    def test_histogram_first_class(self):
        line = ("latency,app=api 0.025=1i,0.05=3i,0.1=6i,+Inf=10i,"
                "sum=0.9,count=10i 1600000000000000000")
        recs = parse_influx_line(line)
        assert len(recs) == 1
        r = recs[0]
        assert r.part_key.schema == "prom-histogram"
        s, c, (les, buckets) = r.values
        assert s == 0.9 and c == 10.0
        assert np.isinf(les[-1])
        np.testing.assert_array_equal(buckets, [1, 3, 6, 10])

    def test_escapes(self):
        recs = parse_influx_line(
            r"my\ metric,tag=a\,b value=1 1600000000000000000")
        assert recs[0].part_key.metric == "my metric"
        assert recs[0].part_key.label_map["tag"] == "a,b"

    def test_default_labels(self):
        recs = parse_influx_line("m value=1 1600000000000000000",
                                 {"_ws_": "demo", "_ns_": "App-1"})
        assert recs[0].part_key.label_map["_ws_"] == "demo"

    def test_bool_and_int_suffixes(self):
        recs = parse_influx_line("m up=t,n=5i 1600000000000000000")
        vals = {r.part_key.metric: r.values[0] for r in recs}
        assert vals == {"m_up": 1.0, "m_n": 5.0}

    def test_string_fields_skipped(self):
        recs = parse_influx_line('m value=1,note="hello" 1600000000000000000')
        assert len(recs) == 1  # only numeric field survives

    def test_missing_timestamp_uses_now(self):
        recs = parse_influx_line("m value=1", now_ms=12345)
        assert recs[0].timestamp == 12345

    def test_malformed(self):
        with pytest.raises(InfluxParseError):
            parse_influx_line("justonefield")
        assert parse_influx_line("") == []
        assert parse_influx_line("# comment") == []


class TestGatewayServer:
    def test_tcp_to_logs(self):
        logs = {s: InMemoryLog() for s in range(4)}
        sink = ContainerSink(logs, num_shards=4, spread=1, flush_every=8)
        srv = GatewayServer(sink, {"_ws_": "demo", "_ns_": "App-1"}).start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port)) as s:
                for i in range(20):
                    s.sendall(
                        f"cpu,host=h{i % 3} value={i} "
                        f"{(1_600_000_000 + i) * 1_000_000_000}\n".encode())
            time.sleep(0.2)
            sink.flush()
            total = 0
            for log in logs.values():
                for sd in log.read_from(0):
                    total += len(sd.container)
            assert total == 20
        finally:
            srv.stop()
