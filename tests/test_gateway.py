"""Gateway tests: Influx line protocol parsing + TCP ingestion path.

Mirrors reference ``gateway/src/test/scala/filodb/gateway`` specs
(InfluxProtocolParser histogram-aware conversion, GatewaySerer routing).
"""

import socket
import time

import numpy as np
import pytest

from filodb_tpu.gateway.influx import InfluxParseError, parse_influx_line
from filodb_tpu.gateway.server import ContainerSink, GatewayServer
from filodb_tpu.kafka.log import InMemoryLog


class TestInfluxParser:
    def test_simple_gauge(self):
        recs = parse_influx_line(
            "cpu_usage,host=h1,app=api value=42.5 1600000000000000000")
        assert len(recs) == 1
        r = recs[0]
        assert r.part_key.schema == "gauge"
        assert r.part_key.metric == "cpu_usage"
        assert r.part_key.label_map["host"] == "h1"
        assert r.timestamp == 1_600_000_000_000
        assert r.values == (42.5,)

    def test_counter(self):
        recs = parse_influx_line("reqs,host=h counter=100i 1600000000000000000")
        assert recs[0].part_key.schema == "prom-counter"
        assert recs[0].values == (100.0,)

    def test_multi_field_fanout(self):
        recs = parse_influx_line(
            "disk,host=h used=10,free=90 1600000000000000000")
        metrics = sorted(r.part_key.metric for r in recs)
        assert metrics == ["disk_free", "disk_used"]

    def test_histogram_first_class(self):
        line = ("latency,app=api 0.025=1i,0.05=3i,0.1=6i,+Inf=10i,"
                "sum=0.9,count=10i 1600000000000000000")
        recs = parse_influx_line(line)
        assert len(recs) == 1
        r = recs[0]
        assert r.part_key.schema == "prom-histogram"
        s, c, (les, buckets) = r.values
        assert s == 0.9 and c == 10.0
        assert np.isinf(les[-1])
        np.testing.assert_array_equal(buckets, [1, 3, 6, 10])

    def test_escapes(self):
        recs = parse_influx_line(
            r"my\ metric,tag=a\,b value=1 1600000000000000000")
        assert recs[0].part_key.metric == "my metric"
        assert recs[0].part_key.label_map["tag"] == "a,b"

    def test_default_labels(self):
        recs = parse_influx_line("m value=1 1600000000000000000",
                                 {"_ws_": "demo", "_ns_": "App-1"})
        assert recs[0].part_key.label_map["_ws_"] == "demo"

    def test_bool_and_int_suffixes(self):
        recs = parse_influx_line("m up=t,n=5i 1600000000000000000")
        vals = {r.part_key.metric: r.values[0] for r in recs}
        assert vals == {"m_up": 1.0, "m_n": 5.0}

    def test_string_fields_skipped(self):
        recs = parse_influx_line('m value=1,note="hello" 1600000000000000000')
        assert len(recs) == 1  # only numeric field survives

    def test_missing_timestamp_uses_now(self):
        recs = parse_influx_line("m value=1", now_ms=12345)
        assert recs[0].timestamp == 12345

    def test_malformed(self):
        with pytest.raises(InfluxParseError):
            parse_influx_line("justonefield")
        assert parse_influx_line("") == []
        assert parse_influx_line("# comment") == []


class TestGatewayServer:
    def test_tcp_to_logs(self):
        logs = {s: InMemoryLog() for s in range(4)}
        sink = ContainerSink(logs, num_shards=4, spread=1, flush_every=8)
        srv = GatewayServer(sink, {"_ws_": "demo", "_ns_": "App-1"}).start()
        try:
            with socket.create_connection(("127.0.0.1", srv.port)) as s:
                for i in range(20):
                    s.sendall(
                        f"cpu,host=h{i % 3} value={i} "
                        f"{(1_600_000_000 + i) * 1_000_000_000}\n".encode())
            time.sleep(0.2)
            sink.flush()
            total = 0
            for log in logs.values():
                for sd in log.read_from(0):
                    total += len(sd.container)
            assert total == 20
        finally:
            srv.stop()


class TestSinkBackpressure:
    """Explicit bounded backpressure (SURVEY §2 P7): producers block at
    max_pending while a flush drains; order is preserved per shard."""

    class SlowLog:
        def __init__(self, delay=0.05):
            import threading as _t
            self.delay = delay
            self.containers = []
            self._lock = _t.Lock()

        def append(self, container):
            import time as _t
            _t.sleep(self.delay)
            with self._lock:
                self.containers.append(container)
                return len(self.containers) - 1

    def _mk_sink(self, delay=0.05, flush_every=10, max_pending=20):
        from filodb_tpu.gateway.server import ContainerSink
        logs = {0: self.SlowLog(delay)}
        sink = ContainerSink(logs, num_shards=1, spread=0,
                             flush_every=flush_every,
                             max_pending=max_pending)
        return sink, logs[0]

    def _records(self, lo, hi):
        from filodb_tpu.core.partkey import PartKey
        from filodb_tpu.core.record import IngestRecord
        key = PartKey.create("gauge", {"_metric_": "bp", "_ws_": "w",
                                       "_ns_": "n"})
        return [IngestRecord(key, 1_600_000_000_000 + i * 1000, (float(i),))
                for i in range(lo, hi)]

    def test_producers_block_at_max_pending(self):
        # one thread's flush drains slowly; the others keep batching until
        # max_pending, where add() must BLOCK them (the explicit signal)
        import threading
        from filodb_tpu.gateway.server import backpressure_waits
        sink, slowlog = self._mk_sink(delay=0.2, flush_every=10,
                                      max_pending=20)
        waits0 = backpressure_waits.value

        def produce(base):
            for lo in range(0, 60, 10):
                sink.add(self._records(base + lo, base + lo + 10))

        threads = [threading.Thread(target=produce, args=(i * 1000,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive()
        sink.flush()
        rows = [r for c in slowlog.containers for r in c.records]
        assert len(rows) == 240
        # per-producer timestamp order preserved across flushed batches
        # (the single-drain guard exists exactly for this: a reordered
        # append would trip the shards' out-of-order drop)
        by_producer: dict[int, list[int]] = {}
        for r in rows:
            producer = (r.timestamp - 1_600_000_000_000) // 1_000_000
            by_producer.setdefault(producer, []).append(r.timestamp)
        for ts_list in by_producer.values():
            assert ts_list == sorted(ts_list)
        # producers actually hit the backpressure wait
        assert backpressure_waits.value > waits0

    def test_concurrent_producers_all_delivered(self):
        import threading
        sink, slowlog = self._mk_sink(delay=0.01, flush_every=25,
                                      max_pending=50)
        def produce(base):
            for lo in range(0, 200, 20):
                sink.add(self._records(base + lo, base + lo + 20))
        threads = [threading.Thread(target=produce, args=(i * 1000,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        sink.flush()
        rows = [r for c in slowlog.containers for r in c.records]
        assert len(rows) == 800
