"""Extent result cache: split+cached evaluation must be indistinguishable
from uncached single-shot evaluation.

Property-style equivalence across plan shapes (aggregated rates, over_time
functions, binary joins, histogram quantiles), including seams where an
extent boundary lands mid-lookback-window; plus the safety properties:
partial (fault-injected) results are never cached, mutable-horizon entries
self-invalidate under live ingest, and unsafe plan shapes bypass wholesale.

Equivalence is semantic, not bit-level: the windowed kernels are
prefix-sum based, so evaluating a step over a different chunk batch can
differ in the final ulp of the kernel dtype. Asserted: identical key sets,
identical step grids, identical NaN masks, values allclose at kernel-dtype
tolerance.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.query import result_cache as rc
from filodb_tpu.query.result_cache import (
    ResultCache,
    ResultCacheConfig,
    plan_signature,
    split_extents,
    splittable_grid,
)
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    histogram_series,
    histogram_stream,
    machine_metrics_series,
)
from filodb_tpu.utils.resilience import FaultInjector, reset_breakers

NUM_SHARDS = 4
START = 1_600_000_000  # epoch sec
INTERVAL = 10_000
N_SAMPLES = 720
STEP = 60  # query step, seconds

QS = START + 100        # deliberately extent-unaligned query start
QE = START + 7000


def build_store():
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    streams = [
        gauge_stream(machine_metrics_series(10, ns="App-2"), N_SAMPLES,
                     start_ms=START * 1000, interval_ms=INTERVAL, seed=11),
        counter_stream(counter_series(6, ns="App-1"), N_SAMPLES,
                       start_ms=START * 1000, interval_ms=INTERVAL, seed=3,
                       reset_every=250),
        histogram_stream(histogram_series(4), N_SAMPLES,
                         start_ms=START * 1000, interval_ms=INTERVAL,
                         seed=7),
    ]
    for stream in streams:
        ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    return ms


@pytest.fixture(scope="module")
def store():
    return build_store()


@pytest.fixture(scope="module")
def plain(store):
    return QueryService(store, "timeseries", NUM_SHARDS, spread=1)


@pytest.fixture
def cached(store):
    # extent_steps=7 with a 5m window: every extent boundary lands inside
    # some series' lookback window (420s extents vs 300s windows)
    return QueryService(store, "timeseries", NUM_SHARDS, spread=1,
                        result_cache={"extent_steps": 7})


def assert_equivalent(direct, split):
    m0, m1 = direct.result, split.result
    i0 = {k: i for i, k in enumerate(m0.keys)}
    i1 = {k: i for i, k in enumerate(m1.keys)}
    assert set(i0) == set(i1)
    if m0.num_series:
        assert np.array_equal(m0.steps_ms, m1.steps_ms)
        if m0.les is not None or m1.les is not None:
            assert np.array_equal(np.asarray(m0.les), np.asarray(m1.les))
    for k, i in i0.items():
        a = np.asarray(m0.values[i])
        b = np.asarray(m1.values[i1[k]])
        assert np.array_equal(np.isnan(a), np.isnan(b)), k
        # kernel-dtype tolerance (float32 on default config)
        assert np.allclose(a, b, rtol=2e-5, atol=1e-9, equal_nan=True), k


PLAN_SHAPES = [
    "sum(rate(http_requests_total[5m]))",
    "increase(http_requests_total[5m])",
    "avg_over_time(heap_usage[3m])",
    "max_over_time(heap_usage[7m])",
    "sum by (host) (rate(heap_usage[2m]))",
    "count(avg_over_time(heap_usage[3m]))",
    # binary join (grouped keys, one-to-one)
    "sum(rate(http_requests_total[5m]))"
    " / sum(increase(http_requests_total[5m]))",
    # scalar-vector arithmetic
    "avg_over_time(heap_usage[3m]) * 2 + 1",
    # histogram quantile over aggregated bucket rates
    "histogram_quantile(0.9, sum by (le) (rate(http_req_latency[5m])))",
    # raw histogram-valued matrix through the cache
    "rate(http_req_latency[5m])",
    "topk(3, avg_over_time(heap_usage[3m]))",
    # plain selector sampling (PeriodicSeries, no window)
    "heap_usage",
]


class TestEquivalence:
    @pytest.mark.parametrize("promql", PLAN_SHAPES)
    def test_cold_and_warm_match_single_shot(self, plain, cached, promql):
        direct = plain.query_range(promql, QS, STEP, QE)
        cold = cached.query_range(promql, QS, STEP, QE)
        warm = cached.query_range(promql, QS, STEP, QE)
        assert_equivalent(direct, cold)
        assert_equivalent(direct, warm)

    def test_seam_mid_lookback_window(self, plain, cached):
        # 90s step with 7-step extents: boundary every 630s, lookback 300s
        # — windows straddle boundaries at non-step-multiple offsets
        q = "sum(rate(http_requests_total[5m]))"
        for shift in (0, 1, 3, 5):
            s, e = QS + shift * 90, QS + 4000 + shift * 90
            assert_equivalent(plain.query_range(q, s, 90, e),
                              cached.query_range(q, s, 90, e))

    def test_sliding_window_reuses_extents(self, plain, cached):
        q = "avg_over_time(heap_usage[3m])"
        cached.query_range(q, QS, STEP, QE)
        h0, m0 = rc.cache_hits.value, rc.cache_misses.value
        direct = plain.query_range(q, QS + STEP, STEP, QE + STEP)
        slid = cached.query_range(q, QS + STEP, STEP, QE + STEP)
        assert_equivalent(direct, slid)
        # full-extent caching: a one-step slide with no intervening ingest
        # re-reads every extent (including the head — same full extent,
        # same version) without a single re-evaluation
        n_slid = len(split_extents((QS + STEP) * 1000, STEP * 1000,
                                   (QE + STEP) * 1000, 7))
        assert rc.cache_hits.value - h0 == n_slid
        assert rc.cache_misses.value - m0 == 0
        # extending past the cached tail extent misses only the new extent
        p0 = rc.cache_partial_hits.value
        h1, m1 = rc.cache_hits.value, rc.cache_misses.value
        ext_s = 7 * STEP
        far = QE + 2 * ext_s  # guaranteed beyond the cached tail extent
        assert_equivalent(plain.query_range(q, QS, STEP, far),
                          cached.query_range(q, QS, STEP, far))
        assert rc.cache_hits.value - h1 >= 10
        assert 1 <= rc.cache_misses.value - m1 <= 3
        assert rc.cache_partial_hits.value == p0 + 1

    def test_unaligned_starts_share_interior_extents(self, cached):
        q = "sum(rate(http_requests_total[5m]))"
        cached.query_range(q, QS, STEP, QE)
        h0 = rc.cache_hits.value
        cached.query_range(q, QS + 7 * STEP, STEP, QE)  # one extent shorter
        assert rc.cache_hits.value > h0


class TestSplitMath:
    def test_split_extents_cover_grid_exactly(self):
        for start in (0, 100, 419_000, 420_000):
            for total in (1, 7, 8, 50):
                step = 60_000
                end = start + (total - 1) * step
                exts = split_extents(start, step, end, 7)
                # coverage: concatenated per-extent grids == full grid
                got = np.concatenate([np.arange(es, ee + 1, step)
                                      for es, ee in exts])
                want = np.arange(start, end + 1, step)
                assert np.array_equal(got, want), (start, total)
                # alignment: interior boundaries are absolute multiples
                for es, ee in exts[:-1]:
                    assert (ee + step) // (7 * step) != es // (7 * step)

    def test_signature_blanks_only_evaluation_range(self):
        from filodb_tpu.promql.parser import TimeStepParams, parse_query
        p1 = parse_query("sum(rate(http_requests_total[5m]))",
                         TimeStepParams(QS, STEP, QE), 300_000)
        p2 = parse_query("sum(rate(http_requests_total[5m]))",
                         TimeStepParams(QS + 600, STEP, QE + 600), 300_000)
        p3 = parse_query("sum(rate(http_requests_total[6m]))",
                         TimeStepParams(QS, STEP, QE), 300_000)
        assert plan_signature(p1) == plan_signature(p2)
        assert plan_signature(p1) != plan_signature(p3)
        assert hash(plan_signature(p1)) == hash(plan_signature(p2))

    def test_splittable_grid_bypasses(self):
        from filodb_tpu.promql.parser import TimeStepParams, parse_query

        def grid(q, step=STEP):
            return splittable_grid(
                parse_query(q, TimeStepParams(QS, step, QE), 300_000))

        assert grid("sum(rate(heap_usage[5m]))") is not None
        # instant query: step 0
        assert splittable_grid(parse_query(
            "heap_usage", TimeStepParams(QS, 0, QS), 300_000)) is None
        # subquery / absent / sort / limit
        assert grid("max_over_time(rate(heap_usage[1m])[10m:1m])") is None
        assert grid("absent_over_time(heap_usage[5m])") is None
        assert grid("sort(avg_over_time(heap_usage[3m]))") is None
        # @ modifier pins evaluation time
        assert grid(f"avg_over_time(heap_usage[3m] @ {START + 500})") is None


class TestSafety:
    def test_partial_results_never_cached(self, store):
        FaultInjector.reset()
        reset_breakers()
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1,
                           result_cache={"extent_steps": 7})
        try:
            # every gather loses exactly the shard-0 leaf (tolerable,
            # below the partial threshold at the 4-way fan-out) → every
            # evaluation, extent or whole, comes back partial
            FaultInjector.arm(
                "gather.child", error=ConnectionError,
                match=lambda ctx: list(ctx.get("shards") or []) == [0])
            r = svc.query_range("sum(rate(http_requests_total[5m]))",
                                QS, STEP, QE)
            assert r.partial
            assert len(svc.result_cache) == 0  # nothing stored
        finally:
            FaultInjector.reset()
            reset_breakers()
        # with faults cleared, the same query is whole and correct again —
        # nothing partial was left behind to serve
        r2 = svc.query_range("sum(rate(http_requests_total[5m]))",
                             QS, STEP, QE)
        assert not r2.partial
        assert len(svc.result_cache) > 0

    def test_live_ingest_invalidates_head_not_history(self):
        # fresh store so ingest here can't interfere with other tests
        ms = TimeSeriesMemStore()
        for s in range(NUM_SHARDS):
            ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                                  groups_per_shard=4))
        keys = machine_metrics_series(12, ns="App-9")
        keys2 = machine_metrics_series(12, ns="App-8")
        for kk in (keys, keys2):
            ingest_routed(ms, "timeseries",
                          gauge_stream(kk, 360, start_ms=START * 1000,
                                       interval_ms=INTERVAL, seed=5),
                          NUM_SHARDS, spread=1)
        svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                           result_cache={"extent_steps": 7})
        plain = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        q = "avg_over_time(heap_usage[3m])"
        qs, qe = START + 100, START + 3500
        svc.query_range(q, qs, STEP, qe)  # populate
        # live ingest: 60 more samples continuing the stream
        for kk in (keys, keys2):
            ingest_routed(ms, "timeseries",
                          gauge_stream(kk, 420, start_ms=START * 1000,
                                       interval_ms=INTERVAL, seed=5),
                          NUM_SHARDS, spread=1)
        # zero stale reads: the cached head must not mask the new rows
        assert_equivalent(plain.query_range(q, qs, STEP, qe),
                          svc.query_range(q, qs, STEP, qe))

    def test_immutable_extents_survive_version_bumps(self):
        ms = TimeSeriesMemStore()
        for s in range(NUM_SHARDS):
            ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                                  groups_per_shard=4))
        keys = machine_metrics_series(12, ns="App-9")
        keys2 = machine_metrics_series(12, ns="App-8")
        for kk in (keys, keys2):
            ingest_routed(ms, "timeseries",
                          gauge_stream(kk, 720, start_ms=START * 1000,
                                       interval_ms=INTERVAL, seed=5),
                          NUM_SHARDS, spread=1)
        # precondition: every shard ingested, so the horizon is real —
        # an empty shard (max_ts -1) conservatively disables immutability
        assert all(s.max_ingested_ts > 0
                   for s in ms.shards_for("timeseries"))
        svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                           result_cache={"extent_steps": 7})
        q = "avg_over_time(heap_usage[3m])"
        # query well behind the horizon (max ts - 300s allowance)
        qs, qe = START + 100, START + 3000
        svc.query_range(q, qs, STEP, qe)
        h0 = rc.cache_hits.value
        # bump data_version far past the head (new rows near max ts only)
        for kk in (keys, keys2):
            ingest_routed(ms, "timeseries",
                          gauge_stream(kk, 740, start_ms=START * 1000,
                                       interval_ms=INTERVAL, seed=5),
                          NUM_SHARDS, spread=1)
        svc.query_range(q, qs, STEP, qe)
        # every extent of the historical window is immutable: all hits
        assert rc.cache_hits.value - h0 == len(
            split_extents(qs * 1000, STEP * 1000, qe * 1000, 7))

    def test_eviction_respects_byte_budget(self, store):
        svc = QueryService(store, "timeseries", NUM_SHARDS, spread=1,
                           result_cache={"extent_steps": 7,
                                         "max_bytes": 20_000})
        e0 = rc.cache_evictions.value
        for i in range(6):
            svc.query_range(f"avg_over_time(heap_usage[{i + 2}m])",
                            QS, STEP, QE)
        assert svc.result_cache.nbytes <= 20_000
        assert rc.cache_evictions.value > e0

    def test_remote_shards_bypass(self, store):
        # a coordinator facade claiming more shards than are local must
        # not trust local versions/horizons
        from filodb_tpu.promql.parser import TimeStepParams
        svc = QueryService(store, "timeseries", NUM_SHARDS + 1, spread=1,
                           result_cache={"extent_steps": 7})
        plan = svc._parse_cached("avg_over_time(heap_usage[3m])",
                                 TimeStepParams(QS, STEP, QE))
        assert svc.result_cache.execute(svc, plan) is None

    def test_instant_queries_bypass(self, plain, cached):
        d = plain.query_instant("sum(heap_usage)", START + 3000)
        c = cached.query_instant("sum(heap_usage)", START + 3000)
        assert_equivalent(d, c)
        assert len(cached.result_cache) == 0


class TestBatchErrors:
    def test_poison_query_isolated(self, cached):
        good = ("avg_over_time(heap_usage[3m])", QS, STEP, QE)
        bad_parse = ("sum(rate(heap_usage[5m])", QS, STEP, QE)  # unbalanced
        out = cached.query_range_many([good, bad_parse, good],
                                      return_errors=True)
        assert not isinstance(out[0], Exception)
        assert isinstance(out[1], Exception)
        assert not isinstance(out[2], Exception)
        assert_equivalent(out[0], out[2])

    def test_batcher_surfaces_per_item_errors(self, cached):
        from filodb_tpu.coordinator.query_service import QueryBatcher
        b = QueryBatcher(cached)
        r = b.query_range("avg_over_time(heap_usage[3m])", QS, STEP, QE)
        assert r.result.num_series > 0
        with pytest.raises(Exception):
            b.query_range("sum(rate(heap_usage[5m])", QS, STEP, QE)

    def test_default_raise_behavior_unchanged(self, cached):
        with pytest.raises(Exception):
            cached.query_range_many(
                [("sum(rate(heap_usage[5m])", QS, STEP, QE)])


class TestResponseCacheKey:
    def test_serial_not_id(self, store):
        from filodb_tpu.http.server import response_cache_key
        a = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        b = QueryService(store, "timeseries", NUM_SHARDS, spread=1)
        assert a.serial != b.serial
        pa = response_cache_key(a, "range", ("q", 1, 2, 3))
        pb = response_cache_key(b, "range", ("q", 1, 2, 3))
        assert pa != pb
        assert pa[0] == a.serial  # stable across the service's lifetime


class TestConfig:
    def test_from_config_forms(self):
        assert ResultCache.from_config(None) is None
        assert ResultCache.from_config(False) is None
        assert ResultCache.from_config({"enabled": False}) is None
        assert isinstance(ResultCache.from_config(True), ResultCache)
        c = ResultCache.from_config({"extent_steps": 5, "max_bytes": 123})
        assert c.config.extent_steps == 5
        assert c.config.max_bytes == 123
        cc = ResultCacheConfig(extent_steps=9)
        assert ResultCache.from_config(cc).config.extent_steps == 9
        same = ResultCache(ResultCacheConfig())
        assert ResultCache.from_config(same) is same


class TestSidecarProvenanceInvariance:
    """The sidecar lane (FILODB_SIDECARS, PR 15) changes HOW a leaf is
    evaluated, never WHAT it returns — so cached extents populated under
    one provenance must serve unchanged under any other, and the cache
    signature must not encode the valve at all."""

    QUERIES = [
        "sum(rate(http_requests_total[5m]))",
        "avg_over_time(heap_usage[3m])",
        "max_over_time(heap_usage[7m])",
    ]

    def test_signature_ignores_valve(self, monkeypatch):
        from filodb_tpu.promql.parser import TimeStepParams, parse_query

        def sig(mode):
            monkeypatch.setenv("FILODB_SIDECARS", mode)
            return plan_signature(parse_query(
                "sum(rate(http_requests_total[5m]))",
                TimeStepParams(QS, STEP, QE), 300_000))

        assert sig("1") == sig("decode") == sig("0")

    @pytest.mark.parametrize("populate,serve", [("1", "0"), ("0", "1"),
                                                ("1", "decode")])
    def test_extents_cached_under_one_mode_serve_another(
            self, plain, cached, monkeypatch, populate, serve):
        for q in self.QUERIES:
            monkeypatch.setenv("FILODB_SIDECARS", populate)
            direct = plain.query_range(q, QS, STEP, QE)
            cold = cached.query_range(q, QS, STEP, QE)
            assert_equivalent(direct, cold)
            # flip the valve: warm hits below come from extents that were
            # computed under the OTHER provenance
            monkeypatch.setenv("FILODB_SIDECARS", serve)
            h0 = rc.cache_hits.value
            warm = cached.query_range(q, QS, STEP, QE)
            assert rc.cache_hits.value > h0
            assert_equivalent(direct, warm)
            assert_equivalent(plain.query_range(q, QS, STEP, QE), warm)
