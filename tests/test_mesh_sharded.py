"""Split-pipeline mesh execution: prepare/bounds/eval caches + per-query
group reduce (``parallel/dist_query.py`` / ``parallel/mesh_engine.py``).

The split form must be indistinguishable from the fused one-shot kernels
in every observable way: bitwise-identical values (both forms run the
same helper float ops in the same order, on the same 8-virtual-device
mesh the conftest forces), the same exec-path parity, and the same
result-cache signatures — the kernel form is an engine implementation
detail, never part of a query's identity.
"""

import numpy as np
import pytest

from filodb_tpu.coordinator.ingestion import ingest_routed
from filodb_tpu.coordinator.query_service import QueryService
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.config import StoreConfig
from filodb_tpu.parallel.dist_query import SPLIT_FNS
from filodb_tpu.parallel.mesh_engine import (
    _M_DISPATCH,
    _M_EVAL,
    F32_SAFE_MAX,
    MeshQueryEngine,
    _device_correction_ok,
)
from filodb_tpu.promql.parser import TimeStepParams, parse_query
from filodb_tpu.testing.data import (
    counter_series,
    counter_stream,
    gauge_stream,
    machine_metrics_series,
)

START = 1_600_000_000
NUM_SHARDS = 4


def build_store(kind="counter", n_series=37, n_samples=240):
    """37 series: not a multiple of any mesh axis, so the shard axis pads;
    240 samples over 4 shards exercises the time axis too."""
    ms = TimeSeriesMemStore()
    for s in range(NUM_SHARDS):
        ms.setup("timeseries", s, StoreConfig(max_chunk_size=100,
                                              groups_per_shard=4))
    if kind == "counter":
        keys = counter_series(n_series, metric="http_requests_total")
        stream = counter_stream(keys, n_samples, start_ms=START * 1000,
                                interval_ms=10_000, seed=7)
    else:
        keys = machine_metrics_series(n_series, metric="gauge_metric")
        stream = gauge_stream(keys, n_samples, start_ms=START * 1000,
                              interval_ms=10_000, seed=7)
    ingest_routed(ms, "timeseries", stream, NUM_SHARDS, spread=1)
    # uneven tails: a third of the series keep reporting for another 40
    # samples, so per-series counts (and the padded valid mask) differ
    extra = counter_stream(keys[::3],
                           40, start_ms=(START + n_samples * 10) * 1000,
                           interval_ms=10_000, seed=8) \
        if kind == "counter" else \
        gauge_stream(keys[::3], 40,
                     start_ms=(START + n_samples * 10) * 1000,
                     interval_ms=10_000, seed=8)
    ingest_routed(ms, "timeseries", extra, NUM_SHARDS, spread=1)
    return ms


def both_forms(ms, query, monkeypatch, start=START + 600, step=60,
               end=START + 2800):
    """Evaluate one query through the SAME engine in split and fused
    form (the result cache is off on a bare QueryService, so both runs
    hit the device)."""
    svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                       engine="mesh")
    eng = svc.mesh_engine
    plan = parse_query(query, TimeStepParams(start, step, end))
    low = eng._lower(plan)
    assert low is not None, f"{query} must lower"
    monkeypatch.setenv("FILODB_MESH_SPLIT", "1")
    split = eng.execute_lowered_many([low], ms, "timeseries")[0]
    monkeypatch.setenv("FILODB_MESH_SPLIT", "0")
    fused = eng.execute_lowered_many([low], ms, "timeseries")[0]
    return split.materialize(), fused.materialize(), svc


def assert_bitwise(a, b):
    assert [str(k) for k in a.keys] == [str(k) for k in b.keys]
    np.testing.assert_array_equal(a.steps_ms, b.steps_ms)
    assert np.asarray(a.values).tobytes() == np.asarray(b.values).tobytes()


def assert_ulps(a, b):
    """Equal to f64 rounding error (scale-relative: deltas of large gauge
    values cancel to near zero, so a tiny absolute term is needed too)."""
    assert [str(k) for k in a.keys] == [str(k) for k in b.keys]
    np.testing.assert_array_equal(a.steps_ms, b.steps_ms)
    np.testing.assert_allclose(np.asarray(a.values), np.asarray(b.values),
                               rtol=1e-12, atol=1e-8, equal_nan=True)


def assert_close(a, b):
    assert sorted(map(str, a.keys)) == sorted(map(str, b.keys))
    oa = np.argsort([str(k) for k in a.keys])
    ob = np.argsort([str(k) for k in b.keys])
    np.testing.assert_allclose(np.asarray(a.values)[oa],
                               np.asarray(b.values)[ob],
                               rtol=1e-9, atol=1e-7, equal_nan=True)


class TestSplitEqualsFused:
    """Every split-eligible fn, split vs fused, bitwise under x64."""

    @pytest.fixture(scope="class")
    def counter_store(self):
        return build_store("counter")

    @pytest.fixture(scope="class")
    def gauge_store(self):
        return build_store("gauge")

    @pytest.mark.parametrize("fn", SPLIT_FNS)
    def test_all_split_fns_sum(self, counter_store, gauge_store, fn,
                               monkeypatch):
        counter = fn in ("rate", "increase")
        ms = counter_store if counter else gauge_store
        metric = "http_requests_total" if counter else "gauge_metric"
        s, f, _ = both_forms(ms, f"sum({fn}({metric}[5m])) by (_ns_)",
                             monkeypatch)
        if fn in ("delta", "stdvar_over_time"):
            # not bit-for-bit: fused delta runs on host-REBASED values
            # (a different placement than the split lane's raw values),
            # and stdvar's variance reduction order is implementation-
            # defined across program boundaries — both agree to ulps
            assert_ulps(s, f)
        else:
            assert_bitwise(s, f)

    @pytest.mark.parametrize("agg", ["avg", "min", "max", "count",
                                     "stddev"])
    def test_rate_agg_matrix(self, counter_store, agg, monkeypatch):
        s, f, _ = both_forms(
            counter_store, f"{agg}(rate(http_requests_total[5m]))",
            monkeypatch)
        assert_bitwise(s, f)

    def test_per_series_no_agg(self, counter_store, monkeypatch):
        s, f, _ = both_forms(counter_store,
                             "rate(http_requests_total[5m])", monkeypatch)
        assert_bitwise(s, f)

    def test_windows_outside_data_all_nan(self, counter_store,
                                          monkeypatch):
        # staleness shape: every window precedes the data (or holds <2
        # samples) → NaN steps, identically in both forms
        s, f, _ = both_forms(counter_store,
                             "sum(rate(http_requests_total[5m]))",
                             monkeypatch, start=START - 3600,
                             end=START - 600)
        assert_bitwise(s, f)
        assert np.isnan(np.asarray(s.values)).all()

    def test_delta_counter_schema_reset_corrected(self, counter_store,
                                                  monkeypatch):
        """The uneven-tail restart (values drop back near zero) is a
        counter reset: delta on a COUNTER schema mirrors the exec
        kernels — reset-corrected like rate/increase, but never
        extrapolate-to-zero clamped — so windows spanning the reset stay
        non-negative instead of swinging ~-30000."""
        s, f, _ = both_forms(counter_store,
                             "sum(delta(http_requests_total[4m]))",
                             monkeypatch)
        assert_ulps(s, f)
        assert np.nanmin(np.asarray(s.values)) >= 0

    def test_split_dispatch_counted(self, counter_store, monkeypatch):
        before = _M_DISPATCH["split"].value
        both_forms(counter_store, "sum(increase(http_requests_total[5m]))",
                   monkeypatch)
        assert _M_DISPATCH["split"].value == before + 1

    def test_eval_cache_shared_across_aggs(self, counter_store,
                                           monkeypatch):
        """Different aggregations over the same inner range function hit
        ONE cached per-series evaluation — the point of keeping grouping
        out of the eval stage."""
        monkeypatch.setenv("FILODB_MESH_SPLIT", "1")
        svc = QueryService(ms := counter_store, "timeseries", NUM_SHARDS,
                           spread=1, engine="mesh")
        eng = svc.mesh_engine
        misses0, hits0 = _M_EVAL["miss"].value, _M_EVAL["hit"].value
        for agg in ("sum", "avg", "max"):
            plan = parse_query(f"{agg}(rate(http_requests_total[5m]))",
                               TimeStepParams(START + 600, 60,
                                              START + 2800))
            eng.execute_lowered_many([eng._lower(plan)], ms,
                                     "timeseries")[0].materialize()
        assert _M_EVAL["miss"].value == misses0 + 1
        assert _M_EVAL["hit"].value == hits0 + 2


class TestSplitEqualsExec:
    """The split path against the scatter-gather exec reference."""

    @pytest.fixture(scope="class")
    def counter_store(self):
        return build_store("counter")

    @pytest.mark.parametrize("query", [
        "sum(rate(http_requests_total[5m]))",
        "sum(rate(http_requests_total[5m])) by (_ns_)",
        "avg(increase(http_requests_total[3m])) by (instance)",
        "rate(http_requests_total[5m])",
        'sum(delta(http_requests_total{_ns_="App-0"}[4m]))',
    ])
    def test_exec_parity(self, counter_store, query, monkeypatch):
        monkeypatch.setenv("FILODB_MESH_SPLIT", "1")
        exec_svc = QueryService(counter_store, "timeseries", NUM_SHARDS,
                                spread=1)
        mesh_svc = QueryService(counter_store, "timeseries", NUM_SHARDS,
                                spread=1, engine="mesh")
        args = (query, START + 600, 60, START + 2800)
        assert_close(exec_svc.query_range(*args).result.materialize(),
                     mesh_svc.query_range(*args).result.materialize())


class TestCacheBehavior:
    def test_result_cache_signature_invariant_across_forms(self,
                                                           monkeypatch):
        """A result cached by the fused form must satisfy a split-form
        repeat (and vice versa): the kernel form is not part of the
        plan signature."""
        from filodb_tpu.query import result_cache as rc

        ms = build_store("counter", n_series=12, n_samples=120)
        svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                           engine="mesh", result_cache=True)
        args = ("sum(rate(http_requests_total[5m]))", START + 600, 60,
                START + 1500)
        monkeypatch.setenv("FILODB_MESH_SPLIT", "0")
        hits0 = rc.cache_hits.value
        a = svc.query_range(*args).result.materialize()
        monkeypatch.setenv("FILODB_MESH_SPLIT", "1")
        b = svc.query_range(*args).result.materialize()
        assert rc.cache_hits.value > hits0
        assert np.asarray(a.values).tobytes() == \
            np.asarray(b.values).tobytes()

    def test_caches_invalidate_on_version_bump(self, monkeypatch):
        """Prepared correction, bounds, and eval entries are keyed by the
        dataset data_version: new ingest must flow into the next answer,
        not a stale cached evaluation."""
        monkeypatch.setenv("FILODB_MESH_SPLIT", "1")
        ms = build_store("counter", n_series=12, n_samples=120)
        svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1,
                           engine="mesh")
        exec_svc = QueryService(ms, "timeseries", NUM_SHARDS, spread=1)
        args = ("sum(increase(http_requests_total[5m]))", START + 600, 60,
                START + 1100)
        first = svc.query_range(*args).result.materialize()
        keys = counter_series(12, metric="http_requests_total")
        more = counter_stream(keys, 60, start_ms=(START + 1200) * 1000,
                              interval_ms=10_000, seed=9)
        ingest_routed(ms, "timeseries", more, NUM_SHARDS, spread=1)
        args2 = (args[0], START + 600, 60, START + 1700)
        after = svc.query_range(*args2).result.materialize()
        ref = exec_svc.query_range(*args2).result.materialize()
        assert_close(after, ref)
        assert np.asarray(after.values).shape != \
            np.asarray(first.values).shape


class TestPrecisionGate:
    def test_x64_always_ok(self):
        assert _device_correction_ok(np.array([[1e12, np.inf, np.nan]]))

    def test_f32_gate(self, monkeypatch):
        import jax.numpy as jnp

        from filodb_tpu.query.engine import kernels

        monkeypatch.setattr(kernels, "fdtype", lambda: jnp.float32)
        small = np.array([[0.0, 123.5, F32_SAFE_MAX - 1]])
        big = np.array([[0.0, F32_SAFE_MAX]])
        assert _device_correction_ok(small)
        assert not _device_correction_ok(big)
        # non-finite values are masked out by the kernels; only finite
        # magnitudes decide the lane
        assert _device_correction_ok(
            np.array([[np.nan, np.inf, -np.inf, 5.0]]))
        assert _device_correction_ok(np.array([[np.nan]]))
