"""Resilience primitives: deadlines, retry policy, circuit breaker, fault
injector, config plumbing and partial-response rendering.

Every test is deterministic: clocks and sleeps are injected, nothing waits
on the wall clock.
"""

import json

import numpy as np
import pytest

from filodb_tpu.utils import resilience
from filodb_tpu.utils.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    Fault,
    FaultInjector,
    ResilienceConfig,
    RetryPolicy,
    breaker_for,
    reset_breakers,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _clean():
    FaultInjector.reset()
    reset_breakers()
    yield
    FaultInjector.reset()
    reset_breakers()
    resilience._config = ResilienceConfig()


# ---------------------------------------------------------------------------
# Deadline


class TestDeadline:
    def test_remaining_and_expiry(self):
        clk = FakeClock()
        d = Deadline.after(10.0, clock=clk.now)
        assert d.remaining() == pytest.approx(10.0)
        assert not d.expired
        clk.advance(10.5)
        assert d.expired

    def test_timeout_derives_from_remaining(self):
        clk = FakeClock()
        d = Deadline.after(10.0, clock=clk.now)
        # plenty of time left: the per-hop cap wins
        assert d.timeout(cap=3.0) == pytest.approx(3.0)
        clk.advance(9.0)
        # less than the cap remains: the deadline wins
        assert d.timeout(cap=3.0) == pytest.approx(1.0)
        assert d.timeout() == pytest.approx(1.0)

    def test_timeout_raises_when_exhausted(self):
        clk = FakeClock()
        d = Deadline.after(1.0, clock=clk.now)
        clk.advance(2.0)
        with pytest.raises(DeadlineExceeded, match="dial"):
            d.timeout(cap=5.0, what="dial")

    def test_check_raises(self):
        clk = FakeClock()
        d = Deadline.after(1.0, clock=clk.now)
        d.check("gather")  # fine while time remains
        clk.advance(1.5)
        with pytest.raises(DeadlineExceeded, match="gather"):
            d.check("gather")


# ---------------------------------------------------------------------------
# RetryPolicy


class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("rng", lambda: 1.0)  # deterministic: full backoff
        sleeps = []
        kw.setdefault("sleep", sleeps.append)
        return RetryPolicy(**kw), sleeps

    def test_backoff_grows_exponentially_and_caps(self):
        p, _ = self._policy(base_backoff_s=0.1, multiplier=2.0,
                            max_backoff_s=0.5)
        assert p.backoff(1) == pytest.approx(0.1)
        assert p.backoff(2) == pytest.approx(0.2)
        assert p.backoff(3) == pytest.approx(0.4)
        assert p.backoff(4) == pytest.approx(0.5)  # capped
        assert p.backoff(10) == pytest.approx(0.5)

    def test_jitter_range(self):
        lo = RetryPolicy(base_backoff_s=1.0, jitter=0.5, rng=lambda: 0.0)
        hi = RetryPolicy(base_backoff_s=1.0, jitter=0.5, rng=lambda: 1.0)
        assert lo.backoff(1) == pytest.approx(0.5)
        assert hi.backoff(1) == pytest.approx(1.0)

    def test_retries_then_succeeds(self):
        p, sleeps = self._policy(max_attempts=3, base_backoff_s=0.1)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise ConnectionError("boom")
            return "ok"

        assert p.call(flaky) == "ok"
        assert len(calls) == 3
        assert sleeps == pytest.approx([0.1, 0.2])

    def test_exhausts_attempts(self):
        p, sleeps = self._policy(max_attempts=3)
        calls = []

        def dead():
            calls.append(1)
            raise ConnectionError("down")

        with pytest.raises(ConnectionError):
            p.call(dead)
        assert len(calls) == 3
        assert len(sleeps) == 2  # no sleep after the final failure

    def test_budget_stops_retries(self):
        p, sleeps = self._policy(max_attempts=10, base_backoff_s=1.0,
                                 budget_s=3.0)
        calls = []

        def dead():
            calls.append(1)
            raise OSError("down")

        with pytest.raises(OSError):
            p.call(dead)
        # backoffs 1s + 2s fill the 3s budget; the third (4s) would burst it
        assert sleeps == pytest.approx([1.0, 2.0])
        assert len(calls) == 3

    def test_deadline_stops_retries(self):
        clk = FakeClock()
        d = Deadline.after(0.5, clock=clk.now)
        p, sleeps = self._policy(max_attempts=10, base_backoff_s=1.0)
        with pytest.raises(ConnectionError):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                   deadline=d)
        assert sleeps == []  # 1s backoff > 0.5s remaining: fail fast

    def test_never_retries_breaker_or_deadline(self):
        p, sleeps = self._policy(max_attempts=5)
        with pytest.raises(CircuitOpenError):
            p.call(lambda: (_ for _ in ()).throw(CircuitOpenError("open")))
        with pytest.raises(DeadlineExceeded):
            p.call(lambda: (_ for _ in ()).throw(DeadlineExceeded("late")),
                   retry_on=(ConnectionError, OSError, TimeoutError))
        assert sleeps == []

    def test_non_retryable_error_passes_through(self):
        p, sleeps = self._policy(max_attempts=5)
        with pytest.raises(ValueError):
            p.call(lambda: (_ for _ in ()).throw(ValueError("logic bug")))
        assert sleeps == []

    def test_on_retry_callback_and_counter(self):
        before = resilience._retries_total.value
        p, _ = self._policy(max_attempts=2)
        seen = []
        with pytest.raises(ConnectionError):
            p.call(lambda: (_ for _ in ()).throw(ConnectionError("x")),
                   on_retry=lambda a, e: seen.append((a, type(e).__name__)))
        assert seen == [(1, "ConnectionError")]
        assert resilience._retries_total.value == before + 1


# ---------------------------------------------------------------------------
# CircuitBreaker


class TestCircuitBreaker:
    def _breaker(self, **kw):
        clk = FakeClock()
        kw.setdefault("failure_threshold", 3)
        kw.setdefault("reset_timeout_s", 10.0)
        return CircuitBreaker("peer:1", clock=clk.now, **kw), clk

    def test_opens_after_threshold(self):
        b, _ = self._breaker()
        assert b.state == "closed"
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open"
        with pytest.raises(CircuitOpenError, match="peer:1"):
            b.guard()

    def test_success_resets_failure_count(self):
        b, _ = self._breaker()
        b.record_failure()
        b.record_failure()
        b.record_success()
        for _ in range(2):
            b.record_failure()
        assert b.state == "closed"

    def test_half_open_admits_single_probe(self):
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        assert not b.allow()
        clk.advance(10.0)
        assert b.state == "half-open"
        assert b.allow()        # the probe
        assert not b.allow()    # concurrent calls still rejected

    def test_probe_success_closes(self):
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        clk.advance(10.0)
        assert b.allow()
        b.record_success()
        assert b.state == "closed"
        assert b.allow()

    def test_probe_failure_reopens(self):
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        clk.advance(10.0)
        assert b.allow()
        b.record_failure()      # one failed probe re-opens immediately
        assert b.state == "open"
        assert not b.allow()
        clk.advance(10.0)
        assert b.allow()        # next probe window

    def test_force_open(self):
        b, clk = self._breaker()
        b.force_open()
        assert b.state == "open"
        assert not b.allow()
        clk.advance(10.0)
        assert b.allow()  # recovers through the normal half-open path

    def test_cancel_probe_frees_slot(self):
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        clk.advance(10.0)
        assert b.allow()
        b.cancel_probe()
        assert b.state == "half-open"
        assert b.allow()  # the slot is free for a later probe

    def test_calling_records_success_and_failure(self):
        b, _ = self._breaker()
        with b.calling():
            pass
        assert b.state == "closed"
        for _ in range(3):
            with pytest.raises(ConnectionError):
                with b.calling():
                    raise ConnectionError("down")
        assert b.state == "open"

    def test_calling_non_transport_error_releases_probe(self):
        """Regression: an exception outside the transport set during a
        half-open probe must release the slot — before, it left
        ``_probing`` set and the breaker wedged half-open forever."""
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        clk.advance(10.0)
        with pytest.raises(RuntimeError):
            with b.calling():
                raise RuntimeError("remote answered with an app error")
        assert b.state == "half-open"
        assert b.allow()  # the next call may probe again

    def test_calling_excludes_deadline_verdicts(self):
        """A deadline expiry says nothing about the peer's health, even
        though DeadlineExceeded is an OSError via TimeoutError."""
        b, _ = self._breaker(failure_threshold=1)
        with pytest.raises(DeadlineExceeded):
            with b.calling():
                raise DeadlineExceeded("out of time")
        assert b.state == "closed"

    def test_calling_body_outcome_wins(self):
        """The body may record first (HTTP error status: the peer
        ANSWERED, so the probe succeeds even though the call raises)."""
        b, clk = self._breaker()
        for _ in range(3):
            b.record_failure()
        clk.advance(10.0)
        with pytest.raises(RuntimeError):
            with b.calling() as outcome:
                outcome.success()
                raise RuntimeError("tagged remote error")
        assert b.state == "closed"

    def test_registry_shares_instances(self):
        a = breaker_for("host:9000")
        b = breaker_for("host:9000")
        c = breaker_for("host:9001")
        assert a is b
        assert a is not c
        reset_breakers()
        assert breaker_for("host:9000") is not a

    def test_registry_uses_config_defaults(self):
        resilience.configure(breaker_failure_threshold=2, breaker_reset_s=7.0)
        b = breaker_for("host:9002")
        assert b.failure_threshold == 2
        assert b.reset_timeout_s == 7.0


# ---------------------------------------------------------------------------
# FaultInjector


class TestFaultInjector:
    def test_noop_when_unarmed(self):
        FaultInjector.fire("remote.dispatch", host="h", port=1)  # no raise

    def test_raises_armed_error_n_times(self):
        f = FaultInjector.arm("remote.dispatch", error=ConnectionError,
                              times=2)
        for _ in range(2):
            with pytest.raises(ConnectionError, match="fault injected"):
                FaultInjector.fire("remote.dispatch", host="h", port=1)
        FaultInjector.fire("remote.dispatch", host="h", port=1)  # spent
        assert f.fired == 2

    def test_match_filters_by_context(self):
        FaultInjector.arm("gather.child", error=ConnectionError,
                          match=lambda ctx: 2 in ctx["shards"])
        FaultInjector.fire("gather.child", index=0, shards=[0, 1])
        with pytest.raises(ConnectionError):
            FaultInjector.fire("gather.child", index=1, shards=[2, 3])

    def test_delay_uses_injected_sleep(self):
        slept = []
        FaultInjector.arm("store.call", delay_s=5.0, sleep=slept.append)
        FaultInjector.fire("store.call", host="h", port=1, op="read")
        assert slept == [5.0]

    def test_exception_instance_passthrough(self):
        FaultInjector.arm("promql.remote", error=OSError("exact instance"))
        with pytest.raises(OSError, match="exact instance"):
            FaultInjector.fire("promql.remote", endpoint="e")

    def test_reset(self):
        FaultInjector.arm("remote.connect", error=ConnectionError)
        assert FaultInjector.armed()
        FaultInjector.reset()
        assert not FaultInjector.armed()
        FaultInjector.fire("remote.connect", host="h", port=1)


# ---------------------------------------------------------------------------
# config plumbing


class TestResilienceConfig:
    def test_configure_overrides_known_keys(self):
        resilience.configure(query_timeout_s=5.0, retry_max_attempts=7,
                             unknown_knob=123)  # unknown keys ignored
        c = resilience.config()
        assert c.query_timeout_s == 5.0
        assert c.retry_max_attempts == 7
        assert not hasattr(c, "unknown_knob")

    def test_default_retry_policy_reflects_config(self):
        resilience.configure(retry_max_attempts=4,
                             retry_base_backoff_s=0.5)
        p = resilience.default_retry_policy()
        assert p.max_attempts == 4
        assert p.base_backoff_s == 0.5
        assert resilience.default_retry_policy(max_attempts=1) \
            .max_attempts == 1

    def test_server_config_carries_resilience_block(self):
        from filodb_tpu.config import ServerConfig
        cfg = ServerConfig.load()
        assert cfg.resilience["query_timeout_s"] == 30.0
        resilience.configure(**cfg.resilience)
        assert resilience.config().allow_partial is True


# ---------------------------------------------------------------------------
# deadline threading through the query service


class TestDeadlineDerivation:
    def test_query_service_stamps_deadline(self):
        from filodb_tpu.coordinator.query_service import QueryService
        from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
        from filodb_tpu.core.store.config import StoreConfig
        from filodb_tpu.query.exec.plan import ExecContext

        ms = TimeSeriesMemStore()
        ms.setup("timeseries", 0, StoreConfig(max_chunk_size=60))
        svc = QueryService(ms, "timeseries", num_shards=1,
                           query_timeout_s=12.0)
        seen = {}
        orig = ExecContext.__init__

        def spy(self, *a, **kw):
            orig(self, *a, **kw)
            seen["deadline"] = kw.get("deadline", self.deadline)

        ExecContext.__init__ = spy
        try:
            svc.query_range("absent_metric", 1_600_000_000, 60,
                            1_600_000_600)
        finally:
            ExecContext.__init__ = orig
        d = seen["deadline"]
        assert d is not None
        assert 0 < d.remaining() <= 12.0

    def test_remote_dispatch_timeout_derives_from_deadline(self):
        """No hard-coded 30s on the wire: an exhausted deadline fails the
        dial before touching the network."""
        from filodb_tpu.coordinator.remote import RemotePlanDispatcher
        from filodb_tpu.query.exec.plan import (
            ExecContext,
            SelectRawPartitionsExec,
        )

        clk = FakeClock()
        disp = RemotePlanDispatcher("127.0.0.1", 1)  # nothing listens
        ctx = ExecContext(None, "timeseries",
                          deadline=Deadline.after(1.0, clock=clk.now))
        clk.advance(2.0)
        leaf = SelectRawPartitionsExec(shard=0, filters=(), chunk_start=0,
                                       chunk_end=1)
        with pytest.raises(DeadlineExceeded):
            disp.dispatch(leaf, ctx)


# ---------------------------------------------------------------------------
# partial-response rendering


def _mk_result(partial, warnings):
    from filodb_tpu.query.model import (
        QueryResult,
        QueryStats,
        RangeVectorKey,
        StepMatrix,
    )
    m = StepMatrix([RangeVectorKey.of({"_metric_": "up"})],
                   np.array([[1.0, 2.0]]),
                   np.array([1000, 2000], dtype=np.int64))
    return QueryResult(m, QueryStats(), "q1", partial=partial,
                       warnings=warnings)


class TestPartialRendering:
    def test_matrix_json_includes_partial_fields(self):
        from filodb_tpu.http import promjson
        r = _mk_result(True, ["shard 2 lost"])
        out = promjson.matrix_json(r)
        assert out["partial"] is True
        assert out["warnings"] == ["shard 2 lost"]

    def test_matrix_json_str_round_trips(self):
        from filodb_tpu.http import promjson
        out = json.loads(promjson.matrix_json_str(_mk_result(
            True, ["shard 2 lost"])))
        assert out["partial"] is True
        assert out["warnings"] == ["shard 2 lost"]
        assert out["status"] == "success"

    def test_vector_json_str_round_trips(self):
        from filodb_tpu.http import promjson
        out = json.loads(promjson.vector_json_str(_mk_result(
            True, ["w"])))
        assert out["partial"] is True
        assert out["warnings"] == ["w"]

    def test_complete_result_omits_fields(self):
        from filodb_tpu.http import promjson
        r = _mk_result(False, [])
        assert "partial" not in promjson.matrix_json(r)
        out = json.loads(promjson.matrix_json_str(r))
        assert "partial" not in out
        assert "warnings" not in out
