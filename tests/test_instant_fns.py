"""Instant-function golden tests.

Pins every instant function against numpy/datetime ground truth (reference
``InstantFunctionSpec`` covers the same surface).
"""

import datetime as dt

import jax.numpy as jnp
import numpy as np
import pytest

from filodb_tpu.query.engine.instantfns import apply_binary_op, apply_instant_fn


def ev(fn, vals, params=()):
    return np.asarray(apply_instant_fn(fn, jnp.asarray(vals), params=params))


class TestMathFns:
    VALS = np.array([-2.5, -1.0, 0.0, 0.4, 1.0, 2.7, 100.0])

    @pytest.mark.parametrize("fn,ref", [
        ("abs", np.abs), ("ceil", np.ceil), ("floor", np.floor),
        ("exp", np.exp), ("sqrt", np.sqrt), ("sgn", np.sign),
        ("sin", np.sin), ("cos", np.cos), ("tan", np.tan),
        ("sinh", np.sinh), ("cosh", np.cosh), ("tanh", np.tanh),
        ("deg", np.degrees), ("rad", np.radians),
    ])
    def test_matches_numpy(self, fn, ref):
        with np.errstate(invalid="ignore"):
            np.testing.assert_allclose(ev(fn, self.VALS), ref(self.VALS),
                                       rtol=1e-12, equal_nan=True)

    def test_logs(self):
        v = np.array([0.5, 1.0, 10.0, 1024.0])
        np.testing.assert_allclose(ev("ln", v), np.log(v), rtol=1e-12)
        np.testing.assert_allclose(ev("log2", v), np.log2(v), rtol=1e-12)
        np.testing.assert_allclose(ev("log10", v), np.log10(v), rtol=1e-12)

    def test_round_with_nearest(self):
        v = np.array([1.24, 1.26, -0.75])
        np.testing.assert_allclose(ev("round", v, (0.5,)),
                                   np.round(v / 0.5) * 0.5, rtol=1e-12)
        np.testing.assert_allclose(ev("round", v), np.round(v), rtol=1e-12)

    def test_clamps(self):
        v = np.array([-5.0, 0.0, 5.0, 50.0])
        np.testing.assert_allclose(ev("clamp", v, (0.0, 10.0)),
                                   np.clip(v, 0, 10))
        np.testing.assert_allclose(ev("clamp_min", v, (1.0,)),
                                   np.maximum(v, 1.0))
        np.testing.assert_allclose(ev("clamp_max", v, (1.0,)),
                                   np.minimum(v, 1.0))


class TestTimeFns:
    # epoch seconds spanning leap years, month ends, DOW wraps
    TIMES = [
        dt.datetime(1970, 1, 1, 0, 0, tzinfo=dt.timezone.utc),
        dt.datetime(2000, 2, 29, 23, 59, tzinfo=dt.timezone.utc),
        dt.datetime(2016, 12, 31, 12, 30, tzinfo=dt.timezone.utc),
        dt.datetime(2020, 2, 28, 6, 1, tzinfo=dt.timezone.utc),
        dt.datetime(2021, 3, 1, 0, 0, tzinfo=dt.timezone.utc),
        dt.datetime(2026, 7, 28, 17, 45, tzinfo=dt.timezone.utc),
        dt.datetime(2100, 2, 28, 3, 3, tzinfo=dt.timezone.utc),  # not leap
    ]

    def secs(self):
        return np.array([t.timestamp() for t in self.TIMES])

    def test_year_month_day(self):
        s = self.secs()
        np.testing.assert_array_equal(ev("year", s),
                                      [t.year for t in self.TIMES])
        np.testing.assert_array_equal(ev("month", s),
                                      [t.month for t in self.TIMES])
        np.testing.assert_array_equal(ev("day_of_month", s),
                                      [t.day for t in self.TIMES])

    def test_hour_minute(self):
        s = self.secs()
        np.testing.assert_array_equal(ev("hour", s),
                                      [t.hour for t in self.TIMES])
        np.testing.assert_array_equal(ev("minute", s),
                                      [t.minute for t in self.TIMES])

    def test_day_of_week(self):
        s = self.secs()
        # promql: 0 = Sunday
        expect = [(t.weekday() + 1) % 7 for t in self.TIMES]
        np.testing.assert_array_equal(ev("day_of_week", s), expect)

    def test_day_of_year(self):
        s = self.secs()
        expect = [t.timetuple().tm_yday for t in self.TIMES]
        np.testing.assert_array_equal(ev("day_of_year", s), expect)

    def test_days_in_month(self):
        import calendar
        s = self.secs()
        expect = [calendar.monthrange(t.year, t.month)[1]
                  for t in self.TIMES]
        np.testing.assert_array_equal(ev("days_in_month", s), expect)


class TestBinaryOps:
    def test_arithmetic(self):
        a = np.array([10.0, 7.0, -3.0])
        b = np.array([3.0, 2.0, 2.0])
        for op, ref in (("+", a + b), ("-", a - b), ("*", a * b),
                        ("/", a / b), ("^", a ** b),
                        ("%", np.fmod(a, b)),
                        ("atan2", np.arctan2(a, b))):
            out = np.asarray(apply_binary_op(op, jnp.asarray(a),
                                             jnp.asarray(b)))
            np.testing.assert_allclose(out, ref, rtol=1e-12, err_msg=op)

    def test_comparison_filtering(self):
        a = np.array([1.0, 5.0, np.nan])
        b = np.array([2.0, 2.0, 2.0])
        out = np.asarray(apply_binary_op(">", jnp.asarray(a),
                                         jnp.asarray(b)))
        assert np.isnan(out[0]) and out[1] == 5.0 and np.isnan(out[2])

    def test_comparison_bool(self):
        a = np.array([1.0, 5.0, np.nan])
        b = np.array([2.0, 2.0, 2.0])
        out = np.asarray(apply_binary_op(">", jnp.asarray(a), jnp.asarray(b),
                                         bool_mode=True))
        assert out[0] == 0.0 and out[1] == 1.0 and np.isnan(out[2])
