"""Device-page format tests: host encode ↔ device decode parity (pure-jax
and Pallas-interpret paths)."""

import jax.numpy as jnp
import numpy as np
import pytest

from filodb_tpu.memory.device_pages import (
    BLOCK,
    decode_f32_page_jax,
    decode_ts_page_jax,
    decode_ts_page_pallas,
    encode_f32_page,
    encode_ts_page,
    page_to_arrays,
)


def ts_series(n, jitter=50, seed=0):
    rng = np.random.default_rng(seed)
    return (np.arange(n, dtype=np.int64) * 10_000
            + rng.integers(-jitter, jitter + 1, n) + 1_600_000_000_000)


class TestTsPages:
    @pytest.mark.parametrize("n", [1, 100, 128, 129, 1000])
    def test_round_trip_jax(self, n):
        ts = ts_series(n)
        page = encode_ts_page(ts)
        bases, slopes, widths, words = page_to_arrays(page)
        offsets = np.asarray(decode_ts_page_jax(bases, slopes, widths, words))
        out = (page.bases[:, None] + offsets.astype(np.int64)).ravel()[:n]
        np.testing.assert_array_equal(out, ts)

    def test_regular_timestamps_zero_width(self):
        ts = np.arange(256, dtype=np.int64) * 10_000
        page = encode_ts_page(ts)
        assert (page.widths == 0).all()  # perfect slope: no residual bits

    def test_round_trip_pallas_interpret(self):
        ts = ts_series(300, seed=3)
        page = encode_ts_page(ts)
        _, slopes, widths, words = page_to_arrays(page)
        offsets = np.asarray(decode_ts_page_pallas(
            slopes, widths, words, interpret=True))
        out = (page.bases[:, None] + offsets.astype(np.int64)).ravel()[:300]
        np.testing.assert_array_equal(out, ts)

    def test_pallas_matches_jax(self):
        ts = ts_series(513, seed=9, jitter=5000)
        page = encode_ts_page(ts)
        bases, slopes, widths, words = page_to_arrays(page)
        a = np.asarray(decode_ts_page_jax(bases, slopes, widths, words))
        b = np.asarray(decode_ts_page_pallas(slopes, widths, words,
                                             interpret=True))
        np.testing.assert_array_equal(a, b)

    def test_compression(self):
        ts = ts_series(10_000, jitter=20)
        page = encode_ts_page(ts)
        # jittered 10s timestamps: well under raw 8B/sample
        assert page.words[:, :].astype(bool).sum() * 4 < ts.nbytes / 4


class TestF32Pages:
    @pytest.mark.parametrize("n", [1, 127, 128, 500])
    def test_round_trip(self, n):
        rng = np.random.default_rng(1)
        v = rng.normal(100, 5, n).astype(np.float32)
        page = encode_f32_page(v)
        bases, shifts, widths, words = page_to_arrays(page)
        out = np.asarray(decode_f32_page_jax(bases, shifts, widths,
                                             words)).ravel()[:n]
        np.testing.assert_array_equal(out, v)

    def test_constant_block_zero_width(self):
        v = np.full(128, 42.5, np.float32)
        page = encode_f32_page(v)
        assert (page.widths == 0).all()

    def test_nan_values(self):
        v = np.array([1.0, np.nan, 3.0, np.inf, -np.inf], np.float32)
        page = encode_f32_page(v)
        bases, shifts, widths, words = page_to_arrays(page)
        out = np.asarray(decode_f32_page_jax(bases, shifts, widths,
                                             words)).ravel()[:5]
        np.testing.assert_array_equal(out, v)


class TestF32Pallas:
    def test_pallas_matches_jax(self):
        from filodb_tpu.memory.device_pages import (
            decode_f32_page_pallas,
        )
        rng = np.random.default_rng(4)
        v = rng.normal(100, 5, 513).astype(np.float32)
        page = encode_f32_page(v)
        bases, shifts, widths, words = page_to_arrays(page)
        a = np.asarray(decode_f32_page_jax(bases, shifts, widths, words))
        b = np.asarray(decode_f32_page_pallas(bases, shifts, widths, words,
                                              interpret=True))
        np.testing.assert_array_equal(a, b)


class TestPallasWindowedSum:
    def test_matches_xla_kernel(self):
        import jax.numpy as jnp
        from filodb_tpu.query.engine import kernels
        from filodb_tpu.query.engine.batch import TS_PAD
        from filodb_tpu.query.engine.pallas_kernels import windowed_sum_pallas

        rng = np.random.default_rng(7)
        P, S = 4, 256
        ts = np.full((P, S), TS_PAD, np.int32)
        vals = np.zeros((P, S), np.float32)
        counts = np.zeros(P, np.int32)
        for p in range(P):
            n = int(rng.integers(S // 2, S))
            ts[p, :n] = np.cumsum(rng.integers(5_000, 15_000, n))
            vals[p, :n] = rng.normal(50, 10, n)
            counts[p] = n
        steps = np.arange(300_000, 1_200_000, 90_000, dtype=np.int32)
        window = np.int32(300_000)
        ref = np.asarray(kernels.range_eval(
            "sum_over_time", jnp.asarray(ts),
            jnp.asarray(vals.astype(np.float64)), jnp.asarray(counts),
            jnp.asarray(steps), jnp.asarray(window)))
        out = np.asarray(windowed_sum_pallas(
            jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(steps),
            jnp.asarray(window), interpret=True))
        # pallas returns 0.0 (not NaN) for empty windows; compare where ref
        # has samples, and zeros elsewhere
        has = ~np.isnan(ref)
        np.testing.assert_allclose(out[has], ref[has], rtol=1e-5)
        assert (out[~has] == 0.0).all()
