"""Live shard migration chaos tests + per-tenant isolation.

Mirrors the reference's multi-jvm handoff/recovery specs
(``ClusterRecoverySpec``, ``ShardManagerSpec`` reassignment arms) for the
PR 6 migration subsystem (``coordinator/migration.py``):

- a shard moves between nodes through the PLANNED → SYNCING → CATCHUP →
  FLIPPING → DONE state machine with query equivalence before/after;
- a parameterized chaos matrix kills the driver at EVERY named
  ``FaultInjector`` kill-point, asserting queries stay correct against an
  unmigrated control and that ``resume()`` completes from the durable
  manifest — zero acked-data loss, zero wrong results;
- abort rolls the shard back to the source cleanly;
- queries touching RECOVERY/HANDOFF shards carry a "recovering" warning;
- rate-limited reassignments are deferred and retried, never dropped;
- one tenant's flood sheds ONLY that tenant (admission + cardinality).
"""

import time

import numpy as np
import pytest

from filodb_tpu.coordinator.cluster import FilodbCluster, Node
from filodb_tpu.coordinator.ingestion import route_container
from filodb_tpu.coordinator.migration import (
    ABORTED,
    DONE,
    KILL_POINTS,
    MigrationManifest,
    ShardMigration,
)
from filodb_tpu.coordinator.shard_manager import ShardManager
from filodb_tpu.coordinator.shardmapper import ShardStatus
from filodb_tpu.core.memstore.memstore import TimeSeriesMemStore
from filodb_tpu.core.store.api import InMemoryColumnStore, InMemoryMetaStore
from filodb_tpu.core.store.config import IngestionConfig, StoreConfig
from filodb_tpu.kafka.log import InMemoryLog
from filodb_tpu.testing.data import gauge_stream, machine_metrics_series
from filodb_tpu.utils import lockcheck, racecheck
from filodb_tpu.utils.resilience import FaultInjector

START = 1_600_000_000
NUM_SHARDS = 4
QUERY = 'sum(heap_usage{_ns_="App-3"})'


@pytest.fixture(autouse=True)
def _clean_faults():
    FaultInjector.reset()
    yield
    FaultInjector.reset()


def _publish(logs, stream, num_shards, spread=1):
    for sd in stream:
        for shard, cont in route_container(sd.container, num_shards,
                                           spread).items():
            logs[shard].append(cont)


@pytest.fixture
def cluster_env():
    # runtime lock-order checker armed for the whole cluster lifetime:
    # every lock the migration machinery creates below is wrapped, and
    # the teardown assertion makes any order cycle or blocking-under-
    # lock observed during the kill-point matrix a test failure
    with lockcheck.session():
        # ...and the shared-state race sanitizer beside it: shard maps,
        # migration manifests, and the migration state machines register
        # themselves, and any write to them that no common lock guards
        # across the kill-point matrix fails the test at teardown
        with racecheck.session():
            cs, meta = InMemoryColumnStore(), InMemoryMetaStore()
            logs = {s: InMemoryLog() for s in range(NUM_SHARDS)}
            keys = machine_metrics_series(12, ns="App-3")
            _publish(logs, gauge_stream(keys, 240, start_ms=START * 1000),
                     NUM_SHARDS)
            cluster = FilodbCluster()
            for n in ("node-a", "node-b"):
                cluster.join(Node(n, TimeSeriesMemStore(cs, meta)))
            config = IngestionConfig("timeseries", NUM_SHARDS,
                                     min_num_nodes=2,
                                     store=StoreConfig(max_chunk_size=60,
                                                       groups_per_shard=2))
            cluster.setup_dataset(config, logs)
            assert cluster.wait_active("timeseries", 10)
            yield cluster, cs
            cluster.stop()
            rvs = racecheck.violations()
        vs = lockcheck.violations()
    assert rvs == [], [v.render() for v in rvs]
    assert vs == [], [v.render() for v in vs]


def _query(cluster):
    svc = cluster.query_service("timeseries", spread=1)
    return svc.query_range(QUERY, START + 600, 300, START + 1500)


def _pick_shard(cluster, owner="node-a"):
    sm = cluster.shard_managers["timeseries"]
    shards = [s for s in range(NUM_SHARDS)
              if sm.mapper.node_for(s) == owner]
    assert shards, f"{owner} owns no shards"
    return shards[0]


class TestBasicMigration:
    def test_migrate_and_query_equivalence(self, cluster_env):
        cluster, cs = cluster_env
        before = _query(cluster)
        shard = _pick_shard(cluster, "node-a")
        mig = cluster.migrate_shard("timeseries", shard, "node-b")
        sm = cluster.shard_managers["timeseries"]
        assert mig.phase == DONE
        assert sm.mapper.node_for(shard) == "node-b"
        assert sm.mapper.statuses[shard] == ShardStatus.ACTIVE
        # the source tore the shard down; the destination serves it
        assert ("timeseries", shard) not in \
            cluster.nodes["node-a"]._workers
        assert ("timeseries", shard) in cluster.nodes["node-b"]._workers
        # manifest cleaned up
        assert cs.read_migration_manifest("timeseries", shard) is None
        after = _query(cluster)
        np.testing.assert_allclose(after.result.values,
                                   before.result.values, rtol=1e-9)

    def test_same_node_rejected(self, cluster_env):
        cluster, _ = cluster_env
        shard = _pick_shard(cluster, "node-a")
        with pytest.raises(ValueError):
            cluster.migrate_shard("timeseries", shard, "node-a")

    def test_manifest_roundtrip(self):
        m = MigrationManifest("ds", 3, "a", "b", "catchup", 5, 10, 20)
        assert MigrationManifest.from_bytes(m.to_bytes()) == m


class TestKillPointChaos:
    """Kill the driver at EVERY named transition; queries must stay
    correct throughout, and resume must complete the move from the
    durable manifest (zero acked-data loss, zero wrong results)."""

    @pytest.mark.parametrize("site", KILL_POINTS)
    def test_kill_and_resume(self, cluster_env, site):
        cluster, cs = cluster_env
        control = _query(cluster)  # unmigrated baseline
        shard = _pick_shard(cluster, "node-a")
        FaultInjector.arm(site, error=RuntimeError, times=1)
        with pytest.raises(RuntimeError):
            cluster.migrate_shard("timeseries", shard, "node-b")
        # mid-migration (any phase): results stay correct — the shard is
        # queryable on whichever side the map currently names
        mid = _query(cluster)
        np.testing.assert_allclose(mid.result.values,
                                   control.result.values, rtol=1e-9)
        # the manifest survived the crash; resume completes the move
        assert cs.read_migration_manifest("timeseries", shard) is not None
        mig = cluster.resume_migration("timeseries", shard)
        assert mig is not None and mig.phase == DONE
        sm = cluster.shard_managers["timeseries"]
        assert sm.mapper.node_for(shard) == "node-b"
        assert sm.mapper.statuses[shard] == ShardStatus.ACTIVE
        assert cs.read_migration_manifest("timeseries", shard) is None
        after = _query(cluster)
        np.testing.assert_allclose(after.result.values,
                                   control.result.values, rtol=1e-9)

    def test_resume_without_manifest_is_noop(self, cluster_env):
        cluster, _ = cluster_env
        assert cluster.resume_migration("timeseries", 0) is None


class TestAbort:
    def test_abort_rolls_back_to_source(self, cluster_env):
        cluster, cs = cluster_env
        control = _query(cluster)
        shard = _pick_shard(cluster, "node-a")
        FaultInjector.arm("migration.catchup", error=RuntimeError, times=1)
        with pytest.raises(RuntimeError):
            cluster.migrate_shard("timeseries", shard, "node-b")
        mig = cluster.migrations[("timeseries", shard)]
        mig.abort()
        assert mig.phase == ABORTED
        sm = cluster.shard_managers["timeseries"]
        assert sm.mapper.node_for(shard) == "node-a"
        assert sm.mapper.statuses[shard] == ShardStatus.ACTIVE
        # destination's partial recovery torn down, manifest cleared
        assert ("timeseries", shard) not in \
            cluster.nodes["node-b"]._workers
        assert cs.read_migration_manifest("timeseries", shard) is None
        after = _query(cluster)
        np.testing.assert_allclose(after.result.values,
                                   control.result.values, rtol=1e-9)


class TestRecoveryWarnings:
    def test_handoff_query_carries_warning(self, cluster_env):
        cluster, _ = cluster_env
        sm = cluster.shard_managers["timeseries"]
        shard = _pick_shard(cluster, "node-a")
        sm.begin_handoff(shard, "node-a")
        try:
            r = _query(cluster)
            assert any("recovering" in w for w in r.warnings), r.warnings
            assert any(f"shard {shard}" in w for w in r.warnings)
        finally:
            sm.abort_handoff(shard, "node-a")
        # back to ACTIVE: no warning
        r2 = _query(cluster)
        assert not any("recovering" in w for w in r2.warnings)

    def test_handoff_is_queryable(self):
        assert ShardStatus.HANDOFF.queryable


class TestDeferredReassignment:
    """Satellite: a rate-limited reassignment is deferred and retried on
    the next membership check — never silently left DOWN forever."""

    def test_deferred_then_reassigned(self):
        sm = ShardManager("ds", 4, min_num_nodes=2,
                          reassignment_min_interval_s=0.3)
        for n in ("n1", "n2", "n3", "n4"):
            sm.add_member(n)
        lost = sm.mapper.shards_of("n1")
        assert lost
        sm.remove_member("n1")  # first reassignment: stamps the shards
        # shards landed somewhere; now kill a node that adopted one while
        # still inside the rate-limit window
        victim = sm.mapper.node_for(lost[0])
        relost = sm.mapper.shards_of(victim)
        sm.remove_member(victim)
        # the freshly-stamped shards are DEFERRED (recorded for retry),
        # not reassigned and not dropped
        assert set(relost) <= sm._deferred
        for s in relost:
            assert sm.mapper.node_for(s) is None
        # next membership check after the interval picks them back up
        time.sleep(0.35)
        sm.add_member("n1")
        assert not sm._deferred
        assert sm.mapper.unassigned_shards() == []

    def test_check_deferred_respects_interval(self):
        sm = ShardManager("ds", 4, min_num_nodes=2,
                          reassignment_min_interval_s=30.0)
        for n in ("n1", "n2", "n3", "n4"):
            sm.add_member(n)
        lost = sm.mapper.shards_of("n1")
        sm.remove_member("n1")
        victim = sm.mapper.node_for(lost[0])
        relost = sm.mapper.shards_of(victim)
        sm.remove_member(victim)
        assert set(relost) <= sm._deferred
        # interval has NOT elapsed: check_deferred must not reassign
        assert sm.check_deferred() == []
        assert set(relost) <= sm._deferred


class TestRebalancePlanning:
    def test_plan_moves_toward_balance(self):
        sm = ShardManager("ds", 4, min_num_nodes=1)
        sm.add_member("n1")  # takes all 4
        sm.add_member("n2")  # idle: existing assignments are stable
        for s in range(4):
            sm.shard_active(s, "n1")
        moves = sm.plan_rebalance()
        assert moves  # n1=4, n2=0 → at least one move
        for shard, src, dst in moves:
            assert src == "n1" and dst == "n2"
        # proposed end state is balanced within min_imbalance
        assert len(moves) == 2

    def test_overloaded_forces_shed(self):
        sm = ShardManager("ds", 4, min_num_nodes=2)
        sm.add_member("n1")
        sm.add_member("n2")
        for s in range(4):
            sm.shard_active(s, sm.mapper.node_for(s))
        # balanced (2/2): only an overload trigger moves anything
        assert sm.plan_rebalance() == []
        moves = sm.plan_rebalance(overloaded="n1", min_imbalance=1)
        assert len(moves) == 1
        assert moves[0][1] == "n1" and moves[0][2] == "n2"

    def test_join_rebalance_via_migration(self, cluster_env):
        cluster, _ = cluster_env
        before = _query(cluster)
        cluster.auto_rebalance = True
        joiner = Node("node-c", TimeSeriesMemStore(
            cluster.nodes["node-a"].memstore.column_store,
            cluster.nodes["node-a"].memstore.meta_store))
        cluster.join(joiner)
        deadline = time.monotonic() + 15
        sm = cluster.shard_managers["timeseries"]
        while time.monotonic() < deadline:
            if sm.mapper.shards_of("node-c") and not cluster.migrations:
                break
            time.sleep(0.05)
        assert sm.mapper.shards_of("node-c"), "joiner received no shard"
        after = _query(cluster)
        np.testing.assert_allclose(after.result.values,
                                   before.result.values, rtol=1e-9)


class TestTenantIsolation:
    """One tenant's flood sheds ONLY that tenant."""

    @pytest.fixture(autouse=True)
    def _clean_governor(self):
        from filodb_tpu.utils import governor
        governor.reset()
        yield
        governor.reset()

    def test_cardinality_quota_per_tenant(self):
        from filodb_tpu.utils import governor
        governor.configure(tenants={"demo/App-0": {"max_series": 4}})
        ms = TimeSeriesMemStore()
        shard = ms.setup("timeseries", 0, StoreConfig(max_chunk_size=50))
        noisy = machine_metrics_series(10, ns="App-0")   # quota 4
        quiet = machine_metrics_series(10, ns="App-9")   # unclassed
        for sd in gauge_stream(noisy + quiet, 10):
            shard.ingest(sd)
        card = shard.cardinality
        assert card.cardinality(["demo", "App-0"]).active_ts == 4
        assert card.cardinality(["demo", "App-9"]).active_ts == 10
        assert shard.stats.quota_dropped.value > 0
        from filodb_tpu.utils.metrics import get_counter
        assert get_counter("filodb_tenant_ingest_dropped",
                           {"tenant": "demo/App-0"}).value > 0

    def test_admission_cap_per_tenant(self):
        from filodb_tpu.utils import governor
        governor.configure(tenants={"noisy": {"max_inflight": 1}})
        g = governor.ResourceGovernor(governor.config())
        with g.admit(tenant="noisy/App-0"):
            # same tenant at cap: immediate shed, reason "tenant"
            with pytest.raises(governor.QueryRejected) as ei:
                with g.admit(tenant="noisy/App-1"):
                    pass
            assert ei.value.reason == "tenant"
            # other tenants (and untenanted) unaffected
            with g.admit(tenant="quiet/App-0"):
                pass
            with g.admit():
                pass
        # slot released: the tenant admits again
        with g.admit(tenant="noisy/App-0"):
            pass

    def test_plan_tenant_extraction(self):
        from filodb_tpu.coordinator.query_service import plan_tenant
        from filodb_tpu.promql.parser import TimeStepParams, parse_query
        plan = parse_query('heap_usage{_ws_="demo",_ns_="App-3"}',
                           TimeStepParams(START, 60, START + 600))
        assert plan_tenant(plan) == "demo/App-3"
        plan2 = parse_query("heap_usage",
                            TimeStepParams(START, 60, START + 600))
        assert plan_tenant(plan2) == ""


class TestDurableManifests:
    def test_localstore_manifest_roundtrip(self, tmp_path):
        from filodb_tpu.core.store.localstore import LocalDiskColumnStore
        cs = LocalDiskColumnStore(str(tmp_path / "columnstore"))
        try:
            assert cs.read_migration_manifest("ds", 1) is None
            cs.write_migration_manifest("ds", 1, b'{"phase": "syncing"}')
            assert cs.read_migration_manifest("ds", 1) == \
                b'{"phase": "syncing"}'
            cs.delete_migration_manifest("ds", 1)
            assert cs.read_migration_manifest("ds", 1) is None
            cs.delete_migration_manifest("ds", 1)  # idempotent
        finally:
            cs.close()

    def test_objectstore_manifest_roundtrip(self, tmp_path):
        from filodb_tpu.core.store.objectstore import open_object_store
        cs, meta = open_object_store({"endpoint": None, "bucket": "t"},
                                     str(tmp_path))
        try:
            assert cs.read_migration_manifest("ds", 2) is None
            cs.write_migration_manifest("ds", 2, b'{"phase": "catchup"}')
            assert cs.read_migration_manifest("ds", 2) == \
                b'{"phase": "catchup"}'
            cs.delete_migration_manifest("ds", 2)
            assert cs.read_migration_manifest("ds", 2) is None
        finally:
            cs.close()
            meta.close()
